#!/usr/bin/env python3
"""Device data-path smoke: gate the constant cache, shape buckets, and the
pipelined-upload counters on the CPU platform (fast, runs anywhere).

Checks (exit 0 when every scenario holds, one PASS/FAIL line each):

1. **Library two-dispatch**: two identical wire dispatches through
   ``ConsensusKernel.device_call_segments_wire``. The constant tables
   (wire dictionary) upload exactly once — the second dispatch adds zero
   constant-upload bytes — and the second dispatch's shape-bucket lookup
   hits. Results are byte-identical across dispatches.
2. **CLI run report**: a multi-batch ``simplex`` run with the device
   kernel forced (FGUMI_TPU_HOST_ENGINE=0, FGUMI_TPU_HYBRID=0 wire path)
   emits a run report whose metrics carry ``device.shape_bucket.*`` and
   ``device.const_cache.*``, whose device section shows exactly one
   constant upload with repeat hits, and whose later dispatches hit the
   shape registry.
3. **Device-resident filter** (ISSUE 11): forced ``--device-filter``
   output is record-identical to ``simplex | filter``; the filter-heavy
   config's run report shows bytes-fetched reduced >= 5x vs the non-fused
   device route; resident bytes release by exit; an injected device fault
   degrades to the host filter cleanly and byte-identically.
4. **Pallas kernel** (ISSUE 19): forced ``FGUMI_TPU_KERNEL=pallas``
   (Mosaic interpret mode on CPU) byte-identical to ``xla`` on the
   simplex and ``--device-filter`` routes, backend counters in the run
   report, clean loud fallback to XLA when the lowering is unavailable.
5. ``--shape-buckets`` rejects malformed specs with a clean error.

Sibling of tools/telemetry_smoke.py / tools/serve_smoke.py /
tools/chaos_smoke.py in the verify flow (.claude/skills/verify).

Usage:  python tools/perf_smoke.py [--keep]
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASE_ENV = {
    **os.environ,
    "PYTHONPATH": REPO,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "",
    "PALLAS_AXON_POOL_IPS": "",
    "FGUMI_TPU_HOST_ENGINE": "0",
    "FGUMI_TPU_HYBRID": "0",
}


def run_cli(args, env=None, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "fgumi_tpu", *args], cwd=REPO,
        env={**BASE_ENV, **(env or {})}, capture_output=True, text=True,
        timeout=timeout)


def check(name, ok, detail=""):
    print(f"{'PASS' if ok else 'FAIL'}  {name}" + (f"  ({detail})"
                                                   if detail else ""))
    return ok


_TWO_DISPATCH = r"""
import json, sys
import numpy as np
sys.path.insert(0, %(repo)r)
from fgumi_tpu.ops.tables import quality_tables
from fgumi_tpu.ops.kernel import (ConsensusKernel, DEVICE_STATS,
                                  pad_segments_gather)
from fgumi_tpu.ops.datapath import CONST_CACHE, SHAPE_REGISTRY
from fgumi_tpu.observe.metrics import METRICS

kernel = ConsensusKernel(quality_tables(45, 40))
kernel.set_force_device()
rng = np.random.default_rng(3)
J, R, L = 64, 4, 32
codes = rng.integers(0, 4, size=(J * R, L), dtype=np.uint8)
quals = rng.integers(20, 41, size=(J * R, L), dtype=np.uint8)
counts = np.full(J, R, dtype=np.int64)
rows = np.arange(J * R)

out = {"rounds": []}
results = []
for i in range(2):
    cd, qd, seg, starts, F_pad, N = pad_segments_gather(
        codes, quals, rows, L, counts)
    ticket = kernel.device_call_segments_wire(cd, qd, seg, F_pad, J)
    w, q, d, e = kernel.resolve_segments_wire(ticket, cd[:N], qd[:N], starts)
    results.append((w.tobytes(), q.tobytes(), d.tobytes(), e.tobytes()))
    out["rounds"].append({
        "const_uploads": CONST_CACHE.uploads,
        "const_upload_bytes": CONST_CACHE.upload_bytes,
        "const_hits": CONST_CACHE.hits,
        "bucket_hits": SHAPE_REGISTRY.hits,
        "bucket_misses": SHAPE_REGISTRY.misses,
    })
out["identical"] = results[0] == results[1]
out["metrics"] = {k: v for k, v in METRICS.snapshot().items()
                  if k.startswith("device.")}
out["stats"] = DEVICE_STATS.snapshot()
print(json.dumps(out))
"""


def two_dispatch_scenario():
    p = subprocess.run(
        [sys.executable, "-c", _TWO_DISPATCH % {"repo": REPO}], cwd=REPO,
        env=BASE_ENV, capture_output=True, text=True, timeout=300)
    ok = check("two-dispatch payload exits 0", p.returncode == 0,
               (p.stderr.strip().splitlines() or ["no stderr"])[-1]
               if p.returncode else "")
    if not ok:
        return False
    out = json.loads(p.stdout.strip().splitlines()[-1])
    r1, r2 = out["rounds"]
    ok &= check("constant tables upload exactly once",
                r1["const_uploads"] >= 1
                and r2["const_uploads"] == r1["const_uploads"],
                f"uploads {r1['const_uploads']} -> {r2['const_uploads']}")
    ok &= check("second dispatch re-uploads zero constant bytes",
                r2["const_upload_bytes"] == r1["const_upload_bytes"],
                f"bytes {r1['const_upload_bytes']} -> "
                f"{r2['const_upload_bytes']}")
    ok &= check("second dispatch hits the constant cache",
                r2["const_hits"] > r1["const_hits"])
    ok &= check("second dispatch's shape-bucket lookup hits",
                r2["bucket_hits"] > r1["bucket_hits"]
                and r2["bucket_misses"] == r1["bucket_misses"],
                f"hits {r1['bucket_hits']} -> {r2['bucket_hits']}, "
                f"misses {r2['bucket_misses']}")
    ok &= check("dispatches byte-identical", out["identical"])
    ok &= check("DeviceStats carries const/upload counters",
                out["stats"].get("const_uploads", 0) >= 1
                and out["stats"].get("const_hits", 0) >= 1)
    return ok


def report_scenario(tmp):
    grouped = os.path.join(tmp, "grouped.bam")
    p = run_cli(["simulate", "grouped-reads", "-o", grouped,
                 "--num-families", "150", "--family-size", "4",
                 "--seed", "5"])
    assert p.returncode == 0, p.stderr
    rpt = os.path.join(tmp, "simplex.report.json")
    p = run_cli(["--run-report", rpt, "simplex", "-i", grouped,
                 "-o", os.path.join(tmp, "cons.bam"), "--min-reads", "1"])
    ok = check("simplex (device) exits 0", p.returncode == 0,
               f"rc={p.returncode}")
    try:
        report = json.load(open(rpt))
    except (OSError, ValueError):
        return check("run report readable", False)
    from fgumi_tpu.observe.report import validate_report

    errs = validate_report(report)
    ok &= check("run report schema-valid", not errs, "; ".join(errs[:3]))
    m = report.get("metrics", {})
    dev = report.get("device", {})
    dispatches = dev.get("dispatches", 0)
    ok &= check("device section carries dispatches",
                dispatches >= 1, f"dispatches={dispatches}")
    ok &= check("report metrics carry device.shape_bucket.*",
                m.get("device.shape_bucket.misses", 0) >= 1
                and m.get("device.shape_bucket.misses", 0)
                + m.get("device.shape_bucket.hits", 0) == dispatches,
                f"misses={m.get('device.shape_bucket.misses')} "
                f"hits={m.get('device.shape_bucket.hits')}")
    ok &= check("report metrics carry device.const_cache.*",
                m.get("device.const_cache.misses", 0) >= 1)
    # uploads happen only on first sight of a table's content, so they
    # equal distinct contents (cache misses), never dispatch count — the
    # repeat-dispatch zero-re-upload property is gated by scenario 1
    ok &= check("device section carries const-cache counters",
                dev.get("const_uploads", 0)
                == m.get("device.const_cache.misses", -1)
                and dev.get("const_upload_bytes", 0) >= 1,
                f"uploads={dev.get('const_uploads')} "
                f"bytes={dev.get('const_upload_bytes')}")
    return ok


def full_column_scenario(tmp):
    """Round-6 gates: the full-column device route is the default device
    path (one link crossing per family batch), routing counters land in
    the run report, both forced routes are byte-identical, and a faulting
    device degrades to the host engine cleanly (exit 0, same bytes)."""
    grouped = os.path.join(tmp, "fc_grouped.bam")
    p = run_cli(["simulate", "grouped-reads", "-o", grouped,
                 "--num-families", "200", "--family-size", "4",
                 "--seed", "11"])
    assert p.returncode == 0, p.stderr
    out_bam = os.path.join(tmp, "fc_cons.bam")
    rpt = os.path.join(tmp, "fc.report.json")
    # hybrid on (native host engine available) so routing is a real choice
    hybrid = {"FGUMI_TPU_HYBRID": "1"}

    p = run_cli(["--run-report", rpt, "simplex", "-i", grouped, "-o",
                 out_bam, "--min-reads", "1"],
                {**hybrid, "FGUMI_TPU_ROUTE": "device"})
    ok = check("full-column device run exits 0", p.returncode == 0,
               f"rc={p.returncode}")
    if not ok:
        return False
    dev_bytes = open(out_bam, "rb").read()
    report = json.load(open(rpt))
    dev = report.get("device", {})
    m = report.get("metrics", {})
    ok &= check("one link crossing per routed family batch",
                dev.get("dispatches", 0) >= 1
                and dev.get("dispatches") == dev.get("route_device"),
                f"dispatches={dev.get('dispatches')} "
                f"route_device={dev.get('route_device')}")
    ok &= check("report metrics carry device.route.*",
                m.get("device.route.device", 0) >= 1)
    ok &= check("device section carries cost-model snapshot",
                isinstance(dev.get("routing"), dict)
                and "link_mbps" in dev.get("routing", {}))

    # identical argv (the @PG CL header line records it) — only env differs
    p = run_cli(["--run-report", rpt, "simplex", "-i", grouped, "-o",
                 out_bam, "--min-reads", "1"],
                {**hybrid, "FGUMI_TPU_ROUTE": "host"})
    ok &= check("forced-host run exits 0", p.returncode == 0)
    ok &= check("forced device/host routes byte-identical",
                open(out_bam, "rb").read() == dev_bytes)

    p = run_cli(["--run-report", rpt, "simplex", "-i", grouped, "-o",
                 out_bam, "--min-reads", "1"],
                {**hybrid, "FGUMI_TPU_ROUTE": "device",
                 "FGUMI_TPU_DEVICE_BACKOFF_S": "0.01",
                 "FGUMI_TPU_FAULT": "device.dispatch:raise:1.0"})
    ok &= check("faulting device degrades cleanly (exit 0)",
                p.returncode == 0, f"rc={p.returncode}")
    ok &= check("fallback engaged loudly", "host engine" in p.stderr)
    ok &= check("degraded run byte-identical",
                open(out_bam, "rb").read() == dev_bytes)
    return ok


_AUDIT_OVERHEAD = r"""
import json, os, sys, time
import numpy as np
sys.path.insert(0, %(repo)r)
os.environ["FGUMI_TPU_AUDIT"] = "off"
from fgumi_tpu.ops.tables import quality_tables
from fgumi_tpu.ops.kernel import ConsensusKernel, pad_segments_gather
from fgumi_tpu.ops.sentinel import SENTINEL
from fgumi_tpu.observe.metrics import METRICS

kernel = ConsensusKernel(quality_tables(45, 40))
kernel.set_force_device()
rng = np.random.default_rng(7)
J, R, L = 64, 4, 32
codes = rng.integers(0, 4, size=(J * R, L), dtype=np.uint8)
quals = rng.integers(20, 41, size=(J * R, L), dtype=np.uint8)
counts = np.full(J, R, dtype=np.int64)
rows = np.arange(J * R)

def one():
    cd, qd, seg, starts, F_pad, N = pad_segments_gather(
        codes, quals, rows, L, counts)
    t = kernel.device_call_segments_wire(cd, qd, seg, F_pad, J)
    return kernel.resolve_segments_wire(t, cd[:N], qd[:N], starts)

one()  # warm-up: compile outside the timed window, unaudited
os.environ["FGUMI_TPU_AUDIT"] = "4"
t0 = time.monotonic()
for _ in range(16):
    one()
wall = time.monotonic() - t0
SENTINEL.drain()
tap = METRICS.histogram("device.audit.tap_s")
snap = SENTINEL.snapshot()
print(json.dumps({
    "wall_s": wall,
    "tap_sum_s": tap.total if tap else 0.0,
    "tap_count": tap.count if tap else 0,
    "sampled": snap["sampled"], "clean": snap["clean"],
    "divergent": snap["divergent"],
}))
"""


def audit_overhead_scenario(tmp):
    """ISSUE 14 perf guard: the shadow-audit sentinel's resolve-thread
    cost (sample decision + input retention; the oracle re-execution runs
    on the background audit thread) stays under 2% of the run's wall even
    at an aggressive 1-in-4 rate — so the default 1-in-64 is far below it
    — measured via the PR 9 ``device.audit.tap_s`` histogram rather than
    noisy wall-vs-wall A/B on a shared-core host. Byte-identity of
    audited vs unaudited runs rides along."""
    p = subprocess.run(
        [sys.executable, "-c", _AUDIT_OVERHEAD % {"repo": REPO}],
        cwd=REPO, env={**BASE_ENV, "FGUMI_TPU_ROUTE": "device"},
        capture_output=True, text=True, timeout=300)
    ok = check("audit-overhead payload exits 0", p.returncode == 0,
               p.stderr.strip().splitlines()[-1] if p.returncode else "")
    if not ok:
        return False
    out = json.loads(p.stdout.strip().splitlines()[-1])
    ok &= check("1-in-4 sampling audited the expected dispatches",
                out["sampled"] == 4 and out["clean"] == 4
                and out["divergent"] == 0,
                f"sampled={out['sampled']} clean={out['clean']}")
    frac = out["tap_sum_s"] / out["wall_s"] if out["wall_s"] else 1.0
    ok &= check("audit tap cost < 2% of dispatch wall "
                "(device.audit.tap_s histogram)",
                out["tap_count"] >= 1 and frac < 0.02,
                f"sum={out['tap_sum_s']:.5f}s wall={out['wall_s']:.3f}s "
                f"frac={frac:.4%}")
    # CLI side: audited vs unaudited byte-identity + off leaves no trace
    grouped = os.path.join(tmp, "audit_grouped.bam")
    p = run_cli(["simulate", "grouped-reads", "-o", grouped,
                 "--num-families", "200", "--family-size", "4",
                 "--seed", "13"])
    assert p.returncode == 0, p.stderr
    out_bam = os.path.join(tmp, "audit_cons.bam")
    rpt = os.path.join(tmp, "audit.report.json")
    p = run_cli(["--run-report", rpt, "simplex", "-i", grouped, "-o",
                 out_bam, "--min-reads", "1"],
                {"FGUMI_TPU_AUDIT": "all", "FGUMI_TPU_ROUTE": "device"})
    ok &= check("fully-audited simplex exits 0", p.returncode == 0,
                f"rc={p.returncode}")
    audited_bytes = open(out_bam, "rb").read()
    report = json.load(open(rpt))
    audit = report.get("audit", {})
    ok &= check("report audit section carries sampled/clean counts",
                audit.get("sampled", 0) >= 1
                and audit.get("clean") == audit.get("sampled")
                and audit.get("divergent") == 0,
                f"sampled={audit.get('sampled')} "
                f"clean={audit.get('clean')}")
    p = run_cli(["--run-report", rpt, "simplex", "-i", grouped, "-o",
                 out_bam, "--min-reads", "1"],
                {"FGUMI_TPU_AUDIT": "off", "FGUMI_TPU_ROUTE": "device"})
    ok &= check("unaudited run exits 0", p.returncode == 0)
    ok &= check("audited vs unaudited byte-identical",
                open(out_bam, "rb").read() == audited_bytes)
    report = json.load(open(rpt))
    ok &= check("FGUMI_TPU_AUDIT=off leaves zero audit traces",
                "audit" not in report
                and "device.audit.sampled" not in report.get("metrics", {}))
    return ok


def _records(path):
    from fgumi_tpu.io.bam import BamReader

    with BamReader(path) as r:
        return [bytes(rec.data) for rec in r]


def device_filter_scenario(tmp):
    """ISSUE 11 gates: forced ``--device-filter`` output is record-
    identical to simplex|filter on a mixed config; on the filter-heavy
    config the run report shows bytes-fetched reduced >= 5x vs the
    non-fused device route; a faulting device degrades to the host filter
    cleanly (exit 0, same records)."""
    grouped = os.path.join(tmp, "df_grouped.bam")
    p = run_cli(["simulate", "grouped-reads", "-o", grouped,
                 "--num-families", "250", "--family-size", "4",
                 "--family-size-distribution", "longtail", "--seed", "13"])
    assert p.returncode == 0, p.stderr
    cons = os.path.join(tmp, "df_cons.bam")
    two_stage = os.path.join(tmp, "df_twostage.bam")
    fused = os.path.join(tmp, "df_fused.bam")
    filt_args = ["--filter-min-reads", "3",
                 "--filter-min-mean-base-quality", "30",
                 "--filter-min-base-quality", "20"]
    dev = {"FGUMI_TPU_ROUTE": "device"}
    p = run_cli(["simplex", "-i", grouped, "-o", cons, "--min-reads", "1"],
                dev)
    ok = check("simplex (reference) exits 0", p.returncode == 0)
    p = run_cli(["filter", "-i", cons, "-o", two_stage, "-M", "3",
                 "-q", "30", "-N", "20"])
    ok &= check("filter (reference) exits 0", p.returncode == 0)
    rpt = os.path.join(tmp, "df.report.json")
    p = run_cli(["--run-report", rpt, "simplex", "-i", grouped, "-o",
                 fused, "--min-reads", "1", "--device-filter"] + filt_args,
                dev)
    ok &= check("forced --device-filter exits 0", p.returncode == 0,
                f"rc={p.returncode}")
    if not ok:
        return False
    ok &= check("--device-filter records identical to simplex|filter",
                _records(fused) == _records(two_stage))
    report = json.load(open(rpt))
    devsec = report.get("device", {})
    ok &= check("resident bytes tracked and released",
                devsec.get("resident_bytes_peak", 0) > 0
                and "resident_bytes" not in devsec,
                f"peak={devsec.get('resident_bytes_peak')}")
    ok &= check("fetch-bytes histogram in the report",
                "device.dispatch.fetch_bytes" in report.get("latency", {}))

    # filter-heavy config: fixed family size 3 under min-reads 6 rejects
    # every record — the fused route fetches stats rows only
    heavy = os.path.join(tmp, "df_heavy.bam")
    p = run_cli(["simulate", "grouped-reads", "-o", heavy,
                 "--num-families", "400", "--family-size", "3",
                 "--seed", "17"])
    assert p.returncode == 0, p.stderr
    rpt_full = os.path.join(tmp, "df_full.report.json")
    p = run_cli(["--run-report", rpt_full, "simplex", "-i", heavy, "-o",
                 os.path.join(tmp, "df_h1.bam"), "--min-reads", "1"], dev)
    ok &= check("heavy non-fused run exits 0", p.returncode == 0)
    rpt_fused = os.path.join(tmp, "df_fused.report.json")
    p = run_cli(["--run-report", rpt_fused, "simplex", "-i", heavy, "-o",
                 os.path.join(tmp, "df_h2.bam"), "--min-reads", "1",
                 "--device-filter", "--filter-min-reads", "6"], dev)
    ok &= check("heavy fused run exits 0", p.returncode == 0)
    try:
        full_b = json.load(open(rpt_full))["device"]["bytes_fetched"]
        fused_b = json.load(open(rpt_fused))["device"]["bytes_fetched"]
    except (OSError, KeyError, ValueError):
        return check("fetch-bytes readable from run reports", False)
    ok &= check("filter-heavy bytes fetched reduced >= 5x",
                full_b >= 5 * max(fused_b, 1),
                f"{full_b} vs {fused_b} "
                f"({full_b / max(fused_b, 1):.1f}x)")
    # dispatch wall p50 (PR 9 histograms): informational on the CPU
    # platform — the hardware-evidence bar (ROADMAP item 1) reads these
    # same keys from a real-TPU run's report
    try:
        p50_full = json.load(open(rpt_full))[
            "latency"]["device.dispatch.wall_s"]["p50"]
        p50_fused = json.load(open(rpt_fused))[
            "latency"]["device.dispatch.wall_s"]["p50"]
        print(f"      dispatch wall p50: full={p50_full}s "
              f"fused={p50_fused}s (informational on CPU)")
    except (OSError, KeyError, ValueError):
        pass

    # device weather: every dispatch faults -> host filter completes the
    # fused stage byte-identically, exit 0
    p = run_cli(["simplex", "-i", grouped, "-o", fused, "--min-reads", "1",
                 "--device-filter"] + filt_args,
                {**dev, "FGUMI_TPU_HYBRID": "1",
                 "FGUMI_TPU_DEVICE_BACKOFF_S": "0.01",
                 "FGUMI_TPU_FAULT": "device.dispatch:raise:1.0"})
    ok &= check("faulting device-filter degrades cleanly (exit 0)",
                p.returncode == 0, f"rc={p.returncode}")
    ok &= check("degraded device-filter records identical",
                _records(fused) == _records(two_stage))
    return ok


def pallas_scenario(tmp):
    """ISSUE 19 gates: forced ``FGUMI_TPU_KERNEL=pallas`` (Mosaic
    interpret mode on this CPU platform) is byte-identical to the XLA
    kernels on both the simplex and ``--device-filter`` routes; the run
    report's device section counts dispatches under the active backend;
    and an unavailable Pallas lowering falls back to XLA cleanly."""
    grouped = os.path.join(tmp, "pk_grouped.bam")
    p = run_cli(["simulate", "grouped-reads", "-o", grouped,
                 "--num-families", "150", "--family-size", "4",
                 "--family-size-distribution", "longtail", "--seed", "19"])
    assert p.returncode == 0, p.stderr
    out_bam = os.path.join(tmp, "pk_cons.bam")
    rpt = os.path.join(tmp, "pk.report.json")
    dev = {"FGUMI_TPU_ROUTE": "device"}

    p = run_cli(["--run-report", rpt, "simplex", "-i", grouped, "-o",
                 out_bam, "--min-reads", "1"],
                {**dev, "FGUMI_TPU_KERNEL": "xla"})
    ok = check("simplex (kernel=xla) exits 0", p.returncode == 0,
               f"rc={p.returncode}")
    if not ok:
        return False
    xla_bytes = open(out_bam, "rb").read()
    devsec = json.load(open(rpt)).get("device", {})
    ok &= check("xla run counts kernel_xla dispatches",
                devsec.get("kernel_xla", 0) >= 1
                and devsec.get("kernel_pallas", 0) == 0,
                f"xla={devsec.get('kernel_xla')} "
                f"pallas={devsec.get('kernel_pallas')}")

    p = run_cli(["--run-report", rpt, "simplex", "-i", grouped, "-o",
                 out_bam, "--min-reads", "1"],
                {**dev, "FGUMI_TPU_KERNEL": "pallas"})
    ok &= check("simplex (kernel=pallas, interpret on CPU) exits 0",
                p.returncode == 0, f"rc={p.returncode}")
    ok &= check("pallas vs xla simplex byte-identical",
                open(out_bam, "rb").read() == xla_bytes)
    report = json.load(open(rpt))
    devsec = report.get("device", {})
    m = report.get("metrics", {})
    ok &= check("pallas run counts kernel_pallas dispatches",
                devsec.get("kernel_pallas", 0) >= 1,
                f"pallas={devsec.get('kernel_pallas')} "
                f"xla={devsec.get('kernel_xla')}")
    ok &= check("report metrics carry device.kernel.pallas",
                m.get("device.kernel.pallas", 0)
                == devsec.get("kernel_pallas", -1))

    # fused consensus->filter route, both backends record-identical
    filt_args = ["--device-filter", "--filter-min-reads", "3",
                 "--filter-min-mean-base-quality", "30",
                 "--filter-min-base-quality", "20"]
    fused_x = os.path.join(tmp, "pk_fused_x.bam")
    fused_p = os.path.join(tmp, "pk_fused_p.bam")
    p = run_cli(["simplex", "-i", grouped, "-o", fused_x,
                 "--min-reads", "1"] + filt_args,
                {**dev, "FGUMI_TPU_KERNEL": "xla"})
    ok &= check("--device-filter (kernel=xla) exits 0", p.returncode == 0)
    p = run_cli(["simplex", "-i", grouped, "-o", fused_p,
                 "--min-reads", "1"] + filt_args,
                {**dev, "FGUMI_TPU_KERNEL": "pallas"})
    ok &= check("--device-filter (kernel=pallas) exits 0",
                p.returncode == 0, f"rc={p.returncode}")
    ok &= check("pallas vs xla --device-filter records identical",
                _records(fused_p) == _records(fused_x))

    # unavailable lowering: loud XLA fallback, same bytes, exit 0
    p = run_cli(["--run-report", rpt, "simplex", "-i", grouped, "-o",
                 out_bam, "--min-reads", "1"],
                {**dev, "FGUMI_TPU_KERNEL": "pallas",
                 "FGUMI_TPU_PALLAS_UNAVAILABLE": "1"})
    ok &= check("unavailable pallas falls back cleanly (exit 0)",
                p.returncode == 0, f"rc={p.returncode}")
    ok &= check("fallback announced loudly",
                "falling back" in p.stderr.lower())
    devsec = json.load(open(rpt)).get("device", {})
    ok &= check("fallback run executed on the XLA kernels",
                devsec.get("kernel_pallas", 0) == 0
                and devsec.get("kernel_xla", 0) >= 1)
    ok &= check("fallback run byte-identical",
                open(out_bam, "rb").read() == xla_bytes)
    return ok


def bad_spec_scenario(tmp):
    p = run_cli(["--shape-buckets", "0.5", "sort", "-i", "x", "-o",
                 os.path.join(tmp, "never.bam")])
    return check("--shape-buckets 0.5 rejected cleanly",
                 p.returncode == 2 and "growth" in p.stderr,
                 f"rc={p.returncode}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch directory")
    opts = ap.parse_args()
    tmp = tempfile.mkdtemp(prefix="fgumi_perf_smoke_")
    ok = True
    try:
        ok &= two_dispatch_scenario()
        ok &= report_scenario(tmp)
        ok &= full_column_scenario(tmp)
        ok &= device_filter_scenario(tmp)
        ok &= pallas_scenario(tmp)
        ok &= audit_overhead_scenario(tmp)
        ok &= bad_spec_scenario(tmp)
    finally:
        if opts.keep:
            print("scratch kept at", tmp)
        else:
            shutil.rmtree(tmp, ignore_errors=True)
    print("perf smoke:", "OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
