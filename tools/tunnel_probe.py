"""One-shot tunnel characterization: upload/fetch bandwidth, duplex overlap,
and dispatch pipelining on the axon-attached TPU.

Run standalone (python tools/tunnel_probe.py); prints one JSON dict. The
round-5 overlap design (pipeline double-buffering, packed wire formats) is
sized from these numbers — see docs/device-feeding.md.
"""

import json
import threading
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    out = {}
    t0 = time.monotonic()
    dev = jax.devices()[0]
    out["init_s"] = round(time.monotonic() - t0, 2)
    out["device"] = str(dev)

    MB = 1 << 20
    up8 = np.random.randint(0, 250, size=(16 * MB,), dtype=np.uint8)

    # --- upload bandwidth (16 MB) ---
    for _ in range(2):
        t0 = time.monotonic()
        d = jax.device_put(up8)
        d.block_until_ready()
        up_s = time.monotonic() - t0
    out["upload_16mb_s"] = round(up_s, 3)
    out["upload_mb_per_s"] = round(16 / up_s, 1)

    # --- fetch bandwidth (16 MB) ---
    for _ in range(2):
        t0 = time.monotonic()
        h = np.asarray(jax.device_get(d))
        fe_s = time.monotonic() - t0
    out["fetch_16mb_s"] = round(fe_s, 3)
    out["fetch_mb_per_s"] = round(16 / fe_s, 1)
    assert h[0] == up8[0]

    # --- duplex: concurrent upload + fetch of 16 MB each ---
    res = {}

    def up_thread():
        t0 = time.monotonic()
        dd = jax.device_put(up8[: 16 * MB])
        dd.block_until_ready()
        res["up"] = time.monotonic() - t0

    def down_thread():
        t0 = time.monotonic()
        np.asarray(jax.device_get(d))
        res["down"] = time.monotonic() - t0

    t0 = time.monotonic()
    ts = [threading.Thread(target=up_thread), threading.Thread(target=down_thread)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    both = time.monotonic() - t0
    out["duplex_both_16mb_s"] = round(both, 3)
    out["duplex_up_s"] = round(res["up"], 3)
    out["duplex_down_s"] = round(res["down"], 3)
    # full duplex: both ~= max(up, fetch); half duplex: both ~= up + fetch
    out["duplex_ratio"] = round(both / (up_s + fe_s), 2)

    # --- dispatch pipelining: 2 jitted calls in flight vs sequential ---
    @jax.jit
    def burn(x):
        # enough compute to be visible: a few passes of elementwise math
        y = x.astype(jnp.float32)
        for _ in range(8):
            y = jnp.sin(y) * 1.0001 + 0.1
        return jnp.sum(y, axis=0)

    a = np.random.rand(2048, 2048).astype(np.float32)
    burn(a).block_until_ready()  # compile
    t0 = time.monotonic()
    burn(a).block_until_ready()
    one = time.monotonic() - t0
    t0 = time.monotonic()
    r1 = burn(a)
    r2 = burn(a)
    r1.block_until_ready()
    r2.block_until_ready()
    two = time.monotonic() - t0
    out["one_dispatch_s"] = round(one, 3)
    out["two_dispatch_s"] = round(two, 3)
    out["dispatch_overlap_ratio"] = round(two / (2 * one), 2)

    # --- does a jit call with big numpy args block on the upload? ---
    big = np.random.randint(0, 250, size=(32 * MB,), dtype=np.uint8)

    @jax.jit
    def touch(x):
        return x[:16].astype(jnp.int32) * 2

    touch(big[: 1024]).block_until_ready()
    t0 = time.monotonic()
    r = touch(big)
    enq = time.monotonic() - t0
    r.block_until_ready()
    tot = time.monotonic() - t0
    out["enqueue_32mb_arg_s"] = round(enq, 3)
    out["complete_32mb_arg_s"] = round(tot, 3)

    # --- device_put async? ---
    t0 = time.monotonic()
    dd = jax.device_put(big)
    enq = time.monotonic() - t0
    dd.block_until_ready()
    tot = time.monotonic() - t0
    out["device_put_enqueue_s"] = round(enq, 3)
    out["device_put_complete_s"] = round(tot, 3)

    # --- overlapped device_put from 2 threads (split halves) vs one ---
    halves = [big[: 16 * MB], big[16 * MB:]]
    t0 = time.monotonic()
    devs = [None, None]

    def putter(i):
        devs[i] = jax.device_put(halves[i])
        devs[i].block_until_ready()

    ts = [threading.Thread(target=putter, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    out["parallel_put_2x16mb_s"] = round(time.monotonic() - t0, 3)

    print(json.dumps(out))


if __name__ == "__main__":
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import devprobe

    devprobe.locked_main(main)  # the chip is single-tenant: hold the flock
