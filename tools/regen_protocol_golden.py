"""Regenerate tests/data/serve_protocol_golden.json against a live daemon.

Drives the checked-in request sequences (unix-socket exchanges and the
TCP conversations) through a fresh JobService and rewrites each golden
response with the normalized live answer. Run after an intentional wire
change, then REVIEW THE DIFF — the golden exists to catch unintentional
ones.

    PYTHONPATH=. python tools/regen_protocol_golden.py
"""

import json
import os
import socket
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fgumi_tpu.serve import protocol  # noqa: E402
from fgumi_tpu.serve.daemon import JobService  # noqa: E402

HERE = os.path.dirname(os.path.abspath(__file__))
GOLDEN = os.path.join(HERE, os.pardir, "tests", "data",
                      "serve_protocol_golden.json")

# keep in sync with tests/test_serve_protocol.py
_VOLATILE_STATS_SECTIONS = ("metrics", "latency", "device", "device_memory",
                            "breaker", "governor", "router", "monitor",
                            "audit", "coalesce")


def _normalize(obj):
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            if k.endswith("_unix") and isinstance(v, (int, float)):
                out[k] = 0
            elif k in ("uptime_s", "pid"):
                out[k] = 0
            elif k in ("report_path", "trace_path"):
                out[k] = None
            elif k in _VOLATILE_STATS_SECTIONS and "schema_version" in obj:
                out[k] = None
            else:
                out[k] = _normalize(v)
        return out
    if isinstance(obj, list):
        return [_normalize(v) for v in obj]
    return obj


def regen_exchanges(golden, tmp):
    svc = JobService(os.path.join(tmp, "serve.sock"), workers=1,
                     queue_limit=1, report_dir=None)
    svc.start_transport()
    try:
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.settimeout(10)
        conn.connect(svc.socket_path)
        stream = conn.makefile("rb")
        for exchange in golden["exchanges"]:
            conn.sendall(protocol.encode_frame(exchange["request"]))
            exchange["response"] = _normalize(protocol.read_frame(stream))
        conn.close()
    finally:
        svc.close()


def regen_tcp(golden, tmp):
    svc = JobService(None, workers=1, queue_limit=1,
                     tcp=("127.0.0.1", 0), auth_token="golden-secret")
    svc.start_transport()
    try:
        for convo in golden["tcp_conversations"]:
            conn = socket.create_connection(("127.0.0.1", svc.tcp_port),
                                            timeout=10)
            stream = conn.makefile("rb")
            for exchange in convo["exchanges"]:
                conn.sendall(protocol.encode_frame(exchange["request"]))
                exchange["response"] = _normalize(
                    protocol.read_frame(stream))
            conn.close()
    finally:
        svc.close()


def main():
    with open(GOLDEN) as f:
        golden = json.load(f)
    with tempfile.TemporaryDirectory() as tmp:
        regen_exchanges(golden, tmp)
        regen_tcp(golden, tmp)
    with open(GOLDEN, "w") as f:
        json.dump(golden, f, indent=1)
        f.write("\n")
    print(f"rewrote {os.path.relpath(GOLDEN)}")


if __name__ == "__main__":
    main()
