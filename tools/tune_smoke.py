#!/usr/bin/env python3
"""Deployment-profile smoke (ISSUE 20): gate the self-tuning loop end to
end on the CPU platform (fast, runs anywhere).

Checks (exit 0 when every scenario holds, one PASS/FAIL line each):

1. **Quick tune**: ``fgumi-tpu tune --quick`` exits 0 and commits a
   schema-valid deployment profile plus a crossover atlas whose cells
   carry positive measured rates for both routes.
2. **Byte identity**: a ``simplex`` run with the freshly tuned profile
   loaded produces record bytes identical to the defaults run — a
   profile tunes throughput, never output.
3. **No slower**: the profile-loaded run's wall clock is within a
   generous CI-noise envelope of the defaults run (the profile must
   never make a run pathologically slower).
4. **Prior-seeded routing**: with the profile applied, the router's very
   first fam-3 batch routes to the side the atlas measured as the winner
   for that workload cell, with ``prior_source == "profile"`` and a cost
   (not probe) decision; the run report carries the ``profile`` section
   and ``tune.*`` gauges.
5. **Precedence + diagnostics**: an explicit env knob survives profile
   application (skipped_explicit), and a malformed profile is a clean
   exit-2 diagnostic.
6. **Replay**: ``tune --replay`` over the quick run's atlas-backing
   microbench cells derives a schema-valid ``source: replay`` profile.

Sibling of tools/perf_smoke.py / tools/serve_smoke.py in the verify
flow (.claude/skills/verify).

Usage:  python tools/tune_smoke.py [--keep]
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASE_ENV = {
    **os.environ,
    "PYTHONPATH": REPO,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "",
    "PALLAS_AXON_POOL_IPS": "",
}
# a stray deployed profile must not leak into the smoke's baseline
BASE_ENV.pop("FGUMI_TPU_PROFILE", None)


def run_cli(args, env=None, timeout=600):
    return subprocess.run(
        [sys.executable, "-m", "fgumi_tpu", *args], cwd=REPO,
        env={**BASE_ENV, **(env or {})}, capture_output=True, text=True,
        timeout=timeout)


def check(name, ok, detail=""):
    print(f"{'PASS' if ok else 'FAIL'}  {name}" + (f"  ({detail})"
                                                   if detail else ""))
    return ok


def record_bytes(path):
    from fgumi_tpu.io.bam import BamReader

    with BamReader(path) as rd:
        return b"".join(r.data for r in rd)


def tune_scenario(tmp):
    prof = os.path.join(tmp, "deploy_profile.json")
    atlas = os.path.join(tmp, "TUNE_ATLAS.json")
    p = run_cli(["tune", "--quick", "-o", prof, "--atlas", atlas])
    ok = check("tune --quick exits 0", p.returncode == 0,
               (p.stderr.strip().splitlines() or ["no stderr"])[-1]
               if p.returncode else "")
    if not ok:
        return False, None, None
    from fgumi_tpu.tune.profile import load_profile, validate_profile

    profile = load_profile(prof)
    validate_profile(profile)  # raises on schema violations
    ok &= check("profile schema-valid", True)
    ok &= check("profile carries router priors",
                bool(profile.get("priors", {}).get("router")))
    doc = json.load(open(atlas))
    cells = doc.get("cells", [])
    ok &= check("atlas carries measured cells", len(cells) >= 3,
                f"{len(cells)} cells")
    ok &= check("atlas cells carry positive rates on both routes",
                all(c.get("device_rows_per_sec", 0) > 0
                    and c.get("host_rows_per_sec", 0) > 0 for c in cells))
    return ok, prof, doc


def identity_scenario(tmp, prof):
    bam = os.path.join(tmp, "grouped.bam")
    p = run_cli(["simulate", "grouped-reads", "-o", bam,
                 "--num-families", "200", "--family-size", "3",
                 "--seed", "7"])
    if not check("simulate exits 0", p.returncode == 0,
                 p.stderr.strip().splitlines()[-1] if p.returncode else ""):
        return False
    cold = os.path.join(tmp, "cold.bam")
    warm = os.path.join(tmp, "warm.bam")
    t0 = time.monotonic()
    p1 = run_cli(["simplex", "-i", bam, "-o", cold, "--min-reads", "1"])
    t_cold = time.monotonic() - t0
    t0 = time.monotonic()
    p2 = run_cli(["--profile", prof, "simplex", "-i", bam, "-o", warm,
                  "--min-reads", "1"])
    t_warm = time.monotonic() - t0
    ok = check("defaults + profile runs exit 0",
               p1.returncode == 0 and p2.returncode == 0,
               (p1.stderr or p2.stderr).strip().splitlines()[-1]
               if p1.returncode or p2.returncode else "")
    if not ok:
        return False
    ok &= check("profile run byte-identical to defaults",
                record_bytes(cold) == record_bytes(warm))
    # generous envelope: a profile must never be pathologically slower
    # (2x + 2s absorbs CI noise on tiny inputs where wall is dominated
    # by interpreter startup, not the tuned path)
    ok &= check("profile run no slower (2x + 2s envelope)",
                t_warm <= 2.0 * t_cold + 2.0,
                f"cold {t_cold:.2f}s warm {t_warm:.2f}s")
    return ok


_ROUTE_PAYLOAD = r"""
import json, sys
sys.path.insert(0, %(repo)r)
from fgumi_tpu.tune import profile as profmod
from fgumi_tpu.ops.router import ROUTER
from fgumi_tpu.native import batch as nb

profile = profmod.load_profile(%(prof)r)
rec = profmod.apply_profile(profile, path=%(prof)r)

class K:
    @staticmethod
    def hybrid_mode():
        return True

# the quick atlas' fam-3 L100 cell: 4000 families x 3 reads
decision = ROUTER.decide_batch(K(), n_rows=12000, n_segments=4000, L=100)
snap = ROUTER.snapshot()
print(json.dumps({
    "native": nb.available(),
    "decision": decision,
    "prior_source": snap["prior_source"],
    "why": (snap.get("last_decision") or {}).get("why"),
    "applied": rec["applied"],
}))
"""


def routing_scenario(tmp, prof, atlas_doc):
    cell = next((c for c in atlas_doc["cells"]
                 if c.get("mean_depth") == 3 and c.get("read_length") == 100),
                None)
    if cell is None:
        return check("atlas carries the fam-3 L100 cell", False)
    p = subprocess.run(
        [sys.executable, "-c",
         _ROUTE_PAYLOAD % {"repo": REPO, "prof": prof}], cwd=REPO,
        env=BASE_ENV, capture_output=True, text=True, timeout=300)
    ok = check("routing payload exits 0", p.returncode == 0,
               (p.stderr.strip().splitlines() or ["no stderr"])[-1]
               if p.returncode else "")
    if not ok:
        return False
    out = json.loads(p.stdout.strip().splitlines()[-1])
    ok &= check("profile seeds the router (prior_source=profile)",
                out["prior_source"] == "profile", out["prior_source"])
    if out["native"]:
        ok &= check("first-batch route matches the atlas winner",
                    out["decision"] == cell["winner"],
                    f"routed {out['decision']}, atlas says {cell['winner']}")
        ok &= check("decision is cost-based, not a probe",
                    out["why"] == "cost", str(out["why"]))
    else:
        check("first-batch route matches the atlas winner",
              out["decision"] == "device",
              "native engine unavailable: device-only"),
    # the profile section rides the run report of a profile-loaded run
    rpt = os.path.join(tmp, "report.json")
    bam = os.path.join(tmp, "grouped.bam")
    out_bam = os.path.join(tmp, "rpt.bam")
    p = run_cli(["--profile", prof, "--run-report", rpt, "simplex",
                 "-i", bam, "-o", out_bam, "--min-reads", "1"])
    ok &= check("profile-loaded run-report run exits 0", p.returncode == 0)
    if p.returncode == 0:
        report = json.load(open(rpt))
        sec = report.get("profile") or {}
        ok &= check("run report carries the profile section",
                    sec.get("path") == prof)
        ok &= check("run report carries tune.* gauges",
                    report.get("metrics", {}).get(
                        "tune.profile.loaded") == 1)
        routing = (report.get("device") or {}).get("routing") or {}
        ok &= check("device.routing stamps prior_source",
                    routing.get("prior_source") in
                    ("profile", "cold", "snapshot"),
                    str(routing.get("prior_source")))
    return ok


def precedence_scenario(tmp, prof):
    rpt = os.path.join(tmp, "prec_report.json")
    bam = os.path.join(tmp, "grouped.bam")
    out_bam = os.path.join(tmp, "prec.bam")
    p = run_cli(["--profile", prof, "--run-report", rpt, "simplex",
                 "-i", bam, "-o", out_bam, "--min-reads", "1"],
                env={"FGUMI_TPU_COALESCE_WINDOW_MS": "9"})
    ok = check("explicit-env run exits 0", p.returncode == 0)
    if p.returncode == 0:
        sec = json.load(open(rpt)).get("profile") or {}
        ok &= check("explicit env knob wins over the profile",
                    "coalesce_window_ms" in
                    sec.get("knobs_skipped_explicit", []),
                    str(sec.get("knobs_skipped_explicit")))
    bad = os.path.join(tmp, "bad_profile.json")
    with open(bad, "w") as fh:
        json.dump({"schema_version": 1, "source": "manual"}, fh)
    p = run_cli(["--profile", bad, "simplex", "-i", bam, "-o", out_bam,
                 "--min-reads", "1"])
    ok &= check("malformed profile is a clean exit-2 diagnostic",
                p.returncode == 2 and "expected" in p.stderr,
                f"rc={p.returncode}")
    return ok


def replay_scenario(tmp):
    micro = os.path.join(tmp, "micro.json")
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "microbench.py"), REPO,
         "--tune-cells-only"], cwd=REPO, env=BASE_ENV,
        capture_output=True, text=True, timeout=600)
    if not check("microbench --tune-cells-only exits 0", p.returncode == 0,
                 (p.stderr.strip().splitlines() or ["?"])[-1]
                 if p.returncode else ""):
        return False
    with open(micro, "w") as fh:
        fh.write(p.stdout.strip().splitlines()[-1])
    prof2 = os.path.join(tmp, "replay_profile.json")
    atlas2 = os.path.join(tmp, "replay_atlas.json")
    p = run_cli(["tune", "--replay", micro, "-o", prof2,
                 "--atlas", atlas2])
    ok = check("tune --replay exits 0", p.returncode == 0,
               (p.stderr.strip().splitlines() or ["?"])[-1]
               if p.returncode else "")
    if not ok:
        return False
    from fgumi_tpu.tune.profile import load_profile, validate_profile

    profile = load_profile(prof2)
    validate_profile(profile)
    ok &= check("replay profile schema-valid, source=replay",
                profile["source"] == "replay", profile["source"])
    return ok


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir")
    args = ap.parse_args()
    tmp = tempfile.mkdtemp(prefix="tune_smoke_")
    ok = True
    try:
        ok, prof, atlas_doc = tune_scenario(tmp)
        if ok:
            ok &= identity_scenario(tmp, prof)
            ok &= routing_scenario(tmp, prof, atlas_doc)
            ok &= precedence_scenario(tmp, prof)
            ok &= replay_scenario(tmp)
    finally:
        if args.keep:
            print(f"scratch kept: {tmp}")
        else:
            shutil.rmtree(tmp, ignore_errors=True)
    print("OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
