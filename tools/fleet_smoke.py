#!/usr/bin/env python3
"""Fleet smoke: 2 TCP daemons + a health-routed balancer — the CI gate
for the fleet resilience tier (ISSUE 12).

Scenarios (exit 0 when every check holds, one PASS/FAIL line each):

1. Fleet up: both daemons answer through the balancer's front end
   (handshake token enforced end to end), 2/2 backends healthy.
2. Spillover on over-capacity: with workers=1 / queue-limit=0 per
   daemon, two concurrent submits land on DIFFERENT backends (job-id
   fleet prefixes prove it), both outputs byte-identical to standalone
   runs; a third concurrent submit is refused with an explicit reason.
3. Kill-one-mid-job takeover: SIGKILL the daemon RUNNING a job. The
   balancer ejects it (breaker open in the balancer's stats), the
   survivor claims the dead daemon's journal lease and requeues the job
   under its ORIGINAL id, the job completes byte-identically to a
   standalone run, and the journal audit shows exactly ONE done event
   fleet-wide (zero double-executions); an idempotent resubmit with the
   same dedupe key answers with the finished job.
4. Warm survivor: the post-takeover job on the surviving daemon reports
   zero XLA recompilations (device.backend_compiles == 0) — scale-out
   keeps the warm-serving economics.
5. Eject -> re-admit: restarting the killed daemon (fresh, its journal
   was consumed) brings its backend closed again through the balancer's
   half-open probes.
6. Fleet tracing + aggregated metrics (ISSUE 17): a traced submit
   through the balancer leaves per-process trace files whose
   `fgumi-tpu trace-merge` stitches into ONE timeline with spans from
   >=3 processes under one trace-id; the balancer's --metrics-port
   /metrics endpoint re-exports both backends' labeled series and
   agrees with the `stats` op's fleet_metrics section; the per-backend
   end-to-end submit-to-done latency summary is surfaced fleet-side.
7. Whale scatter/gather (ISSUE 18): a fresh 2-backend fleet behind
   `balance --scatter 2`. Submitted pipeline/simplex/duplex jobs come
   back as whales (`w-...` ids) whose gathered outputs are
   byte-identical to standalone runs; SIGKILLing the backend running a
   shard mid-flight completes the whale through the journal-lease
   takeover with a fleet-wide audit of exactly one done event per
   shard (zero double-execution, no coordinator requeue); the same
   whale on both backends beats the one-backend fleet by >=1.6x
   aggregate reads/s (enforced when >=3 CPU cores are visible; loudly
   skipped on smaller hosts where shards must timeshare one core); the
   stats op carries schema v3 with the scatter section and /metrics
   exports the fleet.scatter.* gauges from the same snapshot.

Usage:  python tools/fleet_smoke.py [--keep]
"""

import argparse
import json
import os
import re
import shutil
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

TOKEN = "fleet-smoke-secret"

BASE_ENV = {
    **os.environ,
    "PYTHONPATH": REPO,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "",
    "PALLAS_AXON_POOL_IPS": "",
    # force the device kernel AND the device route so warm-vs-cold compile
    # evidence exists even on a CPU-only host
    "FGUMI_TPU_HOST_ENGINE": "0",
    "FGUMI_TPU_ROUTE": "device",
}


def run(args, cwd, env=None, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "fgumi_tpu", *args], cwd=cwd,
        env={**BASE_ENV, **(env or {})}, capture_output=True, text=True,
        timeout=timeout)


def check(name, ok, detail=""):
    print(f"{'PASS' if ok else 'FAIL'}  {name}" + (f"  ({detail})"
                                                   if detail else ""))
    return ok


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def wait_for_ping(client, timeout=120):
    from fgumi_tpu.serve.client import ServeError

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            return client.ping()
        except ServeError:
            time.sleep(0.2)
    return None


def wait_job_tolerant(client, job_id, timeout=240):
    """Poll a job through the balancer, tolerating the takeover window
    (the dead backend's job is briefly unknown fleet-wide until the
    survivor's lease scan adopts it)."""
    from fgumi_tpu.serve.client import ServeError
    from fgumi_tpu.serve.jobs import TERMINAL

    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            job = client.job(job_id)
            last = job
            if job["state"] in TERMINAL:
                return job
        except ServeError as e:
            last = {"state": f"unresolved ({e})"}
        time.sleep(0.25)
    return last


def backend_states(client):
    stats = client.stats()
    return {b["address"]: b["state"] for b in stats["backends"]}


def wait_backend_state(client, address, state, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if backend_states(client).get(address) == state:
                return True
        except Exception:  # noqa: BLE001 - balancer may be briefly busy
            pass
        time.sleep(0.2)
    return False


def journal_events(jdir):
    """Every record from every journal artifact in the fleet dir."""
    out = []
    for name in sorted(os.listdir(jdir)):
        if ".journal" not in name:
            continue
        with open(os.path.join(jdir, name)) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                rec["_file"] = name
                out.append(rec)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch directory")
    opts = ap.parse_args()
    from fgumi_tpu.serve.client import ServeClient, ServeError

    tmp = tempfile.mkdtemp(prefix="fgumi_fleet_")
    ok = True
    procs = {}
    balancer = None
    try:
        wd_std = os.path.join(tmp, "standalone")
        wd_fleet = os.path.join(tmp, "fleet_wd")   # BOTH daemons' cwd:
        # relative job outputs land here no matter which daemon runs the
        # job — the property takeover relies on
        rpt = os.path.join(tmp, "reports")
        jdir = os.path.join(tmp, "journals")
        cache = os.path.join(tmp, "xla_cache")
        for d in (wd_std, wd_fleet, rpt, jdir):
            os.makedirs(d)
        tok = os.path.join(tmp, "token")
        with open(tok, "w") as f:
            f.write(TOKEN + "\n")
        inp = os.path.join(tmp, "grouped.bam")
        p = run(["simulate", "grouped-reads", "-o", inp,
                 "--num-families", "600", "--family-size", "4",
                 "--seed", "7"], cwd=tmp)
        assert p.returncode == 0, p.stderr
        # the kill job gets a much larger input: by the time it runs the
        # daemons are WARM (earlier scenarios compiled its shapes), and a
        # sub-second job would finish before the SIGKILL lands — voiding
        # the mid-job takeover scenario (the observed-running check below
        # enforces this stays true)
        inp_big = os.path.join(tmp, "grouped_big.bam")
        p = run(["simulate", "grouped-reads", "-o", inp_big,
                 "--num-families", "8000", "--family-size", "4",
                 "--seed", "8"], cwd=tmp)
        assert p.returncode == 0, p.stderr

        job1 = ["simplex", "-i", inp, "-o", "out1.bam", "--min-reads", "1"]
        job2 = ["simplex", "-i", inp, "-o", "out2.bam", "--min-reads", "1"]
        kill_job = ["simplex", "-i", inp_big, "-o", "out_kill.bam",
                    "--min-reads", "1"]
        warm_job = ["simplex", "-i", inp, "-o", "out_warm.bam",
                    "--min-reads", "1"]

        # --- standalone references --------------------------------------
        for argv in (job1, job2, kill_job, warm_job):
            p = run(argv, cwd=wd_std)
            assert p.returncode == 0, p.stderr

        # --- fleet up: 2 daemons + balancer, all TCP + token -------------
        ports = {"a": free_port(), "b": free_port()}
        front = free_port()
        metrics_port = free_port()
        bal_trace = os.path.join(tmp, "balancer_trace.json")

        def start_daemon(fid):
            argv = [sys.executable, "-m", "fgumi_tpu", "serve",
                    "--tcp", f"127.0.0.1:{ports[fid]}",
                    "--workers", "1", "--queue-limit", "0",
                    "--journal-dir", jdir, "--fleet-id", fid,
                    "--lease-scan-period", "0.5",
                    "--report-dir", rpt, "--compile-cache", cache,
                    "--token-file", tok]
            return subprocess.Popen(argv, cwd=wd_fleet, env=BASE_ENV,
                                    stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT, text=True)

        procs["a"] = start_daemon("a")
        procs["b"] = start_daemon("b")
        balancer = subprocess.Popen(
            [sys.executable, "-m", "fgumi_tpu", "--trace", bal_trace,
             "balance",
             "--listen", f"tcp:127.0.0.1:{front}",
             "--backend", f"tcp:127.0.0.1:{ports['a']}",
             "--backend", f"tcp:127.0.0.1:{ports['b']}",
             "--token-file", tok, "--poll-period", "0.3",
             "--eject-failures", "2", "--cooldown", "1.0",
             "--probes", "2", "--metrics-port", str(metrics_port)],
            cwd=tmp, env=BASE_ENV, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        client = ServeClient(f"tcp:127.0.0.1:{front}", timeout=30,
                             token=TOKEN)
        ping = wait_for_ping(client)
        ok &= check("balancer front end answers through the token "
                    "handshake", ping is not None
                    and ping.get("tool") == "fgumi-tpu-balance",
                    str(ping))
        addr_a = f"tcp:127.0.0.1:{ports['a']}"
        addr_b = f"tcp:127.0.0.1:{ports['b']}"
        ok &= check("both backends healthy",
                    wait_backend_state(client, addr_a, "closed")
                    and wait_backend_state(client, addr_b, "closed"))

        # --- spillover on over-capacity ---------------------------------
        argv0 = os.path.join(REPO, "fgumi_tpu", "__main__.py")
        j1 = client.submit(job1, argv0=argv0)
        j2 = client.submit(job2, argv0=argv0)
        prefixes = {j1["id"].split("-j-")[0], j2["id"].split("-j-")[0]}
        ok &= check("concurrent submits spill across BOTH backends",
                    prefixes == {"a", "b"},
                    f"{j1['id']} / {j2['id']}")
        over_reason = None
        try:
            client.submit(job1, argv0=argv0)
        except ServeError as e:
            over_reason = str(e)
        ok &= check("over-capacity submit refused with an explicit reason",
                    over_reason is not None
                    and "no backend admitted" in over_reason,
                    over_reason or "admitted!")
        j1 = wait_job_tolerant(client, j1["id"])
        j2 = wait_job_tolerant(client, j2["id"])
        ok &= check("both spillover jobs done",
                    j1 and j2 and j1.get("state") == "done"
                    and j2.get("state") == "done",
                    f"{j1 and j1.get('state')}/{j2 and j2.get('state')}")
        for name in ("out1.bam", "out2.bam"):
            a = open(os.path.join(wd_std, name), "rb").read()
            b = open(os.path.join(wd_fleet, name), "rb").read()
            ok &= check(f"{name} byte-identical to standalone", a == b,
                        f"{len(a)} vs {len(b)} bytes")

        # --- kill-one-mid-job takeover ----------------------------------
        jk = client.submit(kill_job, argv0=argv0, dedupe="kill-fleet")
        victim_id = jk["id"].split("-j-")[0]
        survivor_id = "b" if victim_id == "a" else "a"
        victim_addr = addr_a if victim_id == "a" else addr_b
        observed_running = False
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            state = wait_job_tolerant(client, jk["id"], timeout=1)
            s = state.get("state") if state else None
            if s == "running":
                observed_running = True
                break
            if s in ("done", "failed", "cancelled"):
                break  # finished before the kill: the scenario is void
        # the takeover scenario is only exercised if the SIGKILL lands
        # MID-JOB — a pre-kill completion must fail the gate loudly, not
        # let the later checks pass vacuously
        ok &= check("kill job observed running before SIGKILL",
                    observed_running,
                    str(state and state.get("state")))
        procs[victim_id].kill()   # SIGKILL: no drain, lease dies with it
        procs[victim_id].wait(timeout=30)
        ok &= check("balancer ejects the killed backend",
                    wait_backend_state(client, victim_addr, "open"),
                    json.dumps(backend_states(client)))
        jk_final = wait_job_tolerant(client, jk["id"], timeout=240)
        ok &= check("killed daemon's job finishes under its ORIGINAL id "
                    "via lease takeover",
                    jk_final and jk_final.get("state") == "done"
                    and jk_final.get("id", jk["id"]) == jk["id"],
                    str(jk_final and jk_final.get("state")))
        a = open(os.path.join(wd_std, "out_kill.bam"), "rb").read()
        b_path = os.path.join(wd_fleet, "out_kill.bam")
        b = open(b_path, "rb").read() if os.path.exists(b_path) else b""
        ok &= check("takeover output byte-identical to standalone",
                    a == b, f"{len(a)} vs {len(b)} bytes")
        leftovers = [n for n in os.listdir(wd_fleet) if ".tmp." in n]
        ok &= check("no temp leftovers after takeover", not leftovers,
                    ",".join(leftovers))
        # zero double-execution: exactly one `done` event fleet-wide for
        # the job, and the consumed journal was renamed .claimed
        events = journal_events(jdir)
        done_events = [e for e in events if e.get("id") == jk["id"]
                       and e.get("state") == "done"]
        ok &= check("journal audit: exactly one done event fleet-wide",
                    len(done_events) == 1,
                    f"{len(done_events)} done events")
        claimed = [n for n in os.listdir(jdir)
                   if n == f"{victim_id}.journal.claimed"]
        ok &= check("dead daemon's journal consumed exactly once "
                    "(renamed .claimed)", len(claimed) == 1,
                    ",".join(sorted(os.listdir(jdir))))
        # dedupe audit: the idempotent resubmit answers with the SAME
        # (finished) job instead of executing a second copy
        jk_again = client.submit(kill_job, argv0=argv0,
                                 dedupe="kill-fleet")
        ok &= check("dedupe resubmit answers with the recovered job",
                    jk_again["id"] == jk["id"]
                    and jk_again["state"] == "done",
                    f"{jk_again['id']} ({jk_again['state']})")

        # --- warm survivor: zero recompiles -----------------------------
        jw = client.submit(warm_job, argv0=argv0)
        ok &= check("warm job routed to the survivor",
                    jw["id"].startswith(survivor_id + "-"), jw["id"])
        jw = wait_job_tolerant(client, jw["id"])
        ok &= check("warm job done", jw and jw.get("state") == "done",
                    str(jw and (jw.get("error") or jw.get("state"))))
        try:
            r = json.load(open(os.path.join(rpt,
                                            f"{jw['id']}.report.json")))
        except (OSError, ValueError):
            r = {}
        # absent metric = zero observed compiles (the compile watcher
        # only counts real backend-compile events; serve_smoke reads the
        # same way) — dispatches > 0 proves the device path actually ran
        compiles = r.get("metrics", {}).get("device.backend_compiles", 0)
        dispatches = r.get("device", {}).get("dispatches", 0)
        ok &= check("warm survivor reports zero XLA recompilations",
                    bool(r) and compiles == 0 and dispatches > 0,
                    f"compiles={compiles} dispatches={dispatches}")
        a = open(os.path.join(wd_std, "out_warm.bam"), "rb").read()
        b = open(os.path.join(wd_fleet, "out_warm.bam"), "rb").read()
        ok &= check("warm output byte-identical to standalone", a == b)

        # --- eject -> re-admit after restart ----------------------------
        procs[victim_id] = start_daemon(victim_id)
        ok &= check("restarted backend re-admitted via half-open probes",
                    wait_backend_state(client, victim_addr, "closed",
                                       timeout=90),
                    json.dumps(backend_states(client)))

        # --- fleet tracing + aggregated metrics (ISSUE 17) ---------------
        client_trace = os.path.join(tmp, "client_trace.json")
        before_traces = set(os.listdir(rpt))
        p = run(["--trace", client_trace, "submit",
                 "--socket", f"tcp:127.0.0.1:{front}",
                 "--token-file", tok, "--job-trace", "--",
                 "simplex", "-i", inp, "-o", "out_traced.bam",
                 "--min-reads", "1"], cwd=wd_fleet)
        ok &= check("traced submit through the balancer succeeds",
                    p.returncode == 0, (p.stdout + p.stderr)[-300:])
        backend_traces = [n for n in os.listdir(rpt)
                          if n.endswith(".trace.json")
                          and n not in before_traces]
        ok &= check("backend wrote a per-job trace",
                    len(backend_traces) == 1, ",".join(backend_traces))
        client_ctx = {}
        try:
            client_ctx = json.load(open(client_trace))["otherData"].get(
                "trace_context") or {}
        except (OSError, ValueError, KeyError):
            pass
        tid = client_ctx.get("trace_id")
        ok &= check("client trace carries the fleet trace id", bool(tid),
                    json.dumps(client_ctx))
        # the traced job's run report carries the v5 end-to-end
        # attribution: trace context + a decomposition whose components
        # never sum past the total (capped shares, see observe/report.py)
        job_report = {}
        if backend_traces:
            rpt_name = backend_traces[0].replace(".trace.json",
                                                 ".report.json")
            try:
                job_report = json.load(open(os.path.join(rpt, rpt_name)))
            except (OSError, ValueError):
                pass
        dec = job_report.get("latency_decomposition") or {}
        comp = sum(v for k, v in dec.items() if k != "total_s")
        ok &= check("run report carries the fleet latency decomposition",
                    job_report.get("trace_context", {}).get("trace_id")
                    == tid and "client_to_balancer_s" in dec
                    and "queue_s" in dec and "host_complete_s" in dec
                    and comp <= dec.get("total_s", 0) + 0.005,
                    json.dumps(dec)[:220])
        # the balancer cache needs one poll after the job finished before
        # the e2e summaries appear fleet-side
        fm = {}
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            fm = client.stats().get("fleet_metrics") or {}
            if any(e.get("submit_to_done_s")
                   for e in fm.get("per_backend", [])):
                break
            time.sleep(0.3)
        ok &= check("fleet p99 submit-to-bytes-published surfaced per "
                    "backend (stats op fleet_metrics)",
                    any(e.get("submit_to_done_s", {}).get("p99")
                        is not None for e in fm.get("per_backend", [])),
                    json.dumps(fm.get("per_backend"))[:200])
        metrics_body = urllib.request.urlopen(
            f"http://127.0.0.1:{metrics_port}/metrics",
            timeout=10).read().decode()
        for addr in (addr_a, addr_b):
            ok &= check(f"/metrics exports labeled series for {addr}",
                        f'fgumi_tpu_fleet_backend_up{{backend="{addr}"}} 1'
                        in metrics_body)
        e2e_series = set(re.findall(
            r'fgumi_tpu_serve_job_e2e_submit_to_done_s\{backend="([^"]+)"',
            metrics_body))
        ok &= check("backend e2e latency summaries re-exported on /metrics",
                    len(e2e_series) >= 1, ",".join(sorted(e2e_series)))
        ok &= check("/metrics consistent with the stats op "
                    "(same-snapshot rule)",
                    fm.get("backends_total") == 2
                    and f"fgumi_tpu_fleet_backends_total 2" in metrics_body
                    and f"fgumi_tpu_fleet_backends_healthy "
                        f"{fm.get('backends_healthy')}" in metrics_body,
                    json.dumps({k: fm.get(k) for k in
                                ("backends_total", "backends_healthy")}))

        # --- clean shutdown ---------------------------------------------
        client.shutdown()  # drains the balancer
        rc = balancer.wait(timeout=60)
        ok &= check("balancer exits 0 on shutdown", rc == 0, f"rc={rc}")
        balancer = None

        # --- merged fleet timeline (balancer trace flushed on exit) ------
        merged = os.path.join(tmp, "merged_trace.json")
        p = run(["trace-merge", client_trace, bal_trace,
                 os.path.join(rpt, backend_traces[0]), "-o", merged,
                 "--trace-id", tid or "0" * 32], cwd=tmp)
        ok &= check("trace-merge stitches the fleet timeline",
                    p.returncode == 0, (p.stdout + p.stderr)[-300:])
        try:
            m = json.load(open(merged))
        except (OSError, ValueError):
            m = {"traceEvents": [], "otherData": {}}
        span_pids = {e["pid"] for e in m["traceEvents"]
                     if e.get("ph") == "X"}
        ok &= check("merged trace has spans from >=3 processes",
                    len(span_pids) >= 3, str(sorted(span_pids)))
        names = {e["name"] for e in m["traceEvents"] if e.get("ph") == "X"}
        ok &= check("client, balancer and backend spans all present",
                    "serve.submit" in names and "serve.forward" in names
                    and "pipeline.process" in names,
                    ",".join(sorted(names))[:200])
        ok &= check("merged under ONE trace id",
                    m["otherData"].get("trace_context", {}).get("trace_id")
                    == tid and len(m["otherData"].get("merged_from", []))
                    == 3, json.dumps(m.get("otherData", {}))[:200])
        for fid, proc in procs.items():
            direct = ServeClient(f"tcp:127.0.0.1:{ports[fid]}",
                                 timeout=30, token=TOKEN)
            try:
                direct.shutdown()
            except ServeError:
                pass
            rc = proc.wait(timeout=120)
            ok &= check(f"daemon {fid} exits 0", rc == 0, f"rc={rc}")
        procs.clear()

        # ================================================================
        # Whale scatter/gather (ISSUE 18): a FRESH fleet behind
        # `balance --scatter 2`.
        # ================================================================
        wd_sstd = os.path.join(tmp, "scatter_standalone")
        wd_sfleet = os.path.join(tmp, "scatter_fleet")  # daemons AND the
        # scatter balancers share this cwd: the gather stage resolves the
        # shards' relative output paths against the balancer's own cwd
        # (the documented shared-filesystem assumption)
        jdir2 = os.path.join(tmp, "journals_scatter")
        for d in (wd_sstd, wd_sfleet, jdir2):
            os.makedirs(d)
        fq1 = os.path.join(tmp, "sc_r1.fq.gz")
        fq2 = os.path.join(tmp, "sc_r2.fq.gz")
        p = run(["simulate", "fastq-reads", "-1", fq1, "-2", fq2,
                 "--num-families", "120", "--family-size", "3",
                 "--read-length", "60", "--seed", "23"], cwd=tmp)
        assert p.returncode == 0, p.stderr
        dup = os.path.join(tmp, "sc_duplex.bam")
        p = run(["simulate", "duplex-reads", "-o", dup,
                 "--num-molecules", "180", "--reads-per-strand", "3",
                 "--read-length", "80", "--seed", "11"], cwd=tmp)
        assert p.returncode == 0, p.stderr
        # the kill/perf whale is big on purpose: a shard must run for
        # seconds so the SIGKILL lands mid-shard, and the >=1.6x scaling
        # gate must dwarf the ~1.5s fixed gather+detection overhead
        whale_fams = 30000
        whale_reads = whale_fams * 6
        inp_whale = os.path.join(tmp, "sc_whale.bam")
        p = run(["simulate", "grouped-reads", "-o", inp_whale,
                 "--num-families", str(whale_fams), "--family-size", "6",
                 "--seed", "9"], cwd=tmp, timeout=600)
        assert p.returncode == 0, p.stderr

        sc_jobs = {
            "simplex": ["simplex", "-i", inp, "-o", "out_sc_simplex.bam",
                        "--min-reads", "1"],
            "pipeline": ["pipeline", "-i", fq1, fq2, "-r", "8M+T", "+T",
                         "-o", "out_sc_pipeline.bam",
                         "--filter-min-reads", "1", "--threads", "2",
                         "--sample", "s", "--library", "l"],
            "duplex": ["duplex", "-i", dup, "-o", "out_sc_duplex.bam",
                       "--min-reads", "1"],
        }
        sc_kill = ["simplex", "-i", inp_whale, "-o", "out_sc_kill.bam",
                   "--min-reads", "1"]
        for argv in list(sc_jobs.values()) + [sc_kill]:
            p = run(argv, cwd=wd_sstd, timeout=600)
            assert p.returncode == 0, p.stderr

        # --- scatter fleet up: 2 daemons + `balance --scatter 2` --------
        ports2 = {"c": free_port(), "d": free_port()}
        front2 = free_port()
        mport2 = free_port()

        def start_scatter_daemon(fid):
            argv = [sys.executable, "-m", "fgumi_tpu", "serve",
                    "--tcp", f"127.0.0.1:{ports2[fid]}",
                    "--workers", "1", "--queue-limit", "4",
                    "--journal-dir", jdir2, "--fleet-id", fid,
                    "--lease-scan-period", "0.5",
                    "--compile-cache", cache, "--token-file", tok]
            return subprocess.Popen(argv, cwd=wd_sfleet, env=BASE_ENV,
                                    stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT, text=True)

        def start_scatter_balancer(port, fids, metrics_port=None):
            argv = [sys.executable, "-m", "fgumi_tpu", "balance",
                    "--listen", f"tcp:127.0.0.1:{port}"]
            for fid in fids:
                argv += ["--backend", f"tcp:127.0.0.1:{ports2[fid]}"]
            argv += ["--token-file", tok, "--poll-period", "0.3",
                     "--scatter", "2",
                     "--scatter-wal", os.path.join(tmp, f"sc_{port}.wal")]
            if metrics_port:
                argv += ["--metrics-port", str(metrics_port)]
            return subprocess.Popen(argv, cwd=wd_sfleet, env=BASE_ENV,
                                    stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT, text=True)

        procs["c"] = start_scatter_daemon("c")
        procs["d"] = start_scatter_daemon("d")
        procs["bal_sc"] = start_scatter_balancer(front2, ("c", "d"),
                                                 metrics_port=mport2)
        sclient = ServeClient(f"tcp:127.0.0.1:{front2}", timeout=30,
                              token=TOKEN)
        ping = wait_for_ping(sclient)
        ok &= check("scatter balancer front end answers",
                    ping is not None
                    and ping.get("tool") == "fgumi-tpu-balance", str(ping))
        addr_c = f"tcp:127.0.0.1:{ports2['c']}"
        addr_d = f"tcp:127.0.0.1:{ports2['d']}"
        # both backends must be HEALTHY before any whale goes in: an
        # unknown-depth backend sorts last in routing, so a premature
        # fan-out would stack both shards on the already-polled daemon
        ok &= check("scatter fleet: both backends healthy",
                    wait_backend_state(sclient, addr_c, "closed")
                    and wait_backend_state(sclient, addr_d, "closed"))

        # --- byte-identity: pipeline / simplex / duplex whales ----------
        for name, argv in sc_jobs.items():
            j = sclient.submit(argv, argv0=argv0)
            is_whale = j["id"].startswith("w-")
            rec = sclient.scatter(j["id"]) if is_whale else {}
            nshards = len(rec.get("scatter", {}).get("shards", []))
            j = wait_job_tolerant(sclient, j["id"], timeout=300)
            a = open(os.path.join(wd_sstd, f"out_sc_{name}.bam"),
                     "rb").read()
            bp = os.path.join(wd_sfleet, f"out_sc_{name}.bam")
            b = open(bp, "rb").read() if os.path.exists(bp) else b""
            ok &= check(f"{name} whale scattered 2-way, gathered "
                        "byte-identical to standalone",
                        is_whale and nshards == 2 and j
                        and j.get("state") == "done" and a == b,
                        f"whale={is_whale} shards={nshards} "
                        f"state={j and j.get('state')} "
                        f"{len(a)} vs {len(b)} bytes")
        leftovers = [n for n in os.listdir(wd_sfleet) if ".scatter" in n]
        ok &= check("no shard leftovers after gathers", not leftovers,
                    ",".join(leftovers))

        # --- kill one backend MID-SHARD ---------------------------------
        jk = sclient.submit(sc_kill, argv0=argv0, dedupe="whale-kill")
        ok &= check("kill job accepted as a whale",
                    jk["id"].startswith("w-"), jk["id"])
        victim = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            rec = sclient.scatter(jk["id"]) or {}
            running = [s for s in rec.get("scatter", {}).get("shards", [])
                       if s["state"] == "running" and s["job_id"]]
            if running:
                victim = running[0]["job_id"].split("-j-")[0]
                break
            if rec.get("state") in ("done", "failed", "cancelled"):
                break  # finished before the kill: the scenario is void
            time.sleep(0.1)
        ok &= check("a shard observed running before SIGKILL",
                    victim in ("c", "d"),
                    f"victim={victim} whale={rec.get('state')}")
        if victim in ("c", "d"):
            procs[victim].kill()  # no drain: the shard dies mid-flight
            procs[victim].wait(timeout=30)
        jk_final = wait_job_tolerant(sclient, jk["id"], timeout=300)
        ok &= check("whale completes through the shard-level takeover",
                    jk_final and jk_final.get("state") == "done",
                    str(jk_final and (jk_final.get("error")
                                      or jk_final.get("state"))))
        a = open(os.path.join(wd_sstd, "out_sc_kill.bam"), "rb").read()
        bp = os.path.join(wd_sfleet, "out_sc_kill.bam")
        b = open(bp, "rb").read() if os.path.exists(bp) else b""
        ok &= check("takeover whale output byte-identical to standalone",
                    a == b, f"{len(a)} vs {len(b)} bytes")
        # zero double-execution: the dead daemon's shard finished under
        # its ORIGINAL job id via the journal-lease takeover (attempt
        # stays 0 — the coordinator's requeue grace never expired) and
        # the fleet journals carry exactly one done event per shard
        rec = sclient.scatter(jk["id"]) or {}
        shard_recs = rec.get("scatter", {}).get("shards", [])
        shard_ids = [s["job_id"] for s in shard_recs]
        ok &= check("takeover kept the ORIGINAL shard ids "
                    "(no coordinator requeue)",
                    len(shard_ids) == 2 and all(shard_ids)
                    and all(s["attempt"] == 0 for s in shard_recs),
                    json.dumps(shard_recs))
        events = journal_events(jdir2)
        per_shard = {sid: sum(1 for e in events if e.get("id") == sid
                              and e.get("state") == "done")
                     for sid in shard_ids}
        ok &= check("journal audit: exactly one done event per shard "
                    "(zero double-execution)",
                    bool(per_shard)
                    and all(v == 1 for v in per_shard.values()),
                    json.dumps(per_shard))

        # --- restart the victim, then the scaling gate ------------------
        if victim in ("c", "d"):
            procs[victim] = start_scatter_daemon(victim)
        victim_addr = addr_c if victim == "c" else addr_d
        ok &= check("killed scatter backend re-admitted",
                    wait_backend_state(sclient, victim_addr, "closed",
                                       timeout=90),
                    json.dumps(backend_states(sclient)))
        # warm round: the restarted daemon re-loads the whale shard
        # shapes from the shared compile cache; keep that out of the
        # timed comparison
        jw = sclient.submit(["simplex", "-i", inp_whale, "-o",
                             "out_sc_warm.bam", "--min-reads", "1"],
                            argv0=argv0)
        jw = wait_job_tolerant(sclient, jw["id"], timeout=300)
        ok &= check("warm whale done", jw and jw.get("state") == "done",
                    str(jw and (jw.get("error") or jw.get("state"))))
        t0 = time.monotonic()
        j2 = sclient.submit(["simplex", "-i", inp_whale, "-o",
                             "out_sc_t2.bam", "--min-reads", "1"],
                            argv0=argv0)
        j2 = wait_job_tolerant(sclient, j2["id"], timeout=300)
        t_two = time.monotonic() - t0
        ok &= check("timed 2-backend whale done",
                    j2 and j2.get("state") == "done", f"{t_two:.2f}s")
        # the SAME whale behind a 1-backend scatter balancer: the
        # fairness cap (healthy // whales = 1) strictly serializes the
        # shards, so this measures one backend doing all the work
        front1 = free_port()
        procs["bal_sc1"] = start_scatter_balancer(front1, ("c",))
        sclient1 = ServeClient(f"tcp:127.0.0.1:{front1}", timeout=30,
                               token=TOKEN)
        wait_for_ping(sclient1)
        ok &= check("1-backend scatter balancer up, backend healthy",
                    wait_backend_state(sclient1, addr_c, "closed"))
        t0 = time.monotonic()
        j1 = sclient1.submit(["simplex", "-i", inp_whale, "-o",
                              "out_sc_t1.bam", "--min-reads", "1"],
                             argv0=argv0)
        j1 = wait_job_tolerant(sclient1, j1["id"], timeout=600)
        t_one = time.monotonic() - t0
        ok &= check("timed 1-backend whale done",
                    j1 and j1.get("state") == "done", f"{t_one:.2f}s")
        rps_two = whale_reads / t_two
        rps_one = whale_reads / t_one
        shard_fids = {s["job_id"].split("-j-")[0]
                      for s in (sclient.scatter(j2["id"]) or {})
                      .get("scatter", {}).get("shards", [])
                      if s["job_id"]}
        ok &= check("timed whale spread one shard to EACH backend",
                    shard_fids == {"c", "d"}, str(sorted(shard_fids)))
        cores = len(os.sched_getaffinity(0))
        scaling = (f"{rps_two:,.0f} vs {rps_one:,.0f} reads/s "
                   f"({t_one:.2f}s / {t_two:.2f}s = "
                   f"{t_one / t_two:.2f}x, {cores} core(s))")
        if cores >= 3:
            ok &= check("2-backend fleet beats 1 backend by >=1.6x "
                        "aggregate reads/s on the scatter workload",
                        rps_two >= 1.6 * rps_one, scaling)
        else:
            # the >=1.6x gate needs parallel hardware: pinned to fewer
            # than 3 cores (2 daemons + balancer) the shard processes
            # timeshare ONE cpu and wall-clock cannot improve. Loud
            # skip, never a silent pass — the spread check above still
            # proves both backends did the work, and the bound below
            # that timesharing overhead stays small
            print(f"SKIP  2-backend >=1.6x scaling gate: only {cores} "
                  f"CPU core(s) visible, shards timeshare one core  "
                  f"({scaling})")
            ok &= check("scatter overhead bounded on a timesharing "
                        "host", t_two <= 1.5 * t_one + 1.0, scaling)

        # --- scatter observability --------------------------------------
        snap = sclient.stats()
        sc = snap.get("scatter") or {}
        ok &= check("balancer stats v3 carries the scatter section",
                    snap.get("schema_version") == 3
                    and sc.get("enabled") is True and sc.get("shards") == 2
                    and sc.get("whales", {}).get("done", 0) >= 5,
                    json.dumps({k: sc.get(k) for k in
                                ("enabled", "shards", "whales")}))
        metrics_body = urllib.request.urlopen(
            f"http://127.0.0.1:{mport2}/metrics", timeout=10
        ).read().decode()
        ok &= check("/metrics exports the fleet.scatter.* gauges",
                    "fgumi_tpu_fleet_scatter_enabled 1" in metrics_body
                    and "fgumi_tpu_fleet_scatter_shards_per_whale 2"
                    in metrics_body
                    and 'fgumi_tpu_fleet_scatter_whales_state'
                        '{state="done"}' in metrics_body,
                    "\n".join(ln for ln in metrics_body.splitlines()
                              if "scatter" in ln)[:300])

        # --- scatter fleet clean shutdown -------------------------------
        sclient1.shutdown()
        rc = procs.pop("bal_sc1").wait(timeout=60)
        ok &= check("1-backend scatter balancer exits 0", rc == 0,
                    f"rc={rc}")
        sclient.shutdown()
        rc = procs.pop("bal_sc").wait(timeout=60)
        ok &= check("scatter balancer exits 0 on shutdown", rc == 0,
                    f"rc={rc}")
        for fid in ("c", "d"):
            direct = ServeClient(f"tcp:127.0.0.1:{ports2[fid]}",
                                 timeout=30, token=TOKEN)
            try:
                direct.shutdown()
            except ServeError:
                pass
            rc = procs[fid].wait(timeout=120)
            ok &= check(f"daemon {fid} exits 0", rc == 0, f"rc={rc}")
        procs.clear()
    finally:
        for proc in list(procs.values()) + ([balancer] if balancer
                                            else []):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
        if opts.keep:
            print("scratch kept at", tmp)
        else:
            shutil.rmtree(tmp, ignore_errors=True)
    print("fleet smoke:", "OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
