"""Tunnel probe round 2: fetch bandwidth of DEVICE-COMPUTED arrays (a fetch
of a device_put array is served from a host-side cache and reads as
infinite), duplex overlap, and dispatch pipelining with compute-only args.
"""

import json
import threading
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    out = {}
    dev = jax.devices()[0]
    out["device"] = str(dev)
    MB = 1 << 20

    @jax.jit
    def make(x):
        # produce a 16MB uint8 array on device from a tiny seed
        return (jnp.zeros((16 * MB,), dtype=jnp.uint8) + x).astype(jnp.uint8)

    y = make(np.uint8(3))
    y.block_until_ready()
    # --- fetch bandwidth of a computed array ---
    for _ in range(2):
        t0 = time.monotonic()
        h = np.asarray(jax.device_get(y))
        fe_s = time.monotonic() - t0
        y = make(np.uint8(5))  # new computed array each time (defeat caches)
        y.block_until_ready()
    out["fetch_16mb_s"] = round(fe_s, 3)
    out["fetch_mb_per_s"] = round(16 / fe_s, 1)
    assert h[0] in (3, 5)

    # --- duplex: upload 16MB while fetching a computed 16MB ---
    up8 = np.random.randint(0, 250, size=(16 * MB,), dtype=np.uint8)
    res = {}

    def up_thread():
        t0 = time.monotonic()
        dd = jax.device_put(up8)
        dd.block_until_ready()
        res["up"] = time.monotonic() - t0

    def down_thread():
        t0 = time.monotonic()
        np.asarray(jax.device_get(y))
        res["down"] = time.monotonic() - t0

    # solo timings first
    t0 = time.monotonic()
    dd = jax.device_put(up8)
    dd.block_until_ready()
    up_solo = time.monotonic() - t0
    out["upload_16mb_s"] = round(up_solo, 3)
    y = make(np.uint8(7))
    y.block_until_ready()
    t0 = time.monotonic()
    ts = [threading.Thread(target=up_thread), threading.Thread(target=down_thread)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    both = time.monotonic() - t0
    out["duplex_both_s"] = round(both, 3)
    out["duplex_up_s"] = round(res["up"], 3)
    out["duplex_down_s"] = round(res["down"], 3)
    out["duplex_vs_serial"] = round(both / (up_solo + fe_s), 2)

    # --- dispatch chain: does fetch of result N overlap upload of args N+1
    # when issued from different threads? Simulates the pipeline shape:
    # process thread dispatches (upload), resolve thread fetches.
    @jax.jit
    def kernelish(x):
        # touch the whole array, return same-size result (uint8 in/out)
        return x + jnp.uint8(1)

    a = np.random.randint(0, 200, size=(16 * MB,), dtype=np.uint8)
    r = kernelish(a)
    r.block_until_ready()

    # serial: dispatch+fetch x3
    t0 = time.monotonic()
    for i in range(3):
        rr = kernelish(a + np.uint8(i))
        np.asarray(jax.device_get(rr))
    serial3 = time.monotonic() - t0
    out["serial_3x_dispatch_fetch_s"] = round(serial3, 3)

    # pipelined: dispatcher thread issues 3 dispatches ahead; fetcher drains
    q = []
    lock = threading.Lock()
    done = threading.Event()

    def dispatcher():
        for i in range(3):
            rr = kernelish(a + np.uint8(i + 7))
            with lock:
                q.append(rr)
        done.set()

    fetched = []

    def fetcher():
        got = 0
        while got < 3:
            with lock:
                rr = q.pop(0) if q else None
            if rr is None:
                time.sleep(0.001)
                continue
            fetched.append(np.asarray(jax.device_get(rr)))
            got += 1

    t0 = time.monotonic()
    ts = [threading.Thread(target=dispatcher), threading.Thread(target=fetcher)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    pipe3 = time.monotonic() - t0
    out["pipelined_3x_dispatch_fetch_s"] = round(pipe3, 3)
    out["pipeline_speedup"] = round(serial3 / pipe3, 2)

    print(json.dumps(out))


if __name__ == "__main__":
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import devprobe

    devprobe.locked_main(main)  # the chip is single-tenant: hold the flock
