#!/usr/bin/env python3
"""Fused-chain smoke: gate the in-memory FastqToConsensus handoff.

Checks (exit 0 when every scenario holds, one PASS/FAIL line each):

1. **Byte parity**: the fused ``pipeline`` run is byte-identical to the
   staged (``--no-fuse``) run — both executed in ONE python process so the
   @PG CL provenance lines agree, exactly like the serve daemon's parity
   contract. Also at ``--threads 2``.
2. **No intermediate BAMs**: a filesystem watcher polls the work tree for
   the whole fused run; the only BAM that may ever exist is the final
   output (the staged run, by contrast, must be seen writing
   intermediates — proving the watcher actually watches).
3. **Run report**: the fused run's report carries ``pipeline.chain.*``
   channel metrics, per-stage ``wall_s`` entries, and a smaller
   ``io.bytes_written`` than the staged run (the four intermediate
   encode/decode passes are gone).
4. **Chaos**: an armed ``chain.handoff`` raise exits 3, commits no final
   output, and leaves no temp files behind.

Sibling of tools/telemetry_smoke.py / serve_smoke.py / chaos_smoke.py /
perf_smoke.py in the verify flow (.claude/skills/verify).

Usage:  python tools/chain_smoke.py [--keep]
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASE_ENV = {
    **os.environ,
    "PYTHONPATH": REPO,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "",
    "PALLAS_AXON_POOL_IPS": "",
}


def check(name, ok, detail=""):
    print(f"{'PASS' if ok else 'FAIL'}  {name}" + (f"  ({detail})"
                                                   if detail else ""))
    return ok


# Runs fused + staged in one interpreter (identical sys.argv -> identical
# @PG CL lines) while a watcher thread records every *.bam path that ever
# appears under the work dir.
_PARITY = r"""
import glob, json, os, sys, threading, time
sys.path.insert(0, %(repo)r)
from fgumi_tpu.cli import main as cli_main

work = %(work)r
os.chdir(work)

seen = set()
stop = threading.Event()
def watch():
    # the staged driver may put its temp dir on tmpfs (/dev/shm) instead of
    # next to the output; watch both, so "no intermediate BAMs" means
    # nowhere, not just not-here
    pats = [os.path.join(work, "**", "*.bam"),
            "/dev/shm/fgumi_pipeline_*/*.bam"]
    while not stop.is_set():
        for pat in pats:
            for p in glob.glob(pat, recursive=True):
                if p.startswith(work):
                    seen.add(os.path.relpath(p, work))
                else:
                    seen.add(os.path.basename(p))
        time.sleep(0.005)

def run(argv):
    return cli_main(argv)

base = ["pipeline", "-i", "r1.fq.gz", "r2.fq.gz", "-r", "8M+T", "+T",
        "--sample", "s", "--library", "l", "--filter-min-reads", "2"]

t = threading.Thread(target=watch, daemon=True)
t.start()
rc_f = run(["--run-report", "fused.json"] + base + ["-o", "fused.bam"])
stop.set(); t.join()
fused_seen = sorted(seen)

rc_t2 = run(base + ["-o", "fused_t2.bam", "--threads", "2"])

seen.clear(); stop.clear()
t = threading.Thread(target=watch, daemon=True)
t.start()
rc_s = run(["--run-report", "staged.json"] + base
           + ["-o", "staged.bam", "--no-fuse"])
stop.set(); t.join()
staged_seen = sorted(p for p in seen if p not in
                     ("fused.bam", "fused_t2.bam", "staged.bam"))

out = {
    "rc_fused": rc_f, "rc_threads2": rc_t2, "rc_staged": rc_s,
    "fused_seen": fused_seen, "staged_seen": staged_seen,
    "fused_eq_staged": open("fused.bam", "rb").read()
                       == open("staged.bam", "rb").read(),
    "t2_eq_staged": open("fused_t2.bam", "rb").read()
                    == open("staged.bam", "rb").read(),
}
print("RESULT " + json.dumps(out))
"""

_CHAOS = r"""
import glob, json, os, sys
sys.path.insert(0, %(repo)r)
os.environ["FGUMI_TPU_FAULT"] = "chain.handoff:raise:1.0:1"
from fgumi_tpu.cli import main as cli_main

work = %(work)r
os.chdir(work)
rc = cli_main(["pipeline", "-i", "r1.fq.gz", "r2.fq.gz", "-r", "8M+T",
               "+T", "--sample", "s", "--library", "l",
               "--filter-min-reads", "2", "-o", "chaos.bam"])
left = sorted(os.path.basename(p) for p in
              glob.glob(os.path.join(work, "*"))
              if os.path.basename(p) not in
              ("r1.fq.gz", "r2.fq.gz", "truth.tsv", "fused.bam",
               "fused_t2.bam", "staged.bam", "fused.json", "staged.json"))
print("RESULT " + json.dumps({
    "rc": rc, "output_exists": os.path.exists("chaos.bam"),
    "leftovers": left}))
"""


def run_py(script, timeout=600):
    p = subprocess.run([sys.executable, "-c", script], cwd=REPO,
                       env=BASE_ENV, capture_output=True, text=True,
                       timeout=timeout)
    result = None
    for line in p.stdout.splitlines():
        if line.startswith("RESULT "):
            result = json.loads(line[len("RESULT "):])
    return p, result


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--keep", action="store_true",
                    help="keep the work dir for inspection")
    opts = ap.parse_args()

    from fgumi_tpu.native import batch as nb

    if not nb.available():
        print("SKIP  chain smoke: native batch engine unavailable "
              "(the fused path is gated on it)")
        return 0

    work = tempfile.mkdtemp(prefix="fgumi_chain_smoke_")
    ok = True
    try:
        sim = subprocess.run(
            [sys.executable, "-m", "fgumi_tpu", "simulate", "fastq-reads",
             "-1", "r1.fq.gz", "-2", "r2.fq.gz", "--truth", "truth.tsv",
             "--num-families", "120", "--family-size", "4",
             "--read-length", "80", "--error-rate", "0.005",
             "--seed", "31"],
            cwd=work, env=BASE_ENV, capture_output=True, text=True,
            timeout=300)
        if sim.returncode != 0:
            print(sim.stderr)
            return 1

        p, res = run_py(_PARITY % {"repo": REPO, "work": work})
        if not check("parity run completed", res is not None
                     and res["rc_fused"] == res["rc_staged"]
                     == res["rc_threads2"] == 0,
                     (p.stderr or "")[-300:] if res is None else ""):
            return 1
        ok &= check("fused output byte-identical to staged",
                    res["fused_eq_staged"])
        ok &= check("fused --threads 2 byte-identical to staged",
                    res["t2_eq_staged"])
        ok &= check("fused run created no intermediate BAMs",
                    set(res["fused_seen"]) <= {"fused.bam"},
                    f"saw {res['fused_seen']}")
        ok &= check("watcher sanity: staged run's intermediates were seen",
                    len(res["staged_seen"]) >= 1,
                    f"saw {res['staged_seen']}")

        rep_f = json.load(open(os.path.join(work, "fused.json")))
        rep_s = json.load(open(os.path.join(work, "staged.json")))
        m = rep_f["metrics"]
        chain_keys = [k for k in m if k.startswith("pipeline.chain.")]
        ok &= check("report carries pipeline.chain.* metrics",
                    m.get("pipeline.chain.fused") == 1
                    and any(k.endswith(".batches") for k in chain_keys),
                    f"{len(chain_keys)} keys")
        stages = rep_f.get("stages", {})
        ok &= check("report folds per-stage wall times",
                    all("wall_s" in stages.get(s, {}) for s in
                        ("extract", "sort", "group", "simplex", "filter")))
        wf = m.get("io.bytes_written", 0)
        ws = rep_s["metrics"].get("io.bytes_written", 1 << 60)
        ok &= check("io.bytes_written drops without intermediates",
                    0 < wf < ws, f"fused {wf} vs staged {ws}")

        p, res = run_py(_CHAOS % {"repo": REPO, "work": work})
        if not check("chaos run completed", res is not None,
                     (p.stderr or "")[-300:] if res is None else ""):
            return 1
        ok &= check("chain.handoff fault exits 3", res["rc"] == 3)
        ok &= check("chaos run committed no output and left no temps",
                    not res["output_exists"] and res["leftovers"] == [],
                    f"leftovers {res['leftovers']}")
    finally:
        if opts.keep:
            print(f"work dir kept: {work}")
        else:
            shutil.rmtree(work, ignore_errors=True)
    print("chain smoke:", "OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
