"""Tunnel probe 3: is device_put async? Does a put-based dispatch chain
(put args -> jit on device-resident args -> fetch in another thread)
actually overlap transfers? This is the exact shape the round-5 hybrid
feeder uses.
"""

import json
import threading
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    out = {}
    MB = 1 << 20
    a = np.random.randint(0, 200, size=(16 * MB,), dtype=np.uint8)

    # --- device_put blocking profile ---
    t0 = time.monotonic()
    d = jax.device_put(a)
    enq = time.monotonic() - t0
    d.block_until_ready()
    tot = time.monotonic() - t0
    out["put_enqueue_s"] = round(enq, 3)
    out["put_complete_s"] = round(tot, 3)

    @jax.jit
    def kernelish(x):
        return x + jnp.uint8(1)

    r = kernelish(d)
    r.block_until_ready()

    # --- jit on device-resident args: dispatch blocking profile ---
    t0 = time.monotonic()
    r = kernelish(d)
    disp = time.monotonic() - t0
    r.block_until_ready()
    out["jit_devargs_dispatch_s"] = round(disp, 4)

    # --- serial baseline: put+jit+fetch x3, fully blocking each step ---
    datas = [np.random.randint(0, 200, size=(16 * MB,), dtype=np.uint8)
             for _ in range(6)]
    t0 = time.monotonic()
    for i in range(3):
        dd = jax.device_put(datas[i])
        rr = kernelish(dd)
        np.asarray(jax.device_get(rr))
    serial3 = time.monotonic() - t0
    out["serial3_s"] = round(serial3, 3)

    # --- pipelined: feeder thread puts+dispatches (never blocks on result),
    # fetcher thread drains results ---
    q = []
    lock = threading.Lock()

    def feeder():
        for i in range(3):
            dd = jax.device_put(datas[3 + i])
            rr = kernelish(dd)
            with lock:
                q.append(rr)

    def fetcher():
        got = 0
        while got < 3:
            with lock:
                rr = q.pop(0) if q else None
            if rr is None:
                time.sleep(0.002)
                continue
            np.asarray(jax.device_get(rr))
            got += 1

    t0 = time.monotonic()
    ts = [threading.Thread(target=feeder), threading.Thread(target=fetcher)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    pipe3 = time.monotonic() - t0
    out["pipelined3_s"] = round(pipe3, 3)
    out["speedup"] = round(serial3 / pipe3, 2)

    print(json.dumps(out))


if __name__ == "__main__":
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import devprobe

    devprobe.locked_main(main)  # the chip is single-tenant: hold the flock
