#!/usr/bin/env python3
"""Chaos smoke: run one command under each injected fault and check the
exit-code contract, SIGKILL one of two fleet daemons mid-job and check
the balancer-eject + journal-lease-takeover contract (byte-identical
completion, zero double-execution), then SIGKILL a run mid-write and
check crash-safe commit (no partial file under the final output name).

Usage:  python tools/chaos_smoke.py [--keep]

Exit 0 when every scenario holds; prints a one-line PASS/FAIL per
scenario. Used as the fast out-of-pytest resilience gate (ROADMAP: chaos
tooling satellite); the equivalent in-pytest coverage lives in
tests/test_faults.py / tests/test_atomic_output.py.
"""

import argparse
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BASE_ENV = {
    **os.environ,
    "PYTHONPATH": REPO,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "",
    "PALLAS_AXON_POOL_IPS": "",
}


def run(args, env=None, timeout=300, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "fgumi_tpu", *args], cwd=cwd,
        env={**BASE_ENV, **(env or {})}, capture_output=True, text=True,
        timeout=timeout)


def check(name, ok, detail=""):
    print(f"{'PASS' if ok else 'FAIL'}  {name}" + (f"  ({detail})"
                                                   if detail else ""))
    return ok


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch directory")
    opts = ap.parse_args()
    tmp = tempfile.mkdtemp(prefix="fgumi_chaos_")
    ok = True
    try:
        sim = os.path.join(tmp, "sim.bam")
        p = run(["simulate", "grouped-reads", "-o", sim,
                 "--num-families", "25", "--family-size", "4",
                 "--seed", "11"])
        assert p.returncode == 0, p.stderr

        # clean reference run (device path). Each parity run uses its own
        # cwd with a RELATIVE -o so argv — and hence the @PG CL header
        # line — is byte-identical across runs.
        clean_dir = os.path.join(tmp, "clean")
        os.mkdir(clean_dir)
        p = run(["simplex", "-i", sim, "-o", "out.bam", "--min-reads", "1"],
                env={"FGUMI_TPU_HOST_ENGINE": "0"}, cwd=clean_dir)
        assert p.returncode == 0, p.stderr
        clean = open(os.path.join(clean_dir, "out.bam"), "rb").read()

        # 1) host-side faults: clean nonzero exit, no partial final file
        for point in ("reader.decompress", "writer.compress",
                      "native.batch", "pipeline.process"):
            d = os.path.join(tmp, point.replace(".", "_"))
            os.mkdir(d)
            out = os.path.join(d, "out.bam")
            extra = (["--threads", "4"] if point == "pipeline.process"
                     else [])
            p = run(["simplex", "-i", sim, "-o", out, "--min-reads", "1",
                     *extra],
                    env={"FGUMI_TPU_FAULT": f"{point}:raise:1.0:1"})
            failed_clean = p.returncode != 0 and not os.path.exists(out) \
                and "Traceback" not in p.stderr
            completed = p.returncode == 0 and os.path.exists(out)
            ok &= check(f"{point}:raise -> clean error or completion",
                        failed_clean or completed,
                        f"rc={p.returncode}")

        # 2) device retry: two injected failures absorbed, byte-identical
        for spec, name in (
                ("device.dispatch:raise:1.0:2", "retry"),
                ("device.dispatch:raise:1.0", "host-fallback"),
                ("device.dispatch:oom:1.0:1", "oom-split")):
            d = os.path.join(tmp, name)
            os.mkdir(d)
            env = {"FGUMI_TPU_HOST_ENGINE": "0", "FGUMI_TPU_FAULT": spec,
                   "FGUMI_TPU_DEVICE_BACKOFF_S": "0.01"}
            if name == "oom-split":
                env["FGUMI_TPU_HYBRID"] = "0"
            p = run(["simplex", "-i", sim, "-o", "out.bam",
                     "--min-reads", "1"], env=env, cwd=d)
            got = (open(os.path.join(d, "out.bam"), "rb").read()
                   if p.returncode == 0 else b"")
            if name == "oom-split":
                # the wire path (HYBRID=0) has its own clean reference
                d2 = os.path.join(tmp, "oom_clean")
                os.mkdir(d2)
                p2 = run(["simplex", "-i", sim, "-o", "out.bam",
                          "--min-reads", "1"],
                         env={"FGUMI_TPU_HOST_ENGINE": "0",
                              "FGUMI_TPU_HYBRID": "0"}, cwd=d2)
                ref = open(os.path.join(d2, "out.bam"), "rb").read() \
                    if p2.returncode == 0 else b"?"
            else:
                ref = clean
            ok &= check(f"device.dispatch {name} -> byte-identical",
                        p.returncode == 0 and got == ref,
                        f"rc={p.returncode}")

        # 3) device wedge: a dispatch that never returns is abandoned at
        # its deadline, the batch completes byte-identically on the host
        # engine, the whole run costs seconds (bounded by the deadline,
        # not the hang), and the run report records the breaker opening
        # (ISSUE 7 acceptance)
        # relative --run-report keeps argv — and hence @PG CL provenance —
        # byte-identical between the wedged run and its pure-host twin
        wedge_argv = ["--run-report", "report.json", "simplex", "-i", sim,
                      "-o", "out.bam", "--min-reads", "1"]
        d_host = os.path.join(tmp, "wedge_host_ref")
        os.mkdir(d_host)
        p = run(wedge_argv, env={"FGUMI_TPU_HOST_ENGINE": "1"}, cwd=d_host)
        assert p.returncode == 0, p.stderr
        host_ref = open(os.path.join(d_host, "out.bam"), "rb").read()
        d = os.path.join(tmp, "wedge")
        os.mkdir(d)
        rpt = os.path.join(d, "report.json")
        t0 = time.monotonic()
        p = run(wedge_argv,
                env={"FGUMI_TPU_HOST_ENGINE": "0",
                     "FGUMI_TPU_ROUTE": "device",
                     "FGUMI_TPU_FAULT": "device.wedge:hang:1.0:1",
                     "FGUMI_TPU_FAULT_HANG_S": "30",
                     "FGUMI_TPU_DISPATCH_DEADLINE_S": "2:5"},
                cwd=d)
        wedge_wall = time.monotonic() - t0
        got = (open(os.path.join(d, "out.bam"), "rb").read()
               if p.returncode == 0 else b"")
        ok &= check("device.wedge -> degraded (exit 0), byte-identical "
                    "to the pure host-engine run",
                    p.returncode == 0 and got == host_ref,
                    f"rc={p.returncode}")
        # the wedge cost is the deadline, not the 30 s hang (generous
        # bound: pipeline + interpreter startup ride along)
        ok &= check("wedge cost bounded by the deadline",
                    wedge_wall < 25, f"{wedge_wall:.1f}s")
        try:
            report = __import__("json").load(open(rpt))
            dev = report.get("device", {})
            br = dev.get("breaker", {})
            ok &= check(
                "report records deadline fallback + breaker opening",
                dev.get("deadline_fallbacks", 0) >= 1
                and any(t.get("to") == "open"
                        for t in br.get("transitions", [])),
                f"deadline_fallbacks={dev.get('deadline_fallbacks')} "
                f"breaker={br.get('state')}")
        except (OSError, ValueError) as e:
            ok &= check("report records deadline fallback + breaker "
                        "opening", False, str(e))

        # 3b) silent data corruption (ISSUE 14): corrupt-result at
        # device.fetch with the shadow audit at `all` -> the sentinel
        # detects the divergence within the injected dispatch's own
        # audit, the breaker records an `sdc` trip (quarantine), the run
        # degrades to host and still exits 0 with output byte-identical
        # to the pure-host run (the inline audit repairs the corrupt
        # batch with the oracle tuple it just computed), and the report
        # carries the divergence record + both result digests
        d = os.path.join(tmp, "sdc")
        os.mkdir(d)
        rpt = os.path.join(d, "report.json")
        p = run(wedge_argv,
                env={"FGUMI_TPU_HOST_ENGINE": "0",
                     "FGUMI_TPU_ROUTE": "device",
                     "FGUMI_TPU_AUDIT": "all",
                     "FGUMI_TPU_FLIGHT": d,
                     "FGUMI_TPU_FAULT":
                         "device.fetch:corrupt-result:1.0:1"},
                cwd=d)
        got = (open(os.path.join(d, "out.bam"), "rb").read()
               if p.returncode == 0 else b"")
        ok &= check("corrupt-result + audit=all -> detected, degraded "
                    "(exit 0), byte-identical to the pure host-engine run",
                    p.returncode == 0 and got == host_ref,
                    f"rc={p.returncode}")
        try:
            report = __import__("json").load(open(rpt))
            audit = report.get("audit", {})
            br = report.get("device", {}).get("breaker", {})
            dump_ok = any("sdc" in os.path.basename(f)
                          for f in report.get("flight_dumps", []))
            ok &= check(
                "report records the audit divergence + sdc trip + "
                "flight dump",
                audit.get("divergent", 0) >= 1
                and bool(audit.get("divergence"))
                and br.get("sdc_trips", 0) >= 1
                and any("silent data corruption" in t.get("reason", "")
                        for t in br.get("transitions", []))
                and dump_ok,
                f"divergent={audit.get('divergent')} "
                f"sdc_trips={br.get('sdc_trips')} dump={dump_ok}")
        except (OSError, ValueError) as e:
            ok &= check("report records the audit divergence + sdc trip "
                        "+ flight dump", False, str(e))

        # 3c) the same corruption with the audit OFF documents the
        # undetected baseline: the run exits 0 but silently publishes a
        # corrupt output (differs from the clean run) with zero signal in
        # the report — exactly the gap the sentinel closes
        d = os.path.join(tmp, "sdc_off")
        os.mkdir(d)
        rpt = os.path.join(d, "report.json")
        p = run(wedge_argv,
                env={"FGUMI_TPU_HOST_ENGINE": "0",
                     "FGUMI_TPU_ROUTE": "device",
                     "FGUMI_TPU_AUDIT": "off",
                     "FGUMI_TPU_FAULT":
                         "device.fetch:corrupt-result:1.0:1"},
                cwd=d)
        got = (open(os.path.join(d, "out.bam"), "rb").read()
               if p.returncode == 0 else b"")
        try:
            report = __import__("json").load(open(rpt))
        except (OSError, ValueError):
            report = {}
        ok &= check("corrupt-result + audit=off -> corruption published "
                    "UNDETECTED (exit 0, differing bytes, no audit "
                    "section): the documented baseline",
                    p.returncode == 0 and got != host_ref and len(got) > 0
                    and "audit" not in report,
                    f"rc={p.returncode} bytes={len(got)}")

        # 3e) fused-filter SDC (ISSUE 19): the same corrupt-result fault
        # on the ``--device-filter`` route with the audit at `all` -> the
        # stats-row audit detects the divergence inside the fused
        # dispatch, repairs the batch with the oracle columns (the host
        # filter finishes the stage), the breaker records the sdc trip,
        # and the published output stays byte-identical to the clean
        # fused run
        filt_argv = ["--run-report", "report.json", "simplex", "-i", sim,
                     "-o", "out.bam", "--min-reads", "1",
                     "--device-filter", "--filter-min-reads", "2",
                     "--filter-min-mean-base-quality", "30"]
        d_ref = os.path.join(tmp, "sdc_filter_ref")
        os.mkdir(d_ref)
        p = run(filt_argv, env={"FGUMI_TPU_HOST_ENGINE": "0",
                                "FGUMI_TPU_ROUTE": "device"}, cwd=d_ref)
        assert p.returncode == 0, p.stderr
        filt_ref = open(os.path.join(d_ref, "out.bam"), "rb").read()
        d = os.path.join(tmp, "sdc_filter")
        os.mkdir(d)
        rpt = os.path.join(d, "report.json")
        p = run(filt_argv,
                env={"FGUMI_TPU_HOST_ENGINE": "0",
                     "FGUMI_TPU_ROUTE": "device",
                     "FGUMI_TPU_AUDIT": "all",
                     "FGUMI_TPU_FLIGHT": d,
                     "FGUMI_TPU_FAULT":
                         "device.fetch:corrupt-result:1.0:1"},
                cwd=d)
        got = (open(os.path.join(d, "out.bam"), "rb").read()
               if p.returncode == 0 else b"")
        ok &= check("corrupt-result on --device-filter + audit=all -> "
                    "detected, repaired (exit 0), byte-identical to the "
                    "clean fused run",
                    p.returncode == 0 and got == filt_ref,
                    f"rc={p.returncode}")
        try:
            report = __import__("json").load(open(rpt))
            audit = report.get("audit", {})
            br = report.get("device", {}).get("breaker", {})
            dump_ok = any("sdc" in os.path.basename(f)
                          for f in report.get("flight_dumps", []))
            ok &= check(
                "device-filter report records the audit divergence + "
                "sdc trip + flight dump",
                audit.get("divergent", 0) >= 1
                and br.get("sdc_trips", 0) >= 1
                and dump_ok,
                f"divergent={audit.get('divergent')} "
                f"sdc_trips={br.get('sdc_trips')} dump={dump_ok}")
        except (OSError, ValueError) as e:
            ok &= check("device-filter report records the audit "
                        "divergence + sdc trip + flight dump", False,
                        str(e))

        # 3d) --audit-output: corruption injected below the writer's
        # tally (BGZF layer) is refused before the atomic rename — exit
        # 5, no file published
        d = os.path.join(tmp, "audit_output")
        os.mkdir(d)
        p = run(["--audit-output", "simplex", "-i", sim, "-o", "out.bam",
                 "--min-reads", "1"],
                env={"FGUMI_TPU_FAULT":
                     "writer.compress:corrupt-bytes:1.0:1"}, cwd=d)
        leftovers = os.listdir(d)
        ok &= check("--audit-output refuses a corrupted stream -> exit 5, "
                    "nothing published",
                    p.returncode == 5 and not leftovers
                    and "Traceback" not in p.stderr,
                    f"rc={p.returncode} leftovers={leftovers}")

        # 3e) merged-dispatch fault (ISSUE 15): two concurrent jobs on a
        # coalescing daemon with serve.coalesce:raise armed on EVERY
        # merged launch — each partner degrades to the host engine over
        # its OWN rows, outputs stay byte-identical to the fault-free
        # standalone runs, and the daemon exits 0
        sys.path.insert(0, REPO)
        from fgumi_tpu.serve.client import ServeClient, ServeError

        co_dir = os.path.join(tmp, "coalesce_fault")
        co_std = os.path.join(co_dir, "std")
        co_wd = os.path.join(co_dir, "wd")
        for d in (co_std, co_wd):
            os.makedirs(d)
        co_inp = os.path.join(co_dir, "grouped.bam")
        p = run(["simulate", "grouped-reads", "-o", co_inp,
                 "--num-families", "400", "--family-size", "4",
                 "--seed", "31"])
        assert p.returncode == 0, p.stderr
        co_jobs = [["simplex", "-i", co_inp, "-o", f"out_co{i}.bam",
                    "--min-reads", "1", "--batch-groups", "25"]
                   for i in range(2)]
        for argv in co_jobs:
            p = run(argv, cwd=co_std, env={"FGUMI_TPU_HOST_ENGINE": "0"})
            assert p.returncode == 0, p.stderr
        co_sock = os.path.join(co_dir, "serve.sock")
        co_env = {**BASE_ENV, "FGUMI_TPU_HOST_ENGINE": "0",
                  "FGUMI_TPU_ROUTE": "device",
                  "FGUMI_TPU_COALESCE": "1",
                  "FGUMI_TPU_FAULT": "serve.coalesce:raise:1.0",
                  "FGUMI_TPU_DEVICE_BACKOFF_S": "0.01"}
        dproc = subprocess.Popen(
            [sys.executable, "-m", "fgumi_tpu", "serve", "--socket",
             co_sock, "--workers", "2", "--coalesce-window-ms", "50"],
            cwd=co_wd, env=co_env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        try:
            cclient = ServeClient(co_sock, timeout=30)
            deadline = time.monotonic() + 120
            upc = False
            while time.monotonic() < deadline and not upc:
                try:
                    cclient.ping()
                    upc = True
                except ServeError:
                    time.sleep(0.2)
            assert upc, "coalescing daemon never came up"
            argv0 = os.path.join(REPO, "fgumi_tpu", "__main__.py")
            handles = [cclient.submit(argv, argv0=argv0)
                       for argv in co_jobs]
            states = [cclient.wait(h["id"], timeout=240)["state"]
                      for h in handles]
            ident = True
            for i in range(2):
                ref = open(os.path.join(co_std, f"out_co{i}.bam"),
                           "rb").read()
                got_path = os.path.join(co_wd, f"out_co{i}.bam")
                got = open(got_path, "rb").read() \
                    if os.path.exists(got_path) else b""
                ident &= got == ref
            ok &= check("serve.coalesce:raise -> both jobs done, outputs "
                        "byte-identical to fault-free standalone",
                        states == ["done", "done"] and ident,
                        f"states={states} identical={ident}")
            stats = cclient.request({"v": 1, "op": "stats"}).get(
                "stats", {})
            coal = stats.get("coalesce") or {}
            ok &= check("stats record the merged launches that degraded",
                        coal.get("merged_batches", 0) >= 1
                        and coal.get("partners", 0) >= 2,
                        f"merged={coal.get('merged_batches')} "
                        f"partners={coal.get('partners')}")
            cclient.shutdown()
            rc = dproc.wait(timeout=240)
            ok &= check("coalescing daemon exits 0 under merged-dispatch "
                        "faults", rc == 0, f"rc={rc}")
        finally:
            if dproc.poll() is None:
                dproc.kill()
                dproc.wait(timeout=10)

        # 4) disk full (ISSUE 8): injected ENOSPC mid-spill and mid-merge
        # both honor the resource clean-failure contract — exit 4, no
        # partial output, no stale spill temps, and the run report records
        # the resource event (docs/resilience.md "Resource governance")
        big = os.path.join(tmp, "big.bam")
        p = run(["simulate", "grouped-reads", "-o", big,
                 "--num-families", "120", "--family-size", "4",
                 "--seed", "17"])
        assert p.returncode == 0, p.stderr
        for phase, spec in (
                ("mid-spill", "sort.spill:enospc:1.0:1"),
                ("mid-merge", "writer.compress:enospc:1.0:1")):
            d = os.path.join(tmp, f"enospc_{phase.replace('-', '_')}")
            spill = os.path.join(d, "spill")
            os.makedirs(spill)
            out = os.path.join(d, "out.bam")
            rpt = os.path.join(d, "report.json")
            p = run(["--run-report", rpt, "sort", "-i", big, "-o", out,
                     "--max-records-in-ram", "60", "--tmp-dir", spill],
                    env={"FGUMI_TPU_FAULT": spec})
            leftovers = [n for n in os.listdir(d)
                         if n not in ("report.json", "spill")] \
                + os.listdir(spill)
            ok &= check(f"ENOSPC {phase} -> exit 4, no partial output or "
                        "spill temps",
                        p.returncode == 4 and not leftovers
                        and "Traceback" not in p.stderr,
                        f"rc={p.returncode} leftovers={leftovers}")
            try:
                report = __import__("json").load(open(rpt))
                res = report.get("resource", {})
                ok &= check(f"ENOSPC {phase} -> report records the "
                            "resource event",
                            report.get("exit_status") == 4
                            and any(ev.get("kind") == "enospc"
                                    for ev in res.get("events", [])),
                            f"events={res.get('events')}")
            except (OSError, ValueError) as e:
                ok &= check(f"ENOSPC {phase} -> report records the "
                            "resource event", False, str(e))

        # 5) governed vs ungoverned byte-identity: with the governor
        # rebalancing aggressively (tiny starting channel budgets, fast
        # ticks) the pipeline chain's bytes land identically — budgets
        # change WHEN bytes move, never what is written
        gov_sim = os.path.join(tmp, "gov")
        os.mkdir(gov_sim)
        p = run(["simulate", "fastq-reads", "-1", "r1.fq.gz",
                 "-2", "r2.fq.gz", "--num-families", "60",
                 "--family-size", "3", "--read-length", "60",
                 "--seed", "23"], cwd=gov_sim)
        assert p.returncode == 0, p.stderr
        gov_env = {"FGUMI_TPU_CHAIN_BYTES": str(1 << 20),
                   "FGUMI_TPU_GOVERNOR_PERIOD_S": "0.05"}
        for mode, extra in (("fused", []), ("staged", ["--no-fuse"])):
            outs = {}
            for label, env in (("governed", gov_env),
                               ("ungoverned",
                                {**gov_env, "FGUMI_TPU_GOVERNOR": "0"})):
                d = os.path.join(gov_sim, f"{mode}_{label}")
                os.mkdir(d)
                for f in ("r1.fq.gz", "r2.fq.gz"):
                    os.link(os.path.join(gov_sim, f), os.path.join(d, f))
                p = run(["pipeline", "-i", "r1.fq.gz", "r2.fq.gz",
                         "-r", "8M+T", "+T", "-o", "out.bam",
                         "--filter-min-reads", "1", "--threads", "2",
                         "--sample", "s", "--library", "l", *extra],
                        env=env, cwd=d)
                outs[label] = (open(os.path.join(d, "out.bam"), "rb").read()
                               if p.returncode == 0 else label.encode())
            ok &= check(f"{mode} chain: governed run byte-identical to "
                        "FGUMI_TPU_GOVERNOR=0",
                        outs["governed"] == outs["ungoverned"],
                        f"{len(outs['governed'])} bytes")

        # 6) fleet takeover (ISSUE 12): SIGKILL one of two TCP daemons
        # mid-job; the balancer must eject it, the survivor must claim the
        # dead daemon's journal lease and finish the job byte-identically
        # under its original id, and the journal + dedupe audit must show
        # exactly one execution fleet-wide
        sys.path.insert(0, REPO)
        from fgumi_tpu.serve.client import ServeClient, ServeError

        def _free_port():
            import socket as _socket

            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            return port

        fdir = os.path.join(tmp, "fleet")
        fwd = os.path.join(fdir, "wd")
        fstd = os.path.join(fdir, "std")
        jdir = os.path.join(fdir, "journals")
        for d in (fwd, fstd, jdir):
            os.makedirs(d)
        finp = os.path.join(fdir, "grouped.bam")
        p = run(["simulate", "grouped-reads", "-o", finp,
                 "--num-families", "500", "--family-size", "4",
                 "--seed", "29"])
        assert p.returncode == 0, p.stderr
        fleet_job = ["simplex", "-i", finp, "-o", "out_fleet.bam",
                     "--min-reads", "1"]
        p = run(fleet_job, cwd=fstd, env={"FGUMI_TPU_HOST_ENGINE": "0"})
        assert p.returncode == 0, p.stderr
        ports = {"a": _free_port(), "b": _free_port()}
        front = _free_port()
        fleet_env = {**BASE_ENV, "FGUMI_TPU_HOST_ENGINE": "0"}
        daemons = {}
        bal = None
        try:
            for fid in ("a", "b"):
                daemons[fid] = subprocess.Popen(
                    [sys.executable, "-m", "fgumi_tpu", "serve",
                     "--tcp", f"127.0.0.1:{ports[fid]}", "--workers", "1",
                     "--queue-limit", "0", "--journal-dir", jdir,
                     "--fleet-id", fid, "--lease-scan-period", "0.5"],
                    cwd=fwd, env=fleet_env, stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT, text=True)
            bal = subprocess.Popen(
                [sys.executable, "-m", "fgumi_tpu", "balance",
                 "--listen", f"tcp:127.0.0.1:{front}",
                 "--backend", f"tcp:127.0.0.1:{ports['a']}",
                 "--backend", f"tcp:127.0.0.1:{ports['b']}",
                 "--poll-period", "0.3", "--eject-failures", "2",
                 "--cooldown", "1.0"],
                cwd=fdir, env=fleet_env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            client = ServeClient(f"tcp:127.0.0.1:{front}", timeout=30)
            deadline = time.monotonic() + 120
            up = False
            while time.monotonic() < deadline and not up:
                try:
                    st = client.stats()
                    up = all(b["state"] == "closed"
                             for b in st["backends"])
                except ServeError:
                    time.sleep(0.2)
            ok &= check("fleet: balancer + both backends up", up)
            # argv0 matching the standalone invocation (python -m
            # fgumi_tpu) so @PG CL provenance bytes agree
            argv0 = os.path.join(REPO, "fgumi_tpu", "__main__.py")
            jk = client.submit(fleet_job, dedupe="chaos-fleet",
                               argv0=argv0)
            victim = jk["id"].split("-j-")[0]
            seen_running = False
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                try:
                    state = client.job(jk["id"])["state"]
                    if state == "running":
                        seen_running = True
                        break
                    if state in ("done", "failed", "cancelled"):
                        break  # finished pre-kill: scenario void
                except ServeError:
                    pass
                time.sleep(0.1)
            ok &= check("fleet: job observed running before SIGKILL",
                        seen_running)
            daemons[victim].kill()
            daemons[victim].wait(timeout=30)
            victim_addr = f"tcp:127.0.0.1:{ports[victim]}"
            ejected = False
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and not ejected:
                try:
                    st = client.stats()
                    ejected = any(b["address"] == victim_addr
                                  and b["state"] == "open"
                                  for b in st["backends"])
                except ServeError:
                    pass
                time.sleep(0.2)
            ok &= check("fleet: balancer ejects the SIGKILL'd backend",
                        ejected)
            final = None
            deadline = time.monotonic() + 240
            while time.monotonic() < deadline:
                try:
                    j = client.job(jk["id"])
                    if j["state"] in ("done", "failed", "cancelled"):
                        final = j
                        break
                except ServeError:
                    pass
                time.sleep(0.25)
            ok &= check("fleet: job finishes under its original id via "
                        "lease takeover",
                        final is not None and final["state"] == "done"
                        and final["id"] == jk["id"],
                        str(final and final["state"]))
            ref = open(os.path.join(fstd, "out_fleet.bam"), "rb").read()
            got_path = os.path.join(fwd, "out_fleet.bam")
            got = open(got_path, "rb").read() \
                if os.path.exists(got_path) else b""
            ok &= check("fleet: takeover output byte-identical",
                        ref == got, f"{len(ref)} vs {len(got)} bytes")
            # audit: one done event fleet-wide; dedupe resubmit answers
            # with the finished job instead of running a second copy
            done_events = 0
            for name in os.listdir(jdir):
                if ".journal" not in name:
                    continue
                for line in open(os.path.join(jdir, name)):
                    try:
                        rec = __import__("json").loads(line)
                    except ValueError:
                        continue
                    if rec.get("id") == jk["id"] \
                            and rec.get("state") == "done":
                        done_events += 1
            jk2 = client.submit(fleet_job, dedupe="chaos-fleet",
                                argv0=argv0)
            ok &= check("fleet: no job ran twice (journal + dedupe audit)",
                        done_events == 1 and jk2["id"] == jk["id"]
                        and jk2["state"] == "done",
                        f"done_events={done_events} resubmit={jk2['id']}")
        finally:
            for proc in list(daemons.values()) + ([bal] if bal else []):
                if proc is not None and proc.poll() is None:
                    proc.kill()
                    proc.wait(timeout=10)

        # 7) SIGKILL mid-write: no partial file under the final name
        victim = os.path.join(tmp, "victim.bam")
        code = (
            "import sys, time\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "from fgumi_tpu.io.bam import BamHeader, BamWriter\n"
            "hdr = BamHeader(text='@HD\\tVN:1.6\\n@SQ\\tSN:c\\tLN:9\\n',\n"
            "                ref_names=['c'], ref_lengths=[9])\n"
            f"w = BamWriter({victim!r}, hdr, level=0)\n"
            "print('WRITING', flush=True)\n"
            "while True:\n"
            "    w.write_record_bytes(b'\\x00' * 4096)\n"
            "    w._w.flush(); w._w._f.flush()\n"
            "    time.sleep(0.002)\n")
        child = subprocess.Popen([sys.executable, "-c", code],
                                 stdout=subprocess.PIPE, text=True,
                                 env=BASE_ENV)
        child.stdout.readline()
        time.sleep(0.5)
        child.send_signal(signal.SIGKILL)
        child.wait(timeout=10)
        ok &= check("SIGKILL mid-write -> no partial final file",
                    not os.path.exists(victim))
    finally:
        if opts.keep:
            print("scratch kept at", tmp)
        else:
            shutil.rmtree(tmp, ignore_errors=True)
    print("chaos smoke:", "OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
