#!/usr/bin/env python3
"""Serve smoke: daemon round-trip parity, warm-kernel reuse, capacity
rejection, and SIGTERM drain — the CI gate for the job-service subsystem.

Scenarios (exit 0 when every check holds, one PASS/FAIL line each):

1. Two jobs submitted concurrently to a 2-worker daemon produce outputs
   byte-identical to the same commands run standalone (the daemon resolves
   relative paths against its own working directory, so both runs use the
   same literal argv — provenance lines included — and land in different
   directories).
2. One submission over capacity (workers + queue-limit) is rejected with an
   explicit reason while the admitted jobs complete.
3. Every admitted job leaves a schema-valid per-job run report.
4. Warm-kernel serving: the first device-kernel job reports real XLA
   compilations (``device.backend_compiles``); resubmitting the identical
   command on the warm daemon reports none (and the persistent compile
   cache gained no new entries).
5. SIGTERM drain: a running job finishes and commits its output, new
   submissions are refused, and the daemon exits 0.
6. SIGKILL + journal-driven restart (crash recovery): a daemon with
   --journal is SIGKILL'd mid-job; the restarted daemon replaces the stale
   socket, replays the journal, requeues the job under its ORIGINAL id,
   and the output is byte-identical to the standalone run; an idempotent
   resubmit with the same dedupe key returns the finished job instead of
   running it twice.
7. Live introspection (ISSUE 9): the ``stats`` protocol op and a
   ``--metrics-port`` Prometheus ``/metrics`` scrape return CONSISTENT
   live snapshots (job counts, histogram counts), the scrape parses as
   text format 0.0.4, ``/healthz`` answers 200 on a healthy daemon, the
   ``fgumi-tpu stats`` CLI verb round-trips the same payload, and job
   outputs stay byte-identical to standalone (checks 1/4 above run on the
   same daemon).

Usage:  python tools/serve_smoke.py [--keep]
"""

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASE_ENV = {
    **os.environ,
    "PYTHONPATH": REPO,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "",
    "PALLAS_AXON_POOL_IPS": "",
    # force the device kernel AND the device route so warm-vs-cold compile
    # evidence exists even on a CPU-only host (the adaptive offload policy
    # would price these tiny jobs host-side and dispatch nothing)
    "FGUMI_TPU_HOST_ENGINE": "0",
    "FGUMI_TPU_ROUTE": "device",
}


def run(args, cwd, env=None, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "fgumi_tpu", *args], cwd=cwd,
        env={**BASE_ENV, **(env or {})}, capture_output=True, text=True,
        timeout=timeout)


def check(name, ok, detail=""):
    print(f"{'PASS' if ok else 'FAIL'}  {name}" + (f"  ({detail})"
                                                   if detail else ""))
    return ok


def wait_for_socket(path, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            return True
        time.sleep(0.1)
    return False


def wait_for_ping(client, timeout=120):
    """Socket-file existence is not enough after a SIGKILL restart (the
    stale file lingers until the new daemon claims it); ping instead."""
    from fgumi_tpu.serve.client import ServeError

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            client.ping()
            return True
        except ServeError:
            time.sleep(0.2)
    return False


def cache_entries(d):
    if not os.path.isdir(d):
        return 0
    return sum(len(files) for _, _, files in os.walk(d))


def free_port():
    import socket as _socket

    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def parse_prometheus(body):
    """Minimal text-format 0.0.4 parser: {series_with_labels: float}.
    Raises ValueError on any malformed sample line or duplicate series
    (a real Prometheus server rejects the whole scrape on duplicates)."""
    out = {}
    for line in body.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        if not name or not name[0].isalpha():
            raise ValueError(f"malformed sample line: {line!r}")
        if name in out:
            raise ValueError(f"duplicate series: {name}")
        out[name] = float(value)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch directory")
    opts = ap.parse_args()
    from fgumi_tpu.observe.report import validate_report
    from fgumi_tpu.serve.client import ServeClient, ServeError

    tmp = tempfile.mkdtemp(prefix="fgumi_serve_")
    ok = True
    daemon = None
    try:
        wd_std = os.path.join(tmp, "standalone")
        wd_srv = os.path.join(tmp, "daemon")
        rpt = os.path.join(tmp, "reports")
        cache = os.path.join(tmp, "xla_cache")
        for d in (wd_std, wd_srv, rpt):
            os.makedirs(d)
        inp = os.path.join(tmp, "grouped.bam")
        p = run(["simulate", "grouped-reads", "-o", inp,
                 "--num-families", "600", "--family-size", "4",
                 "--seed", "7"], cwd=tmp)
        assert p.returncode == 0, p.stderr

        # job argvs use relative outputs: same literal command line in both
        # worlds (provenance bytes included); directories keep them apart
        job1 = ["simplex", "-i", inp, "-o", "out1.bam", "--min-reads", "1"]
        job2 = ["sort", "-i", inp, "-o", "out2.bam",
                "--order", "template-coordinate"]

        # --- standalone references -------------------------------------
        for argv in (job1, job2):
            p = run(argv, cwd=wd_std)
            assert p.returncode == 0, p.stderr

        # --- daemon up --------------------------------------------------
        sock = os.path.join(tmp, "serve.sock")
        metrics_port = free_port()
        daemon = subprocess.Popen(
            [sys.executable, "-m", "fgumi_tpu", "serve", "--socket", sock,
             "--workers", "2", "--queue-limit", "0", "--report-dir", rpt,
             "--compile-cache", cache, "--metrics-port",
             str(metrics_port)],
            cwd=wd_srv, env=BASE_ENV, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        ok &= check("daemon socket appears", wait_for_socket(sock))
        client = ServeClient(sock, timeout=30)

        # argv0 matching the standalone invocations (python -m fgumi_tpu)
        argv0 = os.path.join(REPO, "fgumi_tpu", "__main__.py")

        # --- two concurrent jobs + one rejected over capacity -----------
        j1 = client.submit(job1, argv0=argv0)
        j2 = client.submit(job2, argv0=argv0)
        over_reason = None
        try:
            client.submit(job1, argv0=argv0)
        except ServeError as e:
            over_reason = str(e)
        ok &= check("over-capacity submission rejected with reason",
                    over_reason is not None and "queue full" in over_reason,
                    over_reason or "admitted!")
        j1 = client.wait(j1["id"], timeout=240)
        j2 = client.wait(j2["id"], timeout=240)
        ok &= check("both concurrent jobs done",
                    j1["state"] == "done" and j2["state"] == "done",
                    f"{j1['state']}/{j2['state']} "
                    f"{j1.get('error')}/{j2.get('error')}")

        for name in ("out1.bam", "out2.bam"):
            a = open(os.path.join(wd_std, name), "rb").read()
            b = open(os.path.join(wd_srv, name), "rb").read()
            ok &= check(f"{name} byte-identical to standalone", a == b,
                        f"{len(a)} vs {len(b)} bytes")

        # --- per-job run reports ----------------------------------------
        reports = {}
        for j in (j1, j2):
            try:
                reports[j["id"]] = json.load(open(j["report_path"]))
            except (OSError, ValueError, TypeError):
                reports[j["id"]] = None
            errs = (validate_report(reports[j["id"]])
                    if reports[j["id"]] else ["unreadable"])
            ok &= check(f"job {j['id']} run report schema-valid", not errs,
                        "; ".join(errs[:3]))

        # --- warm-kernel evidence ---------------------------------------
        r1 = reports.get(j1["id"]) or {}
        cold_compiles = r1.get("metrics", {}).get("device.backend_compiles",
                                                  0)
        ok &= check("cold job reports XLA compilations",
                    cold_compiles > 0, f"compiles={cold_compiles}")
        entries_before = cache_entries(cache)
        j3 = client.submit(job1, argv0=argv0)  # identical shapes, warm now
        j3 = client.wait(j3["id"], timeout=240)
        ok &= check("warm resubmission done", j3["state"] == "done",
                    str(j3.get("error")))
        r3 = json.load(open(j3["report_path"]))
        warm_compiles = r3.get("metrics", {}).get("device.backend_compiles",
                                                  0)
        ok &= check("warm job skips recompilation",
                    warm_compiles == 0 and r3.get("device", {})
                    .get("dispatches", 0) > 0,
                    f"compiles={warm_compiles} "
                    f"dispatches={r3.get('device', {}).get('dispatches')}")
        ok &= check("compile cache gained no entries on the warm job",
                    cache_entries(cache) == entries_before,
                    f"{entries_before} -> {cache_entries(cache)}")
        a = open(os.path.join(wd_std, "out1.bam"), "rb").read()
        b = open(os.path.join(wd_srv, "out1.bam"), "rb").read()
        ok &= check("warm rerun output still byte-identical", a == b)

        # --- live introspection: stats op + /metrics + /healthz ---------
        import urllib.request

        stats = client.request({"v": 1, "op": "stats"})
        ok &= check("stats op answers ok", stats.get("ok") is True)
        stats = stats.get("stats", {})
        ok &= check("stats carries scheduler/jobs/latency sections",
                    stats.get("scheduler", {}).get("workers") == 2
                    and "latency" in stats and "jobs" in stats)
        done_jobs = stats.get("jobs", {}).get("done", 0)
        ok &= check("stats counts the finished jobs", done_jobs >= 3,
                    f"done={done_jobs}")
        lat = stats.get("latency", {})
        ok &= check("stats carries serve job latency histograms",
                    lat.get("serve.job.run_s", {}).get("count", 0) >= 3
                    and lat.get("serve.job.queue_wait_s", {})
                    .get("count", 0) >= 3,
                    f"latency keys={sorted(lat)[:8]}")
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{metrics_port}/metrics",
                timeout=10).read().decode()
            series = parse_prometheus(body)
            perr = None
        except (OSError, ValueError) as e:
            body, series, perr = "", {}, str(e)
        ok &= check("/metrics parses as Prometheus text format",
                    perr is None and bool(series),
                    perr or f"{len(series)} series")
        # the scrape and the stats op must agree on live state: job counts
        # and histogram sample counts come from the same snapshot source
        scraped_done = series.get('fgumi_tpu_serve_jobs{state="done"}')
        ok &= check("/metrics agrees with stats (job counts)",
                    scraped_done == stats.get("jobs", {}).get("done"),
                    f"scrape={scraped_done} "
                    f"stats={stats.get('jobs', {}).get('done')}")
        hist_ok = all(
            series.get(f"fgumi_tpu_{name.replace('.', '_')}_count")
            == summ["count"] for name, summ in lat.items())
        ok &= check("/metrics agrees with stats (histogram counts)",
                    bool(lat) and hist_ok)
        try:
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{metrics_port}/healthz", timeout=10)
            hz = json.loads(resp.read().decode())
            hz_status = resp.status
        except OSError as e:
            hz, hz_status = {"error": str(e)}, 0
        ok &= check("/healthz answers 200 ok on a healthy daemon",
                    hz_status == 200 and hz.get("status") == "ok",
                    f"{hz_status} {hz}")
        # the CLI verb round-trips the same payload
        p = run(["stats", "--socket", sock, "--section", "scheduler"],
                cwd=tmp)
        try:
            verb = json.loads(p.stdout)
        except ValueError:
            verb = {}
        ok &= check("fgumi-tpu stats verb round-trips",
                    p.returncode == 0
                    and verb.get("scheduler", {}).get("workers") == 2,
                    p.stdout[:120])

        # --- SIGTERM drain ----------------------------------------------
        j4 = client.submit(job1, argv0=argv0)
        daemon.send_signal(signal.SIGTERM)
        # admission must close; allow for signal-delivery latency (a submit
        # racing the handler may still be admitted — it just runs to
        # completion during the drain, which is the documented contract)
        refused = None
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                client.submit(job2, argv0=argv0)
                time.sleep(0.1)
            except ServeError as e:
                refused = str(e)
                # require the DRAIN refusal (or the daemon already gone):
                # accepting any rejection would let a "queue full" bounce
                # satisfy this check without drain ever engaging
                if "draining" in refused or "cannot reach" in refused:
                    break
        ok &= check("post-SIGTERM submission refused by drain",
                    refused is not None
                    and ("draining" in refused or "cannot reach" in refused),
                    refused or "still admitting")
        daemon_rc = daemon.wait(timeout=240)
        ok &= check("daemon exits 0 after drain", daemon_rc == 0,
                    f"rc={daemon_rc}")
        daemon = None
        j4_report = os.path.join(rpt, f"{j4['id']}.report.json")
        r4 = json.load(open(j4_report))
        ok &= check("in-flight job finished during drain",
                    r4["exit_status"] == 0 and not validate_report(r4))
        ok &= check("drained job committed its output",
                    open(os.path.join(wd_srv, "out1.bam"), "rb").read()
                    == open(os.path.join(wd_std, "out1.bam"), "rb").read())
        ok &= check("socket removed on exit", not os.path.exists(sock))

        # --- SIGKILL + journal-driven restart (crash recovery) ----------
        kill_job = ["simplex", "-i", inp, "-o", "out_kill.bam",
                    "--min-reads", "1"]
        p = run(kill_job, cwd=wd_std)
        assert p.returncode == 0, p.stderr
        wd_kill = os.path.join(tmp, "daemon_kill")
        os.makedirs(wd_kill)
        jr = os.path.join(tmp, "journal.jsonl")
        sock2 = os.path.join(tmp, "serve2.sock")
        serve_argv = [sys.executable, "-m", "fgumi_tpu", "serve",
                      "--socket", sock2, "--workers", "1",
                      "--report-dir", rpt, "--compile-cache", cache,
                      "--journal", jr]
        daemon = subprocess.Popen(serve_argv, cwd=wd_kill, env=BASE_ENV,
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT, text=True)
        client2 = ServeClient(sock2, timeout=30)
        ok &= check("journaled daemon up", wait_for_ping(client2))
        jk = client2.submit(kill_job, argv0=argv0, dedupe="kill-restart")
        # kill mid-job: wait until the journal records it running
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if client2.job(jk["id"])["state"] == "running":
                break
            time.sleep(0.05)
        daemon.kill()  # SIGKILL: no drain, no cleanup, socket left behind
        daemon.wait(timeout=30)
        ok &= check("SIGKILL leaves the stale socket behind",
                    os.path.exists(sock2))
        ok &= check("killed job never published output",
                    not os.path.exists(os.path.join(wd_kill,
                                                    "out_kill.bam")))
        # restart: stale socket replaced, journal replayed, job requeued
        daemon = subprocess.Popen(serve_argv, cwd=wd_kill, env=BASE_ENV,
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT, text=True)
        ok &= check("restarted daemon claims the stale socket",
                    wait_for_ping(client2))
        try:
            jk2 = client2.wait(jk["id"], timeout=240)
        except ServeError as e:
            jk2 = {"state": f"lost ({e})"}
        ok &= check("requeued job finishes under its original id",
                    jk2.get("state") == "done", str(jk2.get("state")))
        a = open(os.path.join(wd_std, "out_kill.bam"), "rb").read()
        b_path = os.path.join(wd_kill, "out_kill.bam")
        b = open(b_path, "rb").read() if os.path.exists(b_path) else b""
        ok &= check("recovered output byte-identical to standalone",
                    a == b, f"{len(a)} vs {len(b)} bytes")
        leftovers = [n for n in os.listdir(wd_kill) if ".tmp." in n]
        ok &= check("no temp leftovers after recovery", not leftovers,
                    ",".join(leftovers))
        # idempotent resubmit: the dedupe key survived the restart
        jk3 = client2.submit(kill_job, argv0=argv0, dedupe="kill-restart")
        ok &= check("dedupe key resolves to the recovered job",
                    jk3["id"] == jk["id"] and jk3["state"] == "done",
                    f"{jk3['id']} ({jk3['state']})")
        client2.shutdown()
        rc2 = daemon.wait(timeout=240)
        ok &= check("journaled daemon exits 0", rc2 == 0, f"rc={rc2}")
        daemon = None

        # --- cross-job dispatch coalescing (ISSUE 15) -------------------
        # 4 concurrent small submit jobs on a 4-worker daemon with the
        # merge window armed: per-job outputs byte-identical to
        # standalone (coalesce off), merged_batches > 0 evidence in the
        # stats op, and aggregate wall reported for the throughput story.
        wd_std_c = os.path.join(tmp, "standalone_coalesce")
        wd_srv_c = os.path.join(tmp, "daemon_coalesce")
        for d in (wd_std_c, wd_srv_c):
            os.makedirs(d)
        co_jobs = [["simplex", "-i", inp, "-o", f"outc{i}.bam",
                    "--min-reads", "1", "--batch-groups", "40"]
                   for i in range(4)]
        t0 = time.monotonic()
        for argv in co_jobs:
            p = run(argv, cwd=wd_std_c, env={"FGUMI_TPU_COALESCE": "0"})
            assert p.returncode == 0, p.stderr
        serial_wall = time.monotonic() - t0
        sock3 = os.path.join(tmp, "serve3.sock")
        daemon = subprocess.Popen(
            [sys.executable, "-m", "fgumi_tpu", "serve", "--socket",
             sock3, "--workers", "4", "--queue-limit", "0",
             "--compile-cache", cache, "--coalesce-window-ms", "50"],
            cwd=wd_srv_c, env={**BASE_ENV, "FGUMI_TPU_COALESCE": "1"},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        client3 = ServeClient(sock3, timeout=30)
        ok &= check("coalescing daemon up", wait_for_ping(client3))
        t0 = time.monotonic()
        handles = [client3.submit(argv, argv0=argv0) for argv in co_jobs]
        done = [client3.wait(h["id"], timeout=240) for h in handles]
        merged_wall = time.monotonic() - t0
        ok &= check("4 concurrent coalesced jobs done",
                    all(j["state"] == "done" for j in done),
                    ",".join(j["state"] for j in done))
        ident = True
        for i in range(4):
            a = open(os.path.join(wd_std_c, f"outc{i}.bam"), "rb").read()
            bp = os.path.join(wd_srv_c, f"outc{i}.bam")
            b = open(bp, "rb").read() if os.path.exists(bp) else b""
            ident &= a == b
        ok &= check("coalesced outputs byte-identical to standalone "
                    "(coalesce off)", ident)
        st = client3.request({"v": 1, "op": "stats"}).get("stats", {})
        coal = st.get("coalesce") or {}
        ok &= check("stats op records merged cross-job batches",
                    coal.get("merged_batches", 0) > 0
                    and coal.get("partners", 0) >= 2,
                    f"merged={coal.get('merged_batches')} "
                    f"partners={coal.get('partners')}")
        # informational (not gated: shared-CI hosts are too noisy for a
        # wall-clock assertion): 4 concurrent merged jobs vs 4 serial
        # standalone runs
        print(f"INFO  coalesce aggregate: 4 jobs {merged_wall:.1f}s "
              f"concurrent+merged vs {serial_wall:.1f}s serial "
              f"standalone ({serial_wall / max(merged_wall, 1e-9):.2f}x)")
        client3.shutdown()
        rc3 = daemon.wait(timeout=240)
        ok &= check("coalescing daemon exits 0", rc3 == 0, f"rc={rc3}")
        daemon = None

        # --- forced host route: identity with the window armed ----------
        # coalescing only engages on device dispatches; a ROUTE=host
        # daemon with the window armed must stay byte-identical too
        wd_std_h = os.path.join(tmp, "standalone_host")
        wd_srv_h = os.path.join(tmp, "daemon_host")
        for d in (wd_std_h, wd_srv_h):
            os.makedirs(d)
        host_env = {"FGUMI_TPU_ROUTE": "host", "FGUMI_TPU_HOST_ENGINE": ""}
        for argv in co_jobs[:2]:
            p = run(argv, cwd=wd_std_h, env=host_env)
            assert p.returncode == 0, p.stderr
        sock4 = os.path.join(tmp, "serve4.sock")
        daemon = subprocess.Popen(
            [sys.executable, "-m", "fgumi_tpu", "serve", "--socket",
             sock4, "--workers", "2", "--queue-limit", "0",
             "--coalesce-window-ms", "50"],
            cwd=wd_srv_h,
            env={**BASE_ENV, **host_env, "FGUMI_TPU_COALESCE": "1"},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        client4 = ServeClient(sock4, timeout=30)
        ok &= check("host-route daemon up", wait_for_ping(client4))
        handles = [client4.submit(argv, argv0=argv0)
                   for argv in co_jobs[:2]]
        done = [client4.wait(h["id"], timeout=240) for h in handles]
        ident = all(
            open(os.path.join(wd_std_h, f"outc{i}.bam"), "rb").read()
            == open(os.path.join(wd_srv_h, f"outc{i}.bam"), "rb").read()
            for i in range(2)
            if os.path.exists(os.path.join(wd_srv_h, f"outc{i}.bam")))
        ok &= check("host-route outputs byte-identical with the window "
                    "armed",
                    all(j["state"] == "done" for j in done) and ident
                    and all(os.path.exists(os.path.join(
                        wd_srv_h, f"outc{i}.bam")) for i in range(2)))
        client4.shutdown()
        rc4 = daemon.wait(timeout=240)
        ok &= check("host-route daemon exits 0", rc4 == 0, f"rc={rc4}")
        daemon = None
    finally:
        if daemon is not None and daemon.poll() is None:
            daemon.kill()
            daemon.wait(timeout=10)
        if opts.keep:
            print("scratch kept at", tmp)
        else:
            shutil.rmtree(tmp, ignore_errors=True)
    print("serve smoke:", "OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
