#!/usr/bin/env python3
"""Mesh smoke: gate the production dp x sp compile path on 8 virtual CPU
devices (fast, runs anywhere — the same virtual-mesh trick as the dryrun).

Checks (exit 0 when every scenario holds, one PASS/FAIL line each):

1. **Three-engine byte-identity**: `simplex`, `duplex`, and `codec` run
   single-device and with ``FGUMI_TPU_MESH=dp4xsp2`` and ``dp8`` — the
   sharded outputs' records are byte-identical to the single-device run
   (headers differ only by the recorded command line). The duplex and
   codec runs also force their device combine stages so the sharded
   resident / elementwise combine kernels are exercised, not just priced.
2. **Mesh observability**: the sharded run's report carries
   ``device.mesh`` = {dp, sp, devices}, the ``device.mesh.*`` gauges, and
   per-dispatch ``shards`` / ``psums`` timeline stamps.
3. **1-device fallback**: ``--mesh off`` (and a 1-device mesh) is the
   bit-for-bit legacy path — same records, and the report carries NO mesh
   section.
4. **Loud misconfiguration**: an oversized ``--mesh`` exits 2 with a
   one-line diagnostic, never a silently smaller mesh.

Sibling of tools/perf_smoke.py / tools/serve_smoke.py in the verify flow
(.claude/skills/verify); docs/multi-chip.md explains the compile path.

Usage:  python tools/mesh_smoke.py [--keep]
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASE_ENV = {
    **os.environ,
    "PYTHONPATH": REPO,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "PALLAS_AXON_POOL_IPS": "",
    "FGUMI_TPU_HOST_ENGINE": "0",
    "FGUMI_TPU_HYBRID": "0",
}


def run_cli(args, env=None, timeout=420):
    return subprocess.run(
        [sys.executable, "-m", "fgumi_tpu", *args], cwd=REPO,
        env={**BASE_ENV, **(env or {})}, capture_output=True, text=True,
        timeout=timeout)


def last_err(p):
    """Last stderr line of a failed subprocess, or a rc note (a SIGKILLed
    child has empty stderr — never IndexError inside a FAIL report)."""
    lines = p.stderr.strip().splitlines()
    return lines[-1] if lines else f"rc={p.returncode}, no stderr"


def check(name, ok, detail=""):
    print(f"{'PASS' if ok else 'FAIL'}  {name}" + (f"  ({detail})"
                                                   if detail else ""))
    return ok


def records(path):
    """All record bytes of a BAM (header excluded — it carries the argv)."""
    from fgumi_tpu.io.bam import BamReader

    with BamReader(path) as r:
        return [rec.data for rec in r]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--keep", action="store_true")
    opts = ap.parse_args()
    tmp = tempfile.mkdtemp(prefix="fgumi_mesh_smoke_")
    ok = True
    try:
        j = lambda *p: os.path.join(tmp, *p)  # noqa: E731

        # inputs for the three engines
        for args in (
            ["simulate", "grouped-reads", "-o", j("sim.bam"),
             "--num-families", "500", "--family-size", "6",
             "--read-length", "80", "--error-rate", "0.02", "--seed", "11"],
            ["simulate", "duplex-reads", "-o", j("dup.bam"),
             "--num-molecules", "180", "--reads-per-strand", "3",
             "--read-length", "80", "--seed", "11"],
            ["simulate", "codec-reads", "-o", j("codec.bam"),
             "--num-molecules", "220", "--pairs-per-molecule", "2",
             "--read-length", "80", "--seed", "11"],
        ):
            p = run_cli(args)
            ok &= check(f"simulate {args[1]}", p.returncode == 0,
                        last_err(p) if p.returncode else "")

        engines = (
            ("simplex", j("sim.bam"), {}),
            ("duplex", j("dup.bam"), {"FGUMI_TPU_DUPLEX_COMBINE": "device"}),
            ("codec", j("codec.bam"), {"FGUMI_TPU_CODEC_COMBINE": "device"}),
        )
        single = {}
        for cmd, inp, env in engines:
            out = j(f"{cmd}_single.bam")
            p = run_cli(["--mesh", "off", cmd, "-i", inp, "-o", out,
                         "--min-reads", "1"], env=env)
            ok &= check(f"{cmd} single-device run", p.returncode == 0,
                        last_err(p) if p.returncode else "")
            if p.returncode == 0:
                single[cmd] = records(out)

        for mesh in ("dp4xsp2", "dp8"):
            for cmd, inp, env in engines:
                if cmd not in single:
                    continue
                out = j(f"{cmd}_{mesh}.bam")
                p = run_cli([cmd, "-i", inp, "-o", out, "--min-reads", "1"],
                            env={**env, "FGUMI_TPU_MESH": mesh})
                good = p.returncode == 0 and records(out) == single[cmd]
                ok &= check(f"{cmd} {mesh} byte-identity", good,
                            "" if good else (last_err(p) if p.returncode
                                             else "records differ"))

        # mesh observability: report section + gauges + timeline stamps
        rep = j("mesh_report.json")
        p = run_cli(["--mesh", "dp4xsp2", "--run-report", rep, "simplex",
                     "-i", j("sim.bam"), "-o", j("obs.bam"),
                     "--min-reads", "1", "--stats"])
        good = p.returncode == 0
        mesh_sec = gauges = stamps = False
        if good:
            r = json.load(open(rep))
            dev = r.get("device", {})
            mesh_sec = dev.get("mesh") == {"dp": 4, "sp": 2, "devices": 8,
                                           "platform": "cpu"}
            m = r.get("metrics", {})
            gauges = (m.get("device.mesh.dp") == 4
                      and m.get("device.mesh.sp") == 2
                      and m.get("device.mesh.devices") == 8)
            routing = dev.get("routing", {})
            stamps = "8" in routing.get("mesh", {})
        ok &= check("report device.mesh section", good and mesh_sec)
        ok &= check("report device.mesh.* gauges", good and gauges)
        ok &= check("report per-mesh routing EWMAs", good and stamps)

        # timeline shard stamps (in-process: the subprocess report has no
        # timeline; assert via a short library run)
        p = subprocess.run(
            [sys.executable, "-c", _TIMELINE_SCRIPT % {"repo": REPO}],
            cwd=REPO, env=BASE_ENV, capture_output=True, text=True,
            timeout=300)
        good = p.returncode == 0 and p.stdout.strip().endswith("OK")
        ok &= check("timeline shards/psums stamps", good,
                    "" if good else last_err(p))

        # 1-device fallback: no mesh section in the report
        rep1 = j("single_report.json")
        p = run_cli(["--mesh", "off", "--run-report", rep1, "simplex",
                     "-i", j("sim.bam"), "-o", j("fb.bam"),
                     "--min-reads", "1"])
        good = p.returncode == 0
        if good:
            r = json.load(open(rep1))
            good = ("mesh" not in r.get("device", {})
                    and "device.mesh.dp" not in r.get("metrics", {})
                    and records(j("fb.bam")) == single.get("simplex"))
        ok &= check("1-device fallback (no mesh section, same bytes)", good)

        # loud misconfiguration
        p = run_cli(["--mesh", "dp64xsp2", "simplex", "-i", j("sim.bam"),
                     "-o", j("bad.bam"), "--min-reads", "1"])
        good = p.returncode == 2 and "needs 128 devices" in p.stderr
        ok &= check("oversized --mesh exits 2 with loud error", good,
                    f"rc={p.returncode}")
        p = run_cli(["--mesh", "banana", "simplex", "-i", j("sim.bam"),
                     "-o", j("bad.bam"), "--min-reads", "1"])
        ok &= check("malformed --mesh rejected at parse",
                    p.returncode == 2, f"rc={p.returncode}")
    finally:
        if opts.keep:
            print(f"kept: {tmp}")
        else:
            shutil.rmtree(tmp, ignore_errors=True)
    print("mesh_smoke:", "OK" if ok else "FAILED")
    return 0 if ok else 1


_TIMELINE_SCRIPT = r"""
import sys
import numpy as np
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
from fgumi_tpu.ops.tables import quality_tables
from fgumi_tpu.ops.kernel import (ConsensusKernel, DEVICE_STATS,
                                  pad_segments_mesh)
from fgumi_tpu.parallel.mesh import resolve_mesh

kernel = ConsensusKernel(quality_tables(45, 40))
kernel.set_force_device()
rng = np.random.default_rng(3)
counts = rng.integers(2, 8, size=64).astype(np.int64)
codes = rng.integers(0, 4, size=(int(counts.sum()), 32)).astype(np.uint8)
quals = rng.integers(10, 40, size=codes.shape).astype(np.uint8)
starts = np.concatenate(([0], np.cumsum(counts)))
mesh = resolve_mesh(jax.devices(), (4, 2))
cg, qg, sg, _st, F_loc, gather = pad_segments_mesh(codes, quals, counts,
                                                   mesh)
t = kernel.device_call_segments_wire(cg, qg, sg, F_loc, len(counts),
                                     full=True, mesh=mesh,
                                     mesh_gather=gather)
kernel.resolve_segments_wire(t, codes, quals, starts)
tl = [e for e in DEVICE_STATS.timeline_snapshot() if "shards" in e]
assert tl, "no mesh timeline entries"
e = tl[0]
assert e["shards"] == 8 and e["psums"] == 2 and e["shard_up_bytes"] > 0, e
print("OK")
"""


if __name__ == "__main__":
    sys.exit(main())
