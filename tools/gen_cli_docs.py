#!/usr/bin/env python3
"""Render docs/cli-reference.md from the live argparse tree.

The reference generates its command docs from the CLI definitions via an
xtask (/root/reference/xtask/, wired into CI so the docs cannot drift); this
is the same discipline for fgumi-tpu: one source of truth (cli.build_parser),
one generated artifact, and tests/test_cli_docs.py asserting the checked-in
file matches a fresh render.

Usage:  python tools/gen_cli_docs.py            # rewrite docs/cli-reference.md
        python tools/gen_cli_docs.py --check    # exit 1 if out of date
"""

import argparse
import io
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "docs", "cli-reference.md")

HEADER = """\
# fgumi-tpu CLI reference

<!-- GENERATED FILE — do not edit. Rebuild with `python tools/gen_cli_docs.py`;
     tests/test_cli_docs.py fails when this file drifts from the CLI. -->
"""


def _actions_table(parser):
    """One markdown table of a parser's visible optional arguments."""
    rows = []
    for a in parser._actions:
        if a.help == argparse.SUPPRESS:
            continue
        if isinstance(a, (argparse._SubParsersAction, argparse._HelpAction)):
            continue
        if a.option_strings:
            name = ", ".join(f"`{o}`" for o in a.option_strings)
        else:
            name = f"`{a.dest}`"
        meta = ""
        if a.choices is not None:
            meta = "{" + ", ".join(str(c) for c in a.choices) + "}"
        elif a.nargs not in (0, None) or (a.option_strings
                                          and a.const is None
                                          and not isinstance(
                                              a, argparse._StoreTrueAction)):
            meta = (a.metavar or a.dest or "").upper() if not isinstance(
                a, (argparse._StoreTrueAction,
                    argparse._StoreFalseAction)) else ""
        default = ""
        # identity checks: `0 in (..., False)` is True, which would hide
        # legitimate numeric-zero defaults (--threads 0, --qual-slope 0.0)
        if (a.default is not None and a.default is not argparse.SUPPRESS
                and a.default is not False and a.option_strings):
            default = f"`{a.default}`"
        req = "yes" if getattr(a, "required", False) else ""
        help_text = (a.help or "").replace("|", "\\|").replace("\n", " ")
        rows.append((name, meta, req, default, help_text))
    if not rows:
        return ""
    buf = io.StringIO()
    buf.write("| option | value | required | default | description |\n")
    buf.write("|---|---|---|---|---|\n")
    for name, meta, req, default, help_text in rows:
        buf.write(f"| {name} | {meta} | {req} | {default} | {help_text} |\n")
    return buf.getvalue()


def _walk(parser, title, depth, buf):
    buf.write(f"\n{'#' * depth} {title}\n\n")
    if parser.description:
        buf.write(parser.description.strip() + "\n\n")
    buf.write(f"```\n{parser.format_usage().strip()}\n```\n\n")
    table = _actions_table(parser)
    if table:
        buf.write(table)
    for a in parser._actions:
        if isinstance(a, argparse._SubParsersAction):
            for name, sub in a.choices.items():
                _walk(sub, f"{title} {name}", min(depth + 1, 5), buf)


def render() -> str:
    from fgumi_tpu.cli import build_parser

    # argparse wraps usage lines to the terminal width; pin it so the
    # generated file (and the drift test) are environment-independent
    prev = os.environ.get("COLUMNS")
    os.environ["COLUMNS"] = "100"
    try:
        parser = build_parser()
        return _render_with(parser)
    finally:
        if prev is None:
            os.environ.pop("COLUMNS", None)
        else:
            os.environ["COLUMNS"] = prev


def _render_with(parser) -> str:
    buf = io.StringIO()
    buf.write(HEADER)
    buf.write("\nGenerated from `fgumi_tpu.cli.build_parser()`. "
              "Every tool below is also documented by `fgumi-tpu <tool> -h`.\n")
    sub = next(a for a in parser._actions
               if isinstance(a, argparse._SubParsersAction))
    # one-line summaries live in add_parser(help=...), not .description
    helps = {ca.dest: (ca.help or "") for ca in sub._choices_actions}
    glob = _actions_table(parser)
    if glob:
        buf.write("\n## Global options\n\n"
                  "Given before the tool name (`fgumi-tpu --trace t.json "
                  "dedup ...`); every tool inherits them.\n\n")
        buf.write(glob)
    buf.write("\n## Tools\n\n")
    for name, p in sub.choices.items():
        desc = (helps.get(name) or (p.description or "")).strip()
        desc = desc.split("\n")[0]
        buf.write(f"- [`{name}`](#fgumi-tpu-{name}) — {desc}\n")
    for name, p in sub.choices.items():
        _walk(p, f"fgumi-tpu {name}", 2, buf)
    return buf.getvalue()


def main():
    check = "--check" in sys.argv[1:]
    text = render()
    if check:
        on_disk = open(OUT).read() if os.path.exists(OUT) else ""
        if on_disk != text:
            print(f"{OUT} is out of date; run python tools/gen_cli_docs.py",
                  file=sys.stderr)
            return 1
        print("cli-reference.md up to date")
        return 0
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        f.write(text)
    print(f"wrote {OUT} ({len(text)} bytes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
