#!/usr/bin/env python3
"""Telemetry smoke: run small commands with --trace + --run-report and gate
the artifacts.

Checks (exit 0 when every scenario holds, one PASS/FAIL line each):

1. ``dedup --threads 4`` emits a well-formed Chrome trace-event JSON with
   complete events from >= 3 distinct threads (reader / processor / writer
   at minimum) including pipeline-stage spans, and a schema-valid run
   report whose stage timings and record counts are non-zero — and whose
   ``latency`` section (schema v2) carries ordered histogram summaries
   for the BGZF hot path.
2. ``simplex`` with the device kernel forced (FGUMI_TPU_HOST_ENGINE=0)
   additionally records device-dispatch/fetch spans, non-zero DeviceStats,
   and per-dispatch latency histograms in the report.
3. With both flags off, no trace/report/flight artifacts appear.
4. Chaos wedge: an injected ``device.wedge`` hang under a tight dispatch
   deadline exits 0 (host-engine degradation), and leaves a schema-valid
   flight-recorder black box naming the wedged dispatch, with the dump
   path carried in the run report's ``flight_dumps``.

The in-pytest equivalents live in tests/test_observe.py and
tests/test_run_report.py; this is the fast out-of-pytest gate, a sibling
of tools/chaos_smoke.py.

Usage:  python tools/telemetry_smoke.py [--keep]
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASE_ENV = {
    **os.environ,
    "PYTHONPATH": REPO,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "",
    "PALLAS_AXON_POOL_IPS": "",
}


def run(args, env=None, timeout=300, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "fgumi_tpu", *args], cwd=cwd,
        env={**BASE_ENV, **(env or {})}, capture_output=True, text=True,
        timeout=timeout)


def check(name, ok, detail=""):
    print(f"{'PASS' if ok else 'FAIL'}  {name}" + (f"  ({detail})"
                                                   if detail else ""))
    return ok


def load_trace(path):
    """Parse a trace file; returns (span_events, tid_count, names) or None."""
    try:
        obj = json.load(open(path))
    except (OSError, ValueError):
        return None
    evs = obj.get("traceEvents")
    if not isinstance(evs, list):
        return None
    for ev in evs:
        if not {"name", "ph", "pid", "tid"} <= set(ev):
            return None
        if ev["ph"] == "X" and ("ts" not in ev or "dur" not in ev):
            return None
    spans = [e for e in evs if e["ph"] == "X"]
    return spans, len({e["tid"] for e in spans}), {e["name"] for e in spans}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch directory")
    opts = ap.parse_args()
    from fgumi_tpu.observe.report import validate_report

    tmp = tempfile.mkdtemp(prefix="fgumi_telemetry_")
    ok = True
    try:
        mapped = os.path.join(tmp, "mapped.bam")
        grouped = os.path.join(tmp, "grouped.bam")
        p = run(["simulate", "mapped-reads", "-o", mapped,
                 "--num-families", "50", "--family-size", "4", "--seed", "9"])
        assert p.returncode == 0, p.stderr
        p = run(["simulate", "grouped-reads", "-o", grouped,
                 "--num-families", "40", "--family-size", "4", "--seed", "9"])
        assert p.returncode == 0, p.stderr

        # 1) dedup: threaded pipeline -> >= 3 traced threads + valid report
        trace1 = os.path.join(tmp, "dedup.trace.json")
        rpt1 = os.path.join(tmp, "dedup.report.json")
        p = run(["--trace", trace1, "--run-report", rpt1, "dedup",
                 "-i", mapped, "-o", os.path.join(tmp, "dedup.bam"),
                 "--threads", "4"])
        ok &= check("dedup --trace/--run-report exits 0", p.returncode == 0,
                    f"rc={p.returncode}")
        got = load_trace(trace1)
        ok &= check("dedup trace is well-formed Chrome trace JSON",
                    got is not None)
        if got:
            spans, n_tids, names = got
            ok &= check("dedup trace has spans from >= 3 threads",
                        n_tids >= 3, f"threads={n_tids}")
            ok &= check("dedup trace has pipeline-stage spans",
                        {"pipeline.read", "pipeline.process",
                         "pipeline.sink"} <= names,
                        f"names={sorted(names)}")
        try:
            rpt = json.load(open(rpt1))
        except (OSError, ValueError):
            rpt = None
        errs = validate_report(rpt) if rpt else ["unreadable"]
        ok &= check("dedup run report is schema-valid", not errs,
                    "; ".join(errs[:3]))
        if rpt and not errs:
            busy = sum(v.get("busy_s", 0)
                       for v in rpt.get("stages", {}).values())
            ok &= check("dedup report stage timings non-zero", busy > 0)
            ok &= check("dedup report counts records",
                        sum(rpt.get("records", {}).values()) > 0)
            ok &= check("dedup report counts I/O bytes",
                        rpt.get("io", {}).get("bytes_read", 0) > 0
                        and rpt.get("io", {}).get("bytes_written", 0) > 0)
            lat = rpt.get("latency", {})
            ok &= check("dedup report carries BGZF latency histograms",
                        lat.get("io.bgzf.decompress_s", {})
                        .get("count", 0) > 0
                        and lat.get("io.bgzf.compress_s", {})
                        .get("count", 0) > 0,
                        f"latency keys={sorted(lat)[:6]}")
            ordered = all(
                s["p50"] <= s["p90"] <= s["p99"] <= s["max"]
                for s in lat.values())
            ok &= check("dedup latency quantiles ordered", ordered)

        # 2) simplex on the device kernel: device spans + DeviceStats
        trace2 = os.path.join(tmp, "simplex.trace.json")
        rpt2 = os.path.join(tmp, "simplex.report.json")
        p = run(["--trace", trace2, "--run-report", rpt2, "simplex",
                 "-i", grouped, "-o", os.path.join(tmp, "cons.bam"),
                 "--min-reads", "1", "--threads", "4"],
                # force the device route: the adaptive offload policy would
                # price this tiny workload host-side and emit no device spans
                env={"FGUMI_TPU_HOST_ENGINE": "0",
                     "FGUMI_TPU_ROUTE": "device"})
        ok &= check("simplex (device) exits 0", p.returncode == 0,
                    f"rc={p.returncode}")
        got = load_trace(trace2)
        if got:
            spans, n_tids, names = got
            ok &= check("simplex trace has device-dispatch spans",
                        "device.dispatch" in names and "device.fetch" in names,
                        f"names={sorted(names)}")
            ok &= check("simplex trace has spans from >= 3 threads",
                        n_tids >= 3, f"threads={n_tids}")
        else:
            ok &= check("simplex trace is well-formed", False)
        try:
            rpt = json.load(open(rpt2))
        except (OSError, ValueError):
            rpt = None
        errs = validate_report(rpt) if rpt else ["unreadable"]
        ok &= check("simplex run report is schema-valid", not errs,
                    "; ".join(errs[:3]))
        if rpt and not errs:
            ok &= check("simplex report device dispatches non-zero",
                        rpt.get("device", {}).get("dispatches", 0) > 0)
            lat = rpt.get("latency", {})
            ok &= check("simplex report carries dispatch latency "
                        "histograms",
                        lat.get("device.dispatch.wall_s", {})
                        .get("count", 0) > 0
                        and lat.get("device.dispatch.fetch_s", {})
                        .get("count", 0) > 0,
                        f"latency keys={sorted(lat)[:8]}")

        # 3) flags off -> no artifacts
        off_dir = os.path.join(tmp, "off")
        os.mkdir(off_dir)
        p = run(["dedup", "-i", mapped,
                 "-o", os.path.join(off_dir, "out.bam")])
        residue = [f for f in os.listdir(off_dir) if f != "out.bam"]
        ok &= check("flags off -> no telemetry artifacts",
                    p.returncode == 0 and not residue, f"residue={residue}")

        # 4) chaos wedge -> schema-valid black box + clean degradation
        from fgumi_tpu.observe.flight import validate_dump

        flight_dir = os.path.join(tmp, "flight")
        os.mkdir(flight_dir)
        rpt4 = os.path.join(tmp, "wedge.report.json")
        # identical relative argv in two working dirs (the chaos knobs and
        # the report travel via env), so @PG CL provenance bytes match and
        # the degradation's byte-identity contract is actually testable
        wd_ref = os.path.join(tmp, "wedge_ref")
        wd_chaos = os.path.join(tmp, "wedge_chaos")
        os.mkdir(wd_ref)
        os.mkdir(wd_chaos)
        argv4 = ["simplex", "-i", grouped, "-o", "wedge.bam",
                 "--min-reads", "1"]
        out4 = os.path.join(wd_chaos, "wedge.bam")
        ref4 = os.path.join(wd_ref, "wedge.bam")
        p = run(argv4, cwd=wd_ref)
        assert p.returncode == 0, p.stderr
        p = run(argv4, cwd=wd_chaos,
                env={"FGUMI_TPU_HOST_ENGINE": "0",
                     "FGUMI_TPU_ROUTE": "device",
                     "FGUMI_TPU_FLIGHT": flight_dir,
                     "FGUMI_TPU_RUN_REPORT": rpt4,
                     "FGUMI_TPU_DISPATCH_DEADLINE_S": "0.5:1",
                     "FGUMI_TPU_FAULT_HANG_S": "3",
                     "FGUMI_TPU_FAULT": "device.wedge:hang:1.0:1"})
        ok &= check("wedged run degrades cleanly (exit 0)",
                    p.returncode == 0, f"rc={p.returncode}")
        ok &= check("wedged run output byte-identical to clean run",
                    os.path.exists(out4)
                    and open(out4, "rb").read() == open(ref4, "rb").read())
        dumps = sorted(f for f in os.listdir(flight_dir)
                       if f.startswith("flight-"))
        ok &= check("wedge leaves a flight-recorder black box",
                    len(dumps) >= 1, f"dumps={dumps}")
        if dumps:
            obj = json.load(open(os.path.join(flight_dir, dumps[0])))
            derrs = validate_dump(obj)
            ok &= check("black box is schema-valid", not derrs,
                        "; ".join(derrs[:3]))
            ok &= check("black box names the wedged dispatch",
                        obj.get("reason") == "dispatch-deadline"
                        and obj.get("attrs", {})
                        .get("deadline_fallbacks", 0) >= 1
                        and bool((obj.get("device") or {})
                                 .get("timeline_tail")))
            try:
                r4 = json.load(open(rpt4))
            except (OSError, ValueError):
                r4 = {}
            ok &= check("run report carries the dump path",
                        any(os.path.basename(d) in dumps
                            for d in r4.get("flight_dumps", [])),
                        str(r4.get("flight_dumps")))
    finally:
        if opts.keep:
            print("scratch kept at", tmp)
        else:
            shutil.rmtree(tmp, ignore_errors=True)
    print("telemetry smoke:", "OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
