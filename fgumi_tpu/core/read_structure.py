"""fgbio-style read structures (e.g. ``8M12S+T``).

Behavioral contract mirrors the reference's local implementation
(/root/reference/src/lib/read_structure.rs:1-21, itself matching fgbio 4.1.0
``ReadStructure``):

- Segment kinds: T (template), B (sample barcode), M (molecular barcode),
  C (cell barcode), S (skip).
- At most one segment may be the any-length ``+`` segment, and it may sit at any
  index. Segments strictly after the ``+`` are resolved by walking back from the
  read end; the ``+`` absorbs ``read_len - fixed_length_sum`` bases
  (**zero-or-more**).
- A fully-fixed structure must match the read length exactly; an over-long read
  is an error rather than a silent truncation (read_structure.rs:63-81).
"""

from dataclasses import dataclass

SEGMENT_TYPES = frozenset("TBMCS")

TEMPLATE = "T"
SAMPLE_BARCODE = "B"
MOLECULAR_BARCODE = "M"
CELL_BARCODE = "C"
SKIP = "S"


class ReadStructureError(ValueError):
    pass


@dataclass(frozen=True)
class ReadSegment:
    kind: str
    length: int | None  # None == the any-length '+' segment

    def __str__(self):
        return ("+" if self.length is None else str(self.length)) + self.kind


class ReadStructure:
    """An ordered list of ReadSegments with at most one any-length segment."""

    def __init__(self, segments, rendered=None):
        if not segments:
            raise ReadStructureError("Read structure contained no segments")
        rendered = rendered or "".join(str(s) for s in segments)
        plus = [i for i, s in enumerate(segments) if s.length is None]
        if len(plus) > 1:
            raise ReadStructureError(
                f"Read structure contains more than one any-length (+) segment: {rendered}")
        self.segments = list(segments)
        self.plus_index = plus[0] if plus else None
        self.fixed_length_sum = sum(s.length or 0 for s in segments)
        # Bases occupied by fixed segments strictly after the '+'.
        self.post_plus_len = (
            sum(s.length or 0 for s in segments[self.plus_index + 1:])
            if self.plus_index is not None else 0)
        # Forward offsets up to and including the '+' (or all segments);
        # distance-from-end offsets for segments strictly after the '+'.
        n = len(segments)
        self._offsets = [("start", 0)] * n
        forward_end = n if self.plus_index is None else self.plus_index + 1
        off = 0
        for i in range(forward_end):
            self._offsets[i] = ("start", off)
            off += segments[i].length or 0
        if self.plus_index is not None:
            dist = 0
            for i in range(n - 1, self.plus_index, -1):
                dist += segments[i].length or 0
                self._offsets[i] = ("end", dist)

    @classmethod
    def parse(cls, rs: str) -> "ReadStructure":
        chars = "".join(rs.upper().split())
        segments = []
        i = 0
        n = len(chars)
        while i < n:
            if chars[i] == "+":
                length = None
                i += 1
            elif chars[i].isdigit():
                j = i
                while j < n and chars[j].isdigit():
                    j += 1
                length = int(chars[i:j])
                i = j
            else:
                raise ReadStructureError(
                    f"Read structure is missing a length before an operator: {chars}")
            if i >= n:
                raise ReadStructureError(
                    f"Read structure is missing a segment operator: {chars}")
            kind = chars[i]
            if kind not in SEGMENT_TYPES:
                raise ReadStructureError(
                    f"Read structure contains an unknown segment type: {chars}")
            if length == 0:
                raise ReadStructureError(
                    f"Read structure contains a zero-length segment: {chars}")
            i += 1
            segments.append(ReadSegment(kind, length))
        return cls(segments, chars)

    def __str__(self):
        return "".join(str(s) for s in self.segments)

    def __len__(self):
        return len(self.segments)

    @property
    def has_fixed_length(self) -> bool:
        return self.plus_index is None

    def span_of(self, index: int, read_len: int):
        """[start, end) span of segment `index` in a read of `read_len` bases."""
        anchor, v = self._offsets[index]
        start = v if anchor == "start" else read_len - v
        if self.plus_index == index:
            return (start, read_len - self.post_plus_len)
        return (start, start + self.segments[index].length)

    def check_read_length(self, read_len: int):
        """Returns None if acceptable, else an error message (fgbio validateReadLength)."""
        if read_len < self.fixed_length_sum:
            return (f"read is {read_len}bp but the read structure {self} requires "
                    f"at least {self.fixed_length_sum}bp")
        if self.has_fixed_length and read_len > self.fixed_length_sum:
            return (f"read is {read_len}bp but the fully-fixed read structure {self} "
                    f"requires exactly {self.fixed_length_sum}bp")
        return None

    def extract(self, seq: bytes, quals: bytes):
        """Split a read into per-segment (kind, seq, quals) triples, in order."""
        read_len = len(seq)
        out = []
        for i, seg in enumerate(self.segments):
            start, end = self.span_of(i, read_len)
            out.append((seg.kind, seq[start:end], quals[start:end]))
        return out
