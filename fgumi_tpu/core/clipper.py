"""Record clipping: soft / soft-with-mask / hard CIGAR surgery.

Mirrors /root/reference/crates/fgumi-sam/src/clipper.rs (SamRecordClipper /
RawRecordClipper) and record_utils.rs:
- three modes (ClippingMode, clipper.rs:89-97): soft keeps bases, soft-with-mask
  masks them to N/Q2, hard removes them and converts existing soft clips;
- clip_start/end_of_alignment: consume aligned ops up to the clip point,
  splitting ops at the boundary, swallowing whole insertions at the boundary
  and trailing deletions; unmap the read when no aligned bases would remain
  (clipper.rs:273-455);
- clip_*_of_read: "ensure at least N clipped" semantics counting existing
  clips, upgrading existing clipping when already satisfied (clipper.rs:2205+);
- clip_overlapping_reads: FR pairs only, midpoint of the two 5' ends
  (clipper.rs:673-775);
- clip_extending_past_mate_ends: fgbio numBasesExtendingPastMate against the
  mate's un-soft-clipped span (clipper.rs:784-935);
- upgrade_all_clipping: convert existing soft clips to the configured mode
  (clipper.rs:1264-1450);
- auto-clip extended attributes: per-base tags matching the old read length
  are sliced alongside hard clipping (clip_extended_attributes, clipper.rs:148+).
"""

import struct
from dataclasses import dataclass, field

import numpy as np

from ..constants import reverse_complement_bytes
from ..io.bam import (CIGAR_OPS, FLAG_DUPLICATE, FLAG_MATE_REVERSE,
                      FLAG_MATE_UNMAPPED, FLAG_PAIRED, FLAG_PROPER_PAIR,
                      FLAG_REVERSE, FLAG_SECONDARY, FLAG_SUPPLEMENTARY,
                      FLAG_UNMAPPED, RawRecord, _reg2bin)
from .tag_reversal import TAGS_TO_REVERSE, TAGS_TO_REVERSE_COMPLEMENT

NO_CALL_BASE = ord("N")
MIN_PHRED = 2
UNMAPPED_BIN = 4680

_CONSUMES_READ = frozenset("MI=X")
_CONSUMES_REF = frozenset("MD=XN")
_BASE_TO_NIBBLE = np.full(256, 15, dtype=np.uint8)
for _i, _b in enumerate(b"=ACMGRSVTWYHKDBN"):
    _BASE_TO_NIBBLE[_b] = _i
    _BASE_TO_NIBBLE[ord(chr(_b).lower())] = _i


@dataclass
class MutableRecord:
    """A decoded, mutable BAM record (the Python analog of the reference's
    RecordBuf surgery surface). `aux_entries` holds raw (tag, type_byte,
    value_bytes) TLV entries so tag edits never re-scan the record."""

    name: bytes
    flag: int
    ref_id: int
    pos: int  # 0-based; -1 = unmapped
    mapq: int
    cigar: list  # [(op_char, length)]
    seq: bytes  # ASCII
    quals: bytes
    next_ref_id: int
    next_pos: int
    tlen: int
    aux_entries: list = field(default_factory=list)

    @classmethod
    def from_raw(cls, rec: RawRecord) -> "MutableRecord":
        entries = []
        data = rec.data
        for tag, typ, off in rec._iter_tags():
            from ..io.bam import _skip_tag_value
            end = _skip_tag_value(data, typ, off)
            entries.append((bytes(tag), bytes([typ]), bytes(data[off:end])))
        return cls(name=bytes(rec.name), flag=rec.flag, ref_id=rec.ref_id,
                   pos=rec.pos, mapq=rec.mapq, cigar=rec.cigar(),
                   seq=rec.seq_bytes(), quals=rec.quals().tobytes(),
                   next_ref_id=rec.next_ref_id, next_pos=rec.next_pos,
                   tlen=rec.tlen, aux_entries=entries)

    def encode(self) -> bytes:
        buf = bytearray()
        l_name = len(self.name) + 1
        n = len(self.seq)
        ref_len = sum(ln for op, ln in self.cigar if op in _CONSUMES_REF)
        if self.pos >= 0:
            bin_ = _reg2bin(self.pos, self.pos + (ref_len or 1))
        else:
            bin_ = UNMAPPED_BIN
        buf += struct.pack("<iiBBHHHiiii", self.ref_id, self.pos, l_name,
                           self.mapq, bin_, len(self.cigar), self.flag, n,
                           self.next_ref_id, self.next_pos, self.tlen)
        buf += self.name + b"\x00"
        for op, length in self.cigar:
            buf += struct.pack("<I", (length << 4) | CIGAR_OPS.index(op))
        if n:
            codes = _BASE_TO_NIBBLE[np.frombuffer(self.seq, dtype=np.uint8)]
            if n % 2:
                codes = np.append(codes, 0)
            buf += ((codes[0::2] << 4) | codes[1::2]).astype(np.uint8).tobytes()
            buf += self.quals
        for tag, typ, value in self.aux_entries:
            buf += tag + typ + value
        return bytes(buf)

    # --- aux tag editing over pre-parsed entries ---
    def remove_tag(self, tag: bytes):
        self.aux_entries = [e for e in self.aux_entries if e[0] != tag]

    def set_str_tag(self, tag: bytes, value: bytes):
        self.remove_tag(tag)
        self.aux_entries.append((tag, b"Z", value + b"\x00"))

    def set_int_tag(self, tag: bytes, value: int):
        self.remove_tag(tag)
        self.aux_entries.append((tag, b"i", struct.pack("<i", value)))

    # --- derived geometry ---
    def is_unmapped(self) -> bool:
        return bool(self.flag & FLAG_UNMAPPED)

    def is_reverse(self) -> bool:
        return bool(self.flag & FLAG_REVERSE)

    def reference_length(self) -> int:
        return sum(ln for op, ln in self.cigar if op in _CONSUMES_REF)

    def alignment_end(self) -> int:
        """0-based inclusive reference end."""
        return self.pos + self.reference_length() - 1

    def cigar_string(self) -> str:
        if not self.cigar:
            return "*"
        return "".join(f"{ln}{op}" for op, ln in self.cigar)

    def unsoftclipped_start(self) -> int:
        """0-based start minus leading soft clips only (hard-clipped bases are
        physically absent; record_utils unsoftclipped_start)."""
        pos = self.pos
        for op, ln in self.cigar:
            if op == "H":
                continue
            if op == "S":
                pos -= ln
            break
        return pos

    def unsoftclipped_end(self) -> int:
        end = self.alignment_end()
        for op, ln in reversed(self.cigar):
            if op == "H":
                continue
            if op == "S":
                end += ln
            break
        return end


def _leading(cigar, kind) -> int:
    """Leading hard clip, or soft clip after hard clips."""
    i = 0
    hard = 0
    while i < len(cigar) and cigar[i][0] == "H":
        hard += cigar[i][1]
        i += 1
    if kind == "H":
        return hard
    soft = 0
    while i < len(cigar) and cigar[i][0] == "S":
        soft += cigar[i][1]
        i += 1
    return soft


def read_pos_at_ref_pos(rec: MutableRecord, ref_pos: int,
                        return_last_base_if_deleted: bool = False) -> int:
    """1-based read position at 1-based reference position, 0 if unaligned
    there (record_utils.rs:66-130)."""
    if rec.pos < 0:
        return 0
    read_pos = 0
    ref_cursor = rec.pos + 1  # 1-based
    last_aligned = 0
    for op, ln in rec.cigar:
        if op in "M=X":
            if ref_cursor <= ref_pos < ref_cursor + ln:
                return read_pos + (ref_pos - ref_cursor) + 1
            last_aligned = read_pos + ln
            read_pos += ln
            ref_cursor += ln
        elif op in "IS":
            read_pos += ln
        elif op in "DN":
            if ref_cursor <= ref_pos < ref_cursor + ln:
                return last_aligned if (return_last_base_if_deleted and last_aligned) else 0
            ref_cursor += ln
    return 0


def is_fr_pair(r1: MutableRecord, r2: MutableRecord) -> bool:
    """fgbio isFrPair (record_utils.rs:635-667): paired, both (+ mates) mapped,
    same reference, one forward one reverse, positive 5' < negative 5'."""
    for r in (r1, r2):
        if not r.flag & FLAG_PAIRED or r.flag & (FLAG_UNMAPPED | FLAG_MATE_UNMAPPED):
            return False
    if r1.ref_id != r2.ref_id:
        return False
    if r1.is_reverse() == r2.is_reverse():
        return False
    fwd, rev = (r2, r1) if r1.is_reverse() else (r1, r2)
    # FR iff the positive strand 5' (fwd start) precedes the negative strand 5'
    # (rev alignment end), both 1-based (htsjdk getPairOrientation)
    return fwd.pos + 1 < rev.alignment_end() + 1


def reorient_strand_tags(rec: MutableRecord):
    """Reverse / reverse-complement the strand-sensitive per-base aux tags,
    returning them to read orientation (make_read_unmapped path)."""
    new_entries = []
    for tag, typ, value in rec.aux_entries:
        if tag in TAGS_TO_REVERSE:
            if typ == b"Z":
                value = value[-2::-1] + b"\x00"
            elif typ == b"B":
                sub, n = value[0:1], struct.unpack("<I", value[1:5])[0]
                size = {b"c": 1, b"C": 1, b"s": 2, b"S": 2, b"i": 4, b"I": 4,
                        b"f": 4}[sub]
                body = value[5:5 + n * size]
                rev = b"".join(body[i * size:(i + 1) * size]
                               for i in reversed(range(n)))
                value = sub + value[1:5] + rev
        elif tag in TAGS_TO_REVERSE_COMPLEMENT and typ == b"Z":
            value = reverse_complement_bytes(value[:-1]) + b"\x00"
        new_entries.append((tag, typ, value))
    rec.aux_entries = new_entries


class RecordClipper:
    """Clipping engine; `mode` is 'soft' | 'soft-with-mask' | 'hard'."""

    def __init__(self, mode: str = "hard", auto_clip_attributes: bool = False):
        if mode not in ("soft", "soft-with-mask", "hard"):
            raise ValueError(f"unknown clipping mode {mode!r}")
        self.mode = mode
        self.auto_clip_attributes = auto_clip_attributes

    # ------------------------------------------------------------------
    @staticmethod
    def number_of_clippable_bases(rec: MutableRecord) -> int:
        return sum(ln for op, ln in rec.cigar if op in _CONSUMES_READ)

    @staticmethod
    def make_read_unmapped(rec: MutableRecord):
        """htsjdk SAMUtils.makeReadUnmapped (clipper.rs:205-255)."""
        if rec.is_reverse():
            rec.seq = reverse_complement_bytes(rec.seq)
            rec.quals = rec.quals[::-1]
            reorient_strand_tags(rec)
        rec.flag &= ~(FLAG_REVERSE | FLAG_DUPLICATE | FLAG_SECONDARY |
                      FLAG_SUPPLEMENTARY | FLAG_PROPER_PAIR)
        rec.flag |= FLAG_UNMAPPED
        rec.ref_id = -1
        rec.pos = -1
        rec.mapq = 0
        rec.tlen = 0
        rec.cigar = []

    def _clip_extended_attributes(self, rec: MutableRecord, remove: int,
                                  from_start: bool):
        """Hard mode + auto-clip: slice per-base tags whose length matches the
        pre-clip read length (clipper.rs:148-196)."""
        if self.mode != "hard" or remove == 0 or not self.auto_clip_attributes:
            return
        new_length = len(rec.seq)
        old_length = new_length + remove
        start, end = (remove, old_length) if from_start else (0, new_length)
        new_entries = []
        for tag, typ, value in rec.aux_entries:
            if typ == b"Z" and len(value) - 1 == old_length:
                value = value[start:end] + b"\x00"
            elif typ == b"B":
                sub = value[0:1]
                n = struct.unpack("<I", value[1:5])[0]
                if n == old_length:
                    size = {b"c": 1, b"C": 1, b"s": 2, b"S": 2, b"i": 4,
                            b"I": 4, b"f": 4}[sub]
                    body = value[5:]
                    value = (sub + struct.pack("<I", end - start)
                             + body[start * size:end * size])
            new_entries.append((tag, typ, value))
        rec.aux_entries = new_entries

    # ------------------------------------------------------------------
    def clip_start_of_alignment(self, rec: MutableRecord, bases_to_clip: int) -> int:
        """clipper.rs:273-455. Returns read bases newly clipped."""
        if bases_to_clip == 0 or rec.is_unmapped() or not rec.seq:
            return 0
        num_clippable = self.number_of_clippable_bases(rec)
        if num_clippable <= bases_to_clip:
            self.make_read_unmapped(rec)
            return num_clippable

        ops = rec.cigar
        existing_hard = _leading(ops, "H")
        existing_soft = _leading(ops, "S")
        i = 0
        while i < len(ops) and ops[i][0] in "HS":
            i += 1
        post = ops[i:]

        read_clipped = 0
        ref_clipped = 0
        new_ops = []
        j = 0
        while (read_clipped < bases_to_clip
               or (read_clipped == bases_to_clip and not new_ops
                   and j < len(post) and post[j][0] == "D")):
            if j >= len(post):
                break
            op, ln = post[j]
            j += 1
            consumes_read = op in _CONSUMES_READ
            consumes_ref = op in "M=XD"
            if consumes_read and ln > bases_to_clip - read_clipped:
                if op == "I":
                    read_clipped += ln  # swallow whole insertion at boundary
                else:
                    remaining_clip = bases_to_clip - read_clipped
                    read_clipped += remaining_clip
                    ref_clipped += remaining_clip
                    new_ops.append((op, ln - remaining_clip))
            else:
                if consumes_read:
                    read_clipped += ln
                if consumes_ref:
                    ref_clipped += ln
        new_ops.extend(post[j:])

        if self.mode == "hard":
            added_hard = existing_soft + read_clipped
            final = [("H", existing_hard + added_hard)] + new_ops
            bases_to_remove = added_hard
        else:
            final = []
            if existing_hard:
                final.append(("H", existing_hard))
            final.append(("S", existing_soft + read_clipped))
            final += new_ops
            bases_to_remove = 0
        rec.cigar = final
        if ref_clipped:
            rec.pos += ref_clipped
        if self.mode == "soft-with-mask":
            total_soft = existing_soft + read_clipped
            k = min(total_soft, len(rec.seq))
            rec.seq = b"N" * k + rec.seq[k:]
            rec.quals = bytes([MIN_PHRED]) * k + rec.quals[k:]
        elif self.mode == "hard":
            rec.seq = rec.seq[bases_to_remove:]
            rec.quals = rec.quals[bases_to_remove:]
            self._clip_extended_attributes(rec, bases_to_remove, True)
        return read_clipped

    def clip_end_of_alignment(self, rec: MutableRecord, bases_to_clip: int) -> int:
        """Symmetric counterpart (clipper.rs:456-628)."""
        if bases_to_clip == 0 or rec.is_unmapped() or not rec.seq:
            return 0
        num_clippable = self.number_of_clippable_bases(rec)
        if num_clippable <= bases_to_clip:
            self.make_read_unmapped(rec)
            return num_clippable

        ops = rec.cigar[::-1]  # work on reversed ops
        existing_hard = _leading(ops, "H")
        existing_soft = _leading(ops, "S")
        i = 0
        while i < len(ops) and ops[i][0] in "HS":
            i += 1
        post = ops[i:]

        read_clipped = 0
        new_ops = []
        j = 0
        while (read_clipped < bases_to_clip
               or (read_clipped == bases_to_clip and not new_ops
                   and j < len(post) and post[j][0] == "D")):
            if j >= len(post):
                break
            op, ln = post[j]
            j += 1
            consumes_read = op in _CONSUMES_READ
            if consumes_read and ln > bases_to_clip - read_clipped:
                if op == "I":
                    read_clipped += ln
                else:
                    remaining_clip = bases_to_clip - read_clipped
                    read_clipped += remaining_clip
                    new_ops.append((op, ln - remaining_clip))
            else:
                if consumes_read:
                    read_clipped += ln
        new_ops.extend(post[j:])

        if self.mode == "hard":
            added_hard = existing_soft + read_clipped
            final_rev = [("H", existing_hard + added_hard)] + new_ops
            bases_to_remove = added_hard
        else:
            final_rev = []
            if existing_hard:
                final_rev.append(("H", existing_hard))
            final_rev.append(("S", existing_soft + read_clipped))
            final_rev += new_ops
            bases_to_remove = 0
        rec.cigar = final_rev[::-1]
        if self.mode == "soft-with-mask":
            total_soft = existing_soft + read_clipped
            k = min(total_soft, len(rec.seq))
            cut = len(rec.seq) - k
            rec.seq = rec.seq[:cut] + b"N" * k
            rec.quals = rec.quals[:cut] + bytes([MIN_PHRED]) * k
        elif self.mode == "hard":
            keep = len(rec.seq) - bases_to_remove
            rec.seq = rec.seq[:keep]
            rec.quals = rec.quals[:keep]
            self._clip_extended_attributes(rec, bases_to_remove, False)
        return read_clipped

    def clip_5_prime_end_of_alignment(self, rec, n):
        return (self.clip_end_of_alignment(rec, n) if rec.is_reverse()
                else self.clip_start_of_alignment(rec, n))

    def clip_3_prime_end_of_alignment(self, rec, n):
        return (self.clip_start_of_alignment(rec, n) if rec.is_reverse()
                else self.clip_end_of_alignment(rec, n))

    # --- "ensure at least N clipped" read-level entry points ---
    def clip_start_of_read(self, rec: MutableRecord, clip_length: int) -> int:
        existing = 0
        for op, ln in rec.cigar:
            if op in "SH":
                existing += ln
            else:
                break
        if clip_length > existing:
            return self.clip_start_of_alignment(rec, clip_length - existing)
        self._upgrade_clipping(rec, clip_length, True)
        return 0

    def clip_end_of_read(self, rec: MutableRecord, clip_length: int) -> int:
        existing = 0
        for op, ln in reversed(rec.cigar):
            if op in "SH":
                existing += ln
            else:
                break
        if clip_length > existing:
            return self.clip_end_of_alignment(rec, clip_length - existing)
        self._upgrade_clipping(rec, clip_length, False)
        return 0

    def clip_5_prime_end_of_read(self, rec, n):
        return (self.clip_end_of_read(rec, n) if rec.is_reverse()
                else self.clip_start_of_read(rec, n))

    def clip_3_prime_end_of_read(self, rec, n):
        return (self.clip_start_of_read(rec, n) if rec.is_reverse()
                else self.clip_end_of_read(rec, n))

    # --- clipping upgrades ---
    def _upgrade_clipping(self, rec: MutableRecord, length: int, from_start: bool):
        """clipper.rs:1028-1155: upgrade up to `length` existing clipped bases
        to the configured (more stringent) mode."""
        if self.mode == "soft" or length == 0:
            return
        ops = rec.cigar if from_start else rec.cigar[::-1]
        hard_clipped = _leading(ops, "H")
        soft_clipped = _leading(ops, "S")
        if hard_clipped >= length or soft_clipped == 0:
            return
        to_upgrade = min(soft_clipped, length - hard_clipped)

        if self.mode == "hard":
            i = 0
            while i < len(ops) and ops[i][0] in "HS":
                i += 1
            new_ops = [("H", hard_clipped + to_upgrade)]
            if soft_clipped > to_upgrade:
                new_ops.append(("S", soft_clipped - to_upgrade))
            new_ops.extend(ops[i:])
            rec.cigar = new_ops if from_start else new_ops[::-1]
            if from_start:
                rec.seq = rec.seq[to_upgrade:]
                rec.quals = rec.quals[to_upgrade:]
            else:
                keep = len(rec.seq) - to_upgrade
                rec.seq = rec.seq[:keep]
                rec.quals = rec.quals[:keep]
            self._clip_extended_attributes(rec, to_upgrade, from_start)
        else:  # soft-with-mask
            if from_start:
                rec.seq = b"N" * to_upgrade + rec.seq[to_upgrade:]
                rec.quals = bytes([MIN_PHRED]) * to_upgrade + rec.quals[to_upgrade:]
            else:
                keep = len(rec.seq) - to_upgrade
                rec.seq = rec.seq[:keep] + b"N" * to_upgrade
                rec.quals = rec.quals[:keep] + bytes([MIN_PHRED]) * to_upgrade

    def upgrade_all_clipping(self, rec: MutableRecord):
        """Convert all existing soft clipping to the configured mode
        (clipper.rs:1264-1450). Returns (leading, trailing) upgraded counts."""
        if self.mode == "soft" or rec.is_unmapped():
            return (0, 0)
        if not any(op == "S" for op, _ in rec.cigar):
            return (0, 0)
        leading_hard = _leading(rec.cigar, "H")
        leading_soft = _leading(rec.cigar, "S")
        rev = rec.cigar[::-1]
        trailing_hard = _leading(rev, "H")
        trailing_soft = _leading(rev, "S")
        if leading_soft:
            self._upgrade_clipping(rec, leading_hard + leading_soft, True)
        if trailing_soft:
            self._upgrade_clipping(rec, trailing_hard + trailing_soft, False)
        return (leading_soft, trailing_soft)

    # --- pairwise clipping ---
    def _query_bases_for_ref_region(self, rec: MutableRecord, ref_bases: int,
                                    from_start: bool) -> int:
        """clipper.rs:963-1012."""
        remaining_ref = ref_bases
        query = 0
        ops = rec.cigar if from_start else rec.cigar[::-1]
        for op, ln in ops:
            if remaining_ref == 0:
                break
            consumes_ref = op in "M=XD"
            consumes_query = op in _CONSUMES_READ
            if consumes_ref:
                consumed = min(ln, remaining_ref)
                remaining_ref -= consumed
                if consumes_query:
                    query += consumed
            elif consumes_query and remaining_ref > 0:
                query += ln  # insertion inside the region
        return query

    def clip_overlapping_reads(self, r1: MutableRecord, r2: MutableRecord):
        """FR midpoint overlap clipping (clipper.rs:673-775).
        Returns (clipped_r1, clipped_r2) in the caller's argument order."""
        if not is_fr_pair(r1, r2):
            return (0, 0)
        swapped = r1.is_reverse()
        fwd, rev = (r2, r1) if swapped else (r1, r2)
        if fwd.pos < 0 or rev.pos < 0:
            return (0, 0)
        f_start, f_end = fwd.pos + 1, fwd.pos + fwd.reference_length()
        r_start, r_end = rev.pos + 1, rev.pos + rev.reference_length()
        if max(f_start, r_start) > min(f_end, r_end):
            return (0, 0)
        midpoint = (f_start + r_end) // 2
        if midpoint > f_end:
            midpoint = f_end
        elif midpoint < r_start:
            midpoint = max(r_start - 1, 0)
        f_clip = (self._query_bases_for_ref_region(fwd, f_end - midpoint, False)
                  if f_end > midpoint else 0)
        r_clip = (self._query_bases_for_ref_region(rev, midpoint + 1 - r_start, True)
                  if midpoint + 1 > r_start else 0)
        clipped_f = self.clip_end_of_alignment(fwd, f_clip) if f_clip else 0
        clipped_r = self.clip_start_of_alignment(rev, r_clip) if r_clip else 0
        if self.mode == "hard":
            self.upgrade_all_clipping(fwd)
            self.upgrade_all_clipping(rev)
        return (clipped_r, clipped_f) if swapped else (clipped_f, clipped_r)

    @staticmethod
    def num_bases_extending_past_mate(rec: MutableRecord,
                                      mate_unclipped_start: int,
                                      mate_unclipped_end: int) -> int:
        """fgbio numBasesExtendingPastMate (clipper.rs:784-870); positions are
        1-based."""
        read_length = sum(ln for op, ln in rec.cigar if op in "M=XIS")
        if rec.pos < 0:
            return 0
        if not rec.is_reverse():
            alignment_end = rec.pos + 1 + max(rec.reference_length() - 1, 0)
            if alignment_end >= mate_unclipped_end:
                pos_at = read_pos_at_ref_pos(rec, mate_unclipped_end, False)
                return max(read_length - pos_at, 0)
            trailing_soft = _leading(rec.cigar[::-1], "S")
            gap = mate_unclipped_end - alignment_end
            return max(trailing_soft - gap, 0)
        alignment_start = rec.pos + 1
        if alignment_start > mate_unclipped_start:
            leading_soft = _leading(rec.cigar, "S")
            gap = alignment_start - mate_unclipped_start
            return max(leading_soft - gap, 0)
        pos_at = read_pos_at_ref_pos(rec, mate_unclipped_start, False)
        return max(pos_at - 1, 0)

    def _clip_single_extending(self, rec: MutableRecord, mate_start: int,
                               mate_end: int) -> int:
        n = self.num_bases_extending_past_mate(rec, mate_start, mate_end)
        if n == 0:
            return 0
        if not rec.is_reverse():
            return self.clip_end_of_read(rec, n)
        return self.clip_start_of_read(rec, n)

    def clip_extending_past_mate_ends(self, r1: MutableRecord, r2: MutableRecord):
        """clipper.rs:873-935. Returns (clipped_r1, clipped_r2)."""
        if not is_fr_pair(r1, r2):
            return (0, 0)
        r1_span = (r1.unsoftclipped_start() + 1, r1.unsoftclipped_end() + 1)
        r2_span = (r2.unsoftclipped_start() + 1, r2.unsoftclipped_end() + 1)
        clipped_r1 = self._clip_single_extending(r1, r2_span[0], r2_span[1])
        clipped_r2 = self._clip_single_extending(r2, r1_span[0], r1_span[1])
        return (clipped_r1, clipped_r2)


def clipped_bases(rec: MutableRecord) -> int:
    return sum(ln for op, ln in rec.cigar if op in "SH")
