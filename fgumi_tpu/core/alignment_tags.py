"""NM / UQ / MD alignment-tag regeneration against a reference FASTA.

Mirrors /root/reference/crates/fgumi-sam/src/alignment_tags.rs
(regenerate_alignment_tags_raw, :259-440):
- unmapped (or mapped-but-refless) records get NM/UQ/MD stripped;
- zero-reference-span CIGARs get NM=0, UQ=0, MD="0";
- otherwise walk the CIGAR against the fetched reference span: mismatches and
  read Ns count toward NM and UQ (sum of mismatch quals) and break MD match
  runs; insertions add to NM only; deletions add to NM and write ^bases in MD;
  soft clips advance the read, N-skips advance the reference.
"""

import numpy as np

from .clipper import MutableRecord

_SMALL_STR = [str(i) for i in range(512)]
_CHR = [chr(i) for i in range(256)]


def _int_str(v: int) -> str:
    return _SMALL_STR[v] if 0 <= v < 512 else str(v)


def regenerate_alignment_tags(rec: MutableRecord, ref_names, reference) -> bool:
    """Update NM/UQ/MD on `rec` in place. Returns True when tags were computed
    (False = stripped). `reference` is a core.reference.ReferenceReader."""
    if rec.is_unmapped() or rec.ref_id < 0:
        for tag in (b"NM", b"UQ", b"MD"):
            rec.remove_tag(tag)
        return False
    chrom = ref_names[rec.ref_id]
    ref_span = rec.reference_length()
    if ref_span == 0:
        rec.set_int_tag(b"NM", 0)
        rec.set_int_tag(b"UQ", 0)
        rec.set_str_tag(b"MD", b"0")
        return True
    ref_bases = reference.fetch(chrom, rec.pos, rec.pos + ref_span)

    nm = 0
    uq = 0
    md = []
    match_count = 0
    ref_off = 0
    seq_pos = 0
    seq = rec.seq
    quals = rec.quals
    seq_arr = np.frombuffer(seq, dtype=np.uint8)
    qual_arr = np.frombuffer(bytes(quals), dtype=np.uint8)
    ref_arr = np.frombuffer(ref_bases, dtype=np.uint8)
    for op, ln in rec.cigar:
        if op in "M=X":
            # vectorized per-segment mismatch scan (the per-base Python loop
            # here was ~60% of clip wall time): case-folded compare, read N/n
            # always mismatching, MD assembled from the few mismatch indices
            sseg = seq_arr[seq_pos:seq_pos + ln]
            rseg = ref_arr[ref_off:ref_off + ln]
            mism = ((sseg & np.uint8(0xDF)) != (rseg & np.uint8(0xDF))) \
                | (sseg == ord("N")) | (sseg == ord("n"))
            idx = np.nonzero(mism)[0]
            if len(idx):
                nm += len(idx)
                uq += int(qual_arr[seq_pos:seq_pos + ln][mism].sum())
                gaps = np.diff(idx, prepend=-1) - 1
                chars = rseg[idx]
                md.append(_int_str(match_count + int(gaps[0])))
                md.append(_CHR[chars[0]])
                for g, c in zip(gaps[1:].tolist(), chars[1:].tolist()):
                    md.append(_int_str(g))
                    md.append(_CHR[c])
                match_count = ln - int(idx[-1]) - 1
            else:
                match_count += ln
            seq_pos += ln
            ref_off += ln
        elif op == "I":
            nm += ln
            seq_pos += ln
        elif op == "D":
            nm += ln
            md.append(str(match_count))
            match_count = 0
            md.append("^" + ref_bases[ref_off:ref_off + ln].decode())
            ref_off += ln
        elif op == "S":
            seq_pos += ln
        elif op == "N":
            ref_off += ln
    md.append(str(match_count))
    rec.set_int_tag(b"NM", nm)
    rec.set_int_tag(b"UQ", min(uq, 2**31 - 1))
    rec.set_str_tag(b"MD", "".join(md).encode())
    return True
