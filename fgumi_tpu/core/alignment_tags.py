"""NM / UQ / MD alignment-tag regeneration against a reference FASTA.

Mirrors /root/reference/crates/fgumi-sam/src/alignment_tags.rs
(regenerate_alignment_tags_raw, :259-440):
- unmapped (or mapped-but-refless) records get NM/UQ/MD stripped;
- zero-reference-span CIGARs get NM=0, UQ=0, MD="0";
- otherwise walk the CIGAR against the fetched reference span: mismatches and
  read Ns count toward NM and UQ (sum of mismatch quals) and break MD match
  runs; insertions add to NM only; deletions add to NM and write ^bases in MD;
  soft clips advance the read, N-skips advance the reference.
"""

from .clipper import MutableRecord


def regenerate_alignment_tags(rec: MutableRecord, ref_names, reference) -> bool:
    """Update NM/UQ/MD on `rec` in place. Returns True when tags were computed
    (False = stripped). `reference` is a core.reference.ReferenceReader."""
    if rec.is_unmapped() or rec.ref_id < 0:
        for tag in (b"NM", b"UQ", b"MD"):
            rec.remove_tag(tag)
        return False
    chrom = ref_names[rec.ref_id]
    ref_span = rec.reference_length()
    if ref_span == 0:
        rec.set_int_tag(b"NM", 0)
        rec.set_int_tag(b"UQ", 0)
        rec.set_str_tag(b"MD", b"0")
        return True
    ref_bases = reference.fetch(chrom, rec.pos, rec.pos + ref_span)

    nm = 0
    uq = 0
    md = []
    match_count = 0
    ref_off = 0
    seq_pos = 0
    seq = rec.seq
    quals = rec.quals
    for op, ln in rec.cigar:
        if op in "M=X":
            for k in range(ln):
                ref_base = ref_bases[ref_off + k]
                seq_base = seq[seq_pos]
                if seq_base in (ord("N"), ord("n")) or (seq_base & ~0x20) != (ref_base & ~0x20):
                    nm += 1
                    uq += quals[seq_pos]
                    md.append(str(match_count))
                    match_count = 0
                    md.append(chr(ref_base))
                else:
                    match_count += 1
                seq_pos += 1
            ref_off += ln
        elif op == "I":
            nm += ln
            seq_pos += ln
        elif op == "D":
            nm += ln
            md.append(str(match_count))
            match_count = 0
            md.append("^" + ref_bases[ref_off:ref_off + ln].decode())
            ref_off += ln
        elif op == "S":
            seq_pos += ln
        elif op == "N":
            ref_off += ln
    md.append(str(match_count))
    rec.set_int_tag(b"NM", nm)
    rec.set_int_tag(b"UQ", min(uq, 2**31 - 1))
    rec.set_str_tag(b"MD", "".join(md).encode())
    return True
