"""Deterministic content-hash sharding of grouped BAM streams.

The scatter planner (serve/scatter.py) splits one whale job into N
sub-jobs; every sub-job reads the SAME grouped stream and keeps only the
MI families assigned to its shard. Assignment is a pure function of
record content — never of Python's salted ``hash()``, the shard count's
iteration order, or the backend the shard lands on — so a split is
reproducible across runs, interpreters (PYTHONHASHSEED), and machines:

- ``umi`` axis: splitmix64 finalizer over the family's numeric MI value.
- ``coord`` axis: FNV-1a 64 over the 18-byte both-ends template position
  key (tid1, tid2, biased pos1/pos2, strand pair) — the exact bytes the
  native template-coordinate sort key packs, so records of one family
  (which share the position key by construction of `group`) always hash
  together.

Both hashes read the packed key ``native.batch.template_coord_keys``
already produces (fgumi_native.cc fgumi_template_coord_keys): bytes
0-17 position, bytes 20-27 MI value u64 BE.

Byte-deterministic gather needs more than a disjoint split: the merged
output must interleave shard outputs in the exact order the unsharded
run would have produced. Consensus callers emit families in input
stream order, so each shard filter also records a **manifest** — the
global family ordinal (index of the family in the full input stream)
and MI value of every family it kept. The gather stage k-way merges the
manifests by ordinal and, per winning entry, copies that family's
consensus records from the owning shard's output run (zero records when
the caller dropped the family — min-reads, filtering).

Precondition: the input is a grouped stream (`group` output) where each
family's records are adjacent and every record carries the MI tag —
the same contract the consensus callers themselves rely on.
"""

import os
import struct

import numpy as np

_FNV_OFFSET = np.uint64(0xCBF29CE484222325)
_FNV_PRIME = np.uint64(0x100000001B3)

SHARD_AXES = ("umi", "coord")


class ShardSpec:
    """One shard's slot in an N-way split."""

    __slots__ = ("index", "count", "axis")

    def __init__(self, index: int, count: int, axis: str = "umi"):
        if count < 1:
            raise ValueError(f"shard count must be >= 1 (got {count})")
        if not 0 <= index < count:
            raise ValueError(f"shard index {index} outside 0..{count - 1}")
        if axis not in SHARD_AXES:
            raise ValueError(f"shard axis must be one of {SHARD_AXES} "
                             f"(got {axis!r})")
        self.index = index
        self.count = count
        self.axis = axis

    def __repr__(self):
        return f"ShardSpec({self.index}/{self.count}, axis={self.axis})"


def parse_shard_arg(value: str, axis: str = "umi") -> ShardSpec:
    """``K/N`` (0-based K) -> ShardSpec; loud errors for the CLI."""
    k, sep, n = value.partition("/")
    if not sep or not k.isdigit() or not n.isdigit():
        raise ValueError(f"--shard {value!r}: expected K/N, e.g. 0/4")
    return ShardSpec(int(k), int(n), axis)


def mi_value(mi) -> int:
    """Numeric MI value, the exact parse the native key packs
    (fgumi_native.cc): digits before '/', ASCII whitespace stripped,
    negatives clamp to 0, saturating at u64 max; malformed/absent -> 0."""
    if mi is None:
        return 0
    if isinstance(mi, bytes):
        mi = mi.decode("ascii", "replace")
    base = mi.split("/", 1)[0].strip(" \t\n\r\x0b\x0c")
    negative = False
    if base[:1] in "+-":
        negative = base[0] == "-"
        base = base[1:]
    if not base or not all("0" <= c <= "9" for c in base):
        return 0
    if negative:
        return 0
    return min(int(base), (1 << 64) - 1)


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer: uniform, seed-free family hash from MI."""
    x = x.astype(np.uint64, copy=True)
    with np.errstate(over="ignore"):
        x ^= x >> np.uint64(30)
        x *= np.uint64(0xBF58476D1CE4E5B9)
        x ^= x >> np.uint64(27)
        x *= np.uint64(0x94D049BB133111EB)
        x ^= x >> np.uint64(31)
    return x


def _fnv1a_key18(keys: np.ndarray, ko: np.ndarray) -> np.ndarray:
    """Vectorized FNV-1a 64 over the 18 position bytes of each packed key."""
    h = np.full(len(ko), _FNV_OFFSET, np.uint64)
    with np.errstate(over="ignore"):
        for b in range(18):
            h = (h ^ keys[ko + b].astype(np.uint64)) * _FNV_PRIME
    return h


class ShardFilter:
    """Streaming family-run filter over a grouped record stream.

    Stateful and strictly in stream order: every record of the input
    must pass through exactly once (``wrap_batches`` for the vectorized
    engines, ``record_keep`` for the classic per-record engines — both
    share one run tracker, so fast and classic runs agree bit-for-bit
    on assignment, ordinals, and manifest)."""

    def __init__(self, spec: ShardSpec, manifest_path: str = None):
        self.spec = spec
        self.manifest_path = manifest_path
        self._prev_mi = None      # last record's MI value (run carry)
        self._carry_keep = False  # keep decision of the open family
        self._families = 0        # global family ordinal counter
        self._man_ord = []        # per-batch kept-family ordinal arrays
        self._man_mi = []
        self.records_seen = 0
        self.records_kept = 0

    # -- shared run/assignment core -------------------------------------

    def _assign(self, batch) -> np.ndarray:
        """keep mask for one RecordBatch; advances run/ordinal state."""
        from ..native import batch as nb

        n = batch.n
        keys, out_off = nb.template_coord_keys(
            batch, np.zeros(n, np.int32))
        ko = out_off[:-1]
        mi = np.zeros(n, np.uint64)
        for b in range(8):
            mi = (mi << np.uint64(8)) | keys[ko + (20 + b)].astype(np.uint64)
        newfam = np.empty(n, bool)
        newfam[0] = self._prev_mi is None or mi[0] != self._prev_mi
        if n > 1:
            newfam[1:] = mi[1:] != mi[:-1]
        starts = np.flatnonzero(newfam)
        if self.spec.axis == "umi":
            fam_hash = _mix64(mi[starts])
        else:
            fam_hash = _fnv1a_key18(keys, ko[starts])
        fam_keep = (fam_hash % np.uint64(self.spec.count)) \
            == np.uint64(self.spec.index)
        # per-record keep: families are runs, so a cumulative family index
        # maps each record to its family; index -1 = carry-over family
        fam_idx = np.cumsum(newfam) - 1
        if len(starts):
            keep = np.where(fam_idx >= 0,
                            fam_keep[np.maximum(fam_idx, 0)],
                            self._carry_keep)
        else:
            keep = np.full(n, self._carry_keep)
        kept = np.flatnonzero(fam_keep)
        if len(kept):
            self._man_ord.append((self._families + kept).astype(np.uint64))
            self._man_mi.append(mi[starts[kept]])
        self._families += len(starts)
        self._prev_mi = mi[-1]
        self._carry_keep = bool(keep[-1])
        self.records_seen += n
        self.records_kept += int(keep.sum())
        return keep

    # -- vectorized engines ----------------------------------------------

    def wrap_batches(self, batches):
        """Filter a RecordBatch iterator down to this shard's families.

        Kept records form contiguous runs, so the filtered batch is
        rebuilt by concatenating run slices of the wire buffer — no
        per-record Python loop."""
        from ..io.batch_reader import RecordBatch

        for batch in batches:
            if batch.n == 0:
                continue
            keep = self._assign(batch)
            k = np.flatnonzero(keep)
            if len(k) == batch.n:
                yield batch
                continue
            if not len(k):
                continue
            brk = np.flatnonzero(np.diff(k) != 1)
            run_s = np.concatenate(([0], brk + 1))
            run_e = np.concatenate((brk, [len(k) - 1]))
            parts = [batch.buf[batch.rec_off[k[s]]:batch.data_end[k[e]]]
                     for s, e in zip(run_s, run_e)]
            # copy even the single-run case: a view would pin the parent
            # chunk for the lifetime of the (much smaller) filtered batch
            buf = parts[0].copy() if len(parts) == 1 \
                else np.concatenate(parts)
            lens = batch.data_end[k] - batch.rec_off[k]
            off = np.concatenate(([0], np.cumsum(lens)))[:-1]
            yield RecordBatch(buf, np.ascontiguousarray(off, np.int64))

    # -- classic per-record engines ---------------------------------------

    def record_keep(self, rec) -> bool:
        """Per-record gate for the classic engines (compose FIRST in a
        record_filter chain — it must see every record in stream order).

        Routes the single record through the same native key packer via
        a one-record batch, so classic and fast assignment can never
        drift."""
        from ..io.batch_reader import RecordBatch

        wire = struct.pack("<I", len(rec.data)) + rec.data
        one = RecordBatch(bytearray(wire), np.zeros(1, np.int64))
        return bool(self._assign(one)[0])

    # -- manifest ---------------------------------------------------------

    @property
    def families_seen(self) -> int:
        return self._families

    def manifest(self) -> np.ndarray:
        """(m, 2) uint64 [global family ordinal, MI value] of kept
        families, in stream order."""
        if not self._man_ord:
            return np.empty((0, 2), np.uint64)
        return np.stack([np.concatenate(self._man_ord),
                         np.concatenate(self._man_mi)], axis=1)

    def write_manifest(self, path: str = None):
        path = path or self.manifest_path
        if path is None:
            return
        write_manifest(path, self.manifest())


def write_manifest(path: str, manifest: np.ndarray):
    """Atomic manifest write (tmp + rename): the gather stage must never
    see a torn sidecar after a shard job crash."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.save(f, np.ascontiguousarray(manifest, np.uint64),
                    allow_pickle=False)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def read_manifest(path: str) -> np.ndarray:
    arr = np.load(path, allow_pickle=False)
    if arr.ndim != 2 or arr.shape[1] != 2 or arr.dtype != np.uint64:
        raise ValueError(f"shard manifest {path}: expected (m, 2) uint64, "
                         f"got {arr.dtype}{arr.shape}")
    return arr


class _RunCursor:
    """Family-run reader over one shard's consensus BAM: runs of equal
    MI value, in stream order, taken by matching the manifest entry."""

    def __init__(self, reader):
        self._records = iter(reader)
        self._pending = None  # (mi_value, RawRecord) lookahead

    def _next(self):
        if self._pending is not None:
            out, self._pending = self._pending, None
            return out
        rec = next(self._records, None)
        if rec is None:
            return None
        return (mi_value(rec.get_str(b"MI")), rec)

    def take(self, mi: int):
        """Records of the next run IF its MI matches, else [] (the
        consensus caller dropped that family)."""
        first = self._next()
        if first is None:
            return []
        if first[0] != mi:
            self._pending = first
            return []
        out = [first[1]]
        while True:
            nxt = self._next()
            if nxt is None:
                return out
            if nxt[0] != mi:
                self._pending = nxt
                return out
            out.append(nxt[1])

    def exhausted(self) -> bool:
        if self._pending is not None:
            return False
        nxt = self._next()
        if nxt is None:
            return True
        self._pending = nxt
        return False


def gather_shards(bam_paths, manifest_paths, out_path: str,
                  level: int = None, progress=None) -> dict:
    """Merge N shard consensus BAMs into the byte-deterministic whole.

    Streams the per-shard manifests through the public k-way merge
    (sort.external.merge_keyed_streams) keyed by global family ordinal;
    each winning entry copies its family's records from the owning
    shard's output run. Returns counters {families, records, dropped}.
    ``progress(families_merged)`` is called periodically when given."""
    from ..io.bam import BamWriter
    from ..io.batch_reader import BatchedRecordReader
    from ..sort.external import merge_keyed_streams

    if len(bam_paths) != len(manifest_paths) or not bam_paths:
        raise ValueError("gather needs one manifest per shard BAM")
    manifests = [read_manifest(p) for p in manifest_paths]
    readers = [BatchedRecordReader(p) for p in bam_paths]
    stats = {"families": 0, "records": 0, "dropped": 0}
    try:
        header = readers[0].header
        for i, r in enumerate(readers[1:], 1):
            if r.header.text != header.text:
                raise ValueError(
                    f"shard {i} header differs from shard 0 "
                    f"({bam_paths[i]}): scatter sub-jobs out of sync")
        cursors = [_RunCursor(r) for r in readers]

        def _entries(s, man):
            for row in man:
                yield int(row[0]), (s, int(row[1]))

        streams = [_entries(s, man) for s, man in enumerate(manifests)]
        with BamWriter(out_path, header, level=level) as writer:
            for _ord, (shard, mi) in merge_keyed_streams(streams):
                recs = cursors[shard].take(mi)
                stats["families"] += 1
                if not recs:
                    stats["dropped"] += 1
                for rec in recs:
                    writer.write_record_bytes(rec.data)
                stats["records"] += len(recs)
                if progress is not None and stats["families"] % 4096 == 0:
                    progress(stats["families"])
        for i, cur in enumerate(cursors):
            if not cur.exhausted():
                raise ValueError(
                    f"shard {i} output has families beyond its manifest "
                    f"({bam_paths[i]}): scatter sub-jobs out of sync")
    finally:
        for r in readers:
            r.close()
    return stats
