"""Template assembly and template-coordinate position keys.

Mirrors /root/reference/src/lib/template.rs (Template = all records of one QNAME,
classified primary R1/R2/fragment vs secondary/supplementary) and
/root/reference/src/lib/read_info.rs (ReadInfo: unclipped 5' positions of both ends,
lower coordinate first, with unmapped sentinels; library from the RG->LB header map).
"""

from dataclasses import dataclass, field
from typing import Optional

from ..io.bam import (FLAG_FIRST, FLAG_LAST, FLAG_PAIRED, FLAG_REVERSE,
                      FLAG_SECONDARY, FLAG_SUPPLEMENTARY, FLAG_UNMAPPED, RawRecord)

# Sentinels for unmapped ends (read_info.rs: unmapped reads sort after mapped).
UNKNOWN_REF = 2**31 - 1
UNKNOWN_POS = 2**31 - 1
UNKNOWN_STRAND = 2


@dataclass
class Template:
    """All records sharing one QNAME."""

    name: bytes
    r1: Optional[RawRecord] = None
    r2: Optional[RawRecord] = None
    fragment: Optional[RawRecord] = None
    other: list = field(default_factory=list)  # secondary/supplementary
    mi: object = None  # MoleculeId set by group

    def primary_records(self):
        return [r for r in (self.fragment, self.r1, self.r2) if r is not None]

    def all_records(self):
        return self.primary_records() + self.other

    @property
    def primary_r1(self):
        """The primary first-of-pair read, or the fragment read (template.rs r1 role)."""
        return self.r1 if self.r1 is not None else self.fragment


def classify(records) -> Template:
    """Build a Template from one QNAME's records."""
    t = Template(name=records[0].name)
    for rec in records:
        flg = rec.flag
        if flg & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY):
            t.other.append(rec)
        elif not flg & FLAG_PAIRED:
            t.fragment = rec
        elif flg & FLAG_FIRST:
            t.r1 = rec
        elif flg & FLAG_LAST:
            t.r2 = rec
        else:
            t.other.append(rec)
    return t


def iter_name_groups(records):
    """Yield (name, [records]) for consecutive records sharing a QNAME."""
    current_name, bucket = None, []
    for rec in records:
        if current_name is not None and rec.name != current_name:
            yield current_name, bucket
            bucket = []
        current_name = rec.name
        bucket.append(rec)
    if bucket:
        yield current_name, bucket


def iter_templates(records):
    """Yield Templates from query-grouped records (consecutive same QNAME)."""
    for _name, bucket in iter_name_groups(records):
        yield classify(bucket)


def unclipped_5prime(rec: RawRecord) -> int:
    """Unclipped 5' position: unclipped start for forward, unclipped end for reverse."""
    if rec.flag & FLAG_REVERSE:
        return rec.unclipped_end()
    return rec.unclipped_start()


def is_r1_genomically_earlier(r1: RawRecord, r2: RawRecord) -> bool:
    """commands/common.rs:1086-1100: ref, then unclipped 5', then forward-first."""
    if r1.ref_id != r2.ref_id:
        return r1.ref_id < r2.ref_id
    p1, p2 = unclipped_5prime(r1), unclipped_5prime(r2)
    if p1 != p2:
        return p1 < p2
    return not r1.flag & FLAG_REVERSE


def _end_info(rec: RawRecord):
    return (rec.ref_id, unclipped_5prime(rec), 1 if rec.flag & FLAG_REVERSE else 0)


def read_info_key(template: Template, library: str):
    """Position-group key (ReadInfo, read_info.rs:247-360): library + the two ends'
    (ref, unclipped 5' pos, strand), lower coordinate first; unmapped ends use
    sentinels that sort after mapped."""
    r1, r2 = template.r1, template.r2
    if r1 is None and r2 is None:
        r1 = template.fragment
    unknown = (UNKNOWN_REF, UNKNOWN_POS, UNKNOWN_STRAND)

    def mapped(r):
        return r is not None and not r.flag & FLAG_UNMAPPED

    if r1 is not None and r2 is not None:
        m1, m2 = mapped(r1), mapped(r2)
        if not m1 and not m2:
            a = b = unknown
        elif m1 and not m2:
            a, b = _end_info(r1), unknown
        elif m2 and not m1:
            a, b = _end_info(r2), unknown
        else:
            e1, e2 = _end_info(r1), _end_info(r2)
            a, b = (e1, e2) if e1 <= e2 else (e2, e1)
    elif r1 is not None or r2 is not None:
        r = r1 if r1 is not None else r2
        a, b = (_end_info(r), unknown) if mapped(r) else (unknown, unknown)
    else:
        a = b = unknown
    return (library, *a, *b)


def _hd_fields(header_text: str) -> dict:
    for line in header_text.splitlines():
        if line.startswith("@HD"):
            return dict(f.split(":", 1) for f in line.split("\t")[1:] if ":" in f)
    return {}


def is_template_coordinate_sorted(header_text: str) -> bool:
    """@HD advertises SS:...template-coordinate (sam.rs is_template_coordinate_sorted)."""
    ss = _hd_fields(header_text).get("SS", "")
    return ss.split(":")[-1] == "template-coordinate"


def is_query_grouped(header_text: str) -> bool:
    """@HD advertises GO:query or SO:queryname (sam.rs is_query_grouped)."""
    hd = _hd_fields(header_text)
    return hd.get("GO") == "query" or hd.get("SO") == "queryname"


def library_lookup_from_header(header_text: str) -> dict:
    """RG id -> LB library name from @RG lines (read_info.rs:63-77); missing LB
    maps to 'unknown'."""
    lookup = {}
    for line in header_text.splitlines():
        if not line.startswith("@RG"):
            continue
        fields = dict(f.split(":", 1) for f in line.split("\t")[1:] if ":" in f)
        if "ID" in fields:
            lookup[fields["ID"]] = fields.get("LB", "unknown")
    return lookup
