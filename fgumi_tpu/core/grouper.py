"""Streaming group formation.

MiGrouper mirrors the reference's MI-tag streaming grouper
(/root/reference/src/lib/mi_group.rs:54-336): consecutive records sharing an MI tag
form one group; groups are yielded in input order and batched for device efficiency.
"""


def iter_mi_groups(records, tag: bytes = b"MI"):
    """Yield (mi_value, [RawRecord]) for consecutive records sharing the tag.

    Records missing the tag raise — simplex input must be grouped (mi_group.rs
    contract; the reference errors likewise on missing MI).
    """
    current_mi = None
    current = []
    for rec in records:
        mi = rec.get_str(tag)
        if mi is None:
            raise ValueError(
                f"record {rec.name!r} missing {tag.decode()} tag; run `group` first"
            )
        if mi != current_mi:
            if current:
                yield current_mi, current
            current_mi = mi
            current = [rec]
        else:
            current.append(rec)
    if current:
        yield current_mi, current


def iter_mi_group_batches(records, batch_size: int = 500, tag: bytes = b"MI"):
    """Yield lists of (mi, records) of ~batch_size groups (MiGroupBatch analog)."""
    batch = []
    for group in iter_mi_groups(records, tag):
        batch.append(group)
        if len(batch) >= batch_size:
            yield batch
            batch = []
    if batch:
        yield batch
