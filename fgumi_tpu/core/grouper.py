"""Streaming group formation.

MiGrouper mirrors the reference's MI-tag streaming grouper
(/root/reference/src/lib/mi_group.rs:54-336): consecutive records sharing an MI tag
form one group; groups are yielded in input order and batched for device efficiency.
"""


from ..io.bam import (FLAG_MATE_UNMAPPED, FLAG_PAIRED, FLAG_SECONDARY,
                      FLAG_SUPPLEMENTARY, FLAG_UNMAPPED)


def consensus_pregroup_keep(flag: int, allow_unmapped: bool = False) -> bool:
    """fgbio's ConsensusCallingIterator pre-group filter
    (/root/reference/src/lib/commands/common.rs:259-273): always drop
    secondary/supplementary; drop unmapped-without-mapped-mate unless allowed."""
    if flag & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY):
        return False
    if allow_unmapped:
        return True
    is_mapped = not flag & FLAG_UNMAPPED
    has_mapped_mate = bool(flag & FLAG_PAIRED) and not flag & FLAG_MATE_UNMAPPED
    return is_mapped or has_mapped_mate


def iter_mi_groups(records, tag: bytes = b"MI", record_filter=None):
    """Yield (mi_value, [RawRecord]) for consecutive records sharing the tag.

    Records missing the tag raise — simplex input must be grouped (mi_group.rs
    contract; the reference errors likewise on missing MI).
    """
    current_mi = None
    current = []
    for rec in records:
        if record_filter is not None and not record_filter(rec):
            continue
        mi = rec.get_str(tag)
        if mi is None:
            raise ValueError(
                f"record {rec.name!r} missing {tag.decode()} tag; run `group` first"
            )
        if mi != current_mi:
            if current:
                yield current_mi, current
            current_mi = mi
            current = [rec]
        else:
            current.append(rec)
    if current:
        yield current_mi, current


def iter_mi_group_batches(records, batch_size: int = 500, tag: bytes = b"MI",
                          record_filter=None):
    """Yield lists of (mi, records) of ~batch_size groups (MiGroupBatch analog)."""
    batch = []
    for group in iter_mi_groups(records, tag, record_filter):
        batch.append(group)
        if len(batch) >= batch_size:
            yield batch
            batch = []
    if batch:
        yield batch
