"""Per-base consensus tag reversal for negative-strand reads.

When a consensus read maps to the negative strand, the aligner reverses its
bases/quals but not its per-base consensus tags; this module re-aligns them
(reference: /root/reference/src/lib/tag_reversal.rs:1-70).

- Reversed element-wise: B-arrays ``cd ce ad ae bd be`` and Z-strings
  ``aq bq`` (Phred+33 strings).
- Reverse-complemented: Z-strings ``ac bc`` (single-strand consensus bases).
"""

import struct

from ..constants import reverse_complement_bytes
from ..io.bam import FLAG_REVERSE, RawRecord, _ARRAY_DTYPES, _TAG_SIZES

import numpy as np

TAGS_TO_REVERSE = (b"cd", b"ce", b"ad", b"ae", b"bd", b"be", b"aq", b"bq")
TAGS_TO_REVERSE_COMPLEMENT = (b"ac", b"bc")


def reverse_tag_value_at(buf: bytearray, typ: int, off: int):
    """Reverse one aux tag value in place given its type byte and value offset
    (B-arrays element-wise, Z-strings byte-wise)."""
    if typ == ord("B"):
        sub = buf[off]
        (count,) = struct.unpack_from("<I", bytes(buf[off + 1:off + 5]))
        esize = _TAG_SIZES[sub]
        start = off + 5
        arr = np.frombuffer(bytes(buf[start:start + count * esize]),
                            dtype=_ARRAY_DTYPES[sub])
        buf[start:start + count * esize] = arr[::-1].tobytes()
    elif typ == ord("Z"):
        end = buf.index(b"\x00", off)
        buf[off:end] = bytes(buf[off:end])[::-1]


def revcomp_tag_value_at(buf: bytearray, typ: int, off: int):
    """Reverse-complement one Z-string aux tag value in place."""
    if typ == ord("Z"):
        end = buf.index(b"\x00", off)
        buf[off:end] = reverse_complement_bytes(bytes(buf[off:end]))


def reverse_tag_in_place(buf: bytearray, tag: bytes):
    """Find `tag` and reverse its value in place (first occurrence)."""
    for t_, typ, off in RawRecord(bytes(buf))._iter_tags():
        if t_ == tag:
            reverse_tag_value_at(buf, typ, off)
            return


def revcomp_tag_in_place(buf: bytearray, tag: bytes):
    """Find `tag` (Z string) and reverse-complement its value in place."""
    for t_, typ, off in RawRecord(bytes(buf))._iter_tags():
        if t_ == tag:
            revcomp_tag_value_at(buf, typ, off)
            return


def reverse_per_base_tags(buf: bytearray) -> bool:
    """Reverse/revcomp per-base tags in place; returns True if on reverse strand."""
    rec = RawRecord(bytes(buf))
    if not rec.flag & FLAG_REVERSE:
        return False
    for tag, typ, off in rec._iter_tags():
        if tag in TAGS_TO_REVERSE:
            reverse_tag_value_at(buf, typ, off)
        elif tag in TAGS_TO_REVERSE_COMPLEMENT:
            revcomp_tag_value_at(buf, typ, off)
    return True
