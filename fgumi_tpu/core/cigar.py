"""CIGAR utilities for consensus calling.

Semantics mirror the reference:
- simplify: S/=/X/H -> M, coalesce adjacent same ops
  (/root/reference/crates/fgumi-raw-bam/src/noodles_compat.rs:10-55)
- prefix compatibility (/root/reference/crates/fgumi-sam/src/clipper.rs:2705-2728)
- truncate-to-query-length (vanilla_caller.rs:893-927)

Simplified CIGARs are lists of (op_char, length) with ops from "MIDNP".
"""

_CONSUMES_QUERY = frozenset("MIS=X")


def simplify(cigar):
    """S/=/X/H become M; adjacent equal ops coalesce."""
    out = []
    for op, length in cigar:
        if op in "S=XH":
            op = "M"
        if out and out[-1][0] == op:
            out[-1] = (op, out[-1][1] + length)
        else:
            out.append((op, length))
    return out


def reverse(cigar):
    return list(reversed(cigar))


def truncate_to_query_length(cigar, query_length: int):
    """Keep ops until `query_length` query bases are consumed (clipper semantics)."""
    out = []
    remaining = query_length
    for op, length in cigar:
        if remaining == 0:
            break
        if op in _CONSUMES_QUERY:
            take = min(length, remaining)
            out.append((op, take))
            remaining -= take
        else:
            out.append((op, length))
    return out


def is_prefix(a, b) -> bool:
    """True if simplified CIGAR `a` is a prefix of `b`.

    All ops must match; interior lengths exactly, the last op of `a` may be shorter.
    """
    if len(a) > len(b):
        return False
    last = len(a) - 1
    for i, (op_a, len_a) in enumerate(a):
        op_b, len_b = b[i]
        if op_a != op_b:
            return False
        if i == last:
            if len_a > len_b:
                return False
        elif len_a != len_b:
            return False
    return True


_OP_ORDER = {"M": 0, "I": 1, "D": 2, "N": 3, "S": 4, "H": 5, "P": 6, "=": 7, "X": 8}


def compare(a, b) -> int:
    """Deterministic CIGAR ordering for tie-breaks (vanilla_caller.rs:79-111).

    Element-by-element: length first, then op rank; all-equal prefix -> shorter wins.
    """
    for (op_a, len_a), (op_b, len_b) in zip(a, b):
        if len_a != len_b:
            return -1 if len_a < len_b else 1
        ra, rb = _OP_ORDER[op_a], _OP_ORDER[op_b]
        if ra != rb:
            return -1 if ra < rb else 1
    if len(a) != len(b):
        return -1 if len(a) < len(b) else 1
    return 0


def select_most_common_alignment_group(indexed):
    """fgbio's filterToMostCommonAlignment core (vanilla_caller.rs:50-122).

    Args:
      indexed: [(original_index, length, simplified_cigar)] sorted by DESCENDING length.
    Returns the indices of the winning compatibility group.
    """
    if len(indexed) < 2:
        return [idx for idx, _, _ in indexed]

    groups = []  # (group_cigar, [indices])
    for idx, _length, cig in indexed:
        found = False
        for group_cigar, indices in groups:
            # a read joins every group whose cigar it prefixes (no break — fgbio quirk)
            if is_prefix(cig, group_cigar):
                indices.append(idx)
                found = True
        if not found:
            groups.append((cig, [idx]))

    # larger group wins; tie -> smaller CIGAR wins
    best = None
    for group_cigar, indices in groups:
        if best is None:
            best = (group_cigar, indices)
            continue
        if len(indices) > len(best[1]) or (
            len(indices) == len(best[1]) and compare(group_cigar, best[0]) < 0
        ):
            best = (group_cigar, indices)
    return best[1] if best else []
