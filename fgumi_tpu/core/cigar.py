"""CIGAR utilities for consensus calling.

Semantics mirror the reference:
- simplify: S/=/X/H -> M, coalesce adjacent same ops
  (/root/reference/crates/fgumi-raw-bam/src/noodles_compat.rs:10-55)
- prefix compatibility (/root/reference/crates/fgumi-sam/src/clipper.rs:2705-2728)
- truncate-to-query-length (vanilla_caller.rs:893-927)

Simplified CIGARs are lists of (op_char, length) with ops from "MIDNP".
"""

_CONSUMES_QUERY = frozenset("MIS=X")
_CONSUMES_READ = frozenset("MI=X")  # post-clip-strip read consumption (no S)
_CONSUMES_REF = frozenset("MDN=X")


def reference_length(cigar) -> int:
    """Reference bases consumed (crates/fgumi-raw-bam/src/cigar.rs:137)."""
    return sum(n for op, n in cigar if op in _CONSUMES_REF)


def _end_clips(cigar, from_start: bool):
    """(existing_hard, existing_soft, n_clip_ops) at one end, H outside S."""
    ops = cigar if from_start else list(reversed(cigar))
    hard = soft = skip = 0
    for op, n in ops:
        if op == "H":
            hard += n
            skip += 1
        else:
            break
    for op, n in ops[skip:]:
        if op == "S":
            soft += n
            skip += 1
        else:
            break
    return hard, soft, skip


def clip_cigar(cigar, clip_amount: int, from_start: bool):
    """Virtual hard-clip of `clip_amount` query bases from one end.

    Returns (new_cigar, ref_bases_consumed); ref_bases_consumed adjusts
    alignment_start for start clips. Mirrors clip_cigar_ops_raw
    (crates/fgumi-raw-bam/src/cigar.rs:404-446): existing S+H at the end absorb
    the clip first (soft upgraded to hard); the remainder clips into the
    alignment, splitting ops, swallowing boundary insertions whole, and
    skipping a deletion that abuts the clip point.
    """
    if clip_amount == 0 or not cigar:
        return list(cigar), 0

    hard, soft, skip = _end_clips(cigar, from_start)
    if clip_amount <= hard + soft:
        # upgrade soft clips to hard, no alignment change (cigar.rs:669-745)
        upgrade = min(soft, max(clip_amount - hard, 0))
        new_hard = hard + upgrade
        remaining_soft = soft - upgrade
        inner = cigar[skip:] if from_start else cigar[: len(cigar) - skip]
        if from_start:
            out = [("H", new_hard)]
            if remaining_soft:
                out.append(("S", remaining_soft))
            out.extend(inner)
        else:
            out = list(inner)
            if remaining_soft:
                out.append(("S", remaining_soft))
            out.append(("H", new_hard))
        return out, 0

    alignment_clip = clip_amount - (hard + soft)
    inner = cigar[skip:] if from_start else cigar[: len(cigar) - skip]
    if not from_start:
        inner = list(reversed(inner))

    read_clipped = 0
    ref_clipped = 0
    new_ops = []
    idx = 0
    while idx < len(inner):
        op, n = inner[idx]
        if read_clipped == alignment_clip and not new_ops and op == "D":
            ref_clipped += n
            idx += 1
            continue
        if read_clipped >= alignment_clip:
            break
        is_read = op in _CONSUMES_READ
        is_ref = op in _CONSUMES_REF
        if is_read and n > alignment_clip - read_clipped:
            if op == "I":
                read_clipped += n  # swallow boundary insertion whole
            else:
                take = alignment_clip - read_clipped
                read_clipped += take
                if is_ref:
                    ref_clipped += take
                new_ops.append((op, n - take))
        else:
            if is_read:
                read_clipped += n
            if is_ref:
                ref_clipped += n
        idx += 1
    new_ops.extend(inner[idx:])

    total_hard = hard + soft + read_clipped
    if from_start:
        out = [("H", total_hard)] + new_ops
        return out, ref_clipped
    out = list(reversed(new_ops)) + [("H", total_hard)]
    return out, 0  # end clips never shift alignment_start


def read_pos_at_ref_pos(cigar, alignment_start: int, ref_pos: int,
                        last_if_deleted: bool):
    """1-based query position at 1-based `ref_pos`, or None.

    Mirrors read_pos_at_ref_pos_raw (crates/fgumi-raw-bam/src/cigar.rs:461-506):
    None outside the alignment; inside a deletion returns the last query
    position before it when `last_if_deleted`, else None.
    """
    if ref_pos < alignment_start:
        return None
    ref_off = 0
    query_off = 0
    for op, n in cigar:
        consumes_ref = op in _CONSUMES_REF
        op_ref_start = alignment_start + ref_off
        if consumes_ref:
            op_ref_end = op_ref_start + n - 1
            if op_ref_start <= ref_pos <= op_ref_end:
                if op in _CONSUMES_QUERY:
                    return query_off + (ref_pos - op_ref_start) + 1
                if last_if_deleted:
                    return query_off if query_off > 0 else 1
                return None
            ref_off += n
        if op in _CONSUMES_QUERY:
            query_off += n
    return None


def simplify(cigar):
    """S/=/X/H become M; adjacent equal ops coalesce."""
    out = []
    for op, length in cigar:
        if op in "S=XH":
            op = "M"
        if out and out[-1][0] == op:
            out[-1] = (op, out[-1][1] + length)
        else:
            out.append((op, length))
    return out


def reverse(cigar):
    return list(reversed(cigar))


def truncate_to_query_length(cigar, query_length: int):
    """Keep ops until `query_length` query bases are consumed (clipper semantics)."""
    out = []
    remaining = query_length
    for op, length in cigar:
        if remaining == 0:
            break
        if op in _CONSUMES_QUERY:
            take = min(length, remaining)
            out.append((op, take))
            remaining -= take
        else:
            out.append((op, length))
    return out


def is_prefix(a, b) -> bool:
    """True if simplified CIGAR `a` is a prefix of `b`.

    All ops must match; interior lengths exactly, the last op of `a` may be shorter.
    """
    if len(a) > len(b):
        return False
    last = len(a) - 1
    for i, (op_a, len_a) in enumerate(a):
        op_b, len_b = b[i]
        if op_a != op_b:
            return False
        if i == last:
            if len_a > len_b:
                return False
        elif len_a != len_b:
            return False
    return True


_OP_ORDER = {"M": 0, "I": 1, "D": 2, "N": 3, "S": 4, "H": 5, "P": 6, "=": 7, "X": 8}


def compare(a, b) -> int:
    """Deterministic CIGAR ordering for tie-breaks (vanilla_caller.rs:79-111).

    Element-by-element: length first, then op rank; all-equal prefix -> shorter wins.
    """
    for (op_a, len_a), (op_b, len_b) in zip(a, b):
        if len_a != len_b:
            return -1 if len_a < len_b else 1
        ra, rb = _OP_ORDER[op_a], _OP_ORDER[op_b]
        if ra != rb:
            return -1 if ra < rb else 1
    if len(a) != len(b):
        return -1 if len(a) < len(b) else 1
    return 0


def select_most_common_alignment_group(indexed):
    """fgbio's filterToMostCommonAlignment core (vanilla_caller.rs:50-122).

    Args:
      indexed: [(original_index, length, simplified_cigar)] sorted by DESCENDING length.
    Returns the indices of the winning compatibility group.
    """
    if len(indexed) < 2:
        return [idx for idx, _, _ in indexed]

    groups = []  # (group_cigar, [indices])
    for idx, _length, cig in indexed:
        found = False
        for group_cigar, indices in groups:
            # a read joins every group whose cigar it prefixes (no break — fgbio quirk)
            if is_prefix(cig, group_cigar):
                indices.append(idx)
                found = True
        if not found:
            groups.append((cig, [idx]))

    # larger group wins; tie -> smaller CIGAR wins
    best = None
    for group_cigar, indices in groups:
        if best is None:
            best = (group_cigar, indices)
            continue
        if len(indices) > len(best[1]) or (
            len(indices) == len(best[1]) and compare(group_cigar, best[0]) < 0
        ):
            best = (group_cigar, indices)
    return best[1] if best else []
