"""Whole-genome-in-RAM FASTA reader via the FAI index.

Mirrors /root/reference/src/lib/reference.rs: reads the .fai (name, length,
offset, linebases, linewidth), slurps each contig's raw bytes stripping
newlines, and serves uppercase slices with zero per-fetch allocation beyond
the returned bytes.
"""

import os


class ReferenceReader:
    """FAI-indexed FASTA with every contig held in RAM (reference.rs:182-290)."""

    def __init__(self, fasta_path: str):
        fai_path = fasta_path + ".fai"
        if not os.path.exists(fai_path):
            _write_fai(fasta_path, fai_path)
        entries = []
        with open(fai_path) as f:
            for line in f:
                parts = line.rstrip("\n").split("\t")
                if len(parts) < 5:
                    continue
                entries.append((parts[0], int(parts[1]), int(parts[2]),
                                int(parts[3]), int(parts[4])))
        self._seqs = {}
        with open(fasta_path, "rb") as f:
            data = f.read()
        for name, length, offset, linebases, linewidth in entries:
            if linebases == linewidth or length == 0:
                raw = data[offset:offset + length]
            else:
                n_full = length // linebases
                span = n_full * linewidth + (length - n_full * linebases)
                raw = data[offset:offset + span].replace(b"\n", b"").replace(b"\r", b"")
            self._seqs[name] = raw.upper()

    def contigs(self):
        return list(self._seqs)

    def get(self, chrom: str):
        """Full contig bytes, or None (dict-like access for consensus callers)."""
        return self._seqs.get(chrom)

    def fetch(self, chrom: str, start: int, end: int) -> bytes:
        """Uppercase bases for 0-based half-open [start, end)."""
        seq = self._seqs.get(chrom)
        if seq is None:
            raise KeyError(f"contig {chrom!r} not in reference")
        if start < 0 or end > len(seq):
            raise ValueError(
                f"fetch [{start}, {end}) out of bounds for {chrom} "
                f"(length {len(seq)})")
        return seq[start:end]


def _write_fai(fasta_path: str, fai_path: str):
    """Generate a .fai for a well-formed FASTA (uniform line lengths)."""
    entries = []
    with open(fasta_path, "rb") as f:
        name = None
        length = 0
        offset = 0
        linebases = linewidth = 0
        pos = 0
        for line in f:
            if line.startswith(b">"):
                if name is not None:
                    entries.append((name, length, offset, linebases, linewidth))
                name = line[1:].split()[0].decode()
                pos += len(line)
                offset = pos
                length = 0
                linebases = linewidth = 0
            else:
                stripped = line.rstrip(b"\r\n")
                if stripped and linebases == 0:
                    linebases = len(stripped)
                    linewidth = len(line)
                length += len(stripped)
                pos += len(line)
        if name is not None:
            entries.append((name, length, offset, linebases, linewidth))
    with open(fai_path, "w") as f:
        for name, length, offset, linebases, linewidth in entries:
            f.write(f"{name}\t{length}\t{offset}\t{linebases}\t{linewidth}\n")


def write_fasta(path: str, contigs: dict, line_width: int = 60):
    """Write a FASTA (+ .fai) from {name: bytes}; test/simulate helper."""
    with open(path, "w") as f:
        for name, seq in contigs.items():
            f.write(f">{name}\n")
            s = seq.decode() if isinstance(seq, (bytes, bytearray)) else seq
            for i in range(0, len(s), line_width):
                f.write(s[i:i + line_width] + "\n")
    _write_fai(path, path + ".fai")
