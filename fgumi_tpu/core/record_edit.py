"""In-place edits on raw BAM record bytes (bytearray).

Python analog of the reference's raw-record mutators
(/root/reference/crates/fgumi-raw-bam: set_* fixed-offset writers, remove_tag /
update_*_tag aux TLV editing, reg2bin). All functions take the record's wire
bytes as a bytearray (no block_size prefix) and edit in place where the
layout permits, or return the replacement bytearray when the length changes.
"""

import struct

from ..io.bam import RawRecord, _reg2bin, _skip_tag_value


def set_flags(buf: bytearray, flags: int):
    buf[14:16] = struct.pack("<H", flags)


def set_ref_id(buf: bytearray, ref_id: int):
    buf[0:4] = struct.pack("<i", ref_id)


def set_pos(buf: bytearray, pos: int):
    buf[4:8] = struct.pack("<i", pos)


def set_mate_ref_id(buf: bytearray, ref_id: int):
    buf[20:24] = struct.pack("<i", ref_id)


def set_mate_pos(buf: bytearray, pos: int):
    buf[24:28] = struct.pack("<i", pos)


def set_tlen(buf: bytearray, tlen: int):
    buf[28:32] = struct.pack("<i", tlen)


def set_bin(buf: bytearray):
    """Recompute the BAM bin from pos + reference length."""
    rec = RawRecord(bytes(buf))
    pos = rec.pos
    if pos < 0:
        b = _reg2bin(-1, 0)
    else:
        ref_len = rec.reference_length() or 1
        b = _reg2bin(pos, pos + ref_len)
    buf[10:12] = struct.pack("<H", b)


def cigar_string(rec: RawRecord) -> str:
    ops = rec.cigar()
    if not ops:
        return "*"
    return "".join(f"{n}{op}" for op, n in ops)


def remove_tag(buf: bytearray, tag: bytes):
    """Remove every occurrence of an aux tag; edits in place."""
    remove_tags(buf, (tag,))


def remove_tags(buf: bytearray, tags):
    """Remove every occurrence of each tag in `tags` in one aux scan."""
    rec = RawRecord(bytes(buf))
    spans = []
    for t, typ, off in rec._iter_tags():
        if t in tags:
            spans.append((off - 3, _skip_tag_value(rec.data, typ, off)))
    for start, end in reversed(spans):
        del buf[start:end]


def append_tag_i32(buf: bytearray, tag: bytes, value: int):
    buf += tag + b"i" + struct.pack("<i", value)


def update_tag_i32(buf: bytearray, tag: bytes, value: int):
    remove_tag(buf, tag)
    append_tag_i32(buf, tag, value)


def update_tag_str(buf: bytearray, tag: bytes, value: bytes):
    remove_tag(buf, tag)
    buf += tag + b"Z" + value + b"\x00"


def append_tag_i32_array(buf: bytearray, tag: bytes, values):
    buf += tag + b"Bi" + struct.pack("<I", len(values))
    for v in values:
        buf += struct.pack("<i", v)


def normalize_int_tag_to_smallest_signed(buf: bytearray, tag: bytes):
    """Rewrite an integer tag using the smallest signed type that holds it
    (zipper.rs step 5; matches fgbio's AS/XS normalization)."""
    rec = RawRecord(bytes(buf))
    got = rec.find_tag(tag)
    if got is None or got[0] not in "cCsSiI":
        return
    value = int(got[1])
    if not -(2**31) <= value < 2**31:
        # out of i32 range: leave the tag unchanged (tags.rs:995-997)
        return
    remove_tag(buf, tag)
    if -128 <= value <= 127:
        buf += tag + b"c" + struct.pack("<b", value)
    elif -32768 <= value <= 32767:
        buf += tag + b"s" + struct.pack("<h", value)
    else:
        buf += tag + b"i" + struct.pack("<i", value)


def raw_tag_entries(rec: RawRecord):
    """[(tag, type_byte, value_bytes)] for every aux tag, pre-encoded."""
    out = []
    for tag, typ, off in rec._iter_tags():
        end = _skip_tag_value(rec.data, typ, off)
        out.append((tag, typ, rec.data[off:end]))
    return out


def append_raw_tag_entry(buf: bytearray, entry):
    tag, typ, value_bytes = entry
    buf += tag
    buf.append(typ)
    buf += value_bytes
