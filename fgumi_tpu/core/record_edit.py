"""In-place edits on raw BAM record bytes (bytearray).

Python analog of the reference's raw-record mutators
(/root/reference/crates/fgumi-raw-bam: set_* fixed-offset writers, remove_tag /
update_*_tag aux TLV editing, reg2bin). All functions take the record's wire
bytes as a bytearray (no block_size prefix) and edit in place where the
layout permits, or return the replacement bytearray when the length changes.
"""

import struct

from ..io.bam import (RawRecord, _read_tag_value, _reg2bin,
                      _skip_tag_value)


def set_flags(buf: bytearray, flags: int):
    buf[14:16] = struct.pack("<H", flags)


def set_ref_id(buf: bytearray, ref_id: int):
    buf[0:4] = struct.pack("<i", ref_id)


def set_pos(buf: bytearray, pos: int):
    buf[4:8] = struct.pack("<i", pos)


def set_mate_ref_id(buf: bytearray, ref_id: int):
    buf[20:24] = struct.pack("<i", ref_id)


def set_mate_pos(buf: bytearray, pos: int):
    buf[24:28] = struct.pack("<i", pos)


def set_tlen(buf: bytearray, tlen: int):
    buf[28:32] = struct.pack("<i", tlen)


def set_bin(buf: bytearray):
    """Recompute the BAM bin from pos + reference length."""
    rec = RawRecord(bytes(buf))
    pos = rec.pos
    if pos < 0:
        b = _reg2bin(-1, 0)
    else:
        ref_len = rec.reference_length() or 1
        b = _reg2bin(pos, pos + ref_len)
    buf[10:12] = struct.pack("<H", b)


def cigar_string(rec: RawRecord) -> str:
    ops = rec.cigar()
    if not ops:
        return "*"
    return "".join(f"{n}{op}" for op, n in ops)


def raw_tag_entries(rec: RawRecord):
    """[(tag, type_byte, value_bytes)] for every aux tag, pre-encoded."""
    out = []
    for tag, typ, off in rec._iter_tags():
        end = _skip_tag_value(rec.data, typ, off)
        out.append((tag, typ, rec.data[off:end]))
    return out


class TagEditor:
    """Single-pass aux-tag editor for one record's wire bytes.

    The TLV region parses once; removals and updates stage against the
    parsed entries plus staged appends, and finish() rebuilds the record in
    one concatenation — replacing chains of per-helper full-region scans
    (each remove_tag/update_* call above walks the whole aux region).
    Fixed-field edits keep going directly to the underlying bytearray; the
    prefix (header/name/cigar/seq/qual) is copied verbatim at finish time.

    Ordering semantics match the in-place helpers exactly: removals drop
    every original occurrence, updates re-append at the end, and find()
    returns the first surviving original, else the first staged append —
    what find_tag would see on the rebuilt record.
    """

    __slots__ = ("buf", "aux0", "entries", "_removed", "_appends")

    def __init__(self, buf: bytearray):
        self.buf = buf
        l_read_name = buf[8]
        n_cigar = int.from_bytes(buf[12:14], "little")
        l_seq = int.from_bytes(buf[16:20], "little")
        self.aux0 = 32 + l_read_name + 4 * n_cigar + (l_seq + 1) // 2 + l_seq
        entries = []
        off = self.aux0
        end = len(buf)
        while off + 3 <= end:
            tag = bytes(buf[off:off + 2])
            typ = buf[off + 2]
            nxt = _skip_tag_value(buf, typ, off + 3)
            entries.append((tag, typ, off, nxt))
            off = nxt
        self.entries = entries
        self._removed = set()
        self._appends = []  # (tag, typ_byte, value_bytes)

    def find(self, tag: bytes):
        """(type_char, python value) like RawRecord.find_tag, or None."""
        for t, typ, off, _nxt in self.entries:
            if t == tag and t not in self._removed:
                return chr(typ), _read_tag_value(self.buf, typ, off + 3)
        for t, typ, vb in self._appends:
            if t == tag:
                return chr(typ), _read_tag_value(vb, typ, 0)
        return None

    def get_int(self, tag: bytes):
        got = self.find(tag)
        if got is None or got[0] not in "cCsSiI":
            return None
        return int(got[1])

    def remove(self, tag: bytes):
        self._removed.add(tag)
        self._appends = [a for a in self._appends if a[0] != tag]

    def append_entry(self, tag: bytes, typ: int, value_bytes: bytes):
        self._appends.append((tag, typ, value_bytes))

    def set_i32(self, tag: bytes, value: int):
        self.remove(tag)
        self.append_entry(tag, ord("i"), struct.pack("<i", value))

    def set_str(self, tag: bytes, value: bytes):
        self.remove(tag)
        self.append_entry(tag, ord("Z"), value + b"\x00")

    def set_i32_array(self, tag: bytes, values):
        self.remove(tag)
        self.append_entry(
            tag, ord("B"),
            b"i" + struct.pack("<I", len(values))
            + b"".join(struct.pack("<i", v) for v in values))

    def normalize_int_smallest(self, tag: bytes):
        """AS/XS smallest-signed-type normalization: the tag is always
        removed and re-appended at the end, even when already smallest
        (reference tags.rs:995-1001 removes + re-appends unconditionally,
        so tag ORDER must shift too)."""
        got = self.find(tag)
        if got is None or got[0] not in "cCsSiI":
            return
        value = int(got[1])
        if not -(2**31) <= value < 2**31:
            return
        self.remove(tag)
        if -128 <= value <= 127:
            self.append_entry(tag, ord("c"), struct.pack("<b", value))
        elif -32768 <= value <= 32767:
            self.append_entry(tag, ord("s"), struct.pack("<h", value))
        else:
            self.append_entry(tag, ord("i"), struct.pack("<i", value))

    def finish(self) -> bytes:
        buf = self.buf
        parts = [bytes(buf[:self.aux0])]
        for tag, typ, off, nxt in self.entries:
            if tag in self._removed:
                continue
            parts.append(bytes(buf[off:nxt]))
        for tag, typ, vb in self._appends:
            parts.append(tag + bytes([typ]) + vb)
        return b"".join(parts)
