"""Mate-overlap math: how many bases a read extends past its FR mate.

Port of the semantics of /root/reference/crates/fgumi-raw-bam/src/overlap.rs:
- is_fr_pair (per-record, htsjdk 5'-position logic, overlap.rs:14-61)
- mate soft-clip boundary from the MC tag (overlap.rs:233-247, 277-345)
- bases extending past the mate boundary via CIGAR walks (overlap.rs:172-231, 362-432)

All positions here are 1-based (matching the reference's internal convention).
"""

from ..io.bam import (FLAG_MATE_REVERSE, FLAG_MATE_UNMAPPED, FLAG_PAIRED,
                      FLAG_REVERSE, FLAG_UNMAPPED, RawRecord)

_CIGAR_OPS = set("MIDNSHP=X")


def parse_soft_clips_and_ref_len(cigar_str: str):
    """(leading_soft, ref_len, trailing_soft) from a CIGAR string, or None if malformed.

    Soft clips must sit at the ends (inside hard clips); hard clips only first/last;
    a CIGAR with no reference-consuming op is invalid (overlap.rs:277-345).
    """
    tokens = []
    num = 0
    have_digits = False
    for c in cigar_str:
        # ASCII digits only: str.isdigit() accepts Unicode digits the reference's
        # is_ascii_digit rejects (and some crash int()); fail closed instead.
        if "0" <= c <= "9":
            num = num * 10 + (ord(c) - 48)
            have_digits = True
            continue
        if not have_digits or num == 0 or c not in _CIGAR_OPS:
            return None
        tokens.append((num, c))
        num = 0
        have_digits = False
    if have_digits or not tokens:
        return None

    last = len(tokens) - 1
    leading_soft = trailing_soft = ref_len = 0
    saw_ref_op = False
    for i, (length, op) in enumerate(tokens):
        if op in "MDN=X":
            ref_len += length
            saw_ref_op = True
        elif op in "IP":
            pass
        elif op == "S":
            leading = all(o == "H" for _, o in tokens[:i])
            trailing = all(o == "H" for _, o in tokens[i + 1:])
            if not leading and not trailing:
                return None
            if saw_ref_op:
                trailing_soft += length
            else:
                leading_soft += length
        elif op == "H" and (i == 0 or i == last):
            pass
        else:
            return None
    if not saw_ref_op:
        return None
    return leading_soft, ref_len, trailing_soft


from .cigar import reference_length as _ref_len_from_cigar  # noqa: E402 (shared impl)


def _read_len_from_cigar(cigar) -> int:
    return sum(n for op, n in cigar if op in "MIS=X")


def _leading_soft(cigar) -> int:
    total = 0
    for op, n in cigar:
        if op == "S":
            total += n
        elif op == "H":
            continue
        else:
            break
    return total


def _trailing_soft(cigar) -> int:
    return _leading_soft(list(reversed(cigar)))


def is_fr_pair(rec: RawRecord) -> bool:
    """Per-record FR-pair classification (overlap.rs:14-61)."""
    flg = rec.flag
    if not flg & FLAG_PAIRED:
        return False
    if flg & FLAG_UNMAPPED or flg & FLAG_MATE_UNMAPPED:
        return False
    if rec.ref_id != rec.next_ref_id:
        return False
    is_reverse = bool(flg & FLAG_REVERSE)
    if is_reverse == bool(flg & FLAG_MATE_REVERSE):
        return False
    start = rec.pos + 1
    mate_start = rec.next_pos + 1
    if is_reverse:
        ref_len = rec.reference_length()
        end = start + max(ref_len - 1, 0)
        positive_5p, negative_5p = mate_start, end
    else:
        positive_5p, negative_5p = start, start + rec.tlen
    return positive_5p < negative_5p


def _read_pos_at_ref(cigar, alignment_start_1based: int, target: int, before: bool) -> int:
    """1-based read position at a reference position; 0 if in deletion/outside.

    before=True returns the count of read bases strictly before the position
    (overlap.rs:362-411).
    """
    ref_pos = alignment_start_1based
    read_pos = 0
    for op, length in cigar:
        if op in "M=X":
            # closed-form version of the reference's per-base walk
            if target < ref_pos:
                return 0
            if target < ref_pos + length:
                read_pos += target - ref_pos + 1
                return max(read_pos - 1, 0) if before else read_pos
            read_pos += length
            ref_pos += length
        elif op in "IS":
            read_pos += length
        elif op in "DN":
            if ref_pos <= target < ref_pos + length:
                return 0
            ref_pos += length
    return 0


def is_primary_fr_pair(a: RawRecord, b: RawRecord) -> bool:
    """Symmetric per-pair FR classification (overlap.rs:76-101).

    Both reads and mates mapped, same reference, opposite strands; FR
    orientation evaluated on the reverse-strand record only (the CIGAR-derived
    branch of is_fr_pair), making the test order-independent for dovetails.
    """
    fa, fb = a.flag, b.flag
    if (fa | fb) & (FLAG_UNMAPPED | FLAG_MATE_UNMAPPED):
        return False
    if a.ref_id != b.ref_id:
        return False
    a_rev = bool(fa & FLAG_REVERSE)
    if a_rev == bool(fb & FLAG_REVERSE):
        return False
    return is_fr_pair(a if a_rev else b)


def _bases_extending_past_mate(rec: RawRecord, mate_unclipped_start: int,
                               mate_unclipped_end: int) -> int:
    """Shared boundary walk (overlap.rs:172-231); boundaries 1-based soft-only."""
    cigar = rec.cigar()
    read_length = _read_len_from_cigar(cigar)
    this_pos = rec.pos + 1
    if rec.flag & FLAG_REVERSE:
        if this_pos <= mate_unclipped_start:
            return _read_pos_at_ref(cigar, this_pos, mate_unclipped_start, before=True)
        gap = max(this_pos - mate_unclipped_start, 0)
        return max(_leading_soft(cigar) - gap, 0)
    alignment_end = this_pos - 1 + _ref_len_from_cigar(cigar)
    if alignment_end >= mate_unclipped_end:
        # bases_past == 0 (boundary in a deletion / outside) clips the whole read,
        # matching the reference's read_length.saturating_sub(0) (overlap.rs:214-217).
        bases_past = _read_pos_at_ref(cigar, this_pos, mate_unclipped_end, before=False)
        return max(read_length - bases_past, 0)
    # Read ends before the mate boundary: only excess trailing soft clip is removed.
    trailing_sc = _trailing_soft(cigar)
    gap = max(mate_unclipped_end - alignment_end, 0)
    return max(trailing_sc - gap, 0)


def num_bases_extending_past_mate(rec: RawRecord) -> int:
    """Bases of `rec` extending past its FR mate's soft-clip boundary, 0 if n/a.

    Requires the MC tag; fails closed to 0 when absent/malformed (overlap.rs:117-140).
    """
    if not is_fr_pair(rec):
        return 0
    mc = rec.get_str(b"MC")
    if mc is None:
        return 0
    parsed = parse_soft_clips_and_ref_len(mc)
    if parsed is None:
        return 0
    leading_soft, ref_len, trailing_soft = parsed
    mate_pos = rec.next_pos + 1
    return _bases_extending_past_mate(
        rec, mate_pos - leading_soft, mate_pos - 1 + ref_len + trailing_soft)


def num_bases_extending_past_mate_vs_mate(rec: RawRecord, mate: RawRecord) -> int:
    """Overlap clip with the mate boundary read from the mate record in hand
    (overlap.rs:156-165), so clipping still happens when MC is absent.

    Used by the CODEC caller (mirrors fgbio updateMateCigars backfill); the
    soft-only boundary comes from the mate's own CIGAR, and FR classification
    uses the symmetric per-pair test.
    """
    if not is_primary_fr_pair(rec, mate):
        return 0
    mate_cigar = mate.cigar()
    mate_pos = mate.pos + 1
    start = mate_pos - _leading_soft(mate_cigar)
    end = mate_pos - 1 + _ref_len_from_cigar(mate_cigar) + _trailing_soft(mate_cigar)
    return _bases_extending_past_mate(rec, start, end)
