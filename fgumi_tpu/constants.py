"""Shared DNA / Phred constants.

Parity contract with the reference implementation (fgumi):
- ``MIN_PHRED``/``NO_CALL_BASE`` mirror /root/reference/crates/fgumi-dna/src/lib.rs:17-24
- ``MAX_PHRED`` mirrors /root/reference/crates/fgumi-consensus/src/phred.rs:28
"""

import numpy as np

# Minimum Phred score emitted on consensus bases (fgbio's convention).
MIN_PHRED = 2
# Maximum Phred score handled (SAMUtils.MAX_PHRED_SCORE).
MAX_PHRED = 93

# No-call base characters.
NO_CALL_BASE = ord("N")
NO_CALL_BASE_LOWER = ord("n")

# Canonical base order used throughout consensus calling: A, C, G, T.
DNA_BASES = np.frombuffer(b"ACGT", dtype=np.uint8)

# Base code used for N / invalid bases in packed code arrays.
N_CODE = 4

# ASCII byte -> base code (0..3 for ACGT upper/lower, 4 for everything else).
# Mirrors BASE_TO_INDEX (/root/reference/crates/fgumi-consensus/src/base_builder.rs:307-318),
# with 4 instead of 255 as the invalid sentinel so packed arrays stay uint8-dense.
BASE_TO_CODE = np.full(256, N_CODE, dtype=np.uint8)
for _i, _b in enumerate(b"ACGT"):
    BASE_TO_CODE[_b] = _i
for _i, _b in enumerate(b"acgt"):
    BASE_TO_CODE[_b] = _i

# Base code -> ASCII byte (A, C, G, T, N).
CODE_TO_BASE = np.frombuffer(b"ACGTN", dtype=np.uint8).copy()

# Complement in code space: A<->T, C<->G, N->N.
CODE_COMPLEMENT = np.array([3, 2, 1, 0, 4], dtype=np.uint8)


def reverse_complement_codes(codes: np.ndarray) -> np.ndarray:
    """Reverse-complement an array of base codes (0..4)."""
    return CODE_COMPLEMENT[codes[::-1]]


def reverse_complement_bytes(seq: bytes) -> bytes:
    """Reverse-complement an ASCII DNA byte string (non-ACGT -> N)."""
    codes = BASE_TO_CODE[np.frombuffer(seq, dtype=np.uint8)]
    return CODE_TO_BASE[CODE_COMPLEMENT[codes[::-1]]].tobytes()
