"""Offline autotuner: sweep, crossover atlas, profile derivation.

``fgumi-tpu tune`` runs a workload matrix built from simulate's family
generators — family-depth distribution (fixed / lognormal / longtail),
read length, filter keep-rate, duplex AB/BA balance — through the SAME
in-process harnesses microbench.py uses: the full-column wire kernel
(pad + 1 B/position dispatch + full resolve) on the forced-device side
and the native f64 host engine on the other. Every wire dispatch feeds
the live :data:`~fgumi_tpu.ops.router.ROUTER` EWMAs through the ordinary
resolve path, so the measured link/overhead/wall priors come from the
production instrumentation, not a parallel stopwatch; host walls are fed
explicitly (a direct engine call bypasses the hybrid route's observer).

Outputs:

- the **crossover atlas** (``TUNE_ATLAS.json`` by default): one cell per
  matrix point with rows/s on each side + the winning route, plus a
  per-(distribution, read-length) crossover depth interpolated where the
  winner flips — schema'd JSON like the MULTICHIP_* artifacts.
- the **deployment profile** (:mod:`.profile`): knobs derived from the
  measured walls (coalesce window from the per-dispatch overhead, feeder
  depth from the wall/overhead ratio, mesh from the visible device
  count) and priors from the post-sweep router snapshot + an elementwise
  combine micro-bench for the two AdaptiveChoosers.

``--replay`` skips the sweep and derives the same artifacts from
recorded evidence instead: run-report ``device.routing`` sections and/or
microbench ``tune_cells`` JSON (the ``--backend`` matrix emits those).
"""

import json
import logging
import os
import time

log = logging.getLogger("fgumi_tpu")

ATLAS_SCHEMA_VERSION = 1

#: (name, family-depth distribution, mean depth, read length, keep rate,
#: duplex AB fraction). The quick subset is the CI-runnable spine: the
#: three family sizes whose device/host crossover the router must price
#: (microbench's bench_full_column cells); the full matrix adds the
#: hostile-distribution and read-length axes ROADMAP item 5 calls out.
QUICK_MATRIX = [
    ("fixed3_L100", "fixed", 3, 100, 0.9, 0.5),
    ("fixed10_L100", "fixed", 10, 100, 0.9, 0.5),
    ("fixed30_L100", "fixed", 30, 100, 0.9, 0.5),
]
FULL_MATRIX = QUICK_MATRIX + [
    ("lognormal5_L100", "lognormal", 5, 100, 0.9, 0.5),
    ("lognormal5_L100_keep30", "lognormal", 5, 100, 0.3, 0.5),
    ("longtail3_L100", "longtail", 3, 100, 0.9, 0.5),
    ("longtail3_L150", "longtail", 3, 150, 0.9, 0.7),
    ("fixed10_L150", "fixed", 10, 150, 0.9, 0.5),
]

#: reads per cell — small on purpose: the sweep measures per-row rates
#: and per-dispatch overheads, both of which converge at modest sizes.
QUICK_ROWS = 6_000
FULL_ROWS = 24_000


def _timeit(fn, repeat=3, warmup=1):
    for _ in range(warmup):
        fn()
    best = float("inf")
    for _ in range(repeat):
        t0 = time.monotonic()
        fn()
        best = min(best, time.monotonic() - t0)
    return best


def _cell_pileup(rng, dist, depth, L, n_rows):
    """Family-consistent reads under one matrix cell's depth distribution
    (shared template + 0.5% errors, like microbench._family_pileup — the
    host engine's saturation economics depend on family consistency)."""
    import numpy as np

    from ..simulate import _family_size

    sizes = []
    total = 0
    while total < n_rows:
        s = _family_size(rng, dist, depth)
        sizes.append(s)
        total += s
    counts = np.asarray(sizes, dtype=np.int64)
    starts = np.zeros(len(counts) + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    rows = int(starts[-1])
    # the wire layout packs 4 positions/byte, so the device path requires
    # L % 4 == 0 — pad the tail with no-op positions (N_CODE, qual 0)
    # exactly like the production dense layout does for e.g. L=150
    L_pad = (L + 3) // 4 * 4
    codes = np.full((rows, L_pad), 4, dtype=np.uint8)  # 4 == N_CODE
    quals = np.zeros((rows, L_pad), dtype=np.uint8)
    for i in range(len(counts)):
        template = rng.integers(0, 4, size=(1, L), dtype=np.uint8)
        codes[starts[i]:starts[i + 1], :L] = template
    err = rng.random((rows, L)) < 0.005
    codes[:, :L][err] = (codes[:, :L][err]
                         + rng.integers(1, 4, size=int(err.sum()))) % 4
    quals[:, :L] = rng.integers(25, 41, size=(rows, L), dtype=np.uint8)
    return codes, quals, counts, starts


def _measure_cell(kernel, host, name, dist, depth, L, keep, duplex_ab,
                  rng, n_rows):
    """One atlas cell: wire vs host rows/s on identical pileups."""
    from ..ops.kernel import pad_segments
    from ..ops.router import ROUTER

    codes, quals, counts, starts = _cell_pileup(rng, dist, depth, L,
                                                n_rows)
    rows = len(codes)
    n_fam = len(counts)

    def wire():
        cd, qd, seg, _st, F = pad_segments(codes, quals, counts)
        t = kernel.device_call_segments_wire(cd, qd, seg, F, n_fam,
                                             full=True)
        kernel.resolve_segments_wire(t, codes, quals, starts)

    dt_wire = _timeit(wire)
    cell = {
        "name": name, "distribution": dist, "mean_depth": depth,
        "read_length": L, "keep_rate": keep, "duplex_ab_fraction":
        duplex_ab, "rows": rows, "families": n_fam,
        "device_rows_per_sec": round(rows / dt_wire, 1),
    }
    if host is not None:
        dt_host = _timeit(lambda: host.call_segments(codes, quals, starts))
        # a direct engine call bypasses the hybrid route's observer —
        # feed the live EWMA the same way the production path would
        # (cells = rows x padded positions, the layout actually walked)
        ROUTER.observe_host(rows * codes.shape[1], dt_host)
        cell["host_rows_per_sec"] = round(rows / dt_host, 1)
        cell["device_vs_host"] = round(dt_host / dt_wire, 3)
        cell["winner"] = "device" if dt_wire <= dt_host else "host"
    else:
        cell["winner"] = "device"
    return cell


def _crossover_depths(cells):
    """Per-(distribution, read-length) crossover depth, interpolated
    (log-linear in depth) between the adjacent cells where the
    device-vs-host winner flips; None when one side wins everywhere."""
    import math

    groups = {}
    for c in cells:
        if not c.get("host_rows_per_sec") or not c.get(
                "device_rows_per_sec"):
            continue
        groups.setdefault((c.get("distribution", "?"),
                           c.get("read_length", 0)), []).append(c)
    out = {}
    for (dist, L), grp in sorted(groups.items()):
        grp.sort(key=lambda c: c.get("mean_depth", 0))
        cross = None
        for a, b in zip(grp, grp[1:]):
            # >1 == device wins (equal rows each side, so the wall ratio
            # is the rows/s ratio; replayed microbench cells carry only
            # the rates)
            ra = a["device_rows_per_sec"] / a["host_rows_per_sec"]
            rb = b["device_rows_per_sec"] / b["host_rows_per_sec"]
            if (ra - 1.0) * (rb - 1.0) < 0:
                la, lb = math.log(a["mean_depth"]), math.log(
                    b["mean_depth"])
                f = (0.0 - math.log(ra)) / (math.log(rb) - math.log(ra))
                cross = round(math.exp(la + f * (lb - la)), 2)
                break
        out[f"{dist}_L{L}"] = {
            "crossover_depth": cross,
            "winner_below": grp[0].get("winner"),
            "winner_above": grp[-1].get("winner"),
            "depths_measured": [c.get("mean_depth") for c in grp],
        }
    return out


def _bench_choosers(quick):
    """Elementwise device-vs-host seconds-per-mcell for the two
    AdaptiveChooser stages. The duplex/CODEC combines are elementwise
    select/min kernels over (candidates, L) arrays; this times a
    representative select+min on each side at a serve-realistic size —
    a proxy for the real stages, measured, and orders of magnitude
    better than the cold alternating probe."""
    import numpy as np

    try:
        import jax
        import jax.numpy as jnp
    except Exception:
        return {}
    n, L = (512, 100) if quick else (4096, 150)
    cells = n * L
    a = np.random.default_rng(5).integers(0, 41, size=(n, L),
                                          dtype=np.uint8)
    b = np.random.default_rng(6).integers(0, 41, size=(n, L),
                                          dtype=np.uint8)

    @jax.jit
    def dev_combine(x, y):
        return jnp.where(x == y, jnp.minimum(x, y) + 3,
                         jnp.maximum(x, y) - jnp.minimum(x, y))

    da, db = jnp.asarray(a), jnp.asarray(b)
    dt_dev = _timeit(lambda: jax.block_until_ready(dev_combine(da, db)))
    dt_host = _timeit(lambda: np.where(
        a == b, np.minimum(a, b) + 3,
        np.maximum(a, b) - np.minimum(a, b)))
    pair = {"device_s_per_mcell": round(dt_dev / cells * 1e6, 6),
            "host_s_per_mcell": round(dt_host / cells * 1e6, 6)}
    return {"duplex_combine": dict(pair), "codec_combine": dict(pair)}


def _derive_priors(cells, router_snap, choosers, keep_rates):
    """Profile priors from the post-sweep router snapshot, falling back
    to direct cell timings where a live EWMA never got fed."""
    router = {}
    if router_snap.get("link_samples", 0) > 0:
        router["link_mbps"] = router_snap["link_mbps"]
        router["overhead_s"] = router_snap["overhead_s"]
        router["dispatch_wall_s"] = router_snap["dispatch_wall_s"]
    if router_snap.get("host_samples", 0) > 0:
        router["host_mcells_per_s"] = router_snap["host_mcells_per_s"]
    elif cells:
        hosts = [c["host_rows_per_sec"] * c["read_length"] / 1e6
                 for c in cells if "host_rows_per_sec" in c]
        if hosts:
            router["host_mcells_per_s"] = round(
                sorted(hosts)[len(hosts) // 2], 3)
    for n, me in (router_snap.get("mesh") or {}).items():
        router.setdefault("mesh", {})[n] = {
            k: me[k] for k in ("link_mbps", "overhead_s",
                               "dispatch_wall_s") if k in me}
    if keep_rates:
        router["filter_keep_rate"] = round(
            sum(keep_rates) / len(keep_rates), 4)
    priors = {"router": {k: v for k, v in router.items() if v is not None}}
    if choosers:
        priors["choosers"] = choosers
    if cells:
        priors["crossover"] = [
            {"name": c["name"], "winner": c["winner"],
             "device_rows_per_sec": c["device_rows_per_sec"],
             "host_rows_per_sec": c.get("host_rows_per_sec")}
            for c in cells]
    return priors


def _derive_knobs(router_priors, quick):
    """Measured walls -> knob values, with documented heuristics.

    - coalesce window: holding a batch longer than one per-dispatch
      overhead can only lose (ops/coalesce.py prices exactly this), so
      the window IS the measured overhead, clamped to [0.5, 20] ms.
    - feeder depth: when the dispatch wall dwarfs the fixed overhead the
      link stays busy with depth 2; an overhead-dominated wall hides
      latency behind one more in-flight upload. Clamped [2, 4].
    - mesh: 'auto' only when more than one device is actually visible.
    """
    knobs = {}
    overhead = router_priors.get("overhead_s")
    wall = router_priors.get("dispatch_wall_s")
    if overhead is not None and overhead > 0:
        knobs["coalesce_window_ms"] = round(
            min(max(overhead * 1e3, 0.5), 20.0), 3)
        if wall:
            knobs["feeder_depth"] = int(
                min(max(2 + round(overhead / wall), 2), 4))
    try:
        import sys
        jax = sys.modules.get("jax")
        if jax is not None:
            knobs["mesh"] = "auto" if jax.device_count() > 1 else "off"
    except Exception:
        pass
    return knobs


# ------------------------------------------------------------------ sweep


def run_sweep(quick=False):
    """The in-process measurement pass. Returns (cells, router_snapshot,
    chooser_priors, keep_rates)."""
    import numpy as np

    from ..native import batch as nb
    from ..ops.host_kernel import HostConsensusEngine
    from ..ops.kernel import ConsensusKernel
    from ..ops.router import ROUTER
    from ..ops.tables import quality_tables

    tabs = quality_tables(45, 40)
    kernel = ConsensusKernel(tabs)
    # the sweep measures the wire path itself — on a CPU-pinned host the
    # production route would silently become the host engine and the
    # "device" column would time the wrong thing
    kernel.set_force_device()
    host = HostConsensusEngine(tabs) if nb.available() else None
    if host is None:
        log.warning("tune: native f64 host engine unavailable — the atlas "
                    "will carry device-only cells and no crossover depths")
    matrix = QUICK_MATRIX if quick else FULL_MATRIX
    n_rows = QUICK_ROWS if quick else FULL_ROWS
    rng = np.random.default_rng(11)
    cells = []
    for name, dist, depth, L, keep, ab in matrix:
        log.info("tune: cell %s (dist=%s depth=%d L=%d)", name, dist,
                 depth, L)
        cells.append(_measure_cell(kernel, host, name, dist, depth, L,
                                   keep, ab, rng, n_rows))
    return (cells, ROUTER.snapshot(), _bench_choosers(quick),
            [m[4] for m in matrix])


# ----------------------------------------------------------------- replay


def derive_from_replay(paths):
    """Profile inputs from recorded evidence instead of a live sweep.

    Accepts run-report JSONs (their ``device.routing`` snapshot — the
    EWMAs a real run converged to) and microbench JSONs (their
    ``tune_cells`` per-cell records from the ``--backend`` matrix).
    Numeric router fields are medianed across reports."""
    import statistics

    routings, cells = [], []
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            from .profile import ProfileError
            from ..utils.knobs import knob_error

            raise ProfileError(knob_error(
                "--replay", path, f"unreadable ({e})",
                "a run-report or microbench JSON file")) from None
        routing = (doc.get("device") or {}).get("routing") \
            if isinstance(doc, dict) else None
        if routing:
            routings.append(routing)
        for c in (doc.get("tune_cells") or []) if isinstance(doc, dict) \
                else []:
            cells.append(c)
    router = {}
    for k in ("link_mbps", "overhead_s", "dispatch_wall_s",
              "host_mcells_per_s", "filter_keep_rate"):
        vals = [r[k] for r in routings
                if isinstance(r.get(k), (int, float)) and r[k] > 0]
        if vals:
            router[k] = round(statistics.median(vals), 6)
    return cells, router


# ------------------------------------------------------------------- main


def run_autotune(profile_path, atlas_path=None, quick=False,
                 replay_paths=None, created_unix=None):
    """The ``fgumi-tpu tune`` verb body: sweep (or replay), write atlas +
    profile, log the headline. Returns 0."""
    from .profile import (PROFILE_SCHEMA_VERSION, fingerprint_host,
                          write_profile)
    from ..utils.atomic import discard_output, open_output

    created = int(created_unix if created_unix is not None else time.time())
    fp = fingerprint_host(probe_jax=not replay_paths)
    if replay_paths:
        cells, router = derive_from_replay(replay_paths)
        chooser_priors = {}
        keep_rates = []
        source = "replay"
        priors = {"router": router}
        if cells:
            priors["crossover"] = cells
        router_snap = dict(router)
    else:
        cells, router_snap, chooser_priors, keep_rates = run_sweep(quick)
        priors = _derive_priors(cells, router_snap, chooser_priors,
                                keep_rates)
        source = "autotune"
    knobs = _derive_knobs(priors.get("router", {}), quick)
    profile = {
        "schema_version": PROFILE_SCHEMA_VERSION,
        "tool": "fgumi-tpu tune",
        "created_unix": created,
        "source": source,
        "quick": bool(quick),
        "fingerprint": fp,
        "knobs": knobs,
        "priors": priors,
    }
    write_profile(profile_path, profile)
    log.info("tune: profile -> %s (%d knob(s): %s)", profile_path,
             len(knobs), ", ".join(sorted(knobs)) or "none")
    if atlas_path:
        atlas = {
            "schema_version": ATLAS_SCHEMA_VERSION,
            "kind": "fgumi-tpu-crossover-atlas",
            "tool": "fgumi-tpu tune",
            "created_unix": created,
            "source": source,
            "quick": bool(quick),
            "fingerprint": fp,
            "cells": cells,
            "crossover": _crossover_depths(cells),
        }
        out = open_output(atlas_path, "w")
        try:
            json.dump(atlas, out, indent=2, sort_keys=True)
            out.write("\n")
            out.close()
        except BaseException:
            discard_output(out)
            raise
        log.info("tune: atlas -> %s (%d cell(s))", atlas_path, len(cells))
    return 0
