"""Self-tuning deployment profiles (ROADMAP items 4 + 5).

Closes the telemetry loop PR 9 opened: the offline autotuner
(:mod:`.autotune`, surfaced as ``fgumi-tpu tune``) sweeps a simulated
workload matrix across forced device/host routes, records a crossover
atlas, and derives a schema-versioned :mod:`DeploymentProfile <.profile>`
of measured knob values + router/chooser priors; the CLI and serve daemon
load it at start (``--profile`` / ``FGUMI_TPU_PROFILE``) so a cold
process's first batch routes on the measured side of every crossover
instead of the static guesses. Profiles only change scheduling — never
the bytes written — so byte-identity holds on every route by construction.
"""

from .profile import (PROFILE_SCHEMA_VERSION, ProfileError,  # noqa: F401
                      fingerprint_host, load_profile, validate_profile,
                      write_profile)
