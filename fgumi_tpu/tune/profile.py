"""DeploymentProfile: schema-versioned tuned knobs + measured priors.

A profile is one JSON document produced by ``fgumi-tpu tune`` (or its
``--replay`` mode) and loaded at CLI/daemon start via ``--profile`` /
``FGUMI_TPU_PROFILE``. It carries three sections:

- ``fingerprint`` — the hardware the values were measured on (platform,
  visible cores, RAM, and the JAX backend + device count when JAX was
  live at tune time). A mismatch at load is LOUD (one warning naming
  every differing field, counted in ``tune.profile.fingerprint_mismatch``)
  but not fatal: a profile from a same-generation sibling host is still a
  far better prior than the static guesses.
- ``knobs`` — tuned values for the env-var surface
  (:data:`KNOB_ENV`). Precedence is strict and per knob: an explicit env
  var or CLI flag always wins; the profile fills only unset knobs; code
  defaults remain the floor. Applied once per process (daemon jobs
  re-enter the CLI in fresh contexts and must not re-apply).
- ``priors`` — measured starting points for the adaptive machinery:
  the :class:`~fgumi_tpu.ops.router.OffloadRouter` EWMAs (link rate,
  per-dispatch overhead, dispatch wall, host cells/s, fused-filter
  keep rate, per-mesh-size overrides) and the
  :class:`~fgumi_tpu.ops.router.AdaptiveChooser` seconds-per-mcell pairs.
  Seeding is cold-only — live measurements always win — and stamps
  ``prior_source="profile"`` into the router snapshot so first-batch
  routing is attributable in any run report.

Schema history:

- v1: initial layout (schema_version, tool, created_unix, source,
  fingerprint, knobs, priors).

Parse/validation failures raise :class:`ProfileError` with the shared
knob-diagnostic grammar (utils/knobs.py); the CLI maps it to exit 2 like
every other knob parse error.
"""

import json
import os
import threading

from ..utils.knobs import knob_error

PROFILE_SCHEMA_VERSION = 1

#: profile knob name -> the env var it fills (when that var is unset)
KNOB_ENV = {
    "feeder_depth": "FGUMI_TPU_FEEDER_DEPTH",
    "feeder_bytes": "FGUMI_TPU_FEEDER_BYTES",
    "shape_buckets": "FGUMI_TPU_SHAPE_BUCKETS",
    "chain_bytes": "FGUMI_TPU_CHAIN_BYTES",
    "coalesce_window_ms": "FGUMI_TPU_COALESCE_WINDOW_MS",
    "mesh": "FGUMI_TPU_MESH",
}

_ROUTER_PRIOR_KEYS = ("link_mbps", "overhead_s", "dispatch_wall_s",
                      "host_mcells_per_s", "filter_keep_rate")
_CHOOSER_NAMES = ("duplex_combine", "codec_combine")


class ProfileError(ValueError):
    """A profile failed to parse or validate (CLI: exit 2)."""


# ---------------------------------------------------------------- schema


def _err(path, token, problem, grammar):
    return ProfileError(knob_error(f"profile:{path}", token, problem,
                                   grammar))


def _check_number(path, v, lo=None, hi=None, integer=False):
    kind = "an integer" if integer else "a number"
    bounds = ""
    if lo is not None:
        bounds += f" >= {lo}"
    if hi is not None:
        bounds += f" <= {hi}"
    grammar = kind + bounds
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise _err(path, v, "wrong type", grammar)
    if integer and not isinstance(v, int):
        raise _err(path, v, "not an integer", grammar)
    if lo is not None and v < lo:
        raise _err(path, v, f"below the {lo} floor", grammar)
    if hi is not None and v > hi:
        raise _err(path, v, f"above the {hi} ceiling", grammar)


def _validate_knobs(knobs):
    if not isinstance(knobs, dict):
        raise _err("knobs", knobs, "wrong type", "an object")
    for k in knobs:
        if k not in KNOB_ENV:
            raise _err("knobs", k, "unknown knob",
                       "one of " + ", ".join(sorted(KNOB_ENV)))
    if "feeder_depth" in knobs:
        _check_number("knobs.feeder_depth", knobs["feeder_depth"],
                      lo=2, hi=64, integer=True)
    if "feeder_bytes" in knobs:
        _check_number("knobs.feeder_bytes", knobs["feeder_bytes"],
                      lo=1 << 20, integer=True)
    if "chain_bytes" in knobs:
        _check_number("knobs.chain_bytes", knobs["chain_bytes"],
                      lo=1 << 16, integer=True)
    if "coalesce_window_ms" in knobs:
        _check_number("knobs.coalesce_window_ms",
                      knobs["coalesce_window_ms"], lo=0.0, hi=1000.0)
    if "shape_buckets" in knobs:
        from ..ops.datapath import parse_shape_buckets

        try:
            parse_shape_buckets(knobs["shape_buckets"])
        except ValueError as e:
            raise ProfileError(f"profile:knobs.shape_buckets: {e}") \
                from None
    if "mesh" in knobs:
        from ..parallel.mesh import MeshConfigError, parse_mesh_spec

        try:
            parse_mesh_spec(knobs["mesh"])
        except MeshConfigError as e:
            raise ProfileError(f"profile:knobs.mesh: {e}") from None


def _validate_priors(priors):
    if not isinstance(priors, dict):
        raise _err("priors", priors, "wrong type", "an object")
    router = priors.get("router", {})
    if not isinstance(router, dict):
        raise _err("priors.router", router, "wrong type", "an object")
    for k in _ROUTER_PRIOR_KEYS:
        if router.get(k) is not None:
            hi = 1.0 if k == "filter_keep_rate" else None
            lo = 0.0 if k in ("overhead_s", "dispatch_wall_s",
                              "filter_keep_rate") else 1e-9
            _check_number(f"priors.router.{k}", router[k], lo=lo, hi=hi)
    mesh = router.get("mesh", {})
    if not isinstance(mesh, dict):
        raise _err("priors.router.mesh", mesh, "wrong type",
                   "an object keyed by device count")
    for n, mp in mesh.items():
        if not str(n).isdigit() or int(n) < 2:
            raise _err("priors.router.mesh", n, "bad device count",
                       "integer keys >= 2")
        if not isinstance(mp, dict):
            raise _err(f"priors.router.mesh.{n}", mp, "wrong type",
                       "an object")
        for k in ("link_mbps", "overhead_s", "dispatch_wall_s"):
            if mp.get(k) is not None:
                _check_number(f"priors.router.mesh.{n}.{k}", mp[k], lo=0.0)
    choosers = priors.get("choosers", {})
    if not isinstance(choosers, dict):
        raise _err("priors.choosers", choosers, "wrong type", "an object")
    for name, cp in choosers.items():
        if name not in _CHOOSER_NAMES:
            raise _err("priors.choosers", name, "unknown chooser",
                       "one of " + ", ".join(_CHOOSER_NAMES))
        if not isinstance(cp, dict):
            raise _err(f"priors.choosers.{name}", cp, "wrong type",
                       "an object")
        for k in ("device_s_per_mcell", "host_s_per_mcell"):
            if cp.get(k) is not None:
                _check_number(f"priors.choosers.{name}.{k}", cp[k], lo=0.0)
    crossover = priors.get("crossover", [])
    if not isinstance(crossover, list):
        raise _err("priors.crossover", crossover, "wrong type",
                   "a list of atlas cells")


def validate_profile(profile):
    """Structural validation; raises :class:`ProfileError` on the first
    problem (one consistent diagnostic naming token + grammar)."""
    if not isinstance(profile, dict):
        raise _err("", profile, "wrong type", "a JSON object")
    sv = profile.get("schema_version")
    if not isinstance(sv, int) or sv < 1:
        raise _err("schema_version", sv, "missing or malformed",
                   f"an integer >= 1 (current {PROFILE_SCHEMA_VERSION})")
    if sv > PROFILE_SCHEMA_VERSION:
        raise _err("schema_version", sv, "from a newer fgumi-tpu",
                   f"<= {PROFILE_SCHEMA_VERSION}")
    fp = profile.get("fingerprint")
    if not isinstance(fp, dict):
        raise _err("fingerprint", fp, "missing or malformed", "an object")
    src = profile.get("source")
    if src not in ("autotune", "replay", "manual"):
        raise _err("source", src, "unknown source",
                   "'autotune', 'replay', or 'manual'")
    _validate_knobs(profile.get("knobs", {}))
    _validate_priors(profile.get("priors", {}))
    return profile


# --------------------------------------------------------- fingerprinting


def fingerprint_host(probe_jax=False):
    """The identity of THIS host, for stamping into / comparing against a
    profile. Cheap fields always; the JAX backend + device count only when
    JAX is already imported (or ``probe_jax`` forces the import — the tune
    verb does, an ordinary ``--profile`` load must not pay backend init
    for a host-only command)."""
    import platform
    import sys

    fp = {
        "platform": platform.system().lower(),
        "machine": platform.machine(),
        "cpu_count": os.cpu_count(),
    }
    try:
        fp["ram_bytes"] = (os.sysconf("SC_PAGE_SIZE")
                           * os.sysconf("SC_PHYS_PAGES"))
    except (ValueError, OSError, AttributeError):
        fp["ram_bytes"] = None
    jax = sys.modules.get("jax")
    if jax is None and probe_jax:
        import jax
    if jax is not None:
        try:
            fp["jax_backend"] = jax.default_backend()
            fp["device_count"] = jax.device_count()
        except Exception:  # backend init failure: fingerprint stays cheap
            pass
    return fp


def fingerprint_mismatches(profile_fp, host_fp):
    """Fields present in BOTH fingerprints that disagree. RAM compares at
    quarter-granularity (two otherwise-identical hosts rarely report the
    same byte count)."""
    diffs = []
    for k in sorted(set(profile_fp) & set(host_fp)):
        a, b = profile_fp[k], host_fp[k]
        if a is None or b is None:
            continue
        if k == "ram_bytes":
            if abs(a - b) > max(a, b) / 4:
                diffs.append((k, a, b))
        elif a != b:
            diffs.append((k, a, b))
    return diffs


# --------------------------------------------------------------- load/save


def write_profile(path, profile):
    """Validate + atomically write (crash-safe like every other output)."""
    from ..utils.atomic import discard_output, open_output

    validate_profile(profile)
    out = open_output(path, "w")
    try:
        json.dump(profile, out, indent=2, sort_keys=True)
        out.write("\n")
        out.close()
    except BaseException:
        discard_output(out)
        raise
    return path


def load_profile(path):
    """Parse + validate one profile file; :class:`ProfileError` on any
    problem (missing file, bad JSON, schema violation)."""
    try:
        with open(path) as f:
            raw = f.read()
    except OSError as e:
        raise ProfileError(knob_error("FGUMI_TPU_PROFILE", path,
                                      f"unreadable ({e.strerror})",
                                      "a readable profile JSON path")) \
            from None
    try:
        profile = json.loads(raw)
    except ValueError as e:
        raise ProfileError(knob_error("FGUMI_TPU_PROFILE", path,
                                      f"not valid JSON ({e})",
                                      "a fgumi-tpu tune profile document")) \
            from None
    return validate_profile(profile)


# ------------------------------------------------------------ application

_lock = threading.Lock()
#: the one applied-profile record for this process (None until a profile
#: loads). Daemon jobs re-enter cli.main at depth 0 in fresh contexts;
#: the guard keeps application (env mutation, seeding, the mismatch
#: warning) a process-once event while stamp_metrics() re-stamps the
#: outcome into every invocation's scoped registry.
_APPLIED = None


def applied_info():
    return _APPLIED


def reset_applied_for_tests():
    global _APPLIED
    with _lock:
        _APPLIED = None


def apply_profile(profile, path="<inline>"):
    """Apply a validated profile to this process, once.

    Env knobs: filled only when the env var is unset (explicit env/flags
    win — CLI flags act later and override the env either way). Router /
    chooser priors: seeded cold-only. Returns the application record
    (also stored for :func:`stamp_metrics`)."""
    global _APPLIED
    import logging

    log = logging.getLogger("fgumi_tpu")
    with _lock:
        if _APPLIED is not None:
            return _APPLIED
        record = {"path": path, "applied": [], "skipped_explicit": [],
                  "fingerprint_mismatch": [], "seeded_router": False,
                  "seeded_choosers": []}
        host_fp = fingerprint_host()
        diffs = fingerprint_mismatches(profile.get("fingerprint", {}),
                                       host_fp)
        if diffs:
            record["fingerprint_mismatch"] = [
                {"field": k, "profile": a, "host": b} for k, a, b in diffs]
            log.warning(
                "profile %s was tuned on DIFFERENT hardware (%s); loading "
                "anyway — measured priors from a mismatched host can "
                "misroute until live EWMAs converge", path,
                ", ".join(f"{k}: profile={a!r} host={b!r}"
                          for k, a, b in diffs))
        for knob, value in sorted((profile.get("knobs") or {}).items()):
            env = KNOB_ENV[knob]
            if value is None:
                continue
            if os.environ.get(env) is not None:
                record["skipped_explicit"].append(knob)
            else:
                os.environ[env] = str(value)
                record["applied"].append(knob)
        priors = profile.get("priors") or {}
        from ..ops import router as _router

        if _router.ROUTER.seed_priors(priors.get("router") or {},
                                      source="profile"):
            record["seeded_router"] = True
        for name, chooser in (("duplex_combine", _router.DUPLEX_COMBINE),
                              ("codec_combine", _router.CODEC_COMBINE)):
            cp = (priors.get("choosers") or {}).get(name) or {}
            if chooser.seed(cp.get("device_s_per_mcell"),
                            cp.get("host_s_per_mcell")):
                record["seeded_choosers"].append(name)
        _APPLIED = record
    log.info("profile %s: %d knob(s) applied (%s), %d explicit override(s)"
             ", router priors %s", path, len(record["applied"]),
             ",".join(record["applied"]) or "none",
             len(record["skipped_explicit"]),
             "seeded" if record["seeded_router"] else "not seeded")
    stamp_metrics()
    return record


def stamp_metrics():
    """Stamp the process's profile-application outcome into the CURRENT
    metrics registry (tune.* gauges). Called once at application and again
    per scoped invocation so every run report carries the facts even
    though application itself is process-once."""
    if _APPLIED is None:
        return
    from ..observe.metrics import METRICS

    METRICS.set("tune.profile.loaded", 1)
    METRICS.set("tune.profile.knobs_applied", len(_APPLIED["applied"]))
    METRICS.set("tune.profile.knobs_skipped_explicit",
                len(_APPLIED["skipped_explicit"]))
    METRICS.set("tune.profile.fingerprint_mismatch",
                len(_APPLIED["fingerprint_mismatch"]))
    METRICS.set("tune.profile.seeded_router",
                1 if _APPLIED["seeded_router"] else 0)


def maybe_apply_from_env(profile_flag=None):
    """CLI entry: load + apply the profile named by ``--profile`` (wins)
    or ``FGUMI_TPU_PROFILE``. No-op when neither is set or one already
    applied. Raises :class:`ProfileError` (exit 2) on a bad profile."""
    if _APPLIED is not None:
        stamp_metrics()
        return _APPLIED
    path = profile_flag or os.environ.get("FGUMI_TPU_PROFILE") or None
    if not path:
        return None
    return apply_profile(load_profile(path), path=path)
