"""Health-routed fleet balancer: one front end over N serve daemons.

``fgumi-tpu balance --listen ADDR --backend ADDR ...`` speaks the same
newline-JSON wire protocol as the daemon on its front listener (Unix or
TCP, through the same :class:`~.transport.FrameServer` — deadlines,
connection cap, and handshake auth included) and fans work out across the
backends:

- **Routing** — a ``submit`` goes to the healthy backend with the lowest
  queue depth (``queued + running`` from each backend's ``stats`` op,
  refreshed by the health loop and corrected per-submit). ``status`` /
  ``cancel`` follow a job-id -> backend map learned at submit time, with a
  fan-out fallback — after a lease takeover the job LIVES on a different
  backend than the one it was submitted to, and the fan-out finds it.
- **Health** — a background loop polls every backend's ``stats`` op.
  Failures feed a per-backend closed/open/half-open breaker (the PR 7
  ``DeviceBreaker`` shape): ``eject_failures`` consecutive probe failures
  eject the backend (open), a cooldown (doubling per re-trip) moves it to
  half-open, and ``probe_successes`` consecutive clean probes re-admit it.
  An ejected backend receives no traffic.
- **Failover** — a submit whose backend dies mid-request is re-routed to
  a surviving peer when (and only when) it carries a ``dedupe`` key: the
  key makes the retry idempotent even if the dead backend had already
  admitted it (journal-lease takeover requeues that copy, and the dedupe
  key arbitrates — exactly one executes). Keyless submits surface the
  transport error verbatim; the client owns that retry decision.
- **Backpressure** — a backend shedding under resource pressure answers
  with ``retry_after_s``; the balancer first tries the other backends,
  and only when EVERY healthy backend sheds does it sleep the smallest
  hint once and retry, then propagate the shed to the client (who sleeps
  the hint themselves — nobody hot-loops).
- **Scatter** (``--scatter N``) — a submitted ``pipeline``/``simplex``/
  ``duplex`` whale job is split into N dedupe-keyed shard sub-jobs
  fanned out through this same routing, tracked in the balancer's
  scatter WAL, and gathered into one byte-deterministic BAM
  (serve/scatter.py; docs/serving.md "Scatter/gather").

``drain``/``shutdown`` on the front apply to the balancer itself (close
admission; exit), never to the backends — operators stop daemons
directly. SIGTERM is the same drain."""

import logging
import os
import threading
import time

from . import protocol, transport
from .client import (ServeClient, ServeError, TransportError,
                     TransportTimeout)

log = logging.getLogger("fgumi_tpu")

#: breaker defaults (overridable via `fgumi-tpu balance` flags)
EJECT_FAILURES = 2
COOLDOWN_S = 5.0
PROBE_SUCCESSES = 2
MAX_COOLDOWN_FACTOR = 8

#: cap on one shed-hint sleep inside the balancer — a huge hint is the
#: client's problem to honor, not a reason to hold a connection hostage.
MAX_SHED_SLEEP_S = 10.0


class PeerBreaker:
    """Closed/open/half-open ejection state machine for one backend.

    The :class:`~fgumi_tpu.ops.breaker.DeviceBreaker` shape re-applied to
    a network peer: consecutive failures eject (open), cooldown doubles
    per re-trip (a flapping backend converges to long ejections instead
    of oscillating), half-open admits ONE probe at a time, and
    ``probe_successes`` consecutive clean probes re-admit. ``now`` is
    injectable for tests."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

    def __init__(self, eject_failures: int = EJECT_FAILURES,
                 cooldown_s: float = COOLDOWN_S,
                 probe_successes: int = PROBE_SUCCESSES,
                 now=time.monotonic):
        self.eject_failures = max(int(eject_failures), 1)
        self.cooldown_s = float(cooldown_s)
        self.probe_successes = max(int(probe_successes), 1)
        self._now = now
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._score = 0
        self._opened_at = None
        self._trips = 0
        self._probe_inflight = False
        self._probe_ok = 0
        self.transitions = []  # [(t, from, to, reason)] bounded

    def _advance_locked(self):
        if self._state == self.OPEN:
            cool = self.cooldown_s * min(2 ** max(self._trips - 1, 0),
                                         MAX_COOLDOWN_FACTOR)
            if self._now() - self._opened_at >= cool:
                self._transition_locked(self.HALF_OPEN, "cooldown elapsed")
        return self._state

    def _transition_locked(self, new, reason):
        old = self._state
        if old == new:
            return
        self._state = new
        self.transitions.append((round(self._now(), 3), old, new, reason))
        del self.transitions[:-16]
        if new == self.OPEN:
            self._opened_at = self._now()
            self._trips += 1
        if new == self.HALF_OPEN:
            self._probe_inflight = False
            self._probe_ok = 0
        if new == self.CLOSED:
            self._score = 0
            self._trips = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._advance_locked()

    def allow(self) -> bool:
        """May the next request go to this backend? half-open claims the
        single probe slot; the matching record_* releases it."""
        with self._lock:
            state = self._advance_locked()
            if state == self.CLOSED:
                return True
            if state == self.OPEN:
                return False
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def record_success(self):
        with self._lock:
            state = self._advance_locked()
            if state == self.CLOSED:
                self._score = 0
                return
            if state == self.HALF_OPEN:
                self._probe_inflight = False
                self._probe_ok += 1
                if self._probe_ok >= self.probe_successes:
                    self._transition_locked(
                        self.CLOSED,
                        f"{self._probe_ok} consecutive probe successes")

    def record_failure(self, reason: str):
        with self._lock:
            state = self._advance_locked()
            if state == self.HALF_OPEN:
                self._probe_inflight = False
                self._transition_locked(self.OPEN, f"probe failed: {reason}")
                return
            if state == self.CLOSED:
                self._score += 1
                if self._score >= self.eject_failures:
                    self._transition_locked(self.OPEN, reason)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._advance_locked(),
                "trips": self._trips,
                "transitions": [
                    {"t": t, "from": a, "to": b, "reason": r}
                    for t, a, b, r in self.transitions],
            }


class Backend:
    """One routed-to daemon: its client, breaker, and last known depth."""

    def __init__(self, address: str, token: str = None,
                 timeout_s: float = 30.0, breaker: PeerBreaker = None):
        self.address = address
        # no client-side backoff retries inside the balancer: failure must
        # surface FAST so the breaker ejects and the submit re-routes —
        # the balancer IS the retry layer
        self.client = ServeClient(address, timeout=timeout_s,
                                  retry_policy=transport.RetryPolicy.none(),
                                  token=token)
        self.breaker = breaker or PeerBreaker()
        self._lock = threading.Lock()
        self._depth = None          # queued + running; None = unknown
        self.last_ok_unix = None
        self.last_error = None
        # silent-corruption quarantine (ISSUE 14): a backend whose stats
        # report audit divergences is held out of routing entirely until
        # a later health poll sees the counters back at zero — which only
        # a daemon restart produces, so "re-admitted on restart" is the
        # whole contract. Forwarded traffic succeeding must NOT lift it:
        # a submit that worked proves the backend answers, not that its
        # device tells the truth.
        self.sdc_hold = False
        self.audit_divergent = 0
        # last full stats payload the health poll fetched, plus the wall
        # time it landed: the balancer's /metrics endpoint and its
        # fleet_metrics stats section re-export backend series from THIS
        # cache, so a scrape never fans out live probes (and staleness is
        # visible as fleet.backend.stats_age_s)
        self._last_stats = None
        self._last_stats_unix = None

    @property
    def depth(self):
        with self._lock:
            return self._depth

    def note_depth(self, depth):
        with self._lock:
            self._depth = depth

    def note_ok(self):
        with self._lock:
            self.last_ok_unix = round(time.time(), 3)
            self.last_error = None

    def note_stats(self, stats: dict):
        with self._lock:
            self._last_stats = stats
            self._last_stats_unix = round(time.time(), 3)

    def cached_stats(self):
        """``(stats_payload, scrape_unix)`` from the last successful
        health poll — ``(None, None)`` before the first one lands."""
        with self._lock:
            return self._last_stats, self._last_stats_unix

    def note_error(self, err: str):
        with self._lock:
            self.last_error = str(err)[:200]

    def note_audit(self, divergent: int):
        """Record the latest stats poll's audit divergence count and
        advance the sdc hold; returns ``(became_held, became_clear)``."""
        with self._lock:
            self.audit_divergent = int(divergent)
            if divergent > 0 and not self.sdc_hold:
                self.sdc_hold = True
                return True, False
            if divergent == 0 and self.sdc_hold:
                self.sdc_hold = False
                return False, True
            return False, False

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "address": self.address,
                "state": self.breaker.state,
                "depth": self._depth,
                "last_ok_unix": self.last_ok_unix,
                "last_error": self.last_error,
            }
            if self.sdc_hold or self.audit_divergent:
                out["sdc_hold"] = self.sdc_hold
                out["audit_divergent"] = self.audit_divergent
            return out


class Balancer:
    """The front-end service: wire-protocol dispatch over the backends."""

    def __init__(self, listen: str, backends, token: str = None,
                 backend_token: str = None,
                 max_frame_bytes: int = protocol.MAX_FRAME_BYTES,
                 poll_period_s: float = 1.0,
                 eject_failures: int = EJECT_FAILURES,
                 cooldown_s: float = COOLDOWN_S,
                 probe_successes: int = PROBE_SUCCESSES,
                 conn_cap: int = transport.DEFAULT_CONN_CAP,
                 io_timeout_s: float = transport.DEFAULT_IO_TIMEOUT_S,
                 backend_timeout_s: float = 30.0,
                 job_map_limit: int = 10000,
                 metrics_port: int = None,
                 scatter_shards: int = 0,
                 scatter_axis: str = "umi",
                 scatter_wal: str = None,
                 scatter_grace_s: float = 20.0):
        if not backends:
            raise ValueError("balance needs at least one --backend")
        self.listen_addr = listen
        self.token = token
        self.max_frame_bytes = max_frame_bytes
        self.poll_period_s = float(poll_period_s)
        self.backends = [
            Backend(addr, token=backend_token, timeout_s=backend_timeout_s,
                    breaker=PeerBreaker(eject_failures, cooldown_s,
                                        probe_successes))
            for addr in backends]
        seen = set()
        for b in self.backends:
            if b.address in seen:
                raise ValueError(f"duplicate --backend {b.address}")
            seen.add(b.address)
        self.started_unix = time.time()
        self._jobs_lock = threading.Lock()
        self._job_backend = {}      # job id -> Backend (bounded FIFO-ish)
        #: dedupe key -> (Backend, job id | None): an idempotent resubmit
        #: must reach the backend HOLDING the key, or a fresh backend
        #: would execute a second copy. job id None = the key was SENT
        #: there but the answer never arrived (timeout) — the most
        #: dangerous state, resolved only by that backend answering or
        #: its jobs being taken over (best-effort — a takeover moves keys
        #: between backends, and the daemons' own maps stay the
        #: authority)
        self._dedupe_backend = {}
        self._job_map_limit = int(job_map_limit)
        self._draining = False
        self._drain_lock = threading.Lock()
        self._shutdown = threading.Event()
        self._poll_stop = threading.Event()
        self._poll_threads = []
        # the telemetry scope active at construction (cmd_balance's): the
        # FrameServer's connection threads are plain threads with no
        # contextvar inheritance, so handle_request re-enters this scope —
        # otherwise --trace forward spans and the propagated trace context
        # would land on a dead process-global tracer
        from ..observe.scope import current_scope

        self._telemetry_scope = current_scope()
        kind, target = transport.parse_address(listen)
        if kind == "unix":
            listener = transport.UnixListener(target)
        else:
            host, port = target
            listener = transport.TcpListener(
                host, port, token=token, io_timeout_s=io_timeout_s,
                conn_cap=conn_cap)
        self._listener = listener
        self._frames = transport.FrameServer(
            self.handle_request, [listener], max_frame_bytes,
            on_shutdown=self._shutdown.set, name="fgumi-balance")
        # optional fleet metrics endpoint: the daemon's IntrospectionServer
        # with the balancer's own renderers plugged in (/metrics re-exports
        # backend-labelled series from the health-poll cache; /healthz is
        # 200 while at least one backend is routable)
        self._metrics = None
        if metrics_port is not None:
            from .introspect import IntrospectionServer

            self._metrics = IntrospectionServer(
                self, metrics_port,
                metrics_fn=lambda: render_fleet_prometheus(self),
                healthz_fn=lambda: render_fleet_healthz(self))
        # whale scatter/gather (balance --scatter N): the planner/
        # coordinator that splits recognized consensus jobs across the
        # fleet and k-way merges the shard outputs (serve/scatter.py)
        self._scatter = None
        if scatter_shards:
            from .scatter import ScatterCoordinator

            self._scatter = ScatterCoordinator(
                self, scatter_shards, axis=scatter_axis,
                wal_path=scatter_wal, requeue_grace_s=scatter_grace_s,
                # shard status polls are cheap frame round-trips; track
                # them to the health-poll cadence so shard completion is
                # noticed promptly (capped: a lazy operator poll period
                # must not starve the gather)
                poll_s=min(0.5, poll_period_s))
        self._closed = False

    # -- lifecycle ----------------------------------------------------------

    def bind(self):
        self._frames.bind()
        if self._metrics is not None:
            # busy metrics port fails fast, before any backend traffic
            self._metrics.bind()

    def start(self):
        self.bind()
        self._frames.start()
        if self._metrics is not None:
            self._metrics.start()
        self._poll_threads = []
        for i, b in enumerate(self.backends):
            t = threading.Thread(target=self._poll_loop, args=(b,),
                                 name=f"fgumi-balance-health-{i}",
                                 daemon=True)
            t.start()
            self._poll_threads.append(t)
        if self._scatter is not None:
            # WAL-resumed whales start fanning out once routing is live
            self._scatter.start()
        log.info("balance: listening on %s over %d backend(s): %s%s",
                 self._listener.describe(), len(self.backends),
                 ", ".join(b.address for b in self.backends),
                 f"; scatter {self._scatter.shards}x/{self._scatter.axis}"
                 if self._scatter is not None else "")

    def request_shutdown(self):
        self._shutdown.set()

    def wait_until_shutdown(self, poll_s: float = 0.2):
        while not self._shutdown.wait(poll_s):
            pass
        self.drain()

    def drain(self):
        with self._drain_lock:
            if not self._draining:
                log.info("balance: draining (admission closed)")
            self._draining = True

    @property
    def draining(self) -> bool:
        with self._drain_lock:
            return self._draining

    def close(self, grace_s: float = 10.0):
        if self._closed:
            return
        self._closed = True
        self._shutdown.set()
        self._poll_stop.set()
        if self._scatter is not None:
            # in-flight whales stop cleanly; their WAL state resumes them
            # on the next start
            self._scatter.close()
        for t in self._poll_threads:
            t.join(timeout=5)
        if self._metrics is not None:
            self._metrics.stop()
        self._frames.close()
        # let in-flight forwards answer before the process exits
        deadline = time.monotonic() + grace_s
        while self._frames.live_connections() > 0 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        if isinstance(self._listener, transport.UnixListener):
            self._listener.unlink()
        log.info("balance: stopped")

    @property
    def listen_port(self):
        """Bound TCP port (ephemeral port 0 resolves after bind)."""
        return getattr(self._listener, "port", None)

    @property
    def metrics_port(self):
        """Bound metrics port (None without --metrics-port)."""
        return self._metrics.port if self._metrics is not None else None

    # -- health loop --------------------------------------------------------

    def _poll_loop(self, b: Backend):
        # ONE loop per backend: a hung-but-accepting backend stalls only
        # its own probe (bounded by the probe timeout), never the other
        # backends' depth/health cadence. First pass immediately:
        # routing before the first period would otherwise see every
        # depth as unknown
        while True:
            if b.breaker.allow():
                self._probe(b)
            if self._poll_stop.wait(self.poll_period_s):
                return

    def poll_backends_once(self):
        """One sequential health sweep: refresh depth + feed every
        breaker. Tests and the CLI's startup probe drive this; the live
        balancer runs one independent loop per backend."""
        for b in self.backends:
            if not b.breaker.allow():
                continue  # open, or half-open slot already claimed
            self._probe(b)

    def _probe(self, b: Backend):
        was = b.breaker.state
        try:
            # probe timeout is NOT tied to the poll period: a DEAD
            # backend fails instantly (connection refused), so a generous
            # deadline costs nothing on real deaths — while a tight one
            # ejects a live backend that is merely busy (XLA compiling a
            # job on a loaded host), the spurious-ejection mode the
            # timeout-failover rule exists to prevent
            stats = b.client.stats(timeout=min(b.client.timeout, 10.0))
            b.note_stats(stats)
            sched = stats.get("scheduler") or {}
            b.note_depth(int(sched.get("queued", 0))
                         + int(sched.get("running", 0)))
            # silent-corruption check (ISSUE 14): a backend whose shadow
            # audit caught its device lying is ejected like a failed
            # probe — and held out of routing until its audit counters
            # read zero again, which only a restart produces
            divergent = int((stats.get("audit") or {}).get("divergent", 0))
            became_held, became_clear = b.note_audit(divergent)
            if became_held:
                from ..observe.metrics import METRICS

                METRICS.inc("fleet.balancer.sdc_ejected")
                from ..observe.flight import FLIGHT

                FLIGHT.note("balancer.sdc_eject", address=b.address,
                            divergent=divergent)
                log.error(
                    "balance: backend %s reports %d audit divergence(s) "
                    "— silent data corruption; holding it out of routing "
                    "until its counters reset (restart)",
                    b.address, divergent)
            if became_clear:
                log.warning(
                    "balance: backend %s audit counters are clean again "
                    "(restart observed); lifting the sdc hold", b.address)
            if divergent > 0:
                b.note_error(f"sdc: {divergent} audit divergence(s)")
                b.breaker.record_failure(
                    f"backend reports {divergent} audit divergence(s) "
                    "(silent data corruption)")
            else:
                b.note_ok()
                b.breaker.record_success()
        except ServeError as e:
            b.note_error(e)
            b.breaker.record_failure(f"health probe failed: {e}")
        self._transition_accounting(b, was)

    @staticmethod
    def _note_transition(b: Backend, was: str, now: str):
        from ..observe.flight import FLIGHT

        FLIGHT.note("balancer.backend", address=b.address, state=now,
                    previous=was)
        level = logging.WARNING if now == "open" else logging.INFO
        log.log(level, "balance: backend %s %s -> %s", b.address, was, now)

    # -- routing ------------------------------------------------------------

    def _healthy_backends(self):
        """Routable backends, least-loaded first (unknown depth last among
        the healthy — it answered the breaker but never a stats poll).
        SDC-held backends are excluded outright: half-open probing would
        otherwise route real jobs onto a device known to corrupt results
        (only the health poll's stats re-check can lift the hold)."""
        out = [b for b in self.backends
               if b.breaker.state != "open" and not b.sdc_hold]
        out.sort(key=lambda b: (b.depth is None,
                                b.depth if b.depth is not None else 0))
        return out

    def _bounded_put_locked(self, d: dict, key, value):
        """Insert with drop-oldest-half eviction (caller holds the jobs
        lock). Forgotten JOB entries degrade to the fan-out fallback;
        forgotten DEDUPE entries lose sticky routing (a resubmit of an
        evicted key routes by load again), so that eviction is loud."""
        if len(d) >= self._job_map_limit:
            dropped = list(d)[:self._job_map_limit // 2]
            for k in dropped:
                del d[k]
            if d is self._dedupe_backend:
                log.warning(
                    "balance: dedupe routing map overflowed (limit %d); "
                    "%d oldest keys forgot their sticky backend — "
                    "resubmits of those keys route by load and rely on "
                    "the daemons' own dedupe maps alone",
                    self._job_map_limit, len(dropped))
        d[key] = value

    def _remember_job(self, job_id: str, backend: Backend,
                      dedupe: str = None):
        with self._jobs_lock:
            self._bounded_put_locked(self._job_backend, job_id, backend)
            if dedupe:
                self._bounded_put_locked(self._dedupe_backend, dedupe,
                                         (backend, job_id))

    def _remember_dedupe_pending(self, dedupe: str, backend: Backend):
        """The key was SENT to ``backend`` but no answer arrived: it may
        hold (and be executing) the job. Never overwrite a confirmed
        entry with a pending one."""
        with self._jobs_lock:
            if dedupe not in self._dedupe_backend:
                self._bounded_put_locked(self._dedupe_backend, dedupe,
                                         (backend, None))

    def _backend_for_job(self, job_id: str):
        with self._jobs_lock:
            return self._job_backend.get(job_id)

    def _relocate_dedupe(self, dedupe: str, job_id: str):
        """The key's holder is ejected: find the backend that owns the
        job NOW (a lease takeover moves jobs — and their keys — to the
        claimant). Returns the new holder, or None when the job is
        nowhere reachable (unknown id, or the takeover has not happened
        yet)."""
        if job_id is None:
            return None  # the original submit never answered: no handle
        for b in self._healthy_backends():
            try:
                resp = self._forward(
                    b, {"v": protocol.PROTOCOL_VERSION, "op": "status",
                        "id": job_id})
            except ServeError:
                continue
            if resp.get("ok"):
                self._remember_job(job_id, b, dedupe=dedupe)
                log.info("balance: dedupe key %r relocated to %s "
                         "(takeover)", dedupe, b.address)
                return b
        return None

    # -- request dispatch ---------------------------------------------------

    def handle_request(self, req: dict) -> dict:
        from ..observe.scope import current_scope, scoped_telemetry

        if self._telemetry_scope is not None and current_scope() is None:
            with scoped_telemetry(scope=self._telemetry_scope):
                return self._handle_request(req)
        return self._handle_request(req)

    def _handle_request(self, req: dict) -> dict:
        err = protocol.validate_request(req)
        if err is not None:
            return protocol.error_response(err)
        op = req["op"]
        if op == "hello":
            return transport.hello_response("fgumi-tpu-balance",
                                            self.token, req)
        if op == "ping":
            states = [b.breaker.state for b in self.backends]
            return protocol.ok_response(
                tool="fgumi-tpu-balance", pid=os.getpid(),
                uptime_s=round(time.time() - self.started_unix, 1),
                backends={"total": len(states),
                          "healthy": sum(s != "open" for s in states)},
                draining=self.draining)
        if op == "stats":
            return protocol.ok_response(stats=self.stats_snapshot())
        if op == "scatter":
            if self._scatter is None:
                return protocol.error_response(
                    "scatter is not enabled on this balancer (start it "
                    "with `balance --scatter N`)")
            job_id = req.get("id")
            if job_id is None:
                return protocol.ok_response(
                    scatter=self._scatter.snapshot())
            whale = self._scatter.status(job_id)
            if whale is None:
                return protocol.error_response(f"unknown job {job_id}")
            return protocol.ok_response(scatter=whale)
        if op == "submit":
            if self._scatter is not None:
                resp = self._scatter.maybe_submit(req)
                if resp is not None:
                    return resp  # a whale: planned and fanned out
            return self._route_submit(req)
        if op == "status":
            return self._route_status(req)
        if op == "cancel":
            if self._scatter is not None:
                resp = self._scatter.cancel(req["id"])
                if resp is not None:
                    return resp
            return self._route_cancel(req)
        if op == "drain":
            self.drain()
            return protocol.ok_response(draining=True)
        if op == "shutdown":
            self.drain()
            return protocol.ok_response(draining=True)
        raise AssertionError(f"unhandled op {op}")

    def stats_snapshot(self, scrape=None) -> dict:
        """The balancer's ``stats`` op payload. v2 added ``fleet_metrics``
        (health-poll-cache rollup: fleet depth, per-backend breaker/SDC
        state, takeover counts, e2e latency summaries); v3 added
        ``scatter`` (whale scatter/gather state: per-whale shard counts
        by planned/running/done/requeued — null when ``--scatter`` is
        off). Pass a pre-taken :meth:`backend_scrape` so this payload and
        a concurrent ``/metrics`` render derive from ONE cache read (the
        same-snapshot rule the daemon's introspection keeps); the
        ``scatter`` section is likewise taken exactly once per payload,
        and the ``/metrics`` scatter gauges are rendered from THIS
        payload, never a second coordinator read."""
        from ..observe.metrics import METRICS

        if scrape is None:
            scrape = self.backend_scrape()
        with self._jobs_lock:
            tracked = len(self._job_backend)
        return {
            "schema_version": 3,
            "scatter": (self._scatter.snapshot()
                        if self._scatter is not None else None),
            "tool": "fgumi-tpu-balance",
            "pid": os.getpid(),
            "uptime_s": round(time.time() - self.started_unix, 1),
            "draining": self.draining,
            "poll_period_s": self.poll_period_s,
            "tracked_jobs": tracked,
            "metrics": {k: v for k, v in METRICS.snapshot().items()
                        if k.startswith(("fleet.", "serve.transport."))},
            "fleet_metrics": self._fleet_metrics(scrape),
            "backends": [
                {**snap, "breaker": b.breaker.snapshot()}
                for b, snap, _, _ in scrape],
        }

    def backend_scrape(self):
        """One coherent read of the health loop's cache:
        ``[(Backend, snapshot, cached_stats | None, scrape_unix | None)]``
        in ``--backend`` order. Never touches a backend."""
        out = []
        for b in self.backends:
            stats, stats_unix = b.cached_stats()
            out.append((b, b.snapshot(), stats, stats_unix))
        return out

    @staticmethod
    def _fleet_metrics(scrape) -> dict:
        """Fleet rollup from one :meth:`backend_scrape`: aggregate depth,
        healthy-backend count, takeover totals, and a per-backend
        breakdown carrying each daemon's end-to-end
        ``serve.job.e2e.submit_to_done_s`` summary — the fleet's
        "p99 submit-to-bytes-published" figure, surfaced without a
        scrape of the backends themselves."""
        depth_total, depth_known, healthy = 0, 0, 0
        takeovers = takeover_jobs = 0
        per_backend = []
        for b, snap, stats, stats_unix in scrape:
            routable = (snap["state"] != "open"
                        and not snap.get("sdc_hold"))
            healthy += int(routable)
            if snap["depth"] is not None:
                depth_total += snap["depth"]
                depth_known += 1
            fleet = (stats or {}).get("fleet") or {}
            b_takeovers = int(fleet.get("takeovers") or 0)
            takeovers += b_takeovers
            takeover_jobs += int(fleet.get("takeover_jobs") or 0)
            entry = {
                "address": snap["address"],
                "state": snap["state"],
                "routable": routable,
                "depth": snap["depth"],
                "sdc_hold": bool(snap.get("sdc_hold")),
                "audit_divergent": int(snap.get("audit_divergent") or 0),
                "takeovers": b_takeovers,
                "stats_age_s": (round(time.time() - stats_unix, 1)
                                if stats_unix else None),
            }
            e2e = ((stats or {}).get("latency") or {}).get(
                "serve.job.e2e.submit_to_done_s")
            if e2e is not None:
                entry["submit_to_done_s"] = e2e
            per_backend.append(entry)
        return {
            "backends_total": len(scrape),
            "backends_healthy": healthy,
            "fleet_depth": depth_total,
            "fleet_depth_known_backends": depth_known,
            "takeovers": takeovers,
            "takeover_jobs": takeover_jobs,
            "per_backend": per_backend,
        }

    def _forward(self, b: Backend, req: dict, claimed: bool = False) -> dict:
        """One backend round-trip; never retried client-side (the
        balancer IS the retry layer — failure must surface fast).

        ``claimed``: the caller took the half-open probe slot
        (``breaker.allow()``) and this request IS the probe. Unclaimed
        read traffic (status fan-out, key relocation) feeds the breaker
        only while it is CLOSED — cheap status successes must not close
        a half-open breaker the real probe is still deciding, nor may a
        stray read failure re-trip it and double the cooldown."""
        was = b.breaker.state
        feed = claimed or was == PeerBreaker.CLOSED
        try:
            resp = b.client.request(req, retry=False)
        except TransportError as e:
            b.note_error(e)
            if feed:
                b.breaker.record_failure(f"request failed: {e}")
                self._transition_accounting(b, was)
            raise
        if feed:
            b.breaker.record_success()
            self._transition_accounting(b, was)
        return resp

    def _transition_accounting(self, b: Backend, was: str):
        """Log/flight-note/count a breaker transition caused by forwarded
        traffic — in BOTH directions: a submit acting as the half-open
        probe can re-admit a backend, and the ejected/readmitted metric
        pair must track it."""
        now = b.breaker.state
        if now == was:
            return
        self._note_transition(b, was, now)
        from ..observe.metrics import METRICS

        if was != "open" and now == "open":
            METRICS.inc("fleet.balancer.ejected")
        if was != "closed" and now == "closed":
            METRICS.inc("fleet.balancer.readmitted")

    @staticmethod
    def _stamp_submit(req: dict):
        """Copy a submit frame and stamp the balancer hop onto the copy:
        ``bal_recv_unix`` now, and the ``traceparent`` rewritten so its
        parent is the balancer's own hop span (same trace-id — the chain
        stays causally linked client -> balancer -> backend). A malformed
        incoming traceparent is dropped, never rejected. Returns
        ``(req_copy, (trace_id, parent_span_id, hop_span_id) | None)``;
        the copy is the balancer's to mutate (``bal_sent_unix`` per
        forward attempt), the caller's frame is never touched."""
        from ..observe import trace as trace_mod

        req = dict(req)
        req["bal_recv_unix"] = round(time.time(), 6)
        parsed = trace_mod.parse_traceparent(req.get("traceparent"))
        if parsed is None:
            req.pop("traceparent", None)
            return req, None
        trace_id, parent_span = parsed
        hop_span = trace_mod.mint_span_id()
        req["traceparent"] = trace_mod.format_traceparent(trace_id, hop_span)
        trace_mod.set_trace_context(trace_id=trace_id,
                                    parent_span_id=parent_span,
                                    process_label="balancer")
        return req, (trace_id, parent_span, hop_span)

    def _route_submit(self, req: dict) -> dict:
        from ..observe import trace as trace_mod
        from ..observe.metrics import METRICS

        if self.draining:
            return protocol.error_response(
                "draining: balancer is not accepting new jobs")
        METRICS.inc("fleet.balancer.submits")
        req, hop_ctx = self._stamp_submit(req)
        dedupe = req.get("dedupe")
        slept_hint = False
        # route passes are bounded: each re-scan needs a state change
        # (ejection, shed sleep) and the pathological flapping case must
        # terminate with an explicit answer, not a spin
        for _ in range(2 * len(self.backends) + 2):
            candidates = self._healthy_backends()
            holder = None
            if dedupe:
                with self._jobs_lock:
                    sticky = self._dedupe_backend.get(dedupe)
                if sticky is not None:
                    holder, known_id = sticky
                    if holder not in candidates:
                        # the holder is ejected — but it may be ALIVE and
                        # still executing (an ejection is a routing
                        # verdict, not a death certificate). Routing the
                        # key to a fresh backend would risk a second
                        # execution; first see whether a takeover already
                        # moved the job to a survivor, else refuse
                        # explicitly — a refusal is retryable, a double
                        # execution is not.
                        holder = self._relocate_dedupe(dedupe, known_id)
                        if holder is None:
                            addr = sticky[0].address
                            return protocol.error_response(
                                f"backend {addr} holding dedupe key "
                                f"{dedupe!r} is ejected and may still "
                                "be executing it; retry once it "
                                "recovers or its jobs are taken over")
                    # a known key goes to its holder and NOWHERE else:
                    # skipping past it mid-loop (probe slot taken, a
                    # refusal) must refuse, not spill — any other
                    # backend would execute a second copy
                    candidates = [holder]
            if not candidates:
                return protocol.error_response(
                    "no healthy backends (all "
                    f"{len(self.backends)} ejected)")
            sheds = []
            failed_over = False
            for b in candidates:
                if not b.breaker.allow():
                    if b is holder:
                        return protocol.error_response(
                            f"backend {b.address} holding dedupe key "
                            f"{dedupe!r} is recovering (half-open probe "
                            "in flight); retry shortly")
                    continue  # half-open probe slot already out
                try:
                    # the forwarded submit is the half-open probe when the
                    # backend is recovering — the PR 7 "the batch IS the
                    # probe" idea applied to peers (allow() above claimed
                    # the slot). No client-side retry: failover below is
                    # the retry.
                    req["bal_sent_unix"] = round(time.time(), 6)
                    attrs = {"backend": b.address}
                    if hop_ctx is not None:
                        attrs["trace_id"] = hop_ctx[0]
                        attrs["span_id"] = hop_ctx[2]
                    with trace_mod.span("serve.forward", **attrs):
                        resp = self._forward(b, req, claimed=True)
                except ServeError as e:
                    if not isinstance(e, TransportError):
                        # the backend ANSWERED but refused the
                        # conversation itself — handshake rejection
                        # (token mismatch) or an old daemon rejecting the
                        # hello op. The submit never reached admission,
                        # so the next backend is safe regardless of
                        # dedupe; the breaker hears about the misfit
                        b.note_error(e)
                        b.breaker.record_failure(f"request refused: {e}")
                        if b is holder:
                            return protocol.error_response(
                                f"backend {b.address} holding dedupe "
                                f"key {dedupe!r} refused the "
                                f"conversation ({e}); not spilling the "
                                "key elsewhere — retry once it answers")
                        log.warning("balance: backend %s refused the "
                                    "conversation (%s); trying the next",
                                    b.address, e)
                        continue
                    if isinstance(e, TransportTimeout):
                        # the backend may be ALIVE and still executing:
                        # re-routing would run the job twice (the lease
                        # takeover only arbitrates against dead
                        # backends). Pin the key to this backend so a
                        # RESUBMIT is refused rather than routed to a
                        # fresh backend, and surface the timeout
                        if dedupe is not None:
                            self._remember_dedupe_pending(dedupe, b)
                        return protocol.error_response(
                            f"backend {b.address} timed out mid-submit "
                            f"({e}); not failing over — the backend may "
                            "still be executing it. Poll `status`, or "
                            "retry and the balancer will hold the "
                            "dedupe key to this backend")
                    if dedupe is None:
                        # the dead backend may have admitted it; without a
                        # key a second submit could double-execute —
                        # surface the failure, the client owns the retry
                        return protocol.error_response(
                            f"backend {b.address} failed mid-submit "
                            f"({e}); resubmit with a dedupe key for "
                            "automatic failover")
                    METRICS.inc("fleet.balancer.rerouted")
                    from ..observe.flight import FLIGHT

                    FLIGHT.note("balancer.reroute", address=b.address,
                                dedupe=dedupe)
                    log.warning("balance: backend %s failed mid-submit; "
                                "re-routing dedupe-keyed submit (%s)",
                                b.address, e)
                    failed_over = True
                    continue
                if resp.get("ok"):
                    job = resp.get("job") or {}
                    if job.get("id"):
                        self._remember_job(job["id"], b, dedupe=dedupe)
                        if not resp.get("deduped"):
                            b.note_depth((b.depth or 0) + 1)
                    return resp
                reason = resp.get("error", "")
                was_holder = b is holder
                if was_holder:
                    # the daemon answers a held dedupe key BEFORE any
                    # admission check — so a shed/queue-full/refusal from
                    # the holder proves the key is no longer held there
                    # (job evicted from history, key reissued): this is a
                    # fresh submit again, free to route anywhere
                    with self._jobs_lock:
                        self._dedupe_backend.pop(dedupe, None)
                    holder = None
                    failed_over = True  # state changed: re-scan unpinned
                if "retry_after_s" in resp:
                    sheds.append((resp["retry_after_s"], resp))
                    continue  # pressure here; try a less loaded peer
                if reason.startswith("queue full"):
                    b.note_depth((b.depth or 0) + 1)  # stale depth: learn
                    continue  # spill to the next backend
                if was_holder:
                    continue  # refusal from the ex-holder: others may admit
                return resp  # real refusal (draining/quota/validation)
            if sheds and not slept_hint:
                # EVERY reachable backend is shedding: honor the smallest
                # hint once (bounded), then retry the whole route — the
                # anti-hot-loop contract, balancer side
                hint = max(min(h for h, _ in sheds), 0.05)
                METRICS.inc("fleet.balancer.shed_sleeps")
                log.info("balance: all backends shedding; sleeping "
                         "retry_after_s hint %.2fs", hint)
                time.sleep(min(hint, MAX_SHED_SLEEP_S))
                slept_hint = True
                continue
            if sheds:
                # still shedding after one hint sleep: hand the (smallest)
                # hint to the client verbatim
                return min(sheds, key=lambda hr: hr[0])[1]
            if failed_over:
                continue  # every candidate died mid-submit: re-scan
            return protocol.error_response(
                "no backend admitted the job (all at capacity or "
                "probing)")
        return protocol.error_response(
            "no backend admitted the job (route retries exhausted)")

    def _route_status(self, req: dict) -> dict:
        job_id = req.get("id")
        if job_id is None:
            # aggregate listing: every healthy backend's jobs (+ the
            # balancer's own whale records) + our depth
            jobs = []
            if self._scatter is not None:
                jobs.extend(self._scatter.list_jobs())
            for b in self._healthy_backends():
                try:
                    resp = self._forward(b, req)
                except ServeError:
                    continue
                if resp.get("ok"):
                    jobs.extend(resp.get("jobs") or [])
            return protocol.ok_response(jobs=jobs)
        if self._scatter is not None:
            # whale ids live HERE, not on any backend
            whale = self._scatter.status(job_id)
            if whale is not None:
                return protocol.ok_response(job=whale)
        return self._routed_job_op(req, job_id)

    def _route_cancel(self, req: dict) -> dict:
        return self._routed_job_op(req, req["id"])

    def _routed_job_op(self, req: dict, job_id: str) -> dict:
        """status/cancel for one job id: mapped backend first, then fan
        out — a lease takeover moves jobs between backends and the map
        has no way to know. Fan-out reads never touch a half-open
        breaker's probe slot (_forward feeds only closed breakers)."""
        mapped = self._backend_for_job(job_id)
        tried = []
        last_refusal = None
        if mapped is not None and mapped.breaker.state != "open":
            tried.append(mapped)
            try:
                resp = self._forward(mapped, req)
                if resp.get("ok"):
                    return resp
                # the job's own backend KNOWS it: its refusal ("job is
                # running; never preempted" / "already cancelled") is the
                # actionable answer — the fan-out's "unknown job" from
                # peers must not mask it
                last_refusal = resp
            except ServeError:
                pass
        for b in self._healthy_backends():
            if b in tried:
                continue
            try:
                resp = self._forward(b, req)
            except ServeError:
                continue
            if resp.get("ok"):
                self._remember_job(job_id, b)  # learn the new home
                return resp
            if last_refusal is None:  # the mapped backend's answer wins
                last_refusal = resp
        return last_refusal or protocol.error_response(
            f"unknown job {job_id}")


# ---------------------------------------------------------------------------
# fleet metrics endpoint (balancer --metrics-port)


def render_fleet_prometheus(balancer: Balancer) -> str:
    """The balancer's ``/metrics`` body: fleet rollups, the balancer's own
    counters, and every backend's cached daemon series re-exported under
    the SAME metric names with a ``backend="ADDR"`` label (so one Grafana
    panel graphs ``fgumi_tpu_serve_job_e2e_submit_to_done_s`` quantiles
    per backend). Derived from one :meth:`Balancer.backend_scrape` — the
    identical cache read the ``stats`` op's ``fleet_metrics`` section
    uses, so the two surfaces can never disagree — and never probes a
    backend (staleness shows as ``fleet_backend_stats_age_s``)."""
    from .introspect import _num, _prom_name

    scrape = balancer.backend_scrape()
    snap = balancer.stats_snapshot(scrape=scrape)
    fleet = snap["fleet_metrics"]
    lines = []

    def gauge(dotted, value, labels="", help_text=None):
        name = _prom_name(dotted)
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{labels} {_num(value)}")

    gauge("fleet.balancer.uptime_s", snap["uptime_s"],
          help_text="balancer uptime in seconds")
    gauge("fleet.balancer.draining", int(bool(snap["draining"])))
    gauge("fleet.balancer.tracked_jobs", snap["tracked_jobs"])
    gauge("fleet.backends_total", fleet["backends_total"],
          help_text="configured backends")
    gauge("fleet.backends_healthy", fleet["backends_healthy"],
          help_text="routable backends (breaker not open, no sdc hold)")
    gauge("fleet.depth", fleet["fleet_depth"],
          help_text="queued+running summed over backends with known depth")
    gauge("fleet.takeovers", fleet["takeovers"],
          help_text="journal-lease takeovers summed over the fleet")
    gauge("fleet.takeover_jobs", fleet["takeover_jobs"])
    # whale scatter/gather gauges — from the SAME stats payload (one
    # coordinator snapshot per render, the same-snapshot rule again)
    scatter = snap.get("scatter")
    gauge("fleet.scatter.enabled", int(scatter is not None),
          help_text="1 when this balancer runs with --scatter N")
    if scatter is not None:
        gauge("fleet.scatter.shards_per_whale", scatter["shards"])
        for state, n in sorted(scatter["whales"].items()):
            gauge("fleet.scatter.whales_state",
                  n, f'{{state="{state}"}}')
        shard_states = {}
        for w in scatter["jobs"]:
            for state, n in w["shards"].items():
                shard_states[state] = shard_states.get(state, 0) + n
        for state, n in sorted(shard_states.items()):
            gauge("fleet.scatter.shards_state", n, f'{{state="{state}"}}')
    # the balancer's own flat counters (routing/transport activity —
    # includes the fleet.scatter.* whale/shard/gather counters)
    for dotted, v in sorted(snap["metrics"].items()):
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        lines.append(f"{_prom_name(dotted)} {_num(v)}")
    # per-backend series, all labelled with the backend address
    for entry, (b, _, stats, _) in zip(fleet["per_backend"], scrape):
        label = f'{{backend="{entry["address"]}"}}'
        gauge("fleet.backend.up", int(entry["routable"]), label)
        gauge("fleet.backend.breaker_open",
              int(entry["state"] == "open"), label)
        gauge("fleet.backend.sdc_hold", int(entry["sdc_hold"]), label)
        gauge("fleet.backend.audit_divergent",
              entry["audit_divergent"], label)
        gauge("fleet.backend.takeovers", entry["takeovers"], label)
        if entry["depth"] is not None:
            gauge("fleet.backend.depth", entry["depth"], label)
        if entry["stats_age_s"] is not None:
            gauge("fleet.backend.stats_age_s", entry["stats_age_s"], label)
        if stats is None:
            continue  # no successful poll yet: nothing cached to re-export
        for dotted, v in sorted((stats.get("metrics") or {}).items()):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            lines.append(f"{_prom_name(dotted)}{label} {_num(v)}")
        for dotted, summ in sorted((stats.get("latency") or {}).items()):
            if not isinstance(summ, dict):
                continue
            name = _prom_name(dotted)
            addr = entry["address"]
            for q, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
                if key in summ:
                    lines.append(f'{name}{{backend="{addr}",'
                                 f'quantile="{q}"}} {_num(summ[key])}')
            if "count" in summ:
                lines.append(f"{name}_count{label} {_num(summ['count'])}")
            if "sum" in summ:
                lines.append(f"{name}_sum{label} {_num(summ['sum'])}")
    return "\n".join(lines) + "\n"


def render_fleet_healthz(balancer: Balancer) -> tuple:
    """``(http_status, body_dict)`` for the balancer's ``/healthz``: 200
    while at least one backend is routable and the balancer is not
    draining, 503 otherwise (an upstream LB can eject the front end)."""
    scrape = balancer.backend_scrape()
    routable = sum(1 for _, snap, _, _ in scrape
                   if snap["state"] != "open" and not snap.get("sdc_hold"))
    healthy = routable > 0 and not balancer.draining
    body = {
        "status": "ok" if healthy else "degraded",
        "draining": balancer.draining,
        "backends_total": len(scrape),
        "backends_healthy": routable,
        "uptime_s": round(time.time() - balancer.started_unix, 1),
    }
    return (200 if healthy else 503), body
