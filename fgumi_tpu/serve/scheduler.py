"""Bounded-pool job scheduler: priority FIFO, admission control, drain.

The scheduling problem here is deliberately simple — the daemon's scarce
resource is the one warm device and the host cores around it, so the pool
is small and the policy is legible: jobs run in submission order within
their priority class (``high`` > ``normal`` > ``low``), at most ``workers``
concurrently. What the reference's 14-strategy scheduler zoo spends on
adaptive stage balancing, this spends on *predictability*: an operator can
say exactly why a job ran when it did.

Admission control is capacity-shaped, not queue-shaped: a submit is
admitted iff ``running + queued < workers + queue_limit``, otherwise it is
rejected immediately with a reason string (``queue full: ...``). Rejection
is a first-class answer — the protocol returns it as ``ok: false`` so a
caller can back off or route elsewhere; silently unbounded queues are how
serving systems die.

Drain (operator op or SIGTERM) closes admission; workers finish what is
queued and running, then park. ``join()`` waits for that quiescence.
"""

import heapq
import itertools
import logging
import threading

from .jobs import JobRegistry
from .protocol import PRIORITIES

log = logging.getLogger("fgumi_tpu")

_PRIO_RANK = {p: i for i, p in enumerate(PRIORITIES)}


class Scheduler:
    """Priority-FIFO queue + worker pool executing jobs via ``execute``.

    ``execute(job)`` is the daemon's job runner: it must return the job's
    exit status (int) and never raise (it converts exceptions into the
    job's ``failed`` record); the scheduler still guards against a raise so
    one broken job cannot kill a worker."""

    def __init__(self, execute, registry: JobRegistry, workers: int = 2,
                 queue_limit: int = 8, max_per_client: int = 0):
        if workers < 1:
            raise ValueError("workers must be >= 1")
        if queue_limit < 0:
            raise ValueError("queue-limit must be >= 0")
        if max_per_client < 0:
            raise ValueError("max-per-client must be >= 0")
        self._execute = execute
        self.registry = registry
        self.workers = workers
        self.queue_limit = queue_limit
        #: per-submitter admission quota (0 = unlimited): a client id may
        #: hold at most this many ACTIVE (queued + running) jobs — the
        #: first slice of multi-tenant admission (ROADMAP item 3). Jobs
        #: submitted without a client id are anonymous and never limited.
        self.max_per_client = max_per_client
        self._client_active = {}  # client id -> queued + running count
        self._heap = []  # (priority rank, seq, job)
        self._seq = itertools.count()
        self._cv = threading.Condition()
        self._running = 0
        self._draining = False
        self._threads = []
        self._started = False

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        if self._started:
            return
        self._started = True
        for i in range(self.workers):
            # plain threads on purpose (no contextvar copy): a worker must
            # NOT inherit the serve command's telemetry scope — each job
            # enters its own scope when the CLI re-enters main()
            t = threading.Thread(target=self._worker_loop, args=(i,),
                                 name=f"fgumi-serve-worker-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    # -- admission ----------------------------------------------------------

    def submit(self, job):
        """Admit ``job`` or reject it. Returns (admitted, reason)."""
        from ..observe.metrics import METRICS

        with self._cv:
            if self._draining:
                return False, "draining: daemon is not accepting new jobs"
            client = getattr(job, "client", None)
            if self.max_per_client and client:
                held = self._client_active.get(client, 0)
                if held >= self.max_per_client:
                    METRICS.inc("serve.quota.rejected")
                    return False, (
                        f"quota exceeded: client {client!r} holds {held} "
                        f"active job(s) >= max-per-client "
                        f"{self.max_per_client}")
            active = self._running + len(self._heap)
            capacity = self.workers + self.queue_limit
            if active >= capacity:
                return False, (
                    f"queue full: {self._running} running + "
                    f"{len(self._heap)} queued >= capacity {capacity} "
                    f"({self.workers} workers + {self.queue_limit} queue "
                    "slots)")
            heapq.heappush(self._heap,
                           (_PRIO_RANK[job.priority], next(self._seq), job))
            if client:
                self._client_active[client] = \
                    self._client_active.get(client, 0) + 1
                METRICS.inc("serve.quota.admitted")
                METRICS.max("serve.quota.clients",
                            len(self._client_active))
            self._cv.notify()
            return True, None

    def _release_client_locked(self, job):
        client = getattr(job, "client", None)
        if not client:
            return
        held = self._client_active.get(client, 0) - 1
        if held > 0:
            self._client_active[client] = held
        else:
            self._client_active.pop(client, None)

    def client_quota_state(self) -> dict:
        """{client id: active job count} (status/debugging surface)."""
        with self._cv:
            return dict(self._client_active)

    def cancel(self, job_id: str):
        """Cancel a *queued* job. Returns (ok, reason)."""
        with self._cv:
            for i, (rank, seq, job) in enumerate(self._heap):
                if job.id == job_id:
                    del self._heap[i]
                    heapq.heapify(self._heap)
                    self._release_client_locked(job)
                    self.registry.mark_cancelled(job)
                    return True, None
        job = self.registry.get(job_id)
        if job is None:
            return False, f"unknown job {job_id}"
        if job.state == "running":
            return False, (f"job {job_id} is running; running jobs are "
                           "never preempted")
        if job.state == "queued":
            # popped by a worker but not yet marked running: it is starting
            # this instant — telling the caller "already queued" would
            # contradict the cancel-a-queued-job contract
            return False, (f"job {job_id} is starting; running jobs are "
                           "never preempted")
        return False, f"job {job_id} is already {job.state}"

    # -- drain --------------------------------------------------------------

    def drain(self):
        """Close admission. Queued + running jobs still run to completion."""
        with self._cv:
            if not self._draining:
                log.info("scheduler: draining (admission closed; %d queued, "
                         "%d running)", len(self._heap), self._running)
            self._draining = True
            self._cv.notify_all()

    @property
    def draining(self) -> bool:
        with self._cv:
            return self._draining

    def idle(self) -> bool:
        with self._cv:
            return not self._heap and self._running == 0

    def join(self, timeout: float = None) -> bool:
        """Block until drained-and-idle. True when quiescent."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._heap or self._running:
                wait = None
                if deadline is not None:
                    wait = deadline - time.monotonic()
                    if wait <= 0:
                        return False
                self._cv.wait(wait if wait is not None else 1.0)
            return True

    def depth(self) -> dict:
        with self._cv:
            return {"queued": len(self._heap), "running": self._running,
                    "workers": self.workers,
                    "queue_limit": self.queue_limit,
                    "draining": self._draining}

    def active(self) -> int:
        """queued + running — the load figure fleet routing is based on
        (the balancer reads it off the stats op; the stats `fleet`
        section carries it directly)."""
        with self._cv:
            return len(self._heap) + self._running

    # -- worker -------------------------------------------------------------

    @staticmethod
    def _note_active_jobs(n: int):
        """Arm/disarm the cross-job dispatch coalescer (ops/coalesce.py):
        its merge window only opens while >= 2 jobs are actually RUNNING
        in this process — a lone job never pays a hold. Called UNDER the
        scheduler condition so two workers' updates cannot publish out
        of order (a stale count would disarm the window for the lifetime
        of both jobs, or tax a lone job with partner-less holds); the
        coalescer's own lock nests strictly inside and never calls back.
        Never fails a worker."""
        try:
            from ..ops.coalesce import COALESCER

            COALESCER.set_active_jobs(n)
        except Exception:  # noqa: BLE001 - telemetry must not kill workers
            log.debug("coalescer active-job signal failed", exc_info=True)

    def _worker_loop(self, widx: int):
        while True:
            with self._cv:
                while not self._heap:
                    self._cv.wait()
                _, _, job = heapq.heappop(self._heap)
                self._running += 1
                self._note_active_jobs(self._running)
            try:
                self.registry.mark_running(job)
                rc = self._execute(job)
                # executors normally record the outcome themselves; cover
                # the minimal contract for bare test executors
                if job.state == "running":
                    self.registry.mark_done(job, rc if rc is not None else 0)
            except BaseException as e:  # noqa: BLE001 - worker must survive
                log.exception("serve worker %d: job %s runner raised",
                              widx, job.id)
                if job.state == "running":
                    try:
                        self.registry.mark_failed(
                            job, f"{type(e).__name__}: {e}")
                    except Exception:
                        pass
            finally:
                with self._cv:
                    self._running -= 1
                    self._note_active_jobs(self._running)
                    self._release_client_locked(job)
                    self._cv.notify_all()
