"""Append-only job journal (write-ahead log) for crash-recoverable serving.

The PR 3 daemon kept its job registry in memory only: a SIGKILL (OOM
killer, node preemption) forgot every queued and running job, and clients
were left polling ids that no longer existed. The journal is the daemon's
durable memory — one JSONL record per event, fsync'd before the event is
acted on, schema-versioned like the wire protocol and the run report:

    {"v": 1, "ev": "submit", "t": <unix>, "id": "j-3", "argv": [...],
     "priority": "normal", "argv0": "fgumi-tpu", "tag": null,
     "trace": false, "dedupe": "<idempotency key or null>",
     "client": "<submitter id or null>"}
    {"v": 1, "ev": "state", "t": <unix>, "id": "j-3",
     "state": "running" | "done" | "failed" | "cancelled" | "requeued",
     "exit_status": <int or null>, "error": "<diagnostic or null>"}

Write discipline (the ``utils/atomic`` philosophy applied to an append-only
file): every record is one ``write() + flush() + fsync()`` of a single
``\\n``-terminated line, so a crash can tear at most the final line. Replay
therefore treats the first undecodable line as the torn tail, truncates the
file back to the last good record, and carries on — a corrupt tail costs
one un-acknowledged event, never the history before it.

Recovery semantics (docs/serving.md "Crash recovery"): a job whose last
journaled state is non-terminal (``queued``/``running``/``requeued``) is
**requeued** on daemon restart, in original submission order. This is safe
because job outputs are atomic-commit (PR 1): a job killed mid-run never
published a partial artifact, so re-running it from scratch is
byte-identical to having run it once. Terminal jobs are restored to the
registry read-only so clients polling an old id get its final record, and
``dedupe`` keys are rebuilt so an idempotent resubmit after the crash
returns the already-finished job instead of running it twice.
"""

import json
import logging
import os
import threading
import time

from .jobs import TERMINAL, Job

log = logging.getLogger("fgumi_tpu")

JOURNAL_VERSION = 1

#: journaled states beyond the registry's own (requeued marks a recovery)
_EVENTS = ("submit", "state")


class ReplayResult:
    """Everything a restarting daemon needs from the journal."""

    def __init__(self):
        self.jobs = []            # [record dicts] in submission order
        self.by_id = {}           # id -> merged record (spec + last state)
        self.dedupe = {}          # dedupe key -> job id
        self.max_job_num = 0      # highest numeric j-<n> suffix seen
        self.records = 0          # good records read
        self.truncated_bytes = 0  # torn-tail bytes removed
        self.last_entry_unix = None  # t of the last good record

    def incomplete(self):
        """Submission-ordered records whose last state is non-terminal —
        the requeue set."""
        return [r for r in self.jobs if r["state"] not in TERMINAL]


def replay(path: str) -> ReplayResult:
    """Read a journal, truncating a torn tail in place.

    Missing file -> empty result (first boot). The first line that fails
    to decode — torn write, partial flush, disk garbage — marks the tail:
    everything from its byte offset on is discarded AND the file is
    truncated back to the last good record, so the next append continues
    a clean log instead of interleaving with garbage."""
    out = ReplayResult()
    if not os.path.exists(path):
        return out
    good_end = 0
    with open(path, "rb") as f:
        while True:
            line = f.readline()
            if not line:
                break
            if not line.endswith(b"\n"):
                break  # torn tail: no newline made it to disk
            try:
                rec = json.loads(line)
                if not isinstance(rec, dict) or rec.get("ev") not in _EVENTS:
                    raise ValueError(f"not a journal record: {rec!r:.80}")
                if rec.get("v") != JOURNAL_VERSION:
                    raise ValueError(
                        f"journal version {rec.get('v')!r} != "
                        f"{JOURNAL_VERSION}")
            except ValueError as e:
                log.warning("journal %s: undecodable record at byte %d "
                            "(%s); truncating tail", path, good_end, e)
                break
            good_end += len(line)
            out.records += 1
            out.last_entry_unix = rec.get("t", out.last_entry_unix)
            _fold(out, rec)
        f.seek(0, os.SEEK_END)
        total = f.tell()
    if total > good_end:
        out.truncated_bytes = total - good_end
        with open(path, "r+b") as f:
            f.truncate(good_end)
        log.warning("journal %s: dropped %d torn-tail byte(s)", path,
                    out.truncated_bytes)
    return out


def _fold(out: ReplayResult, rec: dict):
    ev = rec["ev"]
    jid = rec.get("id")
    if not isinstance(jid, str):
        return
    if ev == "submit":
        merged = {
            "id": jid,
            "argv": list(rec.get("argv") or []),
            "priority": rec.get("priority", "normal"),
            "argv0": rec.get("argv0"),
            "tag": rec.get("tag"),
            "trace": bool(rec.get("trace")),
            "dedupe": rec.get("dedupe"),
            "client": rec.get("client"),
            "state": "queued",
            "exit_status": None,
            "error": None,
            "submitted_unix": rec.get("t"),
        }
        if jid not in out.by_id:  # first submit wins (resubmits dedupe)
            out.by_id[jid] = merged
            out.jobs.append(merged)
            if rec.get("dedupe"):
                out.dedupe[rec["dedupe"]] = jid
        suffix = jid.rsplit("-", 1)[-1]
        if suffix.isdigit():
            out.max_job_num = max(out.max_job_num, int(suffix))
    else:  # state
        merged = out.by_id.get(jid)
        if merged is None:
            return  # state for a job whose submit fell off the tail
        state = rec.get("state")
        merged["state"] = "queued" if state == "requeued" else state
        merged["exit_status"] = rec.get("exit_status")
        merged["error"] = rec.get("error")
        if state in TERMINAL:
            merged["finished_unix"] = rec.get("t")


class JobJournal:
    """The append side: one fsync'd line per event (thread-safe).

    Construct AFTER :func:`replay` has truncated any torn tail — the
    journal opens in append mode and trusts the file to end on a record
    boundary."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "ab")
        self.appended = 0

    def _append(self, rec: dict):
        rec = {"v": JOURNAL_VERSION, "t": round(time.time(), 3), **rec}
        line = json.dumps(rec, separators=(",", ":"),
                          sort_keys=True).encode() + b"\n"
        with self._lock:
            if self._f.closed:
                return  # daemon already shut down; nothing left to promise
            self._f.write(line)
            self._f.flush()
            try:
                os.fsync(self._f.fileno())
            except OSError:
                pass  # fsync-incapable target: flush is the best we have
            self.appended += 1

    def record_submit(self, job: Job, dedupe: str = None):
        self._append({"ev": "submit", "id": job.id, "argv": job.argv,
                      "priority": job.priority, "argv0": job.argv0,
                      "tag": job.tag, "trace": job.trace, "dedupe": dedupe,
                      "client": job.client})

    def record_state(self, job: Job):
        self._append({"ev": "state", "id": job.id, "state": job.state,
                      "exit_status": job.exit_status, "error": job.error})

    def record_requeued(self, job_id: str):
        self._append({"ev": "state", "id": job_id, "state": "requeued",
                      "exit_status": None, "error": None})

    def close(self):
        with self._lock:
            if not self._f.closed:
                self._f.close()
