"""Append-only job journal (write-ahead log) for crash-recoverable serving.

The PR 3 daemon kept its job registry in memory only: a SIGKILL (OOM
killer, node preemption) forgot every queued and running job, and clients
were left polling ids that no longer existed. The journal is the daemon's
durable memory — one JSONL record per event, fsync'd before the event is
acted on, schema-versioned like the wire protocol and the run report:

    {"v": 1, "ev": "submit", "t": <unix>, "id": "j-3", "argv": [...],
     "priority": "normal", "argv0": "fgumi-tpu", "tag": null,
     "trace": false, "dedupe": "<idempotency key or null>",
     "client": "<submitter id or null>",
     "traceparent": "<propagated trace context or null>",
     "hops": {"client_sent_unix": ...} | null,
     "shard": {"whale": "w-ab12-1", "index": 0, ...} | null}
    {"v": 1, "ev": "state", "t": <unix>, "id": "j-3",
     "state": "running" | "done" | "failed" | "cancelled" | "requeued",
     "exit_status": <int or null>, "error": "<diagnostic or null>"}

Write discipline (the ``utils/atomic`` philosophy applied to an append-only
file): every record is one ``write() + flush() + fsync()`` of a single
``\\n``-terminated line, so a crash can tear at most the final line. Replay
therefore treats the first undecodable line as the torn tail, truncates the
file back to the last good record, and carries on — a corrupt tail costs
one un-acknowledged event, never the history before it.

Recovery semantics (docs/serving.md "Crash recovery"): a job whose last
journaled state is non-terminal (``queued``/``running``/``requeued``) is
**requeued** on daemon restart, in original submission order. This is safe
because job outputs are atomic-commit (PR 1): a job killed mid-run never
published a partial artifact, so re-running it from scratch is
byte-identical to having run it once. Terminal jobs are restored to the
registry read-only so clients polling an old id get its final record, and
``dedupe`` keys are rebuilt so an idempotent resubmit after the crash
returns the already-finished job instead of running it twice.
"""

import fcntl
import json
import logging
import os
import re
import threading
import time

from .jobs import TERMINAL, Job

log = logging.getLogger("fgumi_tpu")

JOURNAL_VERSION = 1

#: journaled states beyond the registry's own (requeued marks a recovery)
_EVENTS = ("submit", "state")


class ReplayResult:
    """Everything a restarting daemon needs from the journal."""

    def __init__(self):
        self.jobs = []            # [record dicts] in submission order
        self.by_id = {}           # id -> merged record (spec + last state)
        self.dedupe = {}          # dedupe key -> job id
        self.max_job_num = 0      # highest numeric j-<n> suffix seen
        self.records = 0          # good records read
        self.truncated_bytes = 0  # torn-tail bytes removed
        self.last_entry_unix = None  # t of the last good record

    def incomplete(self):
        """Submission-ordered records whose last state is non-terminal —
        the requeue set."""
        return [r for r in self.jobs if r["state"] not in TERMINAL]


def replay(path: str) -> ReplayResult:
    """Read a journal, truncating a torn tail in place.

    Missing file -> empty result (first boot). The first line that fails
    to decode — torn write, partial flush, disk garbage — marks the tail:
    everything from its byte offset on is discarded AND the file is
    truncated back to the last good record, so the next append continues
    a clean log instead of interleaving with garbage."""
    out = ReplayResult()
    if not os.path.exists(path):
        return out
    good_end = 0
    with open(path, "rb") as f:
        while True:
            line = f.readline()
            if not line:
                break
            if not line.endswith(b"\n"):
                break  # torn tail: no newline made it to disk
            try:
                rec = json.loads(line)
                if not isinstance(rec, dict) or rec.get("ev") not in _EVENTS:
                    raise ValueError(f"not a journal record: {rec!r:.80}")
                if rec.get("v") != JOURNAL_VERSION:
                    raise ValueError(
                        f"journal version {rec.get('v')!r} != "
                        f"{JOURNAL_VERSION}")
            except ValueError as e:
                log.warning("journal %s: undecodable record at byte %d "
                            "(%s); truncating tail", path, good_end, e)
                break
            good_end += len(line)
            out.records += 1
            out.last_entry_unix = rec.get("t", out.last_entry_unix)
            _fold(out, rec)
        f.seek(0, os.SEEK_END)
        total = f.tell()
    if total > good_end:
        out.truncated_bytes = total - good_end
        with open(path, "r+b") as f:
            f.truncate(good_end)
        log.warning("journal %s: dropped %d torn-tail byte(s)", path,
                    out.truncated_bytes)
    return out


def _fold(out: ReplayResult, rec: dict):
    ev = rec["ev"]
    jid = rec.get("id")
    if not isinstance(jid, str):
        return
    if ev == "submit":
        merged = {
            "id": jid,
            "argv": list(rec.get("argv") or []),
            "priority": rec.get("priority", "normal"),
            "argv0": rec.get("argv0"),
            "tag": rec.get("tag"),
            "trace": bool(rec.get("trace")),
            "dedupe": rec.get("dedupe"),
            "client": rec.get("client"),
            # trace context survives restart AND fleet takeover: the job
            # keeps its client-visible correlation ids wherever it lands
            "traceparent": rec.get("traceparent"),
            "hops": rec.get("hops"),
            # scatter metadata survives too: a taken-over shard sub-job
            # stays attributable to its whale
            "shard": rec.get("shard"),
            "state": "queued",
            "exit_status": None,
            "error": None,
            "submitted_unix": rec.get("t"),
        }
        if jid not in out.by_id:  # first submit wins (resubmits dedupe)
            out.by_id[jid] = merged
            out.jobs.append(merged)
            if rec.get("dedupe"):
                out.dedupe[rec["dedupe"]] = jid
        suffix = jid.rsplit("-", 1)[-1]
        if suffix.isdigit():
            out.max_job_num = max(out.max_job_num, int(suffix))
    else:  # state
        merged = out.by_id.get(jid)
        if merged is None:
            return  # state for a job whose submit fell off the tail
        state = rec.get("state")
        merged["state"] = "queued" if state == "requeued" else state
        merged["exit_status"] = rec.get("exit_status")
        merged["error"] = rec.get("error")
        if state in TERMINAL:
            merged["finished_unix"] = rec.get("t")


class JobJournal:
    """The append side: one fsync'd line per event (thread-safe).

    Construct AFTER :func:`replay` has truncated any torn tail — the
    journal opens in append mode and trusts the file to end on a record
    boundary."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "ab")
        self.appended = 0

    def _append(self, rec: dict):
        rec = {"v": JOURNAL_VERSION, "t": round(time.time(), 3), **rec}
        line = json.dumps(rec, separators=(",", ":"),
                          sort_keys=True).encode() + b"\n"
        with self._lock:
            if self._f.closed:
                return  # daemon already shut down; nothing left to promise
            self._f.write(line)
            self._f.flush()
            try:
                os.fsync(self._f.fileno())
            except OSError:
                pass  # fsync-incapable target: flush is the best we have
            self.appended += 1

    def record_submit(self, job: Job, dedupe: str = None):
        self._append({"ev": "submit", "id": job.id, "argv": job.argv,
                      "priority": job.priority, "argv0": job.argv0,
                      "tag": job.tag, "trace": job.trace, "dedupe": dedupe,
                      "client": job.client, "traceparent": job.traceparent,
                      "hops": job.hops, "shard": job.shard})

    def record_state(self, job: Job):
        self._append({"ev": "state", "id": job.id, "state": job.state,
                      "exit_status": job.exit_status, "error": job.error})

    def record_requeued(self, job_id: str):
        self._append({"ev": "state", "id": job_id, "state": "requeued",
                      "exit_status": None, "error": None})

    def close(self):
        with self._lock:
            if not self._f.closed:
                self._f.close()


# ---------------------------------------------------------------------------
# fleet leases: journal ownership across daemons sharing a --journal-dir
#
# The liveness primitive is an fcntl flock held on `<fleet-id>.lease` for
# the OWNING daemon's whole lifetime. flock dies with the process — even
# SIGKILL — so "can I take this lock?" is an exact liveness test with no
# heartbeat clocks to tune and no clock-skew failure mode. Takeover is
# therefore race-free by construction: exactly one claimant can hold a dead
# peer's lease lock while consuming its journal, and the journal is renamed
# to `<fleet-id>.journal.claimed` under that lock, so a late second
# claimant (or the dead daemon restarting) finds nothing to replay. All
# daemons must share one real filesystem (flock over NFS is advisory at
# best — docs/serving.md "Fleet operation").

#: fleet ids are path-component-safe by construction.
_FLEET_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")

_JOURNAL_SUFFIX = ".journal"
_LEASE_SUFFIX = ".lease"
_CLAIMED_SUFFIX = ".journal.claimed"


class LeaseHeld(RuntimeError):
    """The lease is held by a live process (reason in str())."""


def validate_fleet_id(fleet_id: str) -> str:
    if not isinstance(fleet_id, str) or not _FLEET_ID_RE.match(fleet_id):
        raise ValueError(
            f"invalid fleet id {fleet_id!r}: must match "
            "[A-Za-z0-9][A-Za-z0-9._-]{0,63}")
    return fleet_id


def fleet_paths(journal_dir: str, fleet_id: str):
    """(journal_path, lease_path) for one daemon's identity in the dir."""
    validate_fleet_id(fleet_id)
    return (os.path.join(journal_dir, fleet_id + _JOURNAL_SUFFIX),
            os.path.join(journal_dir, fleet_id + _LEASE_SUFFIX))


def scan_peer_journals(journal_dir: str, own_id: str):
    """Unclaimed peer journals in the dir: [(peer_id, journal_path,
    lease_path)], excluding our own identity. Sorted for deterministic
    claim order."""
    out = []
    try:
        names = os.listdir(journal_dir)
    except OSError:
        return out
    for name in sorted(names):
        if not name.endswith(_JOURNAL_SUFFIX):
            continue
        peer_id = name[:-len(_JOURNAL_SUFFIX)]
        if peer_id == own_id or not _FLEET_ID_RE.match(peer_id):
            continue
        out.append((peer_id,
                    os.path.join(journal_dir, name),
                    os.path.join(journal_dir, peer_id + _LEASE_SUFFIX)))
    return out


class FleetLease:
    """The flock held on ``<fleet-id>.lease`` for a daemon's lifetime.

    :meth:`acquire` is how a daemon claims its own identity at startup
    (bounded retry: a peer may hold our lock for the instant it takes to
    claim our crashed predecessor's journal); :meth:`try_claim` is the
    one-shot non-blocking grab a takeover scanner uses on a PEER's lease
    — returns None while the peer lives."""

    def __init__(self, path: str):
        self.path = path
        self._fd = None

    @property
    def held(self) -> bool:
        return self._fd is not None

    def acquire(self, wait_s: float = 30.0, poll_s: float = 0.1):
        """Take the lease or raise :class:`LeaseHeld`.

        The bounded wait covers the legitimate contention window — a
        surviving peer holds OUR lease while it consumes our
        predecessor's journal, which is one fsync'd WAL append per
        adopted job and can take seconds for a deep queue on a loaded
        disk. Anything longer means a live daemon with the same fleet
        id, which is a configuration error."""
        if self._fd is not None:
            return
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o600)
        deadline = time.monotonic() + max(wait_s, 0.0)
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    os.close(fd)
                    raise LeaseHeld(
                        f"fleet lease {self.path} is held by a live "
                        "process (another daemon with this fleet id?)")
                time.sleep(poll_s)
        # advisory breadcrumb for operators; the LOCK is the authority
        try:
            os.ftruncate(fd, 0)
            os.write(fd, json.dumps(
                {"pid": os.getpid(), "acquired_unix": round(time.time(), 3)}
            ).encode() + b"\n")
        except OSError:
            pass
        self._fd = fd

    def release(self):
        if self._fd is not None:
            try:
                os.close(self._fd)  # closing the fd drops the flock
            except OSError:
                pass
            self._fd = None

    @staticmethod
    def try_claim(path: str):
        """Non-blocking exclusive grab of a (peer's) lease file.

        Returns an open fd HOLDING the lock when the owner is provably
        dead (flock released by the kernel on its exit), or None while
        the owner lives. The caller must ``os.close()`` the fd once the
        claim work is done."""
        try:
            fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        except OSError:
            return None
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return None
        return fd


def mark_claimed(journal_path: str) -> str:
    """Rename a consumed peer journal to its ``.claimed`` audit name
    (must be called while holding the peer's lease lock). A previous
    claim artifact at the target is replaced — the newest takeover is
    the interesting one."""
    claimed = journal_path[:-len(_JOURNAL_SUFFIX)] + _CLAIMED_SUFFIX
    os.replace(journal_path, claimed)
    return claimed
