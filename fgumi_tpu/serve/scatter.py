"""Whale-job scatter/gather: one sample distributed across the fleet.

A "whale" is a single submitted ``pipeline``/``simplex``/``duplex`` job
big enough to be worth the whole fleet. ``balance --scatter N`` arms the
planner in the balancer: a recognized submit is split into N dedupe-keyed
shard sub-jobs fanned out through the existing health-routed
:meth:`~.balancer.Balancer._route_submit` path, tracked per shard in the
balancer's scatter WAL, and finished by a gather stage that k-way merges
the shards' ordered outputs (``core/sharding.gather_shards``, built on
``sort/external.merge_keyed_streams``) into ONE byte-deterministic BAM —
identical to a single-backend run regardless of shard count, backend
assignment, or which backends died along the way.

How the split stays deterministic: every shard job consumes the FULL
grouped stream and keeps the families whose content hash (UMI ``MI`` value
or template-coordinate bytes — both explicit, never Python's seeded
``hash()``) lands in its bucket, writing a sidecar manifest of (global
family ordinal, MI) pairs. The gather merges manifests by ordinal, so the
merged record order is exactly the single-run order (docs/serving.md
"Fleet operation > Scatter/gather").

Failure semantics:

- A backend dying mid-shard is the fleet's ordinary takeover: the
  survivor's lease scan requeues the shard under its ORIGINAL id, the
  coordinator's status poll (mapped-backend-first, then fan-out) finds it
  again, and the dedupe key guarantees zero double-execution. The
  coordinator only resubmits a shard itself — under an attempt-suffixed
  dedupe key — after the id stays unknown fleet-wide past a grace window,
  i.e. when no journal takeover exists to revive it.
- A shard that terminally *fails* (the command itself exited nonzero)
  fails the whale with the shard's diagnostic; re-running a
  deterministic failure would only repeat it.
- The gather requires the balancer to see the backends' filesystem (the
  same shared-filesystem assumption the journal-lease takeover already
  makes).

Fairness: a whale never monopolizes the fleet — each whale's outstanding
shard count is capped at its share of the healthy backends (at least 1),
recomputed as whales come and go; shard sub-jobs inherit the submitter's
``client`` identity so the daemons' per-client admission quota
(``serve --max-per-client``) bounds a whale exactly like any other
submitter.
"""

import json
import logging
import os
import shlex
import threading
import time

from ..core.sharding import SHARD_AXES
from . import protocol

log = logging.getLogger("fgumi_tpu")

SCATTER_WAL_VERSION = 1

#: whale job states reuse the daemon's lifecycle vocabulary so
#: ``ServeClient.wait`` and ``fgumi-tpu submit`` work unchanged
TERMINAL = frozenset(("done", "failed", "cancelled"))

#: job kinds the planner recognizes (consensus commands whose output is a
#: grouped-order BAM the manifest gather can reassemble)
SCATTERABLE = frozenset(("pipeline", "simplex", "duplex"))

#: submit-refusal substrings the shard runner treats as transient (the
#: fleet is busy/recovering — retry) rather than fatal to the whale
_TRANSIENT_MARKERS = (
    "no backend admitted",
    "no healthy backends",
    "resource_pressure",
    "timed out mid-submit",
    "may still be executing",
    "recovering (half-open",
    "failed mid-submit",
    "refused the conversation",
    "queue full",
)


class ScatterPlan:
    """One whale's shard decomposition (pure data; no I/O)."""

    __slots__ = ("kind", "out_path", "axis", "count", "level",
                 "shard_argvs", "shard_outs", "manifest_paths")

    def __init__(self, kind, out_path, axis, count, level,
                 shard_argvs, shard_outs, manifest_paths):
        self.kind = kind
        self.out_path = out_path
        self.axis = axis
        self.count = int(count)
        self.level = level
        self.shard_argvs = shard_argvs
        self.shard_outs = shard_outs
        self.manifest_paths = manifest_paths

    def to_wire(self) -> dict:
        return {"kind": self.kind, "out": self.out_path, "axis": self.axis,
                "count": self.count, "level": self.level,
                "shard_argvs": [list(a) for a in self.shard_argvs],
                "shard_outs": list(self.shard_outs),
                "manifests": list(self.manifest_paths)}

    @classmethod
    def from_wire(cls, d: dict):
        return cls(d["kind"], d["out"], d["axis"], d["count"], d["level"],
                   [list(a) for a in d["shard_argvs"]],
                   list(d["shard_outs"]), list(d["manifests"]))


def _flag_value(argv, *names):
    """Value of the first ``--flag V`` / ``--flag=V`` occurrence, with
    its index, or (None, -1)."""
    for i, a in enumerate(argv):
        for name in names:
            if a == name and i + 1 < len(argv):
                return argv[i + 1], i + 1
            if a.startswith(name + "="):
                return a[len(name) + 1:], i
    return None, -1


def shard_output_path(out_path: str, index: int, count: int) -> str:
    """The shard sub-job's output next to the whale's final output."""
    return f"{out_path}.s{index}of{count}.scatter.bam"


def plan_scatter(argv, argv0: str, shards: int, axis: str):
    """Decompose a submitted command into shard sub-job argvs.

    Returns a :class:`ScatterPlan`, or None when the command is not a
    scatterable consensus job (anything else — sort, group, simulate,
    a job already carrying ``--shard`` — routes normally). The shard
    argv keeps every user flag; it only rewrites ``-o`` to the shard
    output, appends the ``--shard`` selection plus its manifest path,
    and pins ``--pg-argv`` to the WHALE's command line so every shard
    header (``@PG CL``) — and therefore the gathered header — is
    byte-identical to the single-backend run's."""
    if not argv or argv[0] not in SCATTERABLE or shards < 2:
        return None
    if axis not in SHARD_AXES:
        raise ValueError(f"unknown scatter axis {axis!r} "
                         f"(known: {', '.join(SHARD_AXES)})")
    if _flag_value(argv, "--shard")[0] is not None:
        return None  # already a shard sub-job: never re-scatter
    out, out_i = _flag_value(argv, "-o", "--output")
    if out is None or out == "-":
        return None
    level_s, _ = _flag_value(argv, "--compression-level")
    try:
        level = int(level_s) if level_s is not None else None
    except ValueError:
        return None  # the daemon would reject it; let it answer
    pg = shlex.join([argv0 or "fgumi-tpu"] + list(argv))
    shard_argvs, shard_outs, manifests = [], [], []
    for k in range(shards):
        s_out = shard_output_path(out, k, shards)
        s_argv = list(argv)
        if s_argv[out_i].startswith(("-o=", "--output=")):
            flag = s_argv[out_i].split("=", 1)[0]
            s_argv[out_i] = f"{flag}={s_out}"
        else:
            s_argv[out_i] = s_out
        s_argv += ["--shard", f"{k}/{shards}", "--shard-by", axis,
                   "--shard-manifest", s_out + ".manifest.npy",
                   "--pg-argv", pg]
        shard_argvs.append(s_argv)
        shard_outs.append(s_out)
        manifests.append(s_out + ".manifest.npy")
    return ScatterPlan(argv[0], out, axis, shards, level,
                       shard_argvs, shard_outs, manifests)


# ---------------------------------------------------------------------------
# scatter WAL: the balancer's durable memory of in-flight whales


class ScatterWal:
    """Append-only fsync'd JSONL of whale/shard state (the journal.py
    write discipline: one line per event, torn tail truncated on replay,
    so a balancer crash costs at most the final unacknowledged event).
    Replay returns whale records ready to resume — every shard resubmit
    is idempotent by its dedupe key, so resuming is always safe."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "ab")

    def append(self, rec: dict):
        rec = {"v": SCATTER_WAL_VERSION, "t": round(time.time(), 3), **rec}
        line = json.dumps(rec, separators=(",", ":"),
                          sort_keys=True).encode() + b"\n"
        with self._lock:
            if self._f.closed:
                return
            self._f.write(line)
            self._f.flush()
            try:
                os.fsync(self._f.fileno())
            except OSError:
                pass

    def close(self):
        with self._lock:
            if not self._f.closed:
                self._f.close()

    @staticmethod
    def replay(path: str):
        """``(whales_by_id, max_whale_num)`` folded from the WAL; the
        file is truncated back to the last good record first."""
        whales, max_num = {}, 0
        if not os.path.exists(path):
            return whales, max_num
        good_end = 0
        with open(path, "rb") as f:
            while True:
                line = f.readline()
                if not line or not line.endswith(b"\n"):
                    break
                try:
                    rec = json.loads(line)
                    if not isinstance(rec, dict) \
                            or rec.get("v") != SCATTER_WAL_VERSION:
                        raise ValueError("not a scatter WAL record")
                except ValueError as e:
                    log.warning("scatter wal %s: undecodable record at "
                                "byte %d (%s); truncating tail",
                                path, good_end, e)
                    break
                good_end += len(line)
                _fold_wal(whales, rec)
                suffix = str(rec.get("id", "")).rsplit("-", 1)[-1]
                if rec.get("ev") == "whale" and suffix.isdigit():
                    max_num = max(max_num, int(suffix))
            f.seek(0, os.SEEK_END)
            total = f.tell()
        if total > good_end:
            with open(path, "r+b") as f:
                f.truncate(good_end)
            log.warning("scatter wal %s: dropped %d torn-tail byte(s)",
                        path, total - good_end)
        return whales, max_num


def _fold_wal(whales: dict, rec: dict):
    ev = rec.get("ev")
    if ev == "whale":
        wid = rec["id"]
        if wid in whales:
            return  # first submit wins
        whales[wid] = {
            "id": wid, "argv": list(rec.get("argv") or []),
            "argv0": rec.get("argv0"), "priority": rec.get("priority"),
            "tag": rec.get("tag"), "client": rec.get("client"),
            "dedupe": rec.get("dedupe"), "plan": rec.get("plan"),
            "state": "queued", "error": None,
            "submitted_unix": rec.get("t"),
            "shards": {},  # k -> {state, job_id, attempt, dedupe}
        }
    elif ev == "shard":
        w = whales.get(rec.get("whale"))
        if w is None:
            return
        w["shards"][int(rec["k"])] = {
            "state": rec.get("state"), "job_id": rec.get("job_id"),
            "attempt": int(rec.get("attempt") or 0),
            "dedupe": rec.get("dedupe"),
        }
    elif ev == "whale_state":
        w = whales.get(rec.get("id"))
        if w is None:
            return
        w["state"] = rec.get("state")
        w["error"] = rec.get("error")
        if rec.get("state") in TERMINAL:
            w["finished_unix"] = rec.get("t")


# ---------------------------------------------------------------------------
# the whale record and its coordinator


class WhaleJob:
    """One scattered job's balancer-side record. ``to_wire`` mimics the
    daemon :class:`~.jobs.Job` shape so ``status``/``wait``/``submit``
    clients need no new vocabulary; the extra ``scatter`` section carries
    per-shard state."""

    def __init__(self, whale_id: str, argv, plan: ScatterPlan,
                 argv0=None, priority="normal", tag=None, client=None,
                 dedupe=None):
        self.id = whale_id
        self.argv = list(argv)
        self.argv0 = argv0 or "fgumi-tpu"
        self.priority = priority
        self.tag = tag
        self.client = client
        self.dedupe = dedupe
        self.plan = plan
        self.state = "queued"
        self.error = None
        self.submitted_unix = time.time()
        self.started_unix = None
        self.finished_unix = None
        self.exit_status = None
        self._lock = threading.Lock()
        self._cancel = threading.Event()
        #: k -> {"state", "job_id", "attempt", "dedupe", "unknown_since"}
        self.shards = {
            k: {"state": "planned", "job_id": None, "attempt": 0,
                "dedupe": f"{whale_id}-s{k}", "unknown_since": None}
            for k in range(plan.count)}

    def shard_counts(self) -> dict:
        with self._lock:
            out = {}
            for s in self.shards.values():
                out[s["state"]] = out.get(s["state"], 0) + 1
            return out

    def to_wire(self) -> dict:
        with self._lock:
            shards = [
                {"index": k, "state": s["state"], "job_id": s["job_id"],
                 "attempt": s["attempt"]}
                for k, s in sorted(self.shards.items())]
        return {
            "id": self.id,
            "state": self.state,
            "argv": list(self.argv),
            "priority": self.priority,
            "tag": self.tag,
            "client": self.client,
            "submitted_unix": round(self.submitted_unix, 3),
            "started_unix": (round(self.started_unix, 3)
                             if self.started_unix else None),
            "finished_unix": (round(self.finished_unix, 3)
                              if self.finished_unix else None),
            "exit_status": self.exit_status,
            "error": self.error,
            "scatter": {"axis": self.plan.axis, "count": self.plan.count,
                        "out": self.plan.out_path, "shards": shards},
        }


class ScatterCoordinator:
    """Plans, fans out, tracks, and gathers whale jobs for a balancer.

    One runner thread per in-flight whale (a whale is by definition rare
    and heavy; the thread spends its life sleeping between status polls).
    All backend traffic goes through the balancer's own routing —
    ``_route_submit`` for shard fan-out (dedupe stickiness, breaker
    ejection, shed handling included) and ``_routed_job_op`` for shard
    status/cancel (mapped-backend-first, then fan-out, which is exactly
    how a post-takeover shard is found again)."""

    def __init__(self, balancer, shards: int, axis: str = "umi",
                 wal_path: str = None, poll_s: float = 0.5,
                 requeue_grace_s: float = 20.0, keep_finished: int = 100):
        if shards < 2:
            raise ValueError("--scatter needs at least 2 shards")
        if axis not in SHARD_AXES:
            raise ValueError(f"unknown scatter axis {axis!r} "
                             f"(known: {', '.join(SHARD_AXES)})")
        self.balancer = balancer
        self.shards = int(shards)
        self.axis = axis
        self.poll_s = float(poll_s)
        self.requeue_grace_s = float(requeue_grace_s)
        self.keep_finished = int(keep_finished)
        self._lock = threading.Lock()
        self._whales = {}          # id -> WhaleJob, insertion-ordered
        self._dedupe = {}          # whale dedupe key -> whale id
        self._threads = {}         # id -> runner thread
        self._next_num = 1
        self._closed = threading.Event()
        # per-boot id token: whale ids (and therefore shard dedupe keys)
        # never collide with a previous balancer incarnation's even
        # without a WAL
        self._boot = os.urandom(2).hex()
        self.wal = ScatterWal(wal_path) if wal_path else None
        self._resume = []
        if wal_path:
            replayed, max_num = ScatterWal.replay(wal_path)
            self._next_num = max_num + 1
            self._restore(replayed)

    # -- restart resume -----------------------------------------------------

    def _restore(self, replayed: dict):
        for wid, rec in replayed.items():
            plan = rec.get("plan")
            if not plan:
                continue
            whale = WhaleJob(wid, rec["argv"], ScatterPlan.from_wire(plan),
                             argv0=rec.get("argv0"),
                             priority=rec.get("priority") or "normal",
                             tag=rec.get("tag"), client=rec.get("client"),
                             dedupe=rec.get("dedupe"))
            if rec.get("submitted_unix"):
                whale.submitted_unix = rec["submitted_unix"]
            for k, s in rec["shards"].items():
                if int(k) in whale.shards:
                    whale.shards[int(k)].update(
                        state=s["state"] if s["state"] in
                        ("done", "failed") else "planned",
                        job_id=s.get("job_id"),
                        attempt=s.get("attempt", 0),
                        dedupe=s.get("dedupe")
                        or whale.shards[int(k)]["dedupe"])
            if rec["state"] in TERMINAL:
                whale.state = rec["state"]
                whale.error = rec.get("error")
                whale.finished_unix = rec.get("finished_unix")
                whale.exit_status = 0 if rec["state"] == "done" else 1
            self._whales[wid] = whale
            if whale.dedupe:
                self._dedupe[whale.dedupe] = wid
            if whale.state not in TERMINAL:
                self._resume.append(wid)

    def start(self):
        """Launch runner threads for WAL-resumed whales (after the
        balancer's transport is up — resubmits route immediately)."""
        resumed, self._resume = self._resume, []
        for wid in resumed:
            log.info("scatter: resuming whale %s from the WAL", wid)
            self._start_runner(self._whales[wid])

    def close(self):
        self._closed.set()
        for t in list(self._threads.values()):
            t.join(timeout=5)
        if self.wal is not None:
            self.wal.close()

    # -- submit interception ------------------------------------------------

    def maybe_submit(self, req: dict):
        """Intercept a balancer submit: returns a response frame for a
        whale (planned and fanned out), or None to route it normally."""
        from ..observe.metrics import METRICS

        dedupe = req.get("dedupe")
        if dedupe:
            with self._lock:
                wid = self._dedupe.get(dedupe)
                prior = self._whales.get(wid) if wid else None
            if prior is not None:
                METRICS.inc("fleet.scatter.deduped")
                return protocol.ok_response(job=prior.to_wire(),
                                            deduped=True)
        try:
            plan = plan_scatter(req.get("argv") or [], req.get("argv0"),
                                self.shards, self.axis)
        except ValueError as e:
            return protocol.error_response(str(e))
        if plan is None:
            return None
        if self.balancer.draining:
            return protocol.error_response(
                "draining: balancer is not accepting new jobs")
        with self._lock:
            whale = WhaleJob(
                f"w-{self._boot}-{self._next_num}", req["argv"], plan,
                argv0=req.get("argv0"),
                priority=req.get("priority", protocol.DEFAULT_PRIORITY),
                tag=req.get("tag"), client=req.get("client"),
                dedupe=dedupe)
            self._next_num += 1
            self._whales[whale.id] = whale
            if dedupe:
                self._dedupe[dedupe] = whale.id
            self._evict_locked()
        METRICS.inc("fleet.scatter.whales")
        if self.wal is not None:
            self.wal.append({"ev": "whale", "id": whale.id,
                             "argv": whale.argv, "argv0": whale.argv0,
                             "priority": whale.priority, "tag": whale.tag,
                             "client": whale.client, "dedupe": dedupe,
                             "plan": plan.to_wire()})
        log.info("scatter: whale %s = %s -> %d %s-hash shard(s)",
                 whale.id, plan.out_path, plan.count, plan.axis)
        self._start_runner(whale)
        return protocol.ok_response(job=whale.to_wire())

    def _evict_locked(self):
        terminal = [w for w in self._whales.values()
                    if w.state in TERMINAL]
        while len(terminal) > self.keep_finished:
            victim = terminal.pop(0)
            del self._whales[victim.id]
            if victim.dedupe \
                    and self._dedupe.get(victim.dedupe) == victim.id:
                del self._dedupe[victim.dedupe]

    # -- status / cancel / introspection ------------------------------------

    def status(self, job_id: str):
        """The whale's wire record, or None for a non-whale id."""
        with self._lock:
            whale = self._whales.get(job_id)
        return whale.to_wire() if whale is not None else None

    def list_jobs(self):
        with self._lock:
            return [w.to_wire() for w in self._whales.values()]

    def cancel(self, job_id: str):
        """Cancel a whale: queued shards are cancelled on their backends,
        running shards finish and are discarded (the daemon never
        preempts), no gather runs. Returns the whale record, an error
        response for a terminal whale, or None for a non-whale id."""
        with self._lock:
            whale = self._whales.get(job_id)
        if whale is None:
            return None
        if whale.state in TERMINAL:
            return protocol.error_response(
                f"job {job_id} already {whale.state}")
        whale._cancel.set()
        with whale._lock:
            shard_jobs = [s["job_id"] for s in whale.shards.values()
                          if s["job_id"] and s["state"] not in
                          ("done", "failed")]
        for sid in shard_jobs:
            try:
                self.balancer._routed_job_op(
                    {"v": protocol.PROTOCOL_VERSION, "op": "cancel",
                     "id": sid}, sid)
            except Exception:  # noqa: BLE001 - best-effort fan-out
                pass
        return protocol.ok_response(job=whale.to_wire())

    def snapshot(self) -> dict:
        """The stats op's ``scatter`` section (take ONCE per stats/
        metrics render — the same-snapshot rule ``fleet_metrics``
        follows)."""
        with self._lock:
            whales = list(self._whales.values())
        by_state, jobs = {}, []
        for w in whales:
            by_state[w.state] = by_state.get(w.state, 0) + 1
            jobs.append({"id": w.id, "state": w.state,
                         "out": w.plan.out_path,
                         "shards": w.shard_counts()})
        return {"enabled": True, "shards": self.shards, "axis": self.axis,
                "wal": self.wal.path if self.wal else None,
                "whales": by_state, "jobs": jobs}

    # -- the runner ---------------------------------------------------------

    def _start_runner(self, whale: WhaleJob):
        t = threading.Thread(target=self._run_whale, args=(whale,),
                             name=f"fgumi-scatter-{whale.id}", daemon=True)
        with self._lock:
            self._threads[whale.id] = t
        t.start()

    def _fair_inflight_cap(self) -> int:
        """One whale's allowance of concurrently outstanding shards: its
        share of the healthy backends, floor 1 — N whales split the
        fleet instead of the first one monopolizing it."""
        with self._lock:
            active = sum(1 for w in self._whales.values()
                         if w.state not in TERMINAL) or 1
        healthy = len(self.balancer._healthy_backends()) or 1
        return max(1, healthy // active)

    def _wal_shard(self, whale, k, shard):
        if self.wal is not None:
            self.wal.append({"ev": "shard", "whale": whale.id, "k": k,
                             "attempt": shard["attempt"],
                             "dedupe": shard["dedupe"],
                             "job_id": shard["job_id"],
                             "state": shard["state"]})

    def _finish(self, whale: WhaleJob, state: str, error: str = None):
        whale.state = state
        whale.error = error
        whale.exit_status = (0 if state == "done"
                             else None if state == "cancelled" else 1)
        whale.finished_unix = time.time()
        if self.wal is not None:
            self.wal.append({"ev": "whale_state", "id": whale.id,
                             "state": state, "error": error})
        if error:
            log.error("scatter: whale %s %s: %s", whale.id, state, error)
        else:
            log.info("scatter: whale %s %s in %.2fs", whale.id, state,
                     whale.finished_unix - whale.submitted_unix)

    def _run_whale(self, whale: WhaleJob):
        # runner threads are plain threads with no contextvar inheritance:
        # re-enter the balancer's telemetry scope (the same dance its
        # handle_request does) so fleet.scatter.* counters land in the
        # registry the stats op snapshots, not the process-global fallback
        from ..observe.scope import current_scope, scoped_telemetry

        scope = getattr(self.balancer, "_telemetry_scope", None)
        if scope is not None and current_scope() is None:
            with scoped_telemetry(scope=scope):
                self._run_whale_inner(whale)
        else:
            self._run_whale_inner(whale)

    def _run_whale_inner(self, whale: WhaleJob):
        try:
            self._drive(whale)
        except Exception as e:  # noqa: BLE001 - runner death = whale failed
            log.exception("scatter: whale %s runner crashed", whale.id)
            if whale.state not in TERMINAL:
                self._finish(whale, "failed", f"scatter runner: {e}")
        finally:
            with self._lock:
                self._threads.pop(whale.id, None)

    def _submit_shard(self, whale: WhaleJob, k: int) -> str:
        """One shard fan-out through the balancer's routing. Returns
        None on success, a transient-refusal reason to retry later, or
        raises RuntimeError on a fatal refusal."""
        from ..observe.metrics import METRICS

        shard = whale.shards[k]
        sreq = {"v": protocol.PROTOCOL_VERSION, "op": "submit",
                "argv": list(whale.plan.shard_argvs[k]),
                "priority": whale.priority, "argv0": whale.argv0,
                "trace": False, "tag": f"{whale.id}-s{k}",
                "dedupe": shard["dedupe"],
                "shard": {"whale": whale.id, "index": k,
                          "count": whale.plan.count,
                          "axis": whale.plan.axis},
                "sent_unix": round(time.time(), 6)}
        if whale.client is not None:
            sreq["client"] = whale.client
        resp = self.balancer._route_submit(sreq)
        if resp.get("ok"):
            job = resp.get("job") or {}
            with whale._lock:
                shard["job_id"] = job.get("id")
                shard["state"] = "submitted"
                shard["unknown_since"] = None
            METRICS.inc("fleet.scatter.shards_submitted")
            self._wal_shard(whale, k, shard)
            return None
        reason = resp.get("error", "submit refused")
        if "retry_after_s" in resp or any(m in reason
                                          for m in _TRANSIENT_MARKERS):
            return reason
        raise RuntimeError(f"shard {whale.id}-s{k} refused: {reason}")

    def _poll_shard(self, whale: WhaleJob, k: int):
        """Refresh one outstanding shard from the fleet; drives the
        submitted/running/done/failed transitions and the lost-shard
        requeue."""
        from ..observe.metrics import METRICS

        shard = whale.shards[k]
        sid = shard["job_id"]
        resp = self.balancer._routed_job_op(
            {"v": protocol.PROTOCOL_VERSION, "op": "status", "id": sid},
            sid)
        if resp.get("ok"):
            job = resp.get("job") or {}
            state = job.get("state")
            with whale._lock:
                shard["unknown_since"] = None
                if state == "running" and shard["state"] == "submitted":
                    shard["state"] = "running"
                elif state == "done":
                    shard["state"] = "done"
                elif state == "failed":
                    shard["state"] = "failed"
                    shard["error"] = job.get("error")
                elif state == "cancelled":
                    # a takeover with shrunken capacity (or an operator)
                    # cancelled the shard out from under us: requeue it
                    # under a FRESH dedupe key — the daemon keeps the old
                    # key bound to the cancelled record, and a resubmit
                    # with it would be answered deduped forever
                    shard["attempt"] += 1
                    shard["dedupe"] = \
                        f"{whale.id}-s{k}-a{shard['attempt']}"
                    shard["state"] = "requeued"
                    shard["job_id"] = None
            if shard["state"] == "done":
                METRICS.inc("fleet.scatter.shards_done")
                self._wal_shard(whale, k, shard)
            elif shard["state"] == "failed":
                METRICS.inc("fleet.scatter.shards_failed")
                self._wal_shard(whale, k, shard)
            return
        # unknown fleet-wide: the takeover window (grace), or the shard
        # is genuinely gone (no shared journal to revive it) — requeue
        # under an attempt-suffixed dedupe key so the resubmit can never
        # be answered by a stale copy of the old attempt
        now = time.monotonic()
        with whale._lock:
            if shard["unknown_since"] is None:
                shard["unknown_since"] = now
                return
            if now - shard["unknown_since"] < self.requeue_grace_s:
                return
            shard["attempt"] += 1
            shard["dedupe"] = \
                f"{whale.id}-s{k}-a{shard['attempt']}"
            shard["state"] = "requeued"
            shard["job_id"] = None
            shard["unknown_since"] = None
        METRICS.inc("fleet.scatter.shards_requeued")
        self._wal_shard(whale, k, shard)
        log.warning("scatter: shard %s-s%d lost fleet-wide for %.0fs; "
                    "requeued as attempt %d", whale.id, k,
                    self.requeue_grace_s, shard["attempt"])

    def _drive(self, whale: WhaleJob):
        whale.state = "running"
        whale.started_unix = time.time()
        backoff = self.poll_s
        while not self._closed.is_set():
            if whale._cancel.is_set():
                self._finish(whale, "cancelled")
                return
            counts = whale.shard_counts()
            failed = [k for k, s in whale.shards.items()
                      if s["state"] == "failed"]
            if failed:
                k = failed[0]
                self._finish(
                    whale, "failed",
                    f"shard {k}/{whale.plan.count} failed: "
                    f"{whale.shards[k].get('error') or 'unknown error'}")
                return
            if counts.get("done", 0) == whale.plan.count:
                break  # every shard published: gather
            # fan out pending shards up to this whale's fair share
            outstanding = sum(counts.get(s, 0)
                              for s in ("submitted", "running"))
            cap = self._fair_inflight_cap()
            pending = [k for k in sorted(whale.shards)
                       if whale.shards[k]["state"] in
                       ("planned", "requeued")]
            transient = None
            for k in pending:
                if outstanding >= cap:
                    break
                if self.balancer.draining:
                    self._finish(whale, "failed",
                                 "balancer draining before every shard "
                                 "was submitted")
                    return
                transient = self._submit_shard(whale, k)
                if transient is not None:
                    break  # fleet busy: retry the rest next pass
                outstanding += 1
            # poll the in-flight shards
            for k in sorted(whale.shards):
                if whale.shards[k]["state"] in ("submitted", "running"):
                    self._poll_shard(whale, k)
            if transient is not None:
                backoff = min(backoff * 1.5, 5.0)
            else:
                backoff = self.poll_s
            if self._closed.wait(backoff):
                return
        if self._closed.is_set():
            return
        self._gather(whale)

    def _gather(self, whale: WhaleJob):
        """The merge stage: k-way merge of the shards' manifest-ordered
        outputs into the whale's final BAM, committed atomically."""
        from ..core.sharding import gather_shards
        from ..observe.metrics import METRICS

        plan = whale.plan
        tmp = f"{plan.out_path}.scatter-gather.tmp.{os.getpid()}"
        t0 = time.time()
        try:
            stats = gather_shards(plan.shard_outs, plan.manifest_paths,
                                  tmp, level=plan.level)
            os.replace(tmp, plan.out_path)
        except Exception as e:  # noqa: BLE001 - surfaced on the whale
            METRICS.inc("fleet.scatter.gather_failures")
            try:
                os.unlink(tmp)
            except OSError:
                pass
            # shard outputs are kept for the post-mortem
            self._finish(whale, "failed", f"gather: {e}")
            return
        METRICS.inc("fleet.scatter.gathers")
        METRICS.observe("fleet.scatter.gather_s", time.time() - t0)
        for path in list(plan.shard_outs) + list(plan.manifest_paths):
            try:
                os.unlink(path)
            except OSError:
                pass  # best-effort cleanup; the merged output is law
        log.info("scatter: whale %s gathered %d famil%s (%d records, "
                 "%d dropped) from %d shard(s) in %.2fs -> %s",
                 whale.id, stats["families"],
                 "y" if stats["families"] == 1 else "ies",
                 stats["records"], stats["dropped"], plan.count,
                 time.time() - t0, plan.out_path)
        self._finish(whale, "done")
