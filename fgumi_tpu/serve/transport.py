"""Serve transport abstraction: Unix-socket and TCP listeners, addresses,
the client retry policy, and the shared frame-serving loop.

PR 3's daemon was one Unix socket on one host; the fleet tier needs the
same newline-JSON wire protocol to travel between hosts. This module keeps
every transport concern in one place so the daemon and the balancer serve
through identical machinery:

- **Addresses** — ``unix:/path/to.sock`` or ``tcp:host:port`` (a bare path
  is a Unix socket, the pre-fleet spelling). :func:`parse_address` is loud
  about anything else; :func:`connect` dials either kind.
- **Listeners** — :func:`claim_unix_socket` (the PR 3 stale-socket
  replacement discipline, moved here) and :class:`TcpListener`. A busy TCP
  port raises ``OSError`` at bind time so the CLI can exit 2 before any
  device warm-up, exactly like ``--metrics-port``.
- **The frame server** — :class:`FrameServer` runs the accept loops for
  any number of listeners and applies the per-connection contract: read/
  write deadlines (TCP), a connection cap (over-cap connections are
  answered with one error frame and closed, never silently dropped), and
  the shared-secret handshake. A listener bound to a non-loopback address
  REQUIRES the handshake: the first frame on each connection must be
  ``{"v": 1, "op": "hello", "token": <secret>}`` or the connection is
  rejected — the wire carries argv that the daemon will execute, so an
  open port must never accept work from strangers. Loopback and Unix
  listeners accept (but do not require) the handshake.
- **RetryPolicy** — capped, jittered exponential backoff for the client's
  idempotent operations, replacing the fixed single 0.5 s reconnect.

Nothing here knows about jobs: the server side takes a ``handle(request)
-> response`` callable (the daemon's or balancer's dispatch) and a couple
of lifecycle hooks.
"""

import errno
import logging
import os
import socket
import threading
import time

from . import protocol

log = logging.getLogger("fgumi_tpu")

#: env fallback for the shared-secret handshake token (serve --token-file /
#: balance --token-file / submit --token-file override it per process).
TOKEN_ENV = "FGUMI_TPU_SERVE_TOKEN"

#: default per-connection read/write deadline on TCP connections (seconds).
DEFAULT_IO_TIMEOUT_S = 30.0

#: default concurrent-connection cap on TCP listeners.
DEFAULT_CONN_CAP = 64


class SocketBusy(RuntimeError):
    """Another live daemon already serves this socket path."""


# ---------------------------------------------------------------------------
# addresses


def parse_address(addr: str):
    """``unix:/path`` / ``tcp:host:port`` / bare path -> (kind, target).

    Returns ``("unix", path)`` or ``("tcp", (host, port))``. A bare string
    with no scheme is a Unix socket path (the pre-fleet client spelling
    keeps working). Raises ``ValueError`` with a diagnostic otherwise."""
    if not isinstance(addr, str) or not addr:
        raise ValueError(f"empty serve address {addr!r}")
    if addr.startswith("unix:"):
        path = addr[len("unix:"):]
        if not path:
            raise ValueError(f"unix address without a path: {addr!r}")
        return "unix", path
    if addr.startswith("tcp:"):
        rest = addr[len("tcp:"):]
        host, sep, port_s = rest.rpartition(":")
        if not sep or not host:
            raise ValueError(
                f"tcp address must be tcp:host:port, got {addr!r}")
        try:
            port = int(port_s)
        except ValueError:
            raise ValueError(f"tcp port must be an integer, got {port_s!r}")
        if not 0 <= port <= 65535:
            raise ValueError(f"tcp port {port} out of range 0..65535")
        return "tcp", (host, port)
    if ":" in addr.split(os.sep)[0] and not addr.startswith(("/", ".")):
        # "host:1234" is almost certainly a mistyped tcp address; a Unix
        # socket named like that would be legal but is worth refusing
        # loudly over silently creating a weird socket file
        raise ValueError(
            f"ambiguous address {addr!r}: use unix:PATH or tcp:HOST:PORT")
    return "unix", addr


def format_address(kind: str, target) -> str:
    if kind == "unix":
        return f"unix:{target}"
    host, port = target
    return f"tcp:{host}:{port}"


def is_loopback(host: str) -> bool:
    """True when ``host`` can only be reached from this machine. The
    empty host is NOT loopback — binding "" is INADDR_ANY (every
    interface), so it must hit the token requirement."""
    if not host:
        return False
    if host == "localhost":
        return True
    try:
        infos = socket.getaddrinfo(host, None)
    except socket.gaierror:
        return False  # unresolvable: treat as remote (require the token)
    ips = {info[4][0] for info in infos}
    return bool(ips) and all(
        ip == "::1" or ip.startswith("127.") for ip in ips)


def connect(addr: str, timeout: float = None) -> socket.socket:
    """Dial a serve address; returns the connected socket. ``OSError``
    surfaces to the caller (the client wraps it)."""
    kind, target = parse_address(addr)
    if kind == "unix":
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        if timeout is not None:
            conn.settimeout(timeout)
        conn.connect(target)
        return conn
    host, port = target
    conn = socket.create_connection((host, port), timeout=timeout)
    try:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass  # nagle stays on; correctness is unaffected
    return conn


def load_token(token_file: str = None) -> str:
    """The shared-secret handshake token: ``--token-file`` wins, else the
    ``FGUMI_TPU_SERVE_TOKEN`` env var, else None. A token file's content
    is stripped of surrounding whitespace (trailing newline from echo)."""
    if token_file:
        with open(token_file, "r") as f:
            token = f.read().strip()
        if not token:
            raise ValueError(f"token file {token_file} is empty")
        return token
    token = os.environ.get(TOKEN_ENV, "").strip()
    return token or None


# ---------------------------------------------------------------------------
# retry policy


class RetryPolicy:
    """Capped jittered exponential backoff for idempotent client requests.

    ``attempts`` is the TOTAL number of tries (1 = never retry). Delay
    before retry ``k`` (1-based) is ``min(base_s * multiplier**(k-1),
    cap_s)`` scaled by a uniform jitter in ``[1 - jitter, 1]`` so a fleet
    of clients bounced by the same daemon restart does not reconnect in
    lockstep. ``rng`` is injectable for deterministic tests."""

    def __init__(self, attempts: int = 4, base_s: float = 0.25,
                 cap_s: float = 5.0, multiplier: float = 2.0,
                 jitter: float = 0.5, rng=None):
        if attempts < 1:
            raise ValueError("attempts must be >= 1")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")
        self.attempts = int(attempts)
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        if rng is None:
            import random

            rng = random.random
        self._rng = rng

    def delay_s(self, retry_index: int) -> float:
        """Backoff before the ``retry_index``-th retry (1-based)."""
        raw = min(self.base_s * self.multiplier ** (retry_index - 1),
                  self.cap_s)
        return raw * (1.0 - self.jitter * self._rng())

    @classmethod
    def none(cls) -> "RetryPolicy":
        """Never retry (non-idempotent operations)."""
        return cls(attempts=1)

    def __repr__(self):
        return (f"RetryPolicy(attempts={self.attempts}, "
                f"base_s={self.base_s}, cap_s={self.cap_s})")


# ---------------------------------------------------------------------------
# listeners


def claim_unix_socket(path: str) -> socket.socket:
    """Bind a Unix listener, replacing a *dead* daemon's socket file only.

    Stale means the connect is actively refused (no listener behind the
    file). A timeout or transient error (daemon stopped in a debugger,
    backlog full under a client burst) is treated as BUSY — unlinking a
    live daemon's socket would split-brain the service and that daemon's
    exit would then delete *our* socket file."""
    if os.path.exists(path):
        probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            probe.settimeout(1.0)
            probe.connect(path)
        except (ConnectionRefusedError, FileNotFoundError):
            log.info("serve: replacing stale socket %s", path)
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
        except OSError as e:
            raise SocketBusy(
                f"daemon at {path} did not answer ({e}); "
                "not replacing a possibly-live socket")
        else:
            raise SocketBusy(f"another daemon is already serving {path}")
        finally:
            probe.close()
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.bind(path)
    sock.listen(16)
    return sock


class Listener:
    """One bound listening socket plus its per-connection contract."""

    kind = None

    def __init__(self):
        self.sock = None
        #: per-connection read/write deadline (None = no deadline)
        self.io_timeout_s = None
        #: concurrent-connection cap (None = unlimited)
        self.conn_cap = None
        #: connections must open with a valid hello frame before any
        #: other op is answered
        self.require_auth = False

    def describe(self) -> str:
        raise NotImplementedError

    def close(self):
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None


class UnixListener(Listener):
    kind = "unix"

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._bound = False

    def bind(self):
        if self.sock is None:
            self.sock = claim_unix_socket(self.path)
            self._bound = True
        return self

    def describe(self) -> str:
        return f"unix:{self.path}"

    def unlink(self):
        """Remove the socket file — ONLY if this listener bound it. A
        failed duplicate start (SocketBusy) must never delete the LIVE
        daemon's socket on its way out."""
        if not self._bound:
            return
        try:
            os.unlink(self.path)
        except OSError as e:
            if e.errno != errno.ENOENT:
                log.debug("serve: could not remove socket %s: %s",
                          self.path, e)


class TcpListener(Listener):
    """TCP listener with deadlines, a connection cap, and handshake auth.

    ``require_auth`` defaults to "is the bind address non-loopback":
    exposing the wire protocol beyond this machine without the
    shared-secret handshake is refused at construction (``token`` must be
    set), because a submit frame is arbitrary command execution."""

    kind = "tcp"

    def __init__(self, host: str, port: int, token: str = None,
                 io_timeout_s: float = DEFAULT_IO_TIMEOUT_S,
                 conn_cap: int = DEFAULT_CONN_CAP,
                 require_auth: bool = None):
        super().__init__()
        self.host = host
        self.port = int(port)
        self.token = token
        self.io_timeout_s = io_timeout_s if io_timeout_s and \
            io_timeout_s > 0 else None
        # 0/None = unlimited; negative is a caller bug (it would reject
        # every connection), refused loudly
        if conn_cap is not None and conn_cap < 0:
            raise ValueError(f"conn_cap must be >= 0, got {conn_cap}")
        self.conn_cap = int(conn_cap) if conn_cap else None
        if require_auth is None:
            # non-loopback binds MUST authenticate; a loopback bind with a
            # configured token enforces it too (configuring a secret and
            # not checking it would be a trap)
            require_auth = not is_loopback(host) or token is not None
        self.require_auth = bool(require_auth)
        if self.require_auth and not token:
            raise ValueError(
                f"refusing to listen on non-loopback tcp:{host}:{port} "
                "without a handshake token (--token-file or "
                f"{TOKEN_ENV}): the wire protocol executes submitted "
                "commands")

    def bind(self):
        """Bind + listen. A busy port raises ``OSError`` here so the CLI
        can exit 2 before the device warm-up (the --metrics-port
        discipline)."""
        if self.sock is not None:
            return self
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            # REUSEADDR skips TIME_WAIT on restart; it does NOT allow two
            # live listeners on one port, so busy-port still fails loudly
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((self.host, self.port))
            sock.listen(64)
        except OSError:
            sock.close()
            raise
        self.sock = sock
        if self.port == 0:
            self.port = sock.getsockname()[1]
        return self

    def describe(self) -> str:
        return f"tcp:{self.host}:{self.port}"


# ---------------------------------------------------------------------------
# the frame server


class FrameServer:
    """Accept loops + per-connection frame serving for N listeners.

    ``handle(request) -> response`` is the transport-independent dispatch
    (the daemon's or balancer's). ``on_shutdown()`` fires after a
    successful ``shutdown`` response is on the wire — arming the exit
    *after* the reply so an idle process cannot beat its own sendall.
    """

    def __init__(self, handle, listeners, max_frame_bytes: int,
                 on_shutdown=None, name: str = "fgumi-serve"):
        self._handle = handle
        self.listeners = list(listeners)
        self.max_frame_bytes = max_frame_bytes
        self._on_shutdown = on_shutdown
        self._name = name
        self._threads = []
        self._conn_lock = threading.Lock()
        #: live connections PER listener (keyed by identity): the cap is
        #: a per-listener contract — local Unix clients must never eat
        #: the TCP listener's budget
        self._live_by_listener = {id(lst): 0 for lst in self.listeners}
        self.started = False

    # -- lifecycle ----------------------------------------------------------

    def bind(self):
        for lst in self.listeners:
            lst.bind()
        return self

    def start(self):
        if self.started:
            return
        self.started = True
        self.bind()
        for i, lst in enumerate(self.listeners):
            t = threading.Thread(target=self._accept_loop, args=(lst,),
                                 name=f"{self._name}-accept-{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def close(self):
        for lst in self.listeners:
            lst.close()

    def live_connections(self) -> int:
        with self._conn_lock:
            return sum(self._live_by_listener.values())

    # -- accept + serve -----------------------------------------------------

    def _accept_loop(self, lst: Listener):
        # keep accepting through a drain: clients must be able to poll
        # status while queued/running jobs finish; the loop ends when
        # close() closes the listener
        while True:
            sock = lst.sock  # close() nulls the attribute concurrently
            if sock is None:
                return
            try:
                conn, _ = sock.accept()
            except OSError:
                return  # listener closed during shutdown
            with self._conn_lock:
                held = self._live_by_listener[id(lst)]
                over = lst.conn_cap is not None and held >= lst.conn_cap
                if not over:
                    self._live_by_listener[id(lst)] = held + 1
            if over:
                self._reject_over_cap(conn, lst)
                continue
            t = threading.Thread(target=self._serve_connection,
                                 args=(conn, lst),
                                 name=f"{self._name}-conn", daemon=True)
            t.start()

    def _reject_over_cap(self, conn, lst):
        """One explicit error frame, then close — a silently dropped
        connection looks like a network fault and triggers client
        retries; an explicit refusal is actionable."""
        from ..observe.metrics import METRICS

        METRICS.inc("serve.transport.rejected_cap")
        try:
            conn.settimeout(2.0)
            conn.sendall(protocol.encode_frame(protocol.error_response(
                f"connection limit reached ({lst.conn_cap} concurrent "
                f"connections on {lst.describe()})")))
        except OSError:
            pass
        finally:
            conn.close()

    def _serve_connection(self, conn: socket.socket, lst: Listener):
        from ..observe.metrics import METRICS

        if lst.kind == "tcp":
            METRICS.inc("serve.transport.tcp.connections")
        if lst.io_timeout_s is not None:
            conn.settimeout(lst.io_timeout_s)
        authed = not lst.require_auth
        stream = conn.makefile("rb")
        try:
            while True:
                try:
                    req = protocol.read_frame(stream, self.max_frame_bytes)
                except protocol.ProtocolError as e:
                    self._send(conn, protocol.error_response(str(e)))
                    return  # framing is gone; close rather than resync
                except socket.timeout:
                    METRICS.inc("serve.transport.timeouts")
                    log.debug("serve: connection idle past %.0fs deadline; "
                              "closing", lst.io_timeout_s)
                    return
                if req is None:
                    return
                if not authed:
                    # the ONLY acceptable first frame is a valid hello;
                    # anything else is answered once and the connection
                    # closed — an unauthenticated peer never reaches the
                    # op dispatch
                    if req.get("op") != "hello":
                        METRICS.inc("serve.transport.rejected_auth")
                        self._send(conn, protocol.error_response(
                            "authentication required: this listener "
                            "requires a handshake token (send a hello "
                            "frame with the shared secret first)"))
                        return
                    resp = self._handle(req)
                    self._send(conn, resp)
                    if not resp.get("ok"):
                        METRICS.inc("serve.transport.rejected_auth")
                        return  # bad token: one answer, then hang up
                    authed = True
                    continue
                resp = self._handle(req)
                self._send(conn, resp)
                # arm shutdown only AFTER the reply is on the wire: the
                # main thread exits the process once the pool quiesces,
                # which on an idle daemon can beat this thread's sendall
                # and reset the client mid-response
                if req.get("op") == "shutdown" and resp.get("ok") \
                        and self._on_shutdown is not None:
                    self._on_shutdown()
        except OSError:
            pass  # peer went away mid-frame; nothing to answer
        finally:
            with self._conn_lock:
                self._live_by_listener[id(lst)] -= 1
            try:
                stream.close()
            except OSError:
                pass
            conn.close()

    @staticmethod
    def _send(conn, resp: dict):
        try:
            conn.sendall(protocol.encode_frame(resp))
        except OSError:
            pass


# ---------------------------------------------------------------------------
# handshake helpers (shared by daemon + balancer dispatch)


def hello_response(tool: str, expected_token: str, req: dict) -> dict:
    """Answer one hello frame. With a configured token, the frame's token
    must match (constant-time compare); without one the listener is open
    and any hello is acknowledged. ``server_unix`` (the server's wall
    clock at answer time) rides along so the client can estimate the
    host clock offset — ``fgumi-tpu trace-merge`` uses the estimate to
    align per-host trace timelines; old clients simply ignore it."""
    import hmac

    token = req.get("token")
    if expected_token:
        if not isinstance(token, str) or not hmac.compare_digest(
                token, expected_token):
            return protocol.error_response(
                "invalid handshake token")
        return protocol.ok_response(tool=tool, pid=os.getpid(),
                                    auth="token",
                                    server_unix=round(time.time(), 6))
    return protocol.ok_response(tool=tool, pid=os.getpid(), auth="open",
                                server_unix=round(time.time(), 6))


def clock_offset_estimate(hello_resp: dict, t_send: float,
                          t_recv: float):
    """Estimated ``local_clock - server_clock`` seconds from one
    handshake round trip: the server stamped ``server_unix`` mid-trip, so
    comparing it against the local midpoint bounds the skew by half the
    RTT — plenty for aligning trace timelines (milliseconds matter,
    microseconds don't). None when the server predates the field."""
    server_unix = hello_resp.get("server_unix")
    if not isinstance(server_unix, (int, float)) \
            or isinstance(server_unix, bool):
        return None
    return round((t_send + t_recv) / 2.0 - float(server_unix), 6)


def client_hello(stream, conn, token: str,
                 max_frame_bytes: int = protocol.MAX_FRAME_BYTES):
    """Client side of the handshake: send hello, require an ok answer.
    Returns the response; raises ``protocol.ProtocolError`` on a refusal
    so the caller can surface the daemon's reason verbatim. The response
    carries ``clock_offset_s`` (local minus server wall clock, estimated
    from the round trip) when the server stamps ``server_unix``."""
    t_send = time.time()
    conn.sendall(protocol.encode_frame(
        {"v": protocol.PROTOCOL_VERSION, "op": "hello", "token": token}))
    resp = protocol.read_frame(stream, max_frame_bytes)
    t_recv = time.time()
    if resp is None:
        raise protocol.ProtocolError(
            "connection closed during the handshake")
    if not resp.get("ok"):
        raise protocol.ProtocolError(
            f"handshake rejected: {resp.get('error', 'no reason given')}")
    offset = clock_offset_estimate(resp, t_send, t_recv)
    if offset is not None:
        resp["clock_offset_s"] = offset
        from ..observe import trace as trace_mod

        # stamp the estimate onto the active tracer (if any): its export
        # then carries clock.offset_estimate_s and trace-merge aligns
        # this host's timeline onto the server's clock automatically
        trace_mod.set_clock_offset(offset)
    return resp


__all__ = [
    "DEFAULT_CONN_CAP", "DEFAULT_IO_TIMEOUT_S", "FrameServer", "Listener",
    "RetryPolicy", "SocketBusy", "TcpListener", "TOKEN_ENV", "UnixListener",
    "claim_unix_socket", "client_hello", "clock_offset_estimate",
    "connect", "format_address", "hello_response", "is_loopback",
    "load_token", "parse_address",
]
