"""Persistent job service: warm-kernel serving over a Unix-domain socket.

fgumi started life as a one-shot CLI: every invocation pays process
startup, the ~2s jax import, and XLA compilation before the first batch
moves. That cost model is wrong for repeated runs — the exact workload a
production deployment serves. This package keeps one long-lived process
holding the JAX device, the persistent compile cache, and every warmed jit
executable, and runs pipeline jobs submitted over a newline-delimited JSON
protocol on a Unix-domain socket:

- :mod:`.protocol` — the schema-versioned wire protocol
  (``submit`` / ``status`` / ``cancel`` / ``drain`` / ``shutdown`` /
  ``ping``), frame limits, and validation.
- :mod:`.jobs` — the job registry and per-job state machine
  (queued -> running -> done/failed/cancelled).
- :mod:`.scheduler` — bounded worker pool, FIFO within priority classes,
  admission control with explicit rejection reasons, graceful drain.
- :mod:`.daemon` — the socket server (``fgumi-tpu serve``); executes each
  job by re-entering the ordinary CLI inside its own telemetry scope, so a
  job's metrics/trace/run-report are exactly what the standalone command
  would have produced — and its output bytes are identical too.
- :mod:`.client` — the thin client used by ``fgumi-tpu submit`` and
  ``fgumi-tpu jobs``; retries idempotent requests across daemon restarts
  under a capped jittered exponential backoff, and surfaces admission
  sheds with the governor's ``retry_after_s`` hint.
- :mod:`.transport` — the fleet transport layer: ``unix:``/``tcp:``
  addresses, the TCP listener (per-connection deadlines, connection cap,
  shared-secret handshake for non-loopback binds), and the frame-serving
  loop shared by the daemon and the balancer.
- :mod:`.balancer` — the health-routed front end (``fgumi-tpu balance``):
  routes submits by backend queue depth, ejects unhealthy backends
  through a closed/open/half-open breaker, and re-routes dedupe-keyed
  submits to a surviving peer on failure.
- :mod:`.journal` — the append-only job WAL behind ``serve --journal``:
  fsync'd submit/state records, torn-tail truncation on replay, and the
  requeue-on-restart + dedupe-key recovery semantics that make serving
  crash-recoverable (a SIGKILL'd daemon forgets nothing). With ``serve
  --journal-dir`` the journal becomes a fleet object: each daemon holds an
  fcntl lease on its journal, and a peer (or restart) claims a dead
  daemon's lease exactly once and requeues its incomplete jobs under
  their original ids.

Every job is byte-parity-committed: the daemon overrides provenance
(@PG CL) with the submitting client's command line, and all execution-state
that used to be process-global (metrics, device stats, atomic-output flag,
BGZF level, CLI re-entry depth) is context-scoped, so two concurrent jobs
in one process behave like two processes. ``tools/serve_smoke.py`` gates
this end to end.
"""
