"""Thin job-service client: one connection per request, blocking waits.

Used by ``fgumi-tpu submit`` / ``fgumi-tpu jobs`` and by the smoke gate.
Deliberately dependency-free and synchronous — the protocol is one JSON
frame each way, and reconnect-per-request makes the client robust to a
daemon restart between polls.
"""

import socket
import sys
import time

from . import protocol


class ServeError(RuntimeError):
    """Transport failure or an ``ok: false`` response (reason in str())."""


class ServeClient:
    def __init__(self, socket_path: str, timeout: float = 30.0,
                 max_frame_bytes: int = protocol.MAX_FRAME_BYTES):
        self.socket_path = socket_path
        self.timeout = timeout
        self.max_frame_bytes = max_frame_bytes

    # -- transport ----------------------------------------------------------

    def request(self, obj: dict) -> dict:
        """One request -> one response. Raises ServeError on transport
        failure; returns the response frame verbatim (check ``ok``)."""
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.settimeout(self.timeout)
        try:
            try:
                conn.connect(self.socket_path)
            except OSError as e:
                raise ServeError(
                    f"cannot reach daemon at {self.socket_path}: {e}")
            try:
                conn.sendall(protocol.encode_frame(obj))
                stream = conn.makefile("rb")
                resp = protocol.read_frame(stream, self.max_frame_bytes)
            except (OSError, protocol.ProtocolError) as e:
                raise ServeError(f"daemon connection failed: {e}")
            if resp is None:
                raise ServeError("daemon closed the connection mid-request")
            return resp
        finally:
            conn.close()

    def _checked(self, obj: dict) -> dict:
        resp = self.request(obj)
        if not resp.get("ok"):
            raise ServeError(resp.get("error", "daemon refused the request"))
        return resp

    # -- operations ---------------------------------------------------------

    def ping(self) -> dict:
        return self._checked({"v": protocol.PROTOCOL_VERSION, "op": "ping"})

    def submit(self, argv, priority: str = protocol.DEFAULT_PRIORITY,
               argv0: str = None, tag: str = None,
               trace: bool = False) -> dict:
        """Submit a command; returns the accepted job record. An admission
        rejection (queue full / draining) raises ServeError with the
        daemon's reason."""
        req = {"v": protocol.PROTOCOL_VERSION, "op": "submit",
               "argv": list(argv), "priority": priority,
               "argv0": argv0 if argv0 is not None else sys.argv[0],
               "trace": bool(trace)}
        if tag is not None:
            req["tag"] = tag
        return self._checked(req)["job"]

    def status(self, job_id: str = None) -> dict:
        req = {"v": protocol.PROTOCOL_VERSION, "op": "status"}
        if job_id is not None:
            req["id"] = job_id
        return self._checked(req)

    def job(self, job_id: str) -> dict:
        return self.status(job_id)["job"]

    def cancel(self, job_id: str) -> dict:
        return self._checked({"v": protocol.PROTOCOL_VERSION, "op": "cancel",
                              "id": job_id})["job"]

    def drain(self) -> dict:
        return self._checked({"v": protocol.PROTOCOL_VERSION, "op": "drain"})

    def shutdown(self) -> dict:
        return self._checked({"v": protocol.PROTOCOL_VERSION,
                              "op": "shutdown"})

    def wait(self, job_id: str, timeout: float = None,
             poll_s: float = 0.2) -> dict:
        """Poll until the job reaches a terminal state; returns the record.
        Raises ServeError on timeout (the job keeps running)."""
        from .jobs import TERMINAL

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            job = self.job(job_id)
            if job["state"] in TERMINAL:
                return job
            if deadline is not None and time.monotonic() >= deadline:
                raise ServeError(
                    f"timed out waiting for job {job_id} "
                    f"(still {job['state']})")
            time.sleep(poll_s)
