"""Thin job-service client: one connection per request, blocking waits.

Used by ``fgumi-tpu submit`` / ``fgumi-tpu jobs`` and by the smoke gate.
Deliberately dependency-free and synchronous — the protocol is one JSON
frame each way, and reconnect-per-request makes the client robust to a
daemon restart between polls. Within a request, a connection torn down
under the client (``ECONNRESET``/``EPIPE``/mid-frame close — exactly what
a daemon SIGKILL or restart looks like from this side) gets one bounded
reconnect attempt for idempotent operations before surfacing a
:class:`ServeError`; a ``dedupe``-keyed submit is idempotent by the
daemon's contract and retries the same way. Daemon refusals (``ok:
false``) are surfaced with the daemon's reason verbatim.
"""

import errno
import socket
import sys
import time

from . import protocol


class ServeError(RuntimeError):
    """Transport failure or an ``ok: false`` response (reason in str())."""


#: errnos that mean "the peer vanished mid-conversation" — the retryable
#: class (vs. connection *refused*, which means no daemon is listening).
_RESET_ERRNOS = frozenset({errno.ECONNRESET, errno.EPIPE})

#: pause before the one reconnect attempt: long enough for a restarting
#: daemon to re-claim its socket, short enough not to matter to a human.
RECONNECT_DELAY_S = 0.5


def _is_reset(exc: OSError) -> bool:
    return isinstance(exc, (ConnectionResetError, BrokenPipeError)) \
        or getattr(exc, "errno", None) in _RESET_ERRNOS


class ServeClient:
    def __init__(self, socket_path: str, timeout: float = 30.0,
                 max_frame_bytes: int = protocol.MAX_FRAME_BYTES,
                 reconnects: int = 1):
        self.socket_path = socket_path
        self.timeout = timeout
        self.max_frame_bytes = max_frame_bytes
        self.reconnects = max(int(reconnects), 0)

    # -- transport ----------------------------------------------------------

    def request(self, obj: dict, timeout: float = None,
                retry: bool = True) -> dict:
        """One request -> one response. Raises ServeError on transport
        failure; returns the response frame verbatim (check ``ok``).
        ``timeout`` overrides the client default for this request;
        ``retry=False`` disables the reconnect-on-reset attempt (for
        non-idempotent operations)."""
        attempts = (self.reconnects if retry else 0) + 1
        last = None
        for attempt in range(attempts):
            if attempt:
                time.sleep(RECONNECT_DELAY_S)
            try:
                return self._request_once(obj, timeout)
            except _Retryable as e:
                last = e.error
        raise last

    def _request_once(self, obj: dict, timeout: float = None) -> dict:
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.settimeout(self.timeout if timeout is None else timeout)
        try:
            try:
                conn.connect(self.socket_path)
            except OSError as e:
                raise ServeError(
                    f"cannot reach daemon at {self.socket_path}: {e}")
            try:
                conn.sendall(protocol.encode_frame(obj))
                stream = conn.makefile("rb")
                resp = protocol.read_frame(stream, self.max_frame_bytes)
            except protocol.ProtocolError as e:
                raise ServeError(f"daemon connection failed: {e}")
            except OSError as e:
                err = ServeError(f"daemon connection failed: {e}")
                if _is_reset(e):
                    raise _Retryable(err)  # daemon restarting: retry once
                raise err
            if resp is None:
                # clean close mid-request: the SIGKILL/restart signature
                raise _Retryable(ServeError(
                    "daemon closed the connection mid-request"))
            return resp
        finally:
            conn.close()

    def _checked(self, obj: dict, timeout: float = None,
                 retry: bool = True) -> dict:
        resp = self.request(obj, timeout=timeout, retry=retry)
        if not resp.get("ok"):
            # the daemon's reason verbatim — "queue full: ..." vs
            # "draining: ..." is how callers tell backpressure from refusal
            raise ServeError(resp.get("error", "daemon refused the request"))
        return resp

    # -- operations ---------------------------------------------------------

    def ping(self) -> dict:
        return self._checked({"v": protocol.PROTOCOL_VERSION, "op": "ping"})

    def stats(self) -> dict:
        """Live introspection snapshot (scheduler/quota/journal/breaker/
        governor/device + latency histogram summaries). A daemon predating
        the op answers ``unknown op 'stats'`` — surfaced verbatim as
        ServeError, the documented clean rejection."""
        return self._checked({"v": protocol.PROTOCOL_VERSION,
                              "op": "stats"})["stats"]

    def submit(self, argv, priority: str = protocol.DEFAULT_PRIORITY,
               argv0: str = None, tag: str = None, trace: bool = False,
               dedupe: str = None, client: str = None) -> dict:
        """Submit a command; returns the accepted job record. An admission
        rejection (queue full / draining / over quota / resource pressure)
        raises ServeError with the daemon's reason. ``dedupe``: idempotency
        key — resubmitting the same key returns the original job instead of
        running it twice, which also makes the reconnect retry safe for
        submits; without a key, a submit whose connection resets is NOT
        retried (the daemon may already have admitted it). ``client``:
        submitter identity for the daemon's per-client admission quota
        (serve --max-per-client); anonymous submits are never quota-limited.
        """
        req = {"v": protocol.PROTOCOL_VERSION, "op": "submit",
               "argv": list(argv), "priority": priority,
               "argv0": argv0 if argv0 is not None else sys.argv[0],
               "trace": bool(trace)}
        if tag is not None:
            req["tag"] = tag
        if dedupe is not None:
            req["dedupe"] = dedupe
        if client is not None:
            req["client"] = client
        return self._checked(req, retry=dedupe is not None)["job"]

    def status(self, job_id: str = None, timeout: float = None) -> dict:
        req = {"v": protocol.PROTOCOL_VERSION, "op": "status"}
        if job_id is not None:
            req["id"] = job_id
        return self._checked(req, timeout=timeout)

    def job(self, job_id: str) -> dict:
        return self.status(job_id)["job"]

    def cancel(self, job_id: str) -> dict:
        # no reconnect retry: if the daemon acted before the reset, the
        # retry would be answered "already cancelled" (ok: false) and a
        # cancel that succeeded would surface as a failure
        return self._checked({"v": protocol.PROTOCOL_VERSION, "op": "cancel",
                              "id": job_id}, retry=False)["job"]

    def drain(self) -> dict:
        # idempotent (re-draining a draining daemon is a no-op): retry ok
        return self._checked({"v": protocol.PROTOCOL_VERSION, "op": "drain"})

    def shutdown(self) -> dict:
        # no retry: after a successful shutdown the reconnect would hit
        # connection-refused and report failure for an op that succeeded
        return self._checked({"v": protocol.PROTOCOL_VERSION,
                              "op": "shutdown"}, retry=False)

    def wait(self, job_id: str, timeout: float = None,
             poll_s: float = 0.2) -> dict:
        """Poll until the job reaches a terminal state; returns the record.
        Raises ServeError on timeout (the job keeps running)."""
        from .jobs import TERMINAL

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            job = self.job(job_id)
            if job["state"] in TERMINAL:
                return job
            if deadline is not None and time.monotonic() >= deadline:
                raise ServeError(
                    f"timed out waiting for job {job_id} "
                    f"(still {job['state']})")
            time.sleep(poll_s)


class _Retryable(Exception):
    """Internal: wraps a ServeError the transport may retry once."""

    def __init__(self, error: ServeError):
        super().__init__(str(error))
        self.error = error
