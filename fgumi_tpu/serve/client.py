"""Thin job-service client: one connection per request, blocking waits.

Used by ``fgumi-tpu submit`` / ``fgumi-tpu jobs`` / ``fgumi-tpu balance``
and by the smoke gates. Deliberately dependency-free and synchronous — the
protocol is one JSON frame each way, and reconnect-per-request makes the
client robust to a daemon restart between polls.

Addresses are ``unix:/path``, ``tcp:host:port``, or a bare Unix socket
path (the pre-fleet spelling). On a TCP connection with a configured
token, every request opens with the hello handshake frame before the real
request (serve/transport.py).

Retries: a connection torn down under the client (``ECONNRESET``/
``EPIPE``/mid-frame close/connect refusal — exactly what a daemon SIGKILL
or restart looks like from this side) is retried for idempotent
operations under a capped jittered exponential-backoff
:class:`~.transport.RetryPolicy` (replacing the fixed single 0.5 s
reconnect); a ``dedupe``-keyed submit is idempotent by the daemon's
contract and retries the same way. ``cancel``/``shutdown`` never retry —
their responses are not idempotent. Daemon refusals (``ok: false``) are
surfaced with the daemon's reason verbatim; an admission shed under
resource pressure raises :class:`ShedError` carrying the governor's
``retry_after_s`` hint so callers (``submit --wait``, the balancer) can
sleep exactly that long instead of hot-looping.
"""

import errno
import sys
import time

from . import protocol, transport


class ServeError(RuntimeError):
    """Transport failure or an ``ok: false`` response (reason in str())."""


class TransportError(ServeError):
    """The connection itself failed (unreachable daemon, reset, torn
    frame) — the daemon may or may not have seen the request. The
    balancer re-routes dedupe-keyed submits on exactly this class."""


class TransportTimeout(TransportError):
    """The request was SENT but no response arrived in time. The peer
    may be alive and still executing it — so the balancer must NOT fail
    a submit over to another backend on this class (a live backend plus
    a re-routed copy is two executions; journal-lease takeover only
    arbitrates against DEAD backends). A timeout during connect() is an
    ordinary TransportError: nothing reached the daemon."""


class ShedError(ServeError):
    """Admission shed under resource pressure: not admitted, safe to
    retry after :attr:`retry_after_s` (the governor's hint)."""

    def __init__(self, reason: str, retry_after_s: float):
        super().__init__(reason)
        self.retry_after_s = float(retry_after_s)


#: errnos that mean "the peer vanished mid-conversation" — the retryable
#: class together with connection refusal (daemon restarting).
_RESET_ERRNOS = frozenset({errno.ECONNRESET, errno.EPIPE})


def _is_reset(exc: OSError) -> bool:
    return isinstance(exc, (ConnectionResetError, BrokenPipeError)) \
        or getattr(exc, "errno", None) in _RESET_ERRNOS


class ServeClient:
    def __init__(self, address: str, timeout: float = 30.0,
                 max_frame_bytes: int = protocol.MAX_FRAME_BYTES,
                 retry_policy: transport.RetryPolicy = None,
                 token: str = None):
        self.address = address
        self.kind, _ = transport.parse_address(address)
        self.timeout = timeout
        self.max_frame_bytes = max_frame_bytes
        self.retry_policy = retry_policy or transport.RetryPolicy()
        #: shared-secret handshake token; sent (as a hello frame opening
        #: each connection) whenever set — required by non-loopback TCP
        #: listeners, harmless elsewhere
        self.token = token

    @property
    def socket_path(self) -> str:
        """Back-compat spelling for unix-socket callers."""
        return self.address

    # -- transport ----------------------------------------------------------

    def request(self, obj: dict, timeout: float = None,
                retry: bool = True) -> dict:
        """One request -> one response. Raises ServeError on transport
        failure; returns the response frame verbatim (check ``ok``).
        ``timeout`` overrides the client default for this request;
        ``retry=False`` disables the reconnect-on-failure backoff (for
        non-idempotent operations)."""
        policy = self.retry_policy if retry else transport.RetryPolicy.none()
        last = None
        for attempt in range(policy.attempts):
            if attempt:
                time.sleep(policy.delay_s(attempt))
            try:
                return self._request_once(obj, timeout)
            except _Retryable as e:
                last = e.error
        raise last

    def _request_once(self, obj: dict, timeout: float = None) -> dict:
        try:
            conn = transport.connect(
                self.address, self.timeout if timeout is None else timeout)
        except OSError as e:
            # includes connection-refused: a restarting daemon's window —
            # retryable for idempotent ops under the backoff policy
            raise _Retryable(TransportError(
                f"cannot reach daemon at {self.address}: {e}"))
        try:
            sent = False
            try:
                stream = conn.makefile("rb")
                if self.token is not None:
                    transport.client_hello(stream, conn, self.token,
                                           self.max_frame_bytes)
                conn.sendall(protocol.encode_frame(obj))
                sent = True
                resp = protocol.read_frame(stream, self.max_frame_bytes)
            except protocol.ProtocolError as e:
                # a handshake refusal or garbled frame is a loud daemon
                # answer, not weather — never retried
                raise ServeError(f"daemon connection failed: {e}")
            except TimeoutError as e:
                if sent:
                    # the COMPLETE frame is on the wire and the answer
                    # never came: the peer may be alive and still
                    # working — never treated like a death signature
                    raise TransportTimeout(
                        f"daemon did not answer within the timeout: {e}")
                # handshake or send-phase timeout: the request frame was
                # never fully delivered (a torn frame fails to decode and
                # is never acted on), so nothing is in flight — an
                # ordinary retryable transport failure
                raise _Retryable(TransportError(
                    f"daemon connection timed out before the request "
                    f"was delivered: {e}"))
            except OSError as e:
                err = TransportError(f"daemon connection failed: {e}")
                if _is_reset(e):
                    raise _Retryable(err)  # daemon restarting: retry
                raise err
            if resp is None:
                # clean close mid-request: the SIGKILL/restart signature
                raise _Retryable(TransportError(
                    "daemon closed the connection mid-request"))
            return resp
        finally:
            conn.close()

    def _checked(self, obj: dict, timeout: float = None,
                 retry: bool = True) -> dict:
        resp = self.request(obj, timeout=timeout, retry=retry)
        if not resp.get("ok"):
            # the daemon's reason verbatim — "queue full: ..." vs
            # "draining: ..." is how callers tell backpressure from refusal
            reason = resp.get("error", "daemon refused the request")
            if "retry_after_s" in resp:
                # resource_pressure shed: carries the governor's hint so
                # submit --wait / the balancer sleep it instead of looping
                raise ShedError(reason, resp["retry_after_s"])
            raise ServeError(reason)
        return resp

    # -- operations ---------------------------------------------------------

    def ping(self) -> dict:
        return self._checked({"v": protocol.PROTOCOL_VERSION, "op": "ping"})

    def hello(self) -> dict:
        """Explicit handshake round-trip (the balancer's auth probe)."""
        return self._checked({"v": protocol.PROTOCOL_VERSION, "op": "hello",
                              "token": self.token})

    def stats(self, timeout: float = None) -> dict:
        """Live introspection snapshot (scheduler/quota/journal/breaker/
        governor/device/fleet + latency histogram summaries). A daemon
        predating the op answers ``unknown op 'stats'`` — surfaced
        verbatim as ServeError, the documented clean rejection."""
        return self._checked({"v": protocol.PROTOCOL_VERSION,
                              "op": "stats"}, timeout=timeout)["stats"]

    def submit(self, argv, priority: str = protocol.DEFAULT_PRIORITY,
               argv0: str = None, tag: str = None, trace: bool = False,
               dedupe: str = None, client: str = None,
               traceparent: str = None, shard: dict = None) -> dict:
        """Submit a command; returns the accepted job record. An admission
        rejection (queue full / draining / over quota) raises ServeError
        with the daemon's reason; a resource-pressure shed raises
        :class:`ShedError` with the retry hint. ``dedupe``: idempotency
        key — resubmitting the same key returns the original job instead of
        running it twice, which also makes the reconnect retry safe for
        submits; without a key, a submit whose connection resets is NOT
        retried (the daemon may already have admitted it). ``client``:
        submitter identity for the daemon's per-client admission quota
        (serve --max-per-client); anonymous submits are never quota-limited.

        Trace context: every submit carries a W3C-style ``traceparent``
        (minted here unless the caller provides one) plus its send wall
        time, so fleet-routed jobs are causally linkable end to end; old
        daemons ignore both fields (docs/observability.md). The minted
        context is recorded on the returned record under ``traceparent``
        and — when this process is itself tracing — as a ``serve.submit``
        span tagged with the ids, so a client-side trace file merges
        under the same trace-id as the balancer's and the backend's."""
        from ..observe import trace as trace_mod

        if traceparent is None:
            trace_id = trace_mod.mint_trace_id()
            span_id = trace_mod.mint_span_id()
            traceparent = trace_mod.format_traceparent(trace_id, span_id)
        else:
            parsed = trace_mod.parse_traceparent(traceparent)
            trace_id, span_id = parsed if parsed else (None, None)
        req = {"v": protocol.PROTOCOL_VERSION, "op": "submit",
               "argv": list(argv), "priority": priority,
               "argv0": argv0 if argv0 is not None else sys.argv[0],
               "trace": bool(trace), "traceparent": traceparent,
               "sent_unix": round(time.time(), 6)}
        if tag is not None:
            req["tag"] = tag
        if dedupe is not None:
            req["dedupe"] = dedupe
        if client is not None:
            req["client"] = client
        if shard is not None:
            # scatter metadata (a balancer's whale fan-out stamps it; see
            # serve/scatter.py) — old daemons ignore the field
            req["shard"] = dict(shard)
        if trace_id is not None:
            trace_mod.set_trace_context(trace_id=trace_id,
                                        process_label="client")
        with trace_mod.span("serve.submit", trace_id=trace_id,
                            span_id=span_id):
            job = self._checked(req, retry=dedupe is not None)["job"]
        return job

    def scatter(self, job_id: str = None, timeout: float = None) -> dict:
        """Whale scatter/gather introspection from a ``balance --scatter``
        front end: per-shard state for one whale id, or the whole scatter
        section without one. A daemon answers with its explicit
        balancer-only refusal, and daemons/balancers predating the op
        answer ``unknown op 'scatter'`` — both surfaced verbatim as
        ServeError (the documented clean rejection)."""
        req = {"v": protocol.PROTOCOL_VERSION, "op": "scatter"}
        if job_id is not None:
            req["id"] = job_id
        return self._checked(req, timeout=timeout)["scatter"]

    def status(self, job_id: str = None, timeout: float = None) -> dict:
        req = {"v": protocol.PROTOCOL_VERSION, "op": "status"}
        if job_id is not None:
            req["id"] = job_id
        return self._checked(req, timeout=timeout)

    def job(self, job_id: str) -> dict:
        return self.status(job_id)["job"]

    def cancel(self, job_id: str) -> dict:
        # no reconnect retry: if the daemon acted before the reset, the
        # retry would be answered "already cancelled" (ok: false) and a
        # cancel that succeeded would surface as a failure
        return self._checked({"v": protocol.PROTOCOL_VERSION, "op": "cancel",
                              "id": job_id}, retry=False)["job"]

    def drain(self) -> dict:
        # idempotent (re-draining a draining daemon is a no-op): retry ok
        return self._checked({"v": protocol.PROTOCOL_VERSION, "op": "drain"})

    def shutdown(self) -> dict:
        # no retry: after a successful shutdown the reconnect would hit
        # connection-refused and report failure for an op that succeeded
        return self._checked({"v": protocol.PROTOCOL_VERSION,
                              "op": "shutdown"}, retry=False)

    def wait(self, job_id: str, timeout: float = None,
             poll_s: float = 0.2, unknown_grace_s: float = 15.0) -> dict:
        """Poll until the job reaches a terminal state; returns the record.
        Raises ServeError on timeout (the job keeps running).

        An ``unknown job`` answer is tolerated for ``unknown_grace_s``
        before it is fatal: through a balancer, a job whose backend was
        just SIGKILL'd is briefly unknown FLEET-WIDE — until a survivor's
        lease scan adopts the dead daemon's journal and the id resolves
        again. Failing the wait inside that window would turn the exact
        failover the fleet tier exists for into a client error."""
        from .jobs import TERMINAL

        deadline = None if timeout is None else time.monotonic() + timeout
        unknown_since = None
        while True:
            try:
                job = self.job(job_id)
            except ServeError as e:
                if "unknown job" not in str(e):
                    raise
                now = time.monotonic()
                if unknown_since is None:
                    unknown_since = now
                if now - unknown_since >= unknown_grace_s or (
                        deadline is not None and now >= deadline):
                    raise
                time.sleep(poll_s)
                continue
            unknown_since = None
            if job["state"] in TERMINAL:
                return job
            if deadline is not None and time.monotonic() >= deadline:
                raise ServeError(
                    f"timed out waiting for job {job_id} "
                    f"(still {job['state']})")
            time.sleep(poll_s)


class _Retryable(Exception):
    """Internal: wraps a ServeError the transport may retry."""

    def __init__(self, error: ServeError):
        super().__init__(str(error))
        self.error = error
