"""Job-service wire protocol: newline-delimited JSON frames, schema v1.

One request frame per line, one response frame per line, UTF-8 JSON with a
trailing ``\\n``. A connection may carry any number of request/response
pairs (the client library opens one connection per request for simplicity;
the daemon supports either). The schema is versioned exactly like the run
report: every frame carries ``"v": PROTOCOL_VERSION`` and the daemon
rejects mismatches loudly instead of guessing.

Requests::

    {"v": 1, "op": "submit", "argv": ["simplex", "-i", ...],
     "priority": "normal", "argv0": "fgumi-tpu", "trace": false,
     "tag": "optional-label", "dedupe": "optional-idempotency-key",
     "client": "optional-submitter-id",
     "traceparent": "00-<32hex>-<16hex>-01",   # optional trace context
     "sent_unix": 1723.4,                      # client send wall time
     "bal_recv_unix": 1723.5,                  # stamped by a balancer
     "bal_sent_unix": 1723.5,                  # forward hop
     "shard": {"whale": "w-ab12-1",            # optional scatter metadata:
               "index": 0, "count": 4,         # stamped by a balancer's
               "axis": "umi"}}                 # whale fan-out; old daemons
                                               # ignore it (garnish)
    {"v": 1, "op": "status"}           # all jobs
    {"v": 1, "op": "status", "id": "j-3"}
    {"v": 1, "op": "cancel", "id": "j-3"}
    {"v": 1, "op": "drain"}            # stop admitting, keep serving status
    {"v": 1, "op": "shutdown"}         # drain, finish queued+running, exit
    {"v": 1, "op": "ping"}             # daemon liveness + config echo
    {"v": 1, "op": "stats"}            # live introspection snapshot
                                       # (scheduler/quota/journal/breaker/
                                       # governor/device/fleet + latency
                                       # histogram summaries; daemons
                                       # predating the op reject it cleanly
                                       # with "unknown op 'stats'")
    {"v": 1, "op": "hello",            # transport handshake (fleet tier):
     "token": "shared-secret"}         # REQUIRED as the first frame on a
                                       # non-loopback TCP connection; on a
                                       # Unix/loopback listener it is
                                       # accepted but optional. Old daemons
                                       # reject it cleanly with "unknown op
                                       # 'hello'" — a new balancer probing
                                       # an old daemon gets a loud answer
    {"v": 1, "op": "scatter"}          # whale scatter/gather introspection
    {"v": 1, "op": "scatter",          # (balancer-only: a `balance
     "id": "w-ab12-1"}                 # --scatter` front end answers with
                                       # per-shard state; daemons reject it
                                       # explicitly — they execute shard
                                       # sub-jobs, they never plan them —
                                       # and daemons predating the op
                                       # reject it cleanly with "unknown op
                                       # 'scatter'", docs/serving.md
                                       # "Scatter/gather")

Responses are ``{"v": 1, "ok": true, ...}`` or
``{"v": 1, "ok": false, "error": "<reason>"}``. Submit acceptance returns
the job record; admission rejection is ``ok: false`` with the reason
(queue full / draining) so a load balancer can tell backpressure from
breakage. A ``dedupe`` key makes submission idempotent: resubmitting the
same key — e.g. a client retrying across a daemon restart — returns the
original job record (``"deduped": true``) instead of running the command
twice; keys survive restarts via the job journal (docs/serving.md).

Malformed frames (bad JSON, not an object, unknown op, missing fields) get
an error response; oversized frames (> ``max_frame_bytes``, default 1 MiB)
get an error response and the connection is closed — the daemon must never
buffer unbounded garbage from a confused client.

Version negotiation for the observability fields: ``traceparent`` and the
hop timestamps are OPTIONAL submit fields under the same ``v: 1`` schema,
because :func:`validate_request` deliberately ignores submit fields it
does not know — an old daemon receiving them executes the job exactly as
before (the context is garnish), and a new daemon receiving a frame
without them runs untraced. A *malformed* traceparent (wrong shape,
non-hex, all-zero ids) or a non-numeric hop timestamp is likewise IGNORED
— dropped at parse, never a rejection — so telemetry can never fail a
submission (docs/observability.md "Fleet tracing & attribution").
"""

import json

PROTOCOL_VERSION = 1

#: Hard cap on one frame's bytes (newline included). Large enough for any
#: realistic argv, small enough that a garbage stream cannot balloon the
#: daemon's memory. Override with serve --max-frame-bytes.
MAX_FRAME_BYTES = 1 << 20

OPS = frozenset({"submit", "status", "cancel", "drain", "shutdown", "ping",
                 "stats", "hello", "scatter"})

#: Priority classes, best-first. FIFO within a class.
PRIORITIES = ("high", "normal", "low")
DEFAULT_PRIORITY = "normal"


class ProtocolError(ValueError):
    """A frame this protocol refuses to act on (reason in str())."""


def encode_frame(obj: dict) -> bytes:
    """One JSON object as a newline-terminated wire frame."""
    return json.dumps(obj, separators=(",", ":"), sort_keys=True).encode() \
        + b"\n"


def decode_frame(line: bytes) -> dict:
    """Parse one frame; raises :class:`ProtocolError` with a diagnostic."""
    try:
        obj = json.loads(line)
    except ValueError as e:
        raise ProtocolError(f"malformed frame: not valid JSON ({e})")
    if not isinstance(obj, dict):
        raise ProtocolError(
            f"malformed frame: expected a JSON object, got "
            f"{type(obj).__name__}")
    return obj


def read_frame(stream, max_bytes: int = MAX_FRAME_BYTES):
    """Read one frame from a binary stream (``socket.makefile('rb')``).

    Returns the decoded dict, or None on clean EOF (peer closed between
    frames). Raises :class:`ProtocolError` for an oversized frame or a
    stream that ends mid-frame."""
    line = stream.readline(max_bytes + 1)
    if not line:
        return None
    if len(line) > max_bytes:
        raise ProtocolError(
            f"oversized frame: > {max_bytes} bytes (limit includes the "
            "trailing newline)")
    if not line.endswith(b"\n"):
        raise ProtocolError("truncated frame: stream ended before newline")
    return decode_frame(line)


def validate_request(obj: dict):
    """Return None for a well-formed request, else the rejection reason."""
    v = obj.get("v")
    if v != PROTOCOL_VERSION:
        return (f"unsupported protocol version {v!r} "
                f"(this daemon speaks v{PROTOCOL_VERSION})")
    op = obj.get("op")
    if op not in OPS:
        return f"unknown op {op!r} (known: {', '.join(sorted(OPS))})"
    if op == "submit":
        argv = obj.get("argv")
        if (not isinstance(argv, list) or not argv
                or not all(isinstance(a, str) for a in argv)):
            return "submit requires argv: a non-empty list of strings"
        prio = obj.get("priority", DEFAULT_PRIORITY)
        if prio not in PRIORITIES:
            return (f"unknown priority {prio!r} "
                    f"(known: {', '.join(PRIORITIES)})")
        argv0 = obj.get("argv0")
        if argv0 is not None and not isinstance(argv0, str):
            return "argv0 must be a string"
        dedupe = obj.get("dedupe")
        if dedupe is not None and (not isinstance(dedupe, str)
                                   or not dedupe):
            return "dedupe must be a non-empty string"
        client = obj.get("client")
        if client is not None and (not isinstance(client, str)
                                   or not client):
            return "client must be a non-empty string"
        shard = obj.get("shard")
        if shard is not None and not isinstance(shard, dict):
            return "shard must be an object (whale/index/count/axis)"
    if op == "hello":
        token = obj.get("token")
        if token is not None and not isinstance(token, str):
            return "hello token must be a string"
    if op in ("cancel",) and not isinstance(obj.get("id"), str):
        return f"{op} requires id: a job id string"
    if "id" in obj and obj["id"] is not None \
            and not isinstance(obj["id"], str):
        return "id must be a string"
    return None


def ok_response(**fields) -> dict:
    return {"v": PROTOCOL_VERSION, "ok": True, **fields}


def error_response(reason: str, **fields) -> dict:
    return {"v": PROTOCOL_VERSION, "ok": False, "error": reason, **fields}
