"""Job registry: per-job state machine and wire representation.

Each submitted command becomes a :class:`Job` with a strict lifecycle::

    queued ----> running ----> done     (exit status 0)
      |             \\-------> failed   (nonzero exit / exception; the
      |                                 diagnostic is kept on the record)
      \\----> cancelled                 (queued jobs only — running jobs
                                        are never preempted)

Transitions outside this graph raise, so a scheduler bug can never
resurrect a finished job or mark a cancelled one done. The registry is the
daemon's single source of truth for ``status`` responses and keeps every
terminal job until the daemon exits (bounded by ``keep_finished``, oldest
evicted first) so a client can poll a job that finished between polls.
"""

import collections
import threading
import time

STATES = ("queued", "running", "done", "failed", "cancelled")
_ALLOWED = {
    "queued": {"running", "cancelled"},
    "running": {"done", "failed"},
    "done": set(),
    "failed": set(),
    "cancelled": set(),
}

TERMINAL = frozenset(("done", "failed", "cancelled"))


class Job:
    """One submitted command and its lifecycle bookkeeping."""

    __slots__ = ("id", "argv", "argv0", "priority", "tag", "trace",
                 "client", "state", "submitted_unix", "started_unix",
                 "finished_unix", "exit_status", "error", "report_path",
                 "trace_path", "traceparent", "hops", "shard")

    def __init__(self, job_id: str, argv, priority: str, argv0: str = None,
                 tag: str = None, trace: bool = False, client: str = None,
                 traceparent: str = None, hops: dict = None,
                 shard: dict = None):
        self.id = job_id
        self.argv = list(argv)
        self.argv0 = argv0 or "fgumi-tpu"
        self.priority = priority
        self.tag = tag
        self.trace = bool(trace)
        #: submitter identity for per-client admission quotas (protocol
        #: "client" field; None = anonymous, never quota-limited)
        self.client = client
        #: propagated W3C-style trace context (already validated by the
        #: daemon — malformed values were dropped at parse, so this is
        #: either a well-formed traceparent string or None)
        self.traceparent = traceparent
        #: upstream hop wall-clock timestamps for end-to-end latency
        #: attribution (client_sent_unix / balancer_recv_unix /
        #: balancer_sent_unix as propagated; None when the client sent none)
        self.hops = dict(hops) if hops else None
        #: scatter metadata stamped by a whale fan-out (protocol "shard"
        #: field: whale id / shard index / shard count / hash axis); None
        #: for every ordinary job
        self.shard = dict(shard) if shard else None
        self.state = "queued"
        self.submitted_unix = time.time()
        self.started_unix = None
        self.finished_unix = None
        self.exit_status = None
        self.error = None
        self.report_path = None
        self.trace_path = None

    def to_wire(self) -> dict:
        """The JSON-safe record sent in submit/status responses."""
        return {
            "id": self.id,
            "state": self.state,
            "argv": list(self.argv),
            "priority": self.priority,
            "tag": self.tag,
            "client": self.client,
            "submitted_unix": round(self.submitted_unix, 3),
            "started_unix": (round(self.started_unix, 3)
                             if self.started_unix else None),
            "finished_unix": (round(self.finished_unix, 3)
                              if self.finished_unix else None),
            "exit_status": self.exit_status,
            "error": self.error,
            "report_path": self.report_path,
            "trace_path": self.trace_path,
            "traceparent": self.traceparent,
            "shard": self.shard,
        }


class InvalidTransition(RuntimeError):
    """A state change outside the job lifecycle graph."""


class JobRegistry:
    """Thread-safe id -> :class:`Job` store enforcing the state machine."""

    def __init__(self, keep_finished: int = 1000, on_transition=None,
                 id_prefix: str = ""):
        self._lock = threading.Lock()
        self._jobs = {}
        self._order = []  # insertion order, for stable listing
        # terminal ids in completion order: O(1) eviction on create instead
        # of rescanning the whole history per submission
        self._finished = collections.deque()
        self._next_id = 1
        #: fleet-mode id namespace: daemons sharing a --journal-dir mint
        #: "<fleet-id>-j-<n>" so a takeover can requeue a peer's job under
        #: its ORIGINAL id with no collision against the survivor's own
        self._id_prefix = f"{id_prefix}-" if id_prefix else ""
        self._keep_finished = keep_finished
        #: called as on_transition(job) after every state change — the
        #: daemon's journal hook (fires outside the registry lock, after
        #: the record's fields are final)
        self.on_transition = on_transition

    def create(self, argv, priority: str, argv0: str = None,
               tag: str = None, trace: bool = False,
               client: str = None, traceparent: str = None,
               hops: dict = None, shard: dict = None) -> Job:
        with self._lock:
            job = Job(f"{self._id_prefix}j-{self._next_id}", argv, priority,
                      argv0=argv0, tag=tag, trace=trace, client=client,
                      traceparent=traceparent, hops=hops, shard=shard)
            self._next_id += 1
            self._jobs[job.id] = job
            self._order.append(job.id)
            self._evict_locked()
            return job

    def reserve_ids(self, max_seen: int):
        """Never mint an id at or below ``max_seen``. Fleet restart
        hygiene: a daemon whose journal was consumed by a peer takeover
        (renamed ``.claimed``) replays nothing, but the ids it minted
        before dying now LIVE on the survivor — re-minting them would
        break the fleet-wide-unique-id invariant takeover depends on."""
        with self._lock:
            self._next_id = max(self._next_id, int(max_seen) + 1)

    def restore(self, job: Job):
        """Insert a pre-built job (journal replay): the id is preserved so
        clients polling across a daemon restart still resolve it, and the
        id counter skips past it so new submissions never collide."""
        with self._lock:
            if job.id in self._jobs:
                raise ValueError(f"job id {job.id} already registered")
            self._jobs[job.id] = job
            self._order.append(job.id)
            suffix = job.id.rsplit("-", 1)[-1]
            if suffix.isdigit():
                self._next_id = max(self._next_id, int(suffix) + 1)
            if job.state in TERMINAL:
                self._finished.append(job.id)
            self._evict_locked()

    def _evict_locked(self):
        while len(self._finished) > self._keep_finished:
            jid = self._finished.popleft()
            if jid in self._jobs:  # may already be discard()ed
                del self._jobs[jid]
                self._order.remove(jid)

    def _note_terminal(self, job: Job):
        with self._lock:
            self._finished.append(job.id)

    def discard(self, job_id: str):
        """Forget a job entirely (admission-rejected submissions: keeping
        them would let a rejection storm evict real finished-job history)."""
        with self._lock:
            if job_id in self._jobs:
                del self._jobs[job_id]
                self._order.remove(job_id)

    def get(self, job_id: str) -> Job:
        with self._lock:
            return self._jobs.get(job_id)

    def list(self):
        with self._lock:
            return [self._jobs[j] for j in self._order]

    def counts(self) -> dict:
        with self._lock:
            out = dict.fromkeys(STATES, 0)
            for job in self._jobs.values():
                out[job.state] += 1
            return out

    # -- transitions --------------------------------------------------------

    def _transition(self, job: Job, new_state: str):
        with self._lock:
            if new_state not in _ALLOWED[job.state]:
                raise InvalidTransition(
                    f"job {job.id}: {job.state} -> {new_state} is not a "
                    "legal transition")
            job.state = new_state

    def _notify(self, job: Job):
        # every job transition lands in the flight recorder's always-on
        # ring: a daemon black box shows what the scheduler was doing
        from ..observe.flight import FLIGHT

        FLIGHT.note("serve.job", id=job.id, state=job.state,
                    **({"error": str(job.error)[:200]} if job.error else {}))
        cb = self.on_transition
        if cb is not None:
            try:
                cb(job)
            except Exception:  # noqa: BLE001 - journal loss != daemon loss
                import logging

                logging.getLogger("fgumi_tpu").exception(
                    "job transition hook failed for %s", job.id)

    @staticmethod
    def _observe_latency(job: Job):
        """Fold one job's lifecycle walls into the latency histograms.

        Runs on the scheduler worker thread OUTSIDE any job telemetry
        scope, so the observations land in the process-global registry —
        the daemon-lifetime view the ``stats`` op and ``/metrics`` expose.
        queued→running is observed at start; running→terminal and
        submit→terminal at finish."""
        from ..observe.metrics import METRICS

        if job.state == "running":
            if job.started_unix and job.submitted_unix:
                METRICS.observe("serve.job.queue_wait_s",
                                job.started_unix - job.submitted_unix)
            return
        if job.state in ("done", "failed") and job.finished_unix:
            if job.started_unix:
                METRICS.observe("serve.job.run_s",
                                job.finished_unix - job.started_unix)
            if job.submitted_unix:
                METRICS.observe("serve.job.total_s",
                                job.finished_unix - job.submitted_unix)
            # end-to-end decomposition from the propagated hop timestamps
            # (present only when the client sent them; all clamped >= 0 —
            # host clock skew must not poison a histogram with negatives).
            # serve.job.e2e.submit_to_done_s is the fleet's
            # "p99 submit-to-bytes-published" series: client send wall to
            # job terminal, spanning every hop in between.
            hops = job.hops or {}
            cs = hops.get("client_sent_unix")
            br = hops.get("balancer_recv_unix")
            bs = hops.get("balancer_sent_unix")
            if cs and br:
                METRICS.observe("serve.job.e2e.client_to_balancer_s",
                                max(br - cs, 0.0))
            if bs and job.submitted_unix:
                METRICS.observe("serve.job.e2e.balancer_to_admit_s",
                                max(job.submitted_unix - bs, 0.0))
            elif cs and not bs and job.submitted_unix:
                # direct submit (no balancer hop): one client->admit leg
                METRICS.observe("serve.job.e2e.client_to_admit_s",
                                max(job.submitted_unix - cs, 0.0))
            if cs:
                METRICS.observe("serve.job.e2e.submit_to_done_s",
                                max(job.finished_unix - cs, 0.0))

    def mark_running(self, job: Job):
        self._transition(job, "running")
        job.started_unix = time.time()
        self._observe_latency(job)
        self._notify(job)

    def mark_done(self, job: Job, exit_status: int):
        job.exit_status = int(exit_status)
        if exit_status == 0:
            self._transition(job, "done")
        else:
            job.error = job.error or f"command exited {exit_status}"
            self._transition(job, "failed")
        job.finished_unix = time.time()
        self._note_terminal(job)
        self._observe_latency(job)
        self._notify(job)

    def mark_failed(self, job: Job, error: str):
        job.error = str(error)
        job.exit_status = job.exit_status if job.exit_status else 1
        self._transition(job, "failed")
        job.finished_unix = time.time()
        self._note_terminal(job)
        self._observe_latency(job)
        self._notify(job)

    def mark_cancelled(self, job: Job):
        self._transition(job, "cancelled")
        job.finished_unix = time.time()
        self._note_terminal(job)
        self._notify(job)
