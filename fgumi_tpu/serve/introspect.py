"""Live serve introspection: the ``stats`` op payload, the Prometheus
text-format exporter, and the optional ``--metrics-port`` HTTP listener.

One snapshot builder (:func:`service_stats`) feeds both surfaces, so the
``stats`` protocol op and a ``/metrics`` scrape can never disagree about
the daemon's live state. The registries read here are the PROCESS-GLOBAL
ones: connection and worker threads run outside any job's telemetry scope,
every finished job publishes its counters to the globals at exit
(``observe.scope.publish_to_global``), and latency histograms *merge* on
publish — so counters/gauges are the last finished job's view while
histograms and the structural snapshots (scheduler depth, job counts,
breaker, governor, DeviceStats) are daemon-lifetime.

The HTTP listener binds loopback only, serves two endpoints and nothing
else:

- ``GET /metrics`` — Prometheus text format 0.0.4: every counter/gauge as
  ``fgumi_tpu_<dotted_name_with_underscores>``, every latency histogram as
  a cumulative ``_bucket{le=...}`` series + ``_sum``/``_count``, plus
  daemon gauges (job states, queue depth, breaker state, uptime).
- ``GET /healthz`` — JSON liveness backed by the PR 7 HealthMonitor and
  the device circuit breaker: HTTP 200 while the breaker is not open,
  503 once it trips (a fleet load balancer can eject the replica).
"""

import json
import logging
import os
import re
import threading
import time

log = logging.getLogger("fgumi_tpu")

#: stats payload schema (versioned like the wire protocol + run report).
#: v2 added the ``fleet`` section (journal-lease takeover accounting;
#: None outside --journal-dir fleet mode). v3 added the ``audit`` section
#: (silent-corruption sentinel scoreboard, ops/sentinel.py; None while
#: nothing was audited) — the balancer ejects a backend whose ``audit``
#: reports ``divergent > 0``. v4 added the ``coalesce`` section
#: (cross-job dispatch coalescer scoreboard, ops/coalesce.py; None while
#: the merge window never armed and merged nothing). v5 added the
#: ``device_memory`` section (live accelerator memory summed over local
#: devices — bytes_in_use/peak_bytes from jax memory_stats(); None on
#: CPU backends, which report no memory stats). v6 added the
#: ``routing_state`` section (warm-start persistence of the routing
#: EWMAs, ISSUE 20: where the daemon's routing snapshot lives, whether
#: one was reloaded at start and when it was saved; None on daemons
#: without a snapshot path).
STATS_SCHEMA_VERSION = 6

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(dotted: str) -> str:
    return "fgumi_tpu_" + _NAME_RE.sub("_", dotted)


# ---------------------------------------------------------------------------
# the one snapshot builder


def service_stats(service) -> dict:
    """The ``stats`` op payload for a :class:`~.daemon.JobService`.

    Always includes every key; sections whose subsystem was never touched
    in this process are ``None`` (e.g. ``device`` before the first kernel
    import), so clients can rely on the shape."""
    from ..observe.flight import (audit_snapshot, breaker_snapshot,
                                  coalesce_snapshot,
                                  device_memory_snapshot,
                                  governor_snapshot, live_device_stats,
                                  router_snapshot)
    from ..observe.metrics import METRICS

    stats = live_device_stats()
    sched = service.scheduler
    return {
        "schema_version": STATS_SCHEMA_VERSION,
        "pid": os.getpid(),
        "uptime_s": round(time.time() - service.started_unix, 1),
        "jobs": service.registry.counts(),
        "scheduler": sched.depth(),
        "max_per_client": sched.max_per_client,
        "quota": sched.client_quota_state(),
        "journal": _journal_section(service),
        "fleet": _fleet_section(service),
        "metrics": METRICS.snapshot(),
        "latency": METRICS.summaries(),
        "device": stats.snapshot() if stats is not None else None,
        "device_memory": device_memory_snapshot(),
        "breaker": breaker_snapshot(),
        "governor": governor_snapshot(),
        "monitor": _monitor_section(service),
        "router": router_snapshot(),
        "routing_state": getattr(service, "routing_state", None),
        "audit": audit_snapshot(),
        "coalesce": coalesce_snapshot(),
    }


def _journal_section(service):
    if not service.journal_path:
        return None
    return {"path": service.journal_path,
            **getattr(service, "journal_stats", {})}


def _fleet_section(service):
    """Journal-lease fleet accounting (``serve --journal-dir``): fleet id,
    lease state, takeover history, and the live load figure the balancer
    routes by. None on a standalone daemon."""
    stats = getattr(service, "fleet_stats", None)
    if stats is None:
        return None
    return {**stats, "active_jobs": service.scheduler.active()}


def _monitor_section(service):
    monitor = getattr(service, "_monitor", None)
    if monitor is None:
        return None
    return {"period_s": monitor.period_s, "canaries": monitor.canaries}


# ---------------------------------------------------------------------------
# Prometheus text format


def render_prometheus(service) -> str:
    """The ``/metrics`` body, derived from the same :func:`service_stats`
    snapshot the ``stats`` op returns."""
    from ..observe.metrics import METRICS

    stats = service_stats(service)
    lines = []
    # duplicate guard, keyed on MUNGED names: distinct dotted names can
    # collide after underscore substitution (device.route_device from the
    # DeviceStats snapshot vs the device.route.device registry counter)
    emitted = set()

    def gauge(dotted, value, help_text=None, labels=""):
        name = _prom_name(dotted)
        emitted.add(name)
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{labels} {_num(value)}")

    # daemon structural gauges (always present)
    gauge("serve.uptime_s", stats["uptime_s"], "daemon uptime in seconds")
    jobs_name = _prom_name("serve.jobs")
    lines.append(f"# HELP {jobs_name} jobs by lifecycle state")
    lines.append(f"# TYPE {jobs_name} gauge")
    for state, n in sorted(stats["jobs"].items()):
        lines.append(f'{jobs_name}{{state="{state}"}} {n}')
    sched = stats["scheduler"]
    gauge("serve.queued", sched["queued"])
    gauge("serve.running", sched["running"])
    gauge("serve.workers", sched["workers"])
    gauge("serve.queue_limit", sched["queue_limit"])
    gauge("serve.draining", int(bool(sched["draining"])))
    if stats["breaker"] is not None:
        gauge("device.breaker.open",
              int(stats["breaker"]["state"] == "open"),
              "1 while the device circuit breaker is open")
    if stats["governor"] is not None:
        state = stats["governor"].get("state", "ok")
        gauge("resource.pressure",
              {"ok": 0, "soft": 1, "hard": 2}.get(state, 0),
              "resource pressure state (0 ok / 1 soft / 2 hard)")
    if stats["device"] is not None:
        for key, v in stats["device"].items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                gauge(f"device.{key}", v)
    if stats["device_memory"] is not None:
        # live accelerator memory (absent on CPU backends)
        gauge("device.memory.bytes_in_use",
              stats["device_memory"]["bytes_in_use"],
              "live accelerator bytes in use, summed over local devices")
        gauge("device.memory.peak_bytes",
              stats["device_memory"]["peak_bytes"])
    if stats["audit"] is not None:
        # the silent-corruption scoreboard a fleet balancer ejects on:
        # daemon-lifetime counters straight from the sentinel (the flat
        # device.audit.* registry counters are the last finished job's)
        for key in ("sampled", "clean", "divergent", "dropped"):
            gauge(f"device.audit.{key}", stats["audit"].get(key, 0),
                  "shadow-audit scoreboard (ops/sentinel.py)"
                  if key == "sampled" else None)
    if stats["coalesce"] is not None:
        # cross-job dispatch coalescer scoreboard (ops/coalesce.py):
        # daemon-lifetime merge counters; the flat device.coalesce.*
        # registry counters are the last finished job's view
        for key, v in stats["coalesce"].items():
            if isinstance(v, bool):
                gauge(f"device.coalesce.{key}", int(v))
            elif isinstance(v, (int, float)):
                gauge(f"device.coalesce.{key}", v,
                      "dispatch-coalescer scoreboard (ops/coalesce.py)"
                      if key == "merged_batches" else None)

    # flat counters/gauges from the SAME snapshot the stats op returns
    # (last finished job + anything written outside job scopes). Names the
    # structural loops above already rendered are skipped: a finished job
    # folds DeviceStats into the registry under the same device.* names,
    # and Prometheus rejects a scrape with duplicate series
    for dotted, v in stats["metrics"].items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        name = _prom_name(dotted)
        if name in emitted:
            continue
        emitted.add(name)
        lines.append(f"{name} {_num(v)}")

    # latency histograms: cumulative le-buckets + sum + count. The one
    # read outside the service_stats snapshot — summaries carry no bucket
    # series, so the Histogram copies must come from the registry
    for dotted, hist in METRICS.histograms().items():
        name = _prom_name(dotted)
        lines.append(f"# TYPE {name} histogram")
        for edge, cum in hist.buckets():
            lines.append(f'{name}_bucket{{le="{edge:.9g}"}} {cum}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {hist.count}')
        lines.append(f"{name}_sum {_num(round(hist.total, 6))}")
        lines.append(f"{name}_count {hist.count}")
    return "\n".join(lines) + "\n"


def _num(v):
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def render_healthz(service) -> tuple:
    """``(http_status, body_dict)`` for ``/healthz``: 200 while the device
    breaker is not open (or was never loaded), 503 once it trips."""
    from ..observe.flight import breaker_snapshot

    breaker = breaker_snapshot()
    state = breaker["state"] if breaker else "closed"
    healthy = state != "open"
    body = {
        "status": "ok" if healthy else "degraded",
        "breaker": state,
        "uptime_s": round(time.time() - service.started_unix, 1),
        "jobs": service.registry.counts(),
        "draining": service.scheduler.draining,
    }
    monitor = _monitor_section(service)
    if monitor is not None:
        body["monitor"] = monitor
    return (200 if healthy else 503), body


# ---------------------------------------------------------------------------
# HTTP listener


class IntrospectionServer:
    """Loopback HTTP listener for ``/metrics`` + ``/healthz``.

    ``port=0`` binds an ephemeral port (tests); :attr:`port` reports the
    bound one. Runs on one daemon thread; ``stop()`` joins it.

    The renderers are pluggable so the fleet balancer can reuse the
    listener with its own surfaces (``serve.balancer``): ``metrics_fn``
    returns the ``/metrics`` text body, ``healthz_fn`` returns
    ``(http_status, body_dict)``. Defaults are the daemon renderers
    bound to ``service``."""

    def __init__(self, service, port: int, host: str = "127.0.0.1",
                 metrics_fn=None, healthz_fn=None):
        self.service = service
        self.host = host
        self._metrics_fn = metrics_fn or \
            (lambda: render_prometheus(service))
        self._healthz_fn = healthz_fn or (lambda: render_healthz(service))
        self._requested_port = int(port)
        self._httpd = None
        self._thread = None

    @property
    def port(self):
        return self._httpd.server_address[1] if self._httpd else \
            self._requested_port

    def bind(self):
        """Bind the HTTP listener without serving yet. A busy port
        raises OSError here, so the daemon can fail fast before the
        device warm-up (same discipline as the unix socket)."""
        if self._httpd is None:
            self._httpd = self._build_server()

    def start(self):
        self.bind()
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="fgumi-serve-metrics",
                                        daemon=True)
        self._thread.start()
        log.info("serve: metrics on http://%s:%d/metrics (healthz on "
                 "/healthz)", self.host, self.port)

    def _build_server(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        metrics_fn, healthz_fn = self._metrics_fn, self._healthz_fn

        class _Handler(BaseHTTPRequestHandler):
            # the metrics port is an operator surface, not a log source
            def log_message(self, *args):
                pass

            def do_GET(self):
                try:
                    if self.path.split("?", 1)[0] == "/metrics":
                        body = metrics_fn().encode()
                        ctype = "text/plain; version=0.0.4; charset=utf-8"
                        status = 200
                    elif self.path.split("?", 1)[0] == "/healthz":
                        status, obj = healthz_fn()
                        body = (json.dumps(obj, sort_keys=True) + "\n") \
                            .encode()
                        ctype = "application/json"
                    else:
                        status, body = 404, b"not found\n"
                        ctype = "text/plain"
                except Exception as e:  # noqa: BLE001 - scrape != crash
                    status, ctype = 500, "text/plain"
                    body = f"snapshot failed: {e}\n".encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = ThreadingHTTPServer((self.host, self._requested_port),
                                    _Handler)
        httpd.daemon_threads = True
        return httpd

    def stop(self):
        if self._httpd is not None:
            if self._thread is not None:
                # shutdown() handshakes with a RUNNING serve_forever and
                # deadlocks otherwise (bound-but-never-started teardown)
                self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
