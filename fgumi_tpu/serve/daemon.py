"""The job-service daemon: socket server + warm-process job execution.

One :class:`JobService` owns the Unix-domain listener, the scheduler, and
the registry. Each admitted job is executed by re-entering the ordinary
CLI (``cli.main``) on a worker thread — the whole point of the daemon is
that this re-entry is *warm*: jax is imported, the persistent compile
cache is enabled, and every jit executable compiled by an earlier job is
still in memory, so repeated jobs skip straight to data movement.

Per-job isolation rides on the context-scoped execution state introduced
with this subsystem: the CLI gives every top-level invocation its own
telemetry scope (metrics, DeviceStats, tracer), the atomic-output flag and
BGZF level are contextvars, and provenance (@PG CL) is overridden with the
submitting client's command line — so a job's output is byte-identical to
the same command run standalone, and two concurrent jobs cannot see each
other's counters.

Lifecycle: ``drain`` (op) closes admission but keeps answering status;
``shutdown`` (op) or SIGTERM/SIGINT additionally exits once queued and
running jobs finish. The socket file is unlinked on exit; a stale socket
from a crashed daemon is detected (connect fails) and replaced on start.
"""

import errno
import json
import logging
import os
import socket
import threading
import time

from . import journal as journal_mod
from . import protocol
from .jobs import TERMINAL, Job, JobRegistry
from .scheduler import Scheduler

log = logging.getLogger("fgumi_tpu")


def _drain_device_feeder(timeout: float = 30.0):
    """Run the device upload pipeline dry before the process exits.

    Looked up via sys.modules so a daemon that never dispatched to the
    device doesn't pay the kernel (and jax) import at shutdown."""
    import sys

    kern = sys.modules.get("fgumi_tpu.ops.kernel")
    if kern is None:
        return
    if not kern.DEVICE_FEEDER.drain(timeout=timeout):
        log.warning("device feeder did not drain within %.0fs", timeout)


class SocketBusy(RuntimeError):
    """Another live daemon already serves this socket path."""


def _governor_pressure():
    """The resource governor's admission verdict (None = admit).

    Shedding is the serve analog of the pipeline's budget shrink: under a
    soft watermark new jobs would only deepen the pressure, so they are
    rejected with an explicit ``resource_pressure`` reason and a
    ``retry_after_s`` hint while already-admitted jobs run to completion."""
    from ..utils.governor import GOVERNOR

    return GOVERNOR.admission_pressure()


class JobService:
    def __init__(self, socket_path: str, workers: int = 2,
                 queue_limit: int = 8, report_dir: str = None,
                 max_frame_bytes: int = protocol.MAX_FRAME_BYTES,
                 keep_finished: int = 1000, journal_path: str = None,
                 health_period_s: float = 0.0, max_per_client: int = 0,
                 metrics_port: int = None):
        self.socket_path = socket_path
        self.max_frame_bytes = max_frame_bytes
        self.report_dir = report_dir
        self.registry = JobRegistry(keep_finished=keep_finished,
                                    on_transition=self._on_transition)
        self.scheduler = Scheduler(self._execute, self.registry,
                                   workers=workers, queue_limit=queue_limit,
                                   max_per_client=max_per_client)
        self.started_unix = time.time()
        self.journal_path = journal_path
        self.journal = None
        self.health_period_s = float(health_period_s or 0.0)
        self._monitor = None
        #: optional loopback HTTP listener (serve --metrics-port): /metrics
        #: Prometheus scrape + /healthz, fed by the same snapshot builder
        #: as the `stats` op (serve/introspect.py). None = disabled.
        self.metrics_port = metrics_port
        self._introspection = None
        #: journal replay accounting for the `stats` op (recover() fills it)
        self.journal_stats = {}
        self._dedupe = {}          # dedupe key -> job id (journal-durable)
        self._dedupe_lock = threading.Lock()
        self._recovered = False
        self._sock = None
        self._accept_thread = None
        self._shutdown = threading.Event()
        self._closed = False

    def _on_transition(self, job):
        if self.journal is not None:
            self.journal.record_state(job)

    # -- warm-up ------------------------------------------------------------

    def warm_up(self, compile_cache_dir: str = None, touch_device: bool = True):
        """Pay the cold-start costs once, before the first job.

        Enables the persistent XLA compile cache (optionally at an explicit
        directory), imports jax, and touches the backend so device
        discovery/claiming happens now — not inside job 1's latency."""
        from ..utils.compile_cache import enable_persistent_cache

        cache = enable_persistent_cache(compile_cache_dir)
        if cache:
            log.info("serve: persistent compile cache at %s", cache)
        if not touch_device:
            return
        try:
            t0 = time.monotonic()
            from ..ops.kernel import _ensure_jax

            jax = _ensure_jax()
            devs = jax.devices()
            log.info("serve: warm backend %s (%d device(s)) in %.2fs",
                     devs[0].platform if devs else "none", len(devs),
                     time.monotonic() - t0)
        except Exception as e:  # noqa: BLE001 - serving still works cold
            log.warning("serve: device warm-up failed (%s); jobs will pay "
                        "cold start", e)

    # -- job execution ------------------------------------------------------

    def _job_argv(self, job):
        """The argv actually passed to cli.main: the job's command plus the
        daemon-injected per-job artifact flags (which must precede the
        subcommand; the job's own later flags win on conflict)."""
        pre = []
        if self.report_dir:
            job.report_path = os.path.join(self.report_dir,
                                           f"{job.id}.report.json")
            pre += ["--run-report", job.report_path]
            if job.trace:
                job.trace_path = os.path.join(self.report_dir,
                                              f"{job.id}.trace.json")
                pre += ["--trace", job.trace_path]
        return pre + job.argv

    def _execute(self, job) -> int:
        """Run one job in-process; never raises (outcome on the record)."""
        from ..cli import main as cli_main
        from ..observe.scope import command_argv
        from ..utils import faults

        log.info("serve: job %s starting: %s", job.id, " ".join(job.argv))
        t0 = time.monotonic()
        try:
            # chaos point: serve.dispatch:raise proves a failed job reports
            # `failed` with a diagnostic while the daemon keeps serving
            faults.fire("serve.dispatch")
            # provenance override: outputs record the CLIENT's command line,
            # making daemon runs byte-identical to standalone ones
            with command_argv([job.argv0] + job.argv):
                rc = cli_main(self._job_argv(job))
        except BaseException as e:  # noqa: BLE001 - job outcome, not crash
            self.registry.mark_failed(job, f"{type(e).__name__}: {e}")
            log.warning("serve: job %s failed after %.2fs: %s", job.id,
                        time.monotonic() - t0, job.error)
            return 1
        self.registry.mark_done(job, rc)
        log.info("serve: job %s %s (rc=%d) in %.2fs", job.id, job.state,
                 rc, time.monotonic() - t0)
        return rc

    # -- crash recovery -----------------------------------------------------

    def recover(self):
        """Replay the journal (if configured) and requeue incomplete jobs.

        Idempotent; runs once, before the worker pool starts so a
        requeued job cannot race a fresh submission for its original
        position. Terminal jobs are restored read-only (clients polling
        an id from before the crash get its final record), dedupe keys
        are rebuilt, and non-terminal jobs — queued or running when the
        previous daemon died — are requeued in original submission order.
        That re-run is byte-identical to a single run: atomic output
        commit (PR 1) guarantees the killed attempt published nothing.
        Also sweeps report-dir temp leftovers owned by dead pids and
        older than the journal's last entry."""
        if self._recovered:
            return
        self._recovered = True
        if not self.journal_path:
            return
        from ..observe.metrics import METRICS

        rep = journal_mod.replay(self.journal_path)
        self.journal = journal_mod.JobJournal(self.journal_path)
        self._sweep_report_temps(rep.last_entry_unix)
        requeued = 0
        for rec in rep.jobs:
            job = Job(rec["id"], rec["argv"], rec["priority"],
                      argv0=rec["argv0"], tag=rec["tag"],
                      trace=rec["trace"], client=rec.get("client"))
            if rec.get("submitted_unix"):
                job.submitted_unix = rec["submitted_unix"]
            terminal = rec["state"] in TERMINAL
            if terminal:
                job.state = rec["state"]
                job.exit_status = rec["exit_status"]
                job.error = rec["error"]
                job.finished_unix = rec.get("finished_unix")
            try:
                self.registry.restore(job)
            except ValueError:
                continue  # duplicate record; first wins
            if rec.get("dedupe") and rec["state"] != "cancelled":
                # cancelled jobs never rebind their key: an
                # admission-rejected submit releases its key on the live
                # daemon (see the submit handler), and the journal records
                # it only as submit+cancelled — rebinding here would answer
                # a post-restart retry with the rejected record instead of
                # executing it. (A user-cancelled job re-running on
                # resubmit is the safe direction of the same rule.)
                self._dedupe[rec["dedupe"]] = job.id
            if not terminal:
                self.journal.record_requeued(job.id)
                admitted, reason = self.scheduler.submit(job)
                if admitted:
                    requeued += 1
                else:  # shrunken capacity on restart: record the loss
                    self.registry.mark_cancelled(job)
                    if rec.get("dedupe") \
                            and self._dedupe.get(rec["dedupe"]) == job.id:
                        # same contract as a live admission reject: the
                        # key is released so a retry executes instead of
                        # being answered with the cancelled record
                        del self._dedupe[rec["dedupe"]]
                    log.warning("serve: could not requeue %s: %s",
                                job.id, reason)
        if rep.records or requeued:
            log.info("serve: journal replayed %d record(s); %d job(s) "
                     "requeued", rep.records, requeued)
        METRICS.inc("serve.journal.replayed", rep.records)
        METRICS.inc("serve.journal.requeued", requeued)
        if rep.truncated_bytes:
            METRICS.inc("serve.journal.truncated_bytes", rep.truncated_bytes)
        self.journal_stats = {"replayed": rep.records, "requeued": requeued,
                              "truncated_bytes": rep.truncated_bytes}

    def _sweep_report_temps(self, before_unix):
        """Remove dead-pid atomic-output temps from the report dir.

        A SIGKILL'd predecessor can leave ``.<name>.tmp.<pid>.<seq>``
        leftovers next to per-job reports; anything owned by a dead pid
        and not newer than the journal's last entry (i.e. provably from
        before the crash) is swept. Live pids — including this process —
        are never touched."""
        if not self.report_dir or not os.path.isdir(self.report_dir):
            return
        from ..utils.atomic import _pid_alive

        swept = 0
        for name in os.listdir(self.report_dir):
            if not name.startswith(".") or ".tmp." not in name:
                continue
            pid_s = name.split(".tmp.", 1)[1].split(".", 1)[0]
            if not pid_s.isdigit():
                continue
            pid = int(pid_s)
            if pid == os.getpid() or _pid_alive(pid):
                continue
            path = os.path.join(self.report_dir, name)
            try:
                if before_unix is not None \
                        and os.stat(path).st_mtime > before_unix:
                    continue  # newer than the crash horizon; leave it
                os.unlink(path)
                swept += 1
            except OSError:
                pass
        if swept:
            log.info("serve: swept %d stale report temp(s)", swept)

    # -- socket server ------------------------------------------------------

    def _claim_socket(self):
        """Bind the listener, replacing a *dead* daemon's socket file only.

        Stale means the connect is actively refused (no listener behind the
        file). A timeout or transient error (daemon stopped in a debugger,
        backlog full under a client burst) is treated as BUSY — unlinking a
        live daemon's socket would split-brain the service and that
        daemon's exit would then delete *our* socket file."""
        if os.path.exists(self.socket_path):
            probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                probe.settimeout(1.0)
                probe.connect(self.socket_path)
            except (ConnectionRefusedError, FileNotFoundError):
                log.info("serve: replacing stale socket %s", self.socket_path)
                try:
                    os.unlink(self.socket_path)
                except FileNotFoundError:
                    pass
            except OSError as e:
                raise SocketBusy(
                    f"daemon at {self.socket_path} did not answer ({e}); "
                    "not replacing a possibly-live socket")
            else:
                raise SocketBusy(
                    f"another daemon is already serving {self.socket_path}")
            finally:
                probe.close()
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.bind(self.socket_path)
        sock.listen(16)
        return sock

    def bind(self):
        """Claim the socket AND the metrics port WITHOUT starting to
        serve. Raises SocketBusy / OSError.

        Split from :meth:`start` so the CLI can fail fast on a busy
        socket or metrics port *before* paying (and disturbing) the
        single-tenant device warm-up."""
        if self._sock is None:
            self._sock = self._claim_socket()
        if self.metrics_port is not None and self._introspection is None:
            from .introspect import IntrospectionServer

            self._introspection = IntrospectionServer(self,
                                                      self.metrics_port)
            self._introspection.bind()  # EADDRINUSE surfaces here

    def start(self):
        """Bind (if not already), recover, start workers and the accept
        loop. Recovery runs before the pool so requeued jobs hold their
        original queue positions ahead of any fresh submission."""
        self.bind()
        self.recover()
        self.scheduler.start()
        if self.health_period_s > 0:
            from ..ops.breaker import BREAKER, HealthMonitor

            self._monitor = HealthMonitor(BREAKER,
                                          period_s=self.health_period_s)
            self._monitor.start()
        if self._introspection is not None:
            self._introspection.start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fgumi-serve-accept", daemon=True)
        self._accept_thread.start()
        log.info("serve: listening on %s (%d workers, queue limit %d%s)",
                 self.socket_path, self.scheduler.workers,
                 self.scheduler.queue_limit,
                 f", journal {self.journal_path}" if self.journal_path
                 else "")

    def _accept_loop(self):
        # keep accepting through a drain: clients must be able to poll
        # status while queued/running jobs finish (the documented drain
        # contract); the loop ends when close() closes the listener
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed during shutdown
            t = threading.Thread(target=self._serve_connection, args=(conn,),
                                 name="fgumi-serve-conn", daemon=True)
            t.start()

    def _serve_connection(self, conn: socket.socket):
        stream = conn.makefile("rb")
        try:
            while True:
                try:
                    req = protocol.read_frame(stream, self.max_frame_bytes)
                except protocol.ProtocolError as e:
                    self._send(conn, protocol.error_response(str(e)))
                    return  # framing is gone; close rather than resync
                if req is None:
                    return
                resp = self.handle_request(req)
                self._send(conn, resp)
                # arm shutdown only AFTER the reply is on the wire: the
                # main thread exits the process once the pool quiesces,
                # which on an idle daemon can beat this thread's sendall
                # and reset the client mid-response
                if req.get("op") == "shutdown" and resp.get("ok"):
                    self._shutdown.set()
        except OSError:
            pass  # peer went away mid-frame; nothing to answer
        finally:
            try:
                stream.close()
            except OSError:
                pass
            conn.close()

    @staticmethod
    def _send(conn, resp: dict):
        try:
            conn.sendall(protocol.encode_frame(resp))
        except OSError:
            pass

    # -- request dispatch (transport-independent; tests call it directly) ---

    def handle_request(self, req: dict) -> dict:
        err = protocol.validate_request(req)
        if err is not None:
            return protocol.error_response(err)
        op = req["op"]
        if op == "ping":
            extra = {}
            if self.scheduler.max_per_client:
                # quota surface only when the knob is armed, so the default
                # ping (and its golden fixture) is unchanged
                extra["max_per_client"] = self.scheduler.max_per_client
                extra["quota"] = self.scheduler.client_quota_state()
            return protocol.ok_response(
                tool="fgumi-tpu", pid=os.getpid(),
                uptime_s=round(time.time() - self.started_unix, 1),
                jobs=self.registry.counts(), **self.scheduler.depth(),
                **extra)
        if op == "stats":
            # live introspection: scheduler/quota/journal/breaker/governor/
            # device snapshots + latency histogram summaries — the same
            # builder feeds /metrics, so the two surfaces cannot disagree
            from .introspect import service_stats

            return protocol.ok_response(stats=service_stats(self))
        if op == "submit":
            dedupe = req.get("dedupe")
            with self._dedupe_lock:
                if dedupe:
                    existing = self._dedupe.get(dedupe)
                    if existing is not None:
                        prior = self.registry.get(existing)
                        if prior is not None:
                            # idempotent resubmit: same key -> the SAME
                            # job (running, queued, or finished), never a
                            # second execution — the contract that makes
                            # client retry-after-reconnect safe
                            return protocol.ok_response(
                                job=prior.to_wire(), deduped=True)
                        # job evicted from history: key is stale, reissue
                # resource shed: under a memory/disk pressure watermark the
                # daemon stops taking on NEW work (running jobs finish) —
                # an explicit reason plus a Retry-After-style hint, checked
                # after dedupe so idempotent resubmits of existing jobs
                # still answer (they cost nothing)
                shed = _governor_pressure()
                if shed is not None:
                    # the governor counts the shed; fold_metrics publishes
                    # it as serve.shed.resource at serve-command exit
                    return protocol.error_response(
                        f"resource_pressure: {shed['reason']}",
                        retry_after_s=shed["retry_after_s"])
                job = self.registry.create(
                    req["argv"],
                    req.get("priority", protocol.DEFAULT_PRIORITY),
                    argv0=req.get("argv0"), tag=req.get("tag"),
                    trace=bool(req.get("trace")),
                    client=req.get("client"))
                if dedupe:
                    self._dedupe[dedupe] = job.id
            # journal BEFORE admission: a crash between the two requeues a
            # job the client believes submitted — the safe direction (the
            # reverse silently loses it); a rejection is journaled as the
            # cancelled transition right below
            if self.journal is not None:
                self.journal.record_submit(job, dedupe)
            admitted, reason = self.scheduler.submit(job)
            if not admitted:
                # the response still carries the (cancelled) record so the
                # client sees what was refused, but the registry forgets it:
                # a rejection storm must not evict finished-job history —
                # and the dedupe key is released so a later retry of the
                # same request is not answered with the rejected record
                self.registry.mark_cancelled(job)
                self.registry.discard(job.id)
                if dedupe:
                    with self._dedupe_lock:
                        if self._dedupe.get(dedupe) == job.id:
                            del self._dedupe[dedupe]
                return protocol.error_response(reason, job=job.to_wire())
            return protocol.ok_response(job=job.to_wire())
        if op == "status":
            job_id = req.get("id")
            if job_id is None:
                return protocol.ok_response(
                    jobs=[j.to_wire() for j in self.registry.list()],
                    **self.scheduler.depth())
            job = self.registry.get(job_id)
            if job is None:
                return protocol.error_response(f"unknown job {job_id}")
            return protocol.ok_response(job=job.to_wire())
        if op == "cancel":
            ok, reason = self.scheduler.cancel(req["id"])
            if not ok:
                return protocol.error_response(reason)
            job = self.registry.get(req["id"])
            return protocol.ok_response(job=job.to_wire())
        if op == "drain":
            self.scheduler.drain()
            return protocol.ok_response(**self.scheduler.depth())
        if op == "shutdown":
            # drain here; the socket layer arms the exit event after the
            # response is sent (direct handle_request callers — tests, an
            # embedding app — follow with request_shutdown themselves)
            self.scheduler.drain()
            return protocol.ok_response(**self.scheduler.depth())
        raise AssertionError(f"unhandled op {op}")  # validate() covers this

    # -- lifecycle ----------------------------------------------------------

    def request_shutdown(self):
        """Graceful exit: flag shutdown. Genuinely signal-handler safe —
        sets one event, no locks, no logging; the waiting main loop does
        the drain (and its logging) outside signal context."""
        self._shutdown.set()

    def wait_until_shutdown(self, poll_s: float = 0.2):
        """Block until a shutdown is requested AND the pool is quiescent.
        Closes admission (idempotent drain) once the flag is seen."""
        while not self._shutdown.wait(poll_s):
            pass
        self.scheduler.drain()
        self.scheduler.join()
        _drain_device_feeder()

    def close(self):
        """Tear the listener down and remove the socket file (idempotent)."""
        if self._closed:
            return
        self._closed = True
        self._shutdown.set()
        if self._monitor is not None:
            self._monitor.stop()
        if self._introspection is not None:
            self._introspection.stop()
        if self.journal is not None:
            self.journal.close()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        try:
            os.unlink(self.socket_path)
        except OSError as e:
            if e.errno != errno.ENOENT:
                log.debug("serve: could not remove socket %s: %s",
                          self.socket_path, e)
        log.info("serve: stopped (%s)",
                 json.dumps(self.registry.counts(), sort_keys=True))
