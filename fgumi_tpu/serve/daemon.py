"""The job-service daemon: socket server + warm-process job execution.

One :class:`JobService` owns the listeners (the Unix socket, plus an
optional TCP listener for fleet operation), the scheduler, and the
registry. Each admitted job is executed by re-entering the ordinary CLI
(``cli.main``) on a worker thread — the whole point of the daemon is that
this re-entry is *warm*: jax is imported, the persistent compile cache is
enabled, and every jit executable compiled by an earlier job is still in
memory, so repeated jobs skip straight to data movement.

Per-job isolation rides on the context-scoped execution state introduced
with this subsystem: the CLI gives every top-level invocation its own
telemetry scope (metrics, DeviceStats, tracer), the atomic-output flag and
BGZF level are contextvars, and provenance (@PG CL) is overridden with the
submitting client's command line — so a job's output is byte-identical to
the same command run standalone, and two concurrent jobs cannot see each
other's counters.

Transport rides on :mod:`.transport`: the frame-serving loop, per-
connection deadlines and the connection cap on TCP, and the shared-secret
handshake required on non-loopback binds are all enforced there; this
module only answers validated frames.

Fleet operation (``serve --journal-dir``): daemons sharing a journal
directory each hold an fcntl lease on their own journal
(:class:`~.journal.FleetLease`). A background scanner claims a dead peer's
lease exactly once, requeues its incomplete jobs under their ORIGINAL ids
(job ids are fleet-prefixed so they never collide), and renames the
consumed journal — so a SIGKILL'd daemon's in-flight work completes on a
survivor byte-identically with zero double-execution; dedupe keys
arbitrate the race against a balancer re-routing the same submit.

Lifecycle: ``drain`` (op) closes admission but keeps answering status;
``shutdown`` (op) or SIGTERM/SIGINT additionally exits once queued and
running jobs finish. The socket file is unlinked on exit; a stale socket
from a crashed daemon is detected (connect fails) and replaced on start.
"""

import json
import logging
import os
import threading
import time

from . import journal as journal_mod
from . import protocol, transport
from .jobs import TERMINAL, Job, JobRegistry
from .scheduler import Scheduler
from .transport import SocketBusy  # noqa: F401  (historical import path)

log = logging.getLogger("fgumi_tpu")


def _drain_device_feeder(timeout: float = 30.0):
    """Run the device upload pipeline dry before the process exits.

    Looked up via sys.modules so a daemon that never dispatched to the
    device doesn't pay the kernel (and jax) import at shutdown. The
    dispatch coalescer flushes first: a held merge window would otherwise
    park one upload the feeder drain then waits out."""
    import sys

    coal = sys.modules.get("fgumi_tpu.ops.coalesce")
    if coal is not None and not coal.COALESCER.drain(timeout=timeout / 2):
        log.warning("dispatch coalescer did not flush within %.0fs",
                    timeout / 2)
    kern = sys.modules.get("fgumi_tpu.ops.kernel")
    if kern is None:
        return
    if not kern.DEVICE_FEEDER.drain(timeout=timeout):
        log.warning("device feeder did not drain within %.0fs", timeout)


def _clean_traceparent(value):
    """The traceparent to keep on a job record: the well-formed original,
    or None. Malformed context is IGNORED, never a rejection — telemetry
    garnish must not be able to fail a submission (protocol docstring)."""
    from ..observe.trace import parse_traceparent

    return value if parse_traceparent(value) is not None else None


def _clean_hops(req: dict):
    """Upstream hop timestamps from a submit frame, type-checked.

    Non-numeric (or absent) values are dropped per the same
    malformed-ignored contract as the traceparent. Returns None when no
    usable timestamp survives, so untraced submits keep a None field."""
    hops = {}
    for wire, key in (("sent_unix", "client_sent_unix"),
                      ("bal_recv_unix", "balancer_recv_unix"),
                      ("bal_sent_unix", "balancer_sent_unix")):
        v = req.get(wire)
        if isinstance(v, (int, float)) and not isinstance(v, bool) \
                and v > 0:
            hops[key] = float(v)
    return hops or None


def _governor_pressure():
    """The resource governor's admission verdict (None = admit).

    Shedding is the serve analog of the pipeline's budget shrink: under a
    soft watermark new jobs would only deepen the pressure, so they are
    rejected with an explicit ``resource_pressure`` reason and a
    ``retry_after_s`` hint while already-admitted jobs run to completion."""
    from ..utils.governor import GOVERNOR

    return GOVERNOR.admission_pressure()


class JobService:
    def __init__(self, socket_path: str, workers: int = 2,
                 queue_limit: int = 8, report_dir: str = None,
                 max_frame_bytes: int = protocol.MAX_FRAME_BYTES,
                 keep_finished: int = 1000, journal_path: str = None,
                 health_period_s: float = 0.0, max_per_client: int = 0,
                 metrics_port: int = None, tcp=None, auth_token: str = None,
                 conn_cap: int = transport.DEFAULT_CONN_CAP,
                 io_timeout_s: float = transport.DEFAULT_IO_TIMEOUT_S,
                 journal_dir: str = None, fleet_id: str = None,
                 lease_scan_period_s: float = 2.0,
                 lease_wait_s: float = 30.0):
        if journal_dir and journal_path:
            raise ValueError("--journal and --journal-dir are exclusive")
        if journal_dir:
            if not fleet_id:
                raise ValueError("--journal-dir requires a fleet id")
            journal_mod.validate_fleet_id(fleet_id)
        self.socket_path = socket_path
        self.max_frame_bytes = max_frame_bytes
        self.report_dir = report_dir
        self.registry = JobRegistry(keep_finished=keep_finished,
                                    on_transition=self._on_transition,
                                    id_prefix=fleet_id if journal_dir
                                    else "")
        self.scheduler = Scheduler(self._execute, self.registry,
                                   workers=workers, queue_limit=queue_limit,
                                   max_per_client=max_per_client)
        self.started_unix = time.time()
        self.journal_path = journal_path
        self.journal = None
        self.journal_dir = journal_dir
        self.fleet_id = fleet_id if journal_dir else None
        self.lease_scan_period_s = float(lease_scan_period_s)
        #: how long startup waits out a peer momentarily holding OUR
        #: lease (it is consuming our predecessor's journal — one fsync'd
        #: append per adopted job)
        self.lease_wait_s = float(lease_wait_s)
        self._lease = None
        self._scanner = None
        #: fleet accounting for the `stats` op (None-able section)
        self.fleet_stats = None
        self.health_period_s = float(health_period_s or 0.0)
        self._monitor = None
        #: optional loopback HTTP listener (serve --metrics-port): /metrics
        #: Prometheus scrape + /healthz, fed by the same snapshot builder
        #: as the `stats` op (serve/introspect.py). None = disabled.
        self.metrics_port = metrics_port
        self._introspection = None
        #: journal replay accounting for the `stats` op (recover() fills it)
        self.journal_stats = {}
        self._dedupe = {}          # dedupe key -> job id (journal-durable)
        self._dedupe_lock = threading.Lock()
        self._recovered = False
        #: optional TCP listen address (host, port) beside the Unix socket
        self.tcp = tuple(tcp) if tcp else None
        self.auth_token = auth_token
        self.conn_cap = conn_cap
        self.io_timeout_s = io_timeout_s
        self._unix = transport.UnixListener(socket_path) if socket_path \
            else None
        self._tcp_listener = None
        self._frames = None
        self._shutdown = threading.Event()
        self._closed = False
        #: warm-start persistence accounting for the `stats` op (ISSUE
        #: 20): path of the routing-EWMA snapshot, whether one was
        #: reloaded at start, and the save timestamps. None until
        #: start() resolves the path (journal- or socket-adjacent).
        self.routing_state = None

    def _on_transition(self, job):
        if self.journal is not None:
            self.journal.record_state(job)

    # -- warm-up ------------------------------------------------------------

    def warm_up(self, compile_cache_dir: str = None, touch_device: bool = True):
        """Pay the cold-start costs once, before the first job.

        Enables the persistent XLA compile cache (optionally at an explicit
        directory), imports jax, and touches the backend so device
        discovery/claiming happens now — not inside job 1's latency."""
        from ..utils.compile_cache import enable_persistent_cache

        cache = enable_persistent_cache(compile_cache_dir)
        if cache:
            log.info("serve: persistent compile cache at %s", cache)
        if not touch_device:
            return
        try:
            t0 = time.monotonic()
            from ..ops.kernel import _ensure_jax

            jax = _ensure_jax()
            devs = jax.devices()
            log.info("serve: warm backend %s (%d device(s)) in %.2fs",
                     devs[0].platform if devs else "none", len(devs),
                     time.monotonic() - t0)
        except Exception as e:  # noqa: BLE001 - serving still works cold
            log.warning("serve: device warm-up failed (%s); jobs will pay "
                        "cold start", e)

    # -- job execution ------------------------------------------------------

    def _job_argv(self, job):
        """The argv actually passed to cli.main: the job's command plus the
        daemon-injected per-job artifact flags (which must precede the
        subcommand; the job's own later flags win on conflict)."""
        pre = []
        if self.report_dir:
            job.report_path = os.path.join(self.report_dir,
                                           f"{job.id}.report.json")
            pre += ["--run-report", job.report_path]
            if job.trace:
                job.trace_path = os.path.join(self.report_dir,
                                              f"{job.id}.trace.json")
                pre += ["--trace", job.trace_path]
        return pre + job.argv

    def _execute(self, job) -> int:
        """Run one job in-process; never raises (outcome on the record)."""
        from ..cli import main as cli_main
        from ..observe.scope import command_argv, job_context
        from ..observe.trace import parse_traceparent
        from ..utils import faults

        log.info("serve: job %s starting: %s", job.id, " ".join(job.argv))
        t0 = time.monotonic()
        parsed = parse_traceparent(job.traceparent)
        hops = dict(job.hops or {})
        # the daemon-side lifecycle timestamps complete the hop chain the
        # client/balancer started: the job's run report can then attribute
        # queue wait without a round trip back to the registry
        hops["admitted_unix"] = job.submitted_unix
        hops["started_unix"] = job.started_unix
        try:
            # chaos point: serve.dispatch:raise proves a failed job reports
            # `failed` with a diagnostic while the daemon keeps serving
            faults.fire("serve.dispatch")
            # provenance override: outputs record the CLIENT's command line,
            # making daemon runs byte-identical to standalone ones; the job
            # context hands the propagated trace ids + hop timestamps into
            # the telemetry scope cli.main builds for this job
            with job_context(
                    job_id=job.id,
                    trace_id=parsed[0] if parsed else None,
                    parent_span_id=parsed[1] if parsed else None,
                    hops=hops), \
                    command_argv([job.argv0] + job.argv):
                rc = cli_main(self._job_argv(job))
        except BaseException as e:  # noqa: BLE001 - job outcome, not crash
            self.registry.mark_failed(job, f"{type(e).__name__}: {e}")
            log.warning("serve: job %s failed after %.2fs: %s", job.id,
                        time.monotonic() - t0, job.error)
            return 1
        self.registry.mark_done(job, rc)
        log.info("serve: job %s %s (rc=%d) in %.2fs", job.id, job.state,
                 rc, time.monotonic() - t0)
        return rc

    # -- crash recovery -----------------------------------------------------

    def acquire_lease(self):
        """Fleet mode: take the fcntl lease on this daemon's identity.

        Idempotent; raises :class:`~.journal.LeaseHeld` when another live
        daemon owns this fleet id — the CLI surfaces that as the same
        fail-fast exit 2 a busy socket gets, BEFORE the device warm-up."""
        if not self.journal_dir or self._lease is not None:
            return
        jpath, lpath = journal_mod.fleet_paths(self.journal_dir,
                                               self.fleet_id)
        lease = journal_mod.FleetLease(lpath)
        lease.acquire(wait_s=self.lease_wait_s)
        self._lease = lease
        self.journal_path = jpath
        self.fleet_stats = {
            "fleet_id": self.fleet_id,
            "journal_dir": self.journal_dir,
            "lease": "held",
            "lease_scan_period_s": self.lease_scan_period_s,
            "takeovers": 0, "takeover_jobs": 0,
            "takeover_skipped_dedupe": 0, "last_takeover": None,
        }

    def recover(self):
        """Replay the journal (if configured) and requeue incomplete jobs.

        Idempotent; runs once, before the worker pool starts so a
        requeued job cannot race a fresh submission for its original
        position. Terminal jobs are restored read-only (clients polling
        an id from before the crash get its final record), dedupe keys
        are rebuilt, and non-terminal jobs — queued or running when the
        previous daemon died — are requeued in original submission order.
        That re-run is byte-identical to a single run: atomic output
        commit (PR 1) guarantees the killed attempt published nothing.
        Also sweeps report-dir temp leftovers owned by dead pids and
        older than the journal's last entry.

        Fleet mode (``--journal-dir``): the daemon first takes the fcntl
        lease on its own identity (:class:`~.journal.FleetLease`; raises
        :class:`~.journal.LeaseHeld` if another live daemon owns this
        fleet id), then recovers its own journal exactly as above."""
        if self._recovered:
            return
        self._recovered = True
        self.acquire_lease()
        if not self.journal_path:
            return
        from ..observe.metrics import METRICS

        rep = journal_mod.replay(self.journal_path)
        self.registry.reserve_ids(rep.max_job_num)
        if self.journal_dir:
            # a predecessor's journal a peer CONSUMED (takeover renamed it
            # .claimed) replays nothing here — but the ids it minted now
            # live on the survivor; reserve past them or this daemon would
            # re-mint ids that already exist fleet-wide
            claimed = self.journal_path + ".claimed"
            if os.path.exists(claimed):
                self.registry.reserve_ids(
                    journal_mod.replay(claimed).max_job_num)
        self.journal = journal_mod.JobJournal(self.journal_path)
        self._sweep_report_temps(rep.last_entry_unix)
        requeued = 0
        for rec in rep.jobs:
            requeued += self._restore_record(rec, requeue_via_journal=False)
        if rep.records or requeued:
            log.info("serve: journal replayed %d record(s); %d job(s) "
                     "requeued", rep.records, requeued)
        METRICS.inc("serve.journal.replayed", rep.records)
        METRICS.inc("serve.journal.requeued", requeued)
        if rep.truncated_bytes:
            METRICS.inc("serve.journal.truncated_bytes", rep.truncated_bytes)
        self.journal_stats = {"replayed": rep.records, "requeued": requeued,
                              "truncated_bytes": rep.truncated_bytes}

    def _restore_record(self, rec: dict, requeue_via_journal: bool) -> int:
        """Restore one replayed journal record into the live registry.

        Shared by startup recovery (our own journal; the requeue is
        implied by the journal we replay from) and fleet takeover (a
        PEER's journal; ``requeue_via_journal=True`` writes the adopted
        job into OUR journal so a later crash of this daemon re-recovers
        it). Returns 1 when a job was requeued for execution."""
        job = Job(rec["id"], rec["argv"], rec["priority"],
                  argv0=rec["argv0"], tag=rec["tag"],
                  trace=rec["trace"], client=rec.get("client"),
                  traceparent=_clean_traceparent(rec.get("traceparent")),
                  hops=rec.get("hops") if isinstance(rec.get("hops"), dict)
                  else None,
                  shard=rec.get("shard")
                  if isinstance(rec.get("shard"), dict) else None)
        if rec.get("submitted_unix"):
            job.submitted_unix = rec["submitted_unix"]
        terminal = rec["state"] in TERMINAL
        if terminal:
            job.state = rec["state"]
            job.exit_status = rec["exit_status"]
            job.error = rec["error"]
            job.finished_unix = rec.get("finished_unix")
        dedupe = rec.get("dedupe")
        if dedupe and rec["state"] != "cancelled":
            # cancelled jobs never rebind their key: an admission-rejected
            # submit releases its key on the live daemon (see the submit
            # handler), and the journal records it only as
            # submit+cancelled — rebinding here would answer a
            # post-restart retry with the rejected record instead of
            # executing it. (A user-cancelled job re-running on resubmit
            # is the safe direction of the same rule.)
            with self._dedupe_lock:
                if requeue_via_journal:
                    # PEER takeover: one atomic setdefault under the SAME
                    # lock the live submit handler holds across its
                    # check-and-bind — a balancer-re-routed submit racing
                    # this takeover either sees our claim (and is
                    # answered with the journal copy) or wins the key
                    # first; never both executing.
                    winner = self._dedupe.setdefault(dedupe, job.id)
                else:
                    # OUR OWN journal replay (startup, before the
                    # listeners serve): later records rebind last-wins —
                    # the live handler legitimately reissues a stale key
                    # whose first job was evicted from history, and both
                    # submits are in the journal. Nothing concurrent can
                    # race this; supersede-cancel here would silently
                    # drop a job the client believed admitted.
                    self._dedupe[dedupe] = job.id
                    winner = job.id
            if winner != job.id and not terminal:
                # the race the dedupe key exists to arbitrate: a balancer
                # already re-routed this submit here (or another takeover
                # adopted it). The journal copy must NOT run again — it is
                # recorded as superseded, and clients polling the original
                # id are pointed at the winning record.
                job.state = "cancelled"
                job.error = f"superseded by dedupe key (job {winner})"
                job.finished_unix = time.time()
                terminal = True
                if self.fleet_stats is not None:
                    self.fleet_stats["takeover_skipped_dedupe"] += 1
        try:
            self.registry.restore(job)
        except ValueError:
            return 0  # duplicate record; first wins
        if terminal:
            return 0
        if requeue_via_journal and self.journal is not None:
            self.journal.record_submit(job, dedupe)
        if self.journal is not None:
            self.journal.record_requeued(job.id)
        admitted, reason = self.scheduler.submit(job)
        if admitted:
            return 1
        # shrunken capacity on restart: record the loss
        self.registry.mark_cancelled(job)
        with self._dedupe_lock:
            if dedupe and self._dedupe.get(dedupe) == job.id:
                # same contract as a live admission reject: the
                # key is released so a retry executes instead of
                # being answered with the cancelled record
                del self._dedupe[dedupe]
        log.warning("serve: could not requeue %s: %s", job.id, reason)
        return 0

    # -- fleet takeover -----------------------------------------------------

    def scan_for_takeovers(self) -> int:
        """One pass over the journal dir: claim every dead peer's journal.

        Returns the number of takeovers performed. Runs on the scanner
        thread and (tests) synchronously; registry/scheduler/journal are
        all thread-safe. A drained daemon adopts nothing — it is leaving."""
        if not self.journal_dir or self.scheduler.draining:
            return 0
        from ..observe.metrics import METRICS

        METRICS.inc("fleet.lease_scans")
        claimed = 0
        for peer_id, jpath, lpath in journal_mod.scan_peer_journals(
                self.journal_dir, self.fleet_id):
            fd = journal_mod.FleetLease.try_claim(lpath)
            if fd is None:
                continue  # the peer lives; its flock is its heartbeat
            try:
                if not os.path.exists(jpath):
                    continue  # lost the race to another claimant
                self._takeover(peer_id, jpath)
                claimed += 1
            except Exception:  # noqa: BLE001 - one bad journal != daemon
                log.exception("fleet: takeover of %s failed", peer_id)
            finally:
                os.close(fd)
        return claimed

    def _takeover(self, peer_id: str, jpath: str):
        """Adopt one dead peer's journal (caller holds its lease lock).

        Incomplete jobs are requeued here under their ORIGINAL ids and
        journaled into OUR journal (so this daemon crashing later loses
        nothing); terminal jobs are restored read-only so clients polling
        across the takeover still resolve them. The consumed journal is
        renamed to ``.claimed`` under the lock — a second claimant or the
        restarting peer finds nothing to replay: exactly-once by
        construction."""
        from ..observe.flight import FLIGHT
        from ..observe.metrics import METRICS

        rep = journal_mod.replay(jpath)
        requeued = 0
        for rec in rep.jobs:
            requeued += self._restore_record(rec, requeue_via_journal=True)
        claimed_path = journal_mod.mark_claimed(jpath)
        METRICS.inc("fleet.takeovers")
        METRICS.inc("fleet.takeover_jobs", requeued)
        if self.fleet_stats is not None:
            self.fleet_stats["takeovers"] += 1
            self.fleet_stats["takeover_jobs"] += requeued
            self.fleet_stats["last_takeover"] = {
                "peer": peer_id, "requeued": requeued,
                "records": rep.records, "t_unix": round(time.time(), 3),
                "journal": claimed_path,
            }
        FLIGHT.note("fleet.takeover", peer=peer_id, requeued=requeued,
                    records=rep.records)
        log.warning("fleet: took over journal of dead peer %r — %d "
                    "record(s) replayed, %d job(s) requeued under their "
                    "original ids", peer_id, rep.records, requeued)

    def _sweep_report_temps(self, before_unix):
        """Remove dead-pid atomic-output temps from the report dir.

        A SIGKILL'd predecessor can leave ``.<name>.tmp.<pid>.<seq>``
        leftovers next to per-job reports; anything owned by a dead pid
        and not newer than the journal's last entry (i.e. provably from
        before the crash) is swept. Live pids — including this process —
        are never touched."""
        if not self.report_dir or not os.path.isdir(self.report_dir):
            return
        from ..utils.atomic import _pid_alive

        swept = 0
        for name in os.listdir(self.report_dir):
            if not name.startswith(".") or ".tmp." not in name:
                continue
            pid_s = name.split(".tmp.", 1)[1].split(".", 1)[0]
            if not pid_s.isdigit():
                continue
            pid = int(pid_s)
            if pid == os.getpid() or _pid_alive(pid):
                continue
            path = os.path.join(self.report_dir, name)
            try:
                if before_unix is not None \
                        and os.stat(path).st_mtime > before_unix:
                    continue  # newer than the crash horizon; leave it
                os.unlink(path)
                swept += 1
            except OSError:
                pass
        if swept:
            log.info("serve: swept %d stale report temp(s)", swept)

    # -- socket server ------------------------------------------------------

    def _build_frames(self):
        listeners = []
        if self._unix is not None:
            listeners.append(self._unix)
        if self.tcp is not None and self._tcp_listener is None:
            host, port = self.tcp
            self._tcp_listener = transport.TcpListener(
                host, port, token=self.auth_token,
                io_timeout_s=self.io_timeout_s, conn_cap=self.conn_cap)
        if self._tcp_listener is not None:
            listeners.append(self._tcp_listener)
        if not listeners:
            raise ValueError("serve needs a --socket or a --tcp listener")
        return transport.FrameServer(
            self.handle_request, listeners, self.max_frame_bytes,
            on_shutdown=self._shutdown.set, name="fgumi-serve")

    def bind(self):
        """Claim every listener AND the metrics port WITHOUT starting to
        serve. Raises SocketBusy / OSError.

        Split from :meth:`start` so the CLI can fail fast on a busy
        socket, TCP port, or metrics port *before* paying (and
        disturbing) the single-tenant device warm-up."""
        if self._frames is None:
            self._frames = self._build_frames()
        self._frames.bind()  # busy unix socket / EADDRINUSE surface here
        if self.metrics_port is not None and self._introspection is None:
            from .introspect import IntrospectionServer

            self._introspection = IntrospectionServer(self,
                                                      self.metrics_port)
            self._introspection.bind()  # EADDRINUSE surfaces here

    @property
    def tcp_port(self):
        """The bound TCP port (after bind; port 0 = ephemeral resolves)."""
        return self._tcp_listener.port if self._tcp_listener else None

    def start_transport(self):
        """Bind and serve frames WITHOUT recovery, workers, or monitors —
        the protocol-surface harness the wire tests drive."""
        self.bind()
        self._frames.start()

    # ------------------------- routing warm start (ISSUE 20 satellite) ---

    ROUTING_STATE_SCHEMA_VERSION = 1

    def _routing_state_path(self):
        """Journal-adjacent (the durable location the operator already
        chose) or socket-adjacent on journal-less daemons."""
        base = self.journal_path or self.socket_path
        return (base + ".routing.json") if base else None

    def load_routing_state(self):
        """Reload the previous daemon's routing EWMAs so a restart does
        not re-learn the link/host/keep-rate crossovers from priors.
        Cold-EWMAs-only by construction (router.restore_state), so a
        profile's seeds or live measurements are never clobbered; a
        restored router stamps ``prior_source="snapshot"``."""
        path = self._routing_state_path()
        self.routing_state = {"path": path, "loaded": False,
                              "saved_unix": None}
        if not path or not os.path.exists(path):
            return False
        try:
            with open(path) as f:
                state = json.load(f)
        except (OSError, ValueError) as e:
            log.warning("serve: unreadable routing snapshot %s (%s); "
                        "starting cold", path, e)
            return False
        if state.get("schema_version") != self.ROUTING_STATE_SCHEMA_VERSION:
            log.warning("serve: routing snapshot %s has schema %s "
                        "(want %d); starting cold", path,
                        state.get("schema_version"),
                        self.ROUTING_STATE_SCHEMA_VERSION)
            return False
        from ..observe.metrics import METRICS
        from ..ops import router as router_mod

        restored = router_mod.ROUTER.restore_state(
            state.get("router") or {}, source="snapshot")
        for name, chooser in (("duplex_combine",
                               router_mod.DUPLEX_COMBINE),
                              ("codec_combine", router_mod.CODEC_COMBINE)):
            if chooser.restore_state(
                    (state.get("choosers") or {}).get(name) or {}):
                restored = True
        self.routing_state.update(loaded=bool(restored),
                                  saved_unix=state.get("saved_unix"))
        if restored:
            METRICS.inc("tune.routing_state.restored")
            log.info("serve: warm-started routing EWMAs from %s "
                     "(saved %s)", path, state.get("saved_unix"))
        return restored

    def save_routing_state(self):
        """Snapshot the live routing EWMAs (router incl. keep-rate,
        choosers, the coalescer's effective window for the record) next
        to the journal on drain/close; crash-safe via the atomic-rename
        writer. The coalesce window needs no restore of its own — it is
        priced off the router's overhead EWMA, which the snapshot
        carries."""
        path = self._routing_state_path()
        if not path:
            return None
        import sys

        from ..ops import router as router_mod
        from ..utils.atomic import discard_output, open_output

        state = {
            "schema_version": self.ROUTING_STATE_SCHEMA_VERSION,
            "saved_unix": int(time.time()),
            "router": router_mod.ROUTER.export_state(),
            "choosers": {
                "duplex_combine":
                    router_mod.DUPLEX_COMBINE.export_state(),
                "codec_combine": router_mod.CODEC_COMBINE.export_state(),
            },
        }
        coal = sys.modules.get("fgumi_tpu.ops.coalesce")
        if coal is not None:
            state["coalesce_window_ms"] = round(coal.window_s() * 1e3, 3)
        try:
            out = open_output(path, "w")
            try:
                json.dump(state, out, indent=2, sort_keys=True)
                out.write("\n")
                out.close()
            except BaseException:
                discard_output(out)
                raise
        except OSError as e:
            log.warning("serve: could not save routing snapshot %s: %s",
                        path, e)
            return None
        if self.routing_state is not None:
            self.routing_state["saved_unix"] = state["saved_unix"]
        log.info("serve: routing EWMAs -> %s", path)
        return path

    def start(self):
        """Bind (if not already), recover, start workers and the accept
        loops. Recovery runs before the pool so requeued jobs hold their
        original queue positions ahead of any fresh submission."""
        self.bind()
        self.recover()
        self.load_routing_state()
        # arm the cross-job dispatch coalescer's serving signal: its merge
        # window may auto-open whenever >= 2 of this daemon's jobs are
        # running (the scheduler feeds the live count; ops/coalesce.py)
        from ..ops.coalesce import COALESCER

        COALESCER.set_serving(True)
        self.scheduler.start()
        if self.health_period_s > 0:
            from ..ops.breaker import BREAKER, HealthMonitor

            self._monitor = HealthMonitor(BREAKER,
                                          period_s=self.health_period_s)
            self._monitor.start()
        if self._introspection is not None:
            self._introspection.start()
        if self.journal_dir and self.lease_scan_period_s > 0:
            self._scanner = _TakeoverScanner(self, self.lease_scan_period_s)
            self._scanner.start()
        self._frames.start()
        log.info("serve: listening on %s (%d workers, queue limit %d%s%s)",
                 " + ".join(lst.describe()
                            for lst in self._frames.listeners),
                 self.scheduler.workers, self.scheduler.queue_limit,
                 f", journal {self.journal_path}" if self.journal_path
                 else "",
                 f", fleet id {self.fleet_id}" if self.fleet_id else "")

    # -- request dispatch (transport-independent; tests call it directly) ---

    def handle_request(self, req: dict) -> dict:
        err = protocol.validate_request(req)
        if err is not None:
            return protocol.error_response(err)
        op = req["op"]
        if op == "hello":
            # the transport layer enforces WHEN a hello is required (first
            # frame on an auth-required listener); this answers WHETHER
            # the offered token matches
            return transport.hello_response("fgumi-tpu", self.auth_token,
                                            req)
        if op == "ping":
            extra = {}
            if self.scheduler.max_per_client:
                # quota surface only when the knob is armed, so the default
                # ping (and its golden fixture) is unchanged
                extra["max_per_client"] = self.scheduler.max_per_client
                extra["quota"] = self.scheduler.client_quota_state()
            return protocol.ok_response(
                tool="fgumi-tpu", pid=os.getpid(),
                uptime_s=round(time.time() - self.started_unix, 1),
                jobs=self.registry.counts(), **self.scheduler.depth(),
                **extra)
        if op == "stats":
            # live introspection: scheduler/quota/journal/breaker/governor/
            # device/fleet snapshots + latency histogram summaries — the
            # same builder feeds /metrics, so the two surfaces cannot
            # disagree
            from .introspect import service_stats

            return protocol.ok_response(stats=service_stats(self))
        if op == "scatter":
            # balancer-only op: daemons EXECUTE shard sub-jobs, they never
            # plan or gather them — an explicit refusal here (vs the
            # version-skew "unknown op") tells the operator they pointed a
            # scatter client at a daemon instead of a balance front end
            return protocol.error_response(
                "op 'scatter' is balancer-only: this is a daemon, not a "
                "balance front end — submit whales through `fgumi-tpu "
                "balance --scatter N` (docs/serving.md)")
        if op == "submit":
            dedupe = req.get("dedupe")
            with self._dedupe_lock:
                if dedupe:
                    existing = self._dedupe.get(dedupe)
                    if existing is not None:
                        prior = self.registry.get(existing)
                        if prior is not None:
                            # idempotent resubmit: same key -> the SAME
                            # job (running, queued, or finished), never a
                            # second execution — the contract that makes
                            # client retry-after-reconnect safe
                            return protocol.ok_response(
                                job=prior.to_wire(), deduped=True)
                        # job evicted from history: key is stale, reissue
                # resource shed: under a memory/disk pressure watermark the
                # daemon stops taking on NEW work (running jobs finish) —
                # an explicit reason plus a Retry-After-style hint, checked
                # after dedupe so idempotent resubmits of existing jobs
                # still answer (they cost nothing)
                shed = _governor_pressure()
                if shed is not None:
                    # the governor counts the shed; fold_metrics publishes
                    # it as serve.shed.resource at serve-command exit
                    return protocol.error_response(
                        f"resource_pressure: {shed['reason']}",
                        retry_after_s=shed["retry_after_s"])
                job = self.registry.create(
                    req["argv"],
                    req.get("priority", protocol.DEFAULT_PRIORITY),
                    argv0=req.get("argv0"), tag=req.get("tag"),
                    trace=bool(req.get("trace")),
                    client=req.get("client"),
                    traceparent=_clean_traceparent(req.get("traceparent")),
                    hops=_clean_hops(req),
                    shard=req.get("shard")
                    if isinstance(req.get("shard"), dict) else None)
                if dedupe:
                    self._dedupe[dedupe] = job.id
            # journal BEFORE admission: a crash between the two requeues a
            # job the client believes submitted — the safe direction (the
            # reverse silently loses it); a rejection is journaled as the
            # cancelled transition right below
            if self.journal is not None:
                self.journal.record_submit(job, dedupe)
            admitted, reason = self.scheduler.submit(job)
            if not admitted:
                # the response still carries the (cancelled) record so the
                # client sees what was refused, but the registry forgets it:
                # a rejection storm must not evict finished-job history —
                # and the dedupe key is released so a later retry of the
                # same request is not answered with the rejected record
                self.registry.mark_cancelled(job)
                self.registry.discard(job.id)
                if dedupe:
                    with self._dedupe_lock:
                        if self._dedupe.get(dedupe) == job.id:
                            del self._dedupe[dedupe]
                return protocol.error_response(reason, job=job.to_wire())
            return protocol.ok_response(job=job.to_wire())
        if op == "status":
            job_id = req.get("id")
            if job_id is None:
                return protocol.ok_response(
                    jobs=[j.to_wire() for j in self.registry.list()],
                    **self.scheduler.depth())
            job = self.registry.get(job_id)
            if job is None:
                return protocol.error_response(f"unknown job {job_id}")
            return protocol.ok_response(job=job.to_wire())
        if op == "cancel":
            ok, reason = self.scheduler.cancel(req["id"])
            if not ok:
                return protocol.error_response(reason)
            job = self.registry.get(req["id"])
            return protocol.ok_response(job=job.to_wire())
        if op == "drain":
            self.scheduler.drain()
            return protocol.ok_response(**self.scheduler.depth())
        if op == "shutdown":
            # drain here; the transport layer arms the exit event after the
            # response is sent (direct handle_request callers — tests, an
            # embedding app — follow with request_shutdown themselves)
            self.scheduler.drain()
            return protocol.ok_response(**self.scheduler.depth())
        raise AssertionError(f"unhandled op {op}")  # validate() covers this

    # -- lifecycle ----------------------------------------------------------

    def request_shutdown(self):
        """Graceful exit: flag shutdown. Genuinely signal-handler safe —
        sets one event, no locks, no logging; the waiting main loop does
        the drain (and its logging) outside signal context."""
        self._shutdown.set()

    def wait_until_shutdown(self, poll_s: float = 0.2):
        """Block until a shutdown is requested AND the pool is quiescent.
        Closes admission (idempotent drain) once the flag is seen."""
        while not self._shutdown.wait(poll_s):
            pass
        self.scheduler.drain()
        self.scheduler.join()
        _drain_device_feeder()

    def close(self):
        """Tear the listeners down and remove the socket file (idempotent)."""
        if self._closed:
            return
        self._closed = True
        self._shutdown.set()
        # persist the learned routing EWMAs for the next daemon's warm
        # start (covers graceful drain, SIGTERM, and error teardown alike
        # — close() is the one always-reached exit path)
        self.save_routing_state()
        import sys

        coal = sys.modules.get("fgumi_tpu.ops.coalesce")
        if coal is not None:
            coal.COALESCER.set_serving(False)
        if self._scanner is not None:
            self._scanner.stop()
        if self._monitor is not None:
            self._monitor.stop()
        if self._introspection is not None:
            self._introspection.stop()
        if self.journal is not None:
            self.journal.close()
        if self._frames is not None:
            self._frames.close()
        if self._unix is not None:
            self._unix.unlink()
        if self._lease is not None:
            self._lease.release()
        log.info("serve: stopped (%s)",
                 json.dumps(self.registry.counts(), sort_keys=True))


class _TakeoverScanner:
    """Background loop claiming dead peers' journals (fleet mode)."""

    def __init__(self, service: JobService, period_s: float):
        self.service = service
        self.period_s = period_s
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop,
                                        name="fgumi-fleet-lease",
                                        daemon=True)
        self._thread.start()
        log.info("fleet: lease takeover scan every %.1fs in %s",
                 self.period_s, self.service.journal_dir)

    def stop(self, timeout: float = 5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def _loop(self):
        while not self._stop.wait(self.period_s):
            try:
                self.service.scan_for_takeovers()
            except Exception:  # noqa: BLE001 - scanner must survive
                log.exception("fleet: takeover scan raised")
