"""UMI assignment strategies.

Mirrors /root/reference/crates/fgumi-umi/src/assigner.rs:
- MoleculeId {None, Single, PairedA, PairedB} rendered "42" / "42/A" / "42/B"
  (crates/fgumi-umi/src/lib.rs:20-80)
- identity: exact match on uppercased strings, IDs in sorted order (assigner.rs:915-936)
- edit: transitive single-linkage within Hamming distance; components get IDs in
  order of their smallest member (assigner.rs:999-1108)
- adjacency: UMI-tools directed graph — count-desc (tie: string) order, BFS capture
  of unassigned children with child_count <= parent_count/2 + 1 within distance
  (assigner.rs:1552-1640,1174-1420)
- paired: adjacency over canonicalized dual UMIs (A-B vs B-A), /A-/B strand IDs by
  orientation vs the root (assigner.rs:1735-2235)

Invalid UMIs (non-ACGT, >32 bases per segment) never join a valid molecule; each
distinct (uppercased) invalid string gets its own Single id
(assign_with_invalid_fallback, assigner.rs:692-707).

The all-pairs Hamming distance work — the hot part for large position groups — is
vectorized over byte matrices; groups above ``DEVICE_THRESHOLD`` unique UMIs compute
the candidate-distance matrix as an XLA kernel on the accelerator (XOR/compare +
popcount-style reduction), the "brute-force-on-accelerator" design SURVEY.md §7
replaces the reference's BK-tree/N-gram indexes with.
"""

from collections import deque
from dataclasses import dataclass

import numpy as np

# Unique-UMI count above which the pairwise distance matrix moves to the device.
DEVICE_THRESHOLD = 1024



@dataclass(frozen=True)
class MoleculeId:
    """kind: '' (none), 'S' (single), 'A'/'B' (paired strands)."""

    kind: str
    id: int = -1

    def render(self) -> str:
        if self.kind == "S":
            return str(self.id)
        if self.kind in ("A", "B"):
            return f"{self.id}/{self.kind}"
        return ""


NONE_ID = MoleculeId("")


def render_mis_array(mols) -> np.ndarray:
    """Vectorized MoleculeId.render over a list: one S-dtype numpy array
    (itemsize covers the longest value; consumers read true lengths via
    np.char.str_len). Replaces 100k+ per-object render()/encode() calls in
    the group emission path with three array passes.

    Assigners return ONE MoleculeId object per molecule (repeated by
    reference across its templates), so the attribute reads run on the
    identity-deduped uniques and the full-size result is a single gather."""
    n = len(mols)
    obj = np.fromiter(map(id, mols), np.int64, n)
    uniq, first, inverse = np.unique(obj, return_index=True,
                                     return_inverse=True)
    umols = [mols[int(i)] for i in first]
    m = len(umols)
    ids = np.fromiter((mo.id for mo in umols), np.int64, m)
    kinds = np.fromiter((ord(mo.kind) if mo.kind else 0 for mo in umols),
                        np.uint8, m)
    s = ids.astype("S20")
    out = np.where(kinds == 0, np.bytes_(b""), s)
    ab = (kinds == ord("A")) | (kinds == ord("B"))
    if ab.any():
        suffix = np.where(kinds == ord("A"), np.bytes_(b"/A"),
                          np.bytes_(b"/B"))
        out = np.where(ab, np.char.add(s, suffix), out)
    return out[inverse]


_VALID_SET = frozenset("ACGTacgt")


def _is_encodable(umi: str) -> bool:
    """BitEnc-encodable: every dash-separated segment is ACGT (case-folded), <=32."""
    for seg in umi.split("-"):
        # strip orientation prefix ("aa:"/"bb:") if present
        seg = seg.rsplit(":", 1)[-1]
        if len(seg) > 32:
            return False
        if not _VALID_SET.issuperset(seg):
            return False
    return True


def _umi_matrix(umis) -> np.ndarray:
    """(N, L) uint8 byte matrix of equal-length strings."""
    return np.frombuffer("".join(umis).encode(), dtype=np.uint8).reshape(len(umis), -1)


# Above this many unique UMIs, dense all-pairs matrices become untenable
# (O(U^2) memory and transfer) and candidate pairs come from pigeonhole
# chunk indexing instead — the analog of the reference's NgramIndex
# (crates/fgumi-umi/src/assigner.rs:228,267,394: exact-match on one of
# d+1 chunks is necessary for Hamming distance <= d).
SPARSE_THRESHOLD = 8192


def set_index_threshold(n):
    """--index-threshold mapping (group.rs:860-863): below the threshold the
    neighbor graph is built by the dense pairwise scan, at/above it by the
    indexed candidate search (pigeonhole n-gram / BK-tree). 0 = always
    dense (linear-scan semantics); None restores the measured default.

    The default here (8192) is far above the reference's 100 because the
    dense path is a vectorized array scan, not a per-pair loop — it wins
    until well past the reference's crossover."""
    global SPARSE_THRESHOLD
    SPARSE_THRESHOLD = (8192 if n is None
                        else (1 << 62) if int(n) == 0 else int(n))
# unique-UMI count above which the directed BFS runs natively
# (fgumi_adjacency_bfs); tests force the Python loop by raising this
_NATIVE_BFS_THRESHOLD = 512


class NeighborGraph:
    """Match-graph adjacency: neighbors(i) -> ascending indices j != i.

    Dense mode wraps a boolean within-matrix (small groups); sparse mode
    holds per-node neighbor lists from pigeonhole candidate generation."""

    def __init__(self, n, within=None, lists=None):
        self.n = n
        self._within = within
        self._lists = lists

    def neighbors(self, i: int) -> np.ndarray:
        if self._within is not None:
            row = np.nonzero(self._within[i])[0]
            return row[row != i]
        return self._lists[i]

    def flat(self):
        """(nbr_flat, nbr_start) arrays for the native BFS: neighbors of i
        are nbr_flat[nbr_start[i]:nbr_start[i+1]], ascending."""
        lists = (self._lists if self._lists is not None
                 else [self.neighbors(i) for i in range(self.n)])
        starts = np.zeros(self.n + 1, dtype=np.int64)
        np.cumsum([len(x) for x in lists], out=starts[1:])
        flat = (np.concatenate(lists).astype(np.int64)
                if self.n else np.empty(0, np.int64))
        return flat, starts


def build_neighbor_graph(mat: np.ndarray, max_mismatches: int,
                         rev_mat: np.ndarray = None) -> NeighborGraph:
    """Graph of pairs with hamming(mat[i], mat[j]) <= d (or, with rev_mat,
    additionally hamming(rev_mat[i], mat[j]) <= d — the paired-UMI cross
    condition, symmetric because strand reversal is an involution)."""
    n = mat.shape[0]
    # pigeonhole completeness needs d+1 disjoint chunks: with d+1 > L a pair
    # can differ everywhere yet still be within distance d, so stay dense
    if n < SPARSE_THRESHOLD or max_mismatches + 1 > mat.shape[1]:
        within = pairwise_distances(mat) <= max_mismatches
        if rev_mat is not None:
            within |= pairwise_distances(rev_mat, mat) <= max_mismatches
        return NeighborGraph(n, within=within)
    from ..native import batch as nb

    if nb.available():
        pair_sets = [nb.umi_neighbor_pairs(mat, None, max_mismatches)]
        if rev_mat is not None:
            pair_sets.append(
                nb.umi_neighbor_pairs(rev_mat, mat, max_mismatches))
        return _lists_from_pairs(n, pair_sets)
    pair_sets = [_pigeonhole_pairs(mat, mat, max_mismatches)]
    if rev_mat is not None:
        pair_sets.append(_pigeonhole_pairs(rev_mat, mat, max_mismatches))
    return _lists_from_pairs(n, pair_sets)


def _pigeonhole_pairs(A: np.ndarray, B: np.ndarray, d: int):
    """Candidate (i, j) index arrays with hamming(A[i], B[j]) <= d, i != j.

    Split columns into d+1 chunks; any pair within distance d agrees exactly
    on at least one chunk, so exact-match buckets per chunk generate a
    complete candidate set which is then distance-verified in bulk."""
    n, L = A.shape
    out_i = []
    out_j = []
    chunks = np.array_split(np.arange(L), min(d + 1, L))
    same = A is B
    for cols in chunks:
        if len(cols) == 0:
            continue
        kb = np.ascontiguousarray(B[:, cols])
        key_b = kb.view([("", np.uint8, kb.shape[1])]).ravel()
        order_b = np.argsort(key_b, kind="stable")
        sb = key_b[order_b]
        bounds = np.flatnonzero(np.concatenate(
            ([True], sb[1:] != sb[:-1], [True])))
        if same:
            for s, e in zip(bounds[:-1], bounds[1:]):
                if e - s < 2:
                    continue
                idxs = np.sort(order_b[s:e])
                dm = pairwise_distances(np.ascontiguousarray(B[idxs]))
                ii, jj = np.nonzero(dm <= d)
                keep = ii < jj
                out_i.append(idxs[ii[keep]])
                out_j.append(idxs[jj[keep]])
        else:
            ka = np.ascontiguousarray(A[:, cols])
            key_a = ka.view([("", np.uint8, ka.shape[1])]).ravel()
            order_a = np.argsort(key_a, kind="stable")
            sa = key_a[order_a]
            a_bounds = np.flatnonzero(np.concatenate(
                ([True], sa[1:] != sa[:-1], [True])))
            # probe B buckets by key bytes (void-dtype ordering comparisons
            # are unreliable; equality via bytes is exact)
            b_index = {sb[bounds[k]].tobytes(): (bounds[k], bounds[k + 1])
                       for k in range(len(bounds) - 1)}
            for s, e in zip(a_bounds[:-1], a_bounds[1:]):
                got = b_index.get(sa[s].tobytes())
                if got is None:
                    continue
                ai = order_a[s:e]
                bj = order_b[got[0]:got[1]]
                dm = pairwise_distances(np.ascontiguousarray(A[ai]),
                                        np.ascontiguousarray(B[bj]))
                ii, jj = np.nonzero(dm <= d)
                gi, gj = ai[ii], bj[jj]
                keep = gi != gj
                out_i.append(gi[keep])
                out_j.append(gj[keep])
    if not out_i:
        return (np.empty(0, np.int64), np.empty(0, np.int64))
    return (np.concatenate(out_i).astype(np.int64),
            np.concatenate(out_j).astype(np.int64))


def _lists_from_pairs(n: int, pair_sets) -> NeighborGraph:
    """Symmetrize + dedupe pair arrays into sorted per-node neighbor lists."""
    all_i = []
    all_j = []
    for pi, pj in pair_sets:
        all_i.append(pi)
        all_j.append(pj)
    i = np.concatenate(all_i) if all_i else np.empty(0, np.int64)
    j = np.concatenate(all_j) if all_j else np.empty(0, np.int64)
    # undirected: add both directions, dedupe on i*n+j
    src = np.concatenate([i, j])
    dst = np.concatenate([j, i])
    enc = np.unique(src * n + dst)
    src = enc // n
    dst = enc % n
    splits = np.searchsorted(src, np.arange(1, n))
    lists = np.split(dst, splits)
    return NeighborGraph(n, lists=lists)


def pairwise_distances(mat_a: np.ndarray, mat_b: np.ndarray = None) -> np.ndarray:
    """All-pairs Hamming distances between byte matrices (int16).

    Large inputs run as a one-hot einsum on the accelerator — the XLA equivalent
    of the reference's XOR+popcount BitEnc path (crates/fgumi-dna/src/bitenc.rs:111-124),
    batched over the whole position group at once.
    """
    if mat_b is None:
        mat_b = mat_a
    n, m = mat_a.shape[0], mat_b.shape[0]
    if max(n, m) >= DEVICE_THRESHOLD:
        return _device_pairwise(mat_a, mat_b)
    return (mat_a[:, None, :] != mat_b[None, :, :]).sum(axis=2, dtype=np.int16)


def _pow2_pad_rows(mat: np.ndarray) -> np.ndarray:
    """Pad rows up to the next power of two with an unused byte value.

    Real position groups arrive in every size; without padding each distinct
    (n, m) pair would trigger a fresh XLA compile (~2s — measured as the
    entire 16k-group 'cliff'). Pow2 bucketing keeps the compiled-shape
    vocabulary logarithmic, exactly as the consensus kernel pads its
    segment batches (ops/kernel.py pad_segments)."""
    n = mat.shape[0]
    n_pad = 1 << (n - 1).bit_length() if n > 1 else 1
    if n_pad == n:
        return mat
    pad = np.zeros((n_pad - n, mat.shape[1]), dtype=mat.dtype)
    return np.concatenate([mat, pad])


_dist_jit = None


def _get_dist_jit():
    """Module-level jitted pairwise kernel: one compile per padded shape for
    the process lifetime (a per-call jax.jit closure would recompile every
    call — measured at ~0.5s per group)."""
    global _dist_jit
    if _dist_jit is None:
        import jax
        import jax.numpy as jnp

        from ..utils.compile_cache import enable_persistent_cache

        enable_persistent_cache()  # cross-process reuse of the compiles

        # group/dedup runs reach the device only through this kernel, so the
        # persistent XLA cache must be enabled here too (first 16k-UMI group
        # otherwise pays the ~2s compile in every CLI invocation)
        from ..ops.kernel import _enable_persistent_compile_cache

        _enable_persistent_compile_cache()

        @jax.jit
        def dist(a, b):
            # one-hot over the observed byte alphabet -> matmul on the MXU
            alphabet = jnp.unique(jnp.concatenate([a.ravel(), b.ravel()]),
                                  size=8, fill_value=0)
            oh_a = (a[..., None] == alphabet).astype(jnp.bfloat16)  # (N, L, K)
            oh_b = (b[..., None] == alphabet).astype(jnp.bfloat16)
            matches = jnp.einsum("nlk,mlk->nm", oh_a, oh_b)
            return (a.shape[1] - matches).astype(jnp.int16)

        _dist_jit = dist
    return _dist_jit


def _device_pairwise(mat_a: np.ndarray, mat_b: np.ndarray) -> np.ndarray:
    import jax.numpy as jnp

    dist = _get_dist_jit()

    from ..ops.kernel import DEVICE_STATS

    n, m = mat_a.shape[0], mat_b.shape[0]
    pad_a = _pow2_pad_rows(mat_a)
    pad_b = _pow2_pad_rows(mat_b)
    DEVICE_STATS.add_dispatch(2 * pad_a.shape[0] * pad_b.shape[0]
                              * pad_a.shape[1] * 8)  # one-hot matmul (K=8)
    full = DEVICE_STATS.fetch(dist(jnp.asarray(pad_a), jnp.asarray(pad_b)))
    return full[:n, :m]


def _assert_uniform_length(lengths) -> None:
    it = iter(lengths)
    first = next(it, None)
    if first is None:
        return
    for ln in it:
        if ln != first:
            raise ValueError(f"Multiple UMI lengths: {ln} vs {first}")


class _Counter:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def next_id(self) -> int:
        v = self.value
        self.value += 1
        return v


def _with_invalid_fallback(umis, resolve, counter):
    """Per-input ids with a per-distinct-invalid-string fallback (assigner.rs:692-707)."""
    invalid_to_id = {}
    out = []
    for i, umi in enumerate(umis):
        mid = resolve(i, umi)
        if mid is None:
            key = umi.upper()
            if key not in invalid_to_id:
                invalid_to_id[key] = MoleculeId("S", counter.next_id())
            mid = invalid_to_id[key]
        out.append(mid)
    return out


class IdentityUmiAssigner:
    """Exact-match grouping; IDs assigned over sorted unique uppercased UMIs."""

    def __init__(self):
        self.counter = _Counter()

    def split_by_orientation(self) -> bool:
        return True

    def assign(self, raw_umis):
        if not raw_umis:
            return []
        canon = [u.upper() for u in raw_umis]
        mapping = {c: MoleculeId("S", self.counter.next_id()) for c in sorted(set(canon))}
        return [mapping[c] for c in canon]


class SimpleErrorUmiAssigner:
    """Transitive single-linkage clustering within ``max_mismatches`` (edit strategy)."""

    def __init__(self, max_mismatches: int = 1):
        self.max_mismatches = max_mismatches
        self.counter = _Counter()

    def split_by_orientation(self) -> bool:
        return True

    def assign(self, raw_umis):
        if not raw_umis:
            return []
        upper = [u.upper() for u in raw_umis]
        valid = sorted({u for u in set(upper) if _is_encodable(u)})
        _assert_uniform_length(len(u) for u in valid)
        umi_to_id = {}
        if valid:
            mat = _umi_matrix(valid)
            graph = build_neighbor_graph(mat, self.max_mismatches)
            # connected components = transitive closure of the match graph
            n = len(valid)
            comp = np.full(n, -1, dtype=np.int64)
            n_comp = 0
            for i in range(n):
                if comp[i] >= 0:
                    continue
                stack = [i]
                comp[i] = n_comp
                while stack:
                    j = stack.pop()
                    nbrs = graph.neighbors(j)
                    for k in nbrs[comp[nbrs] < 0]:
                        comp[k] = n_comp
                        stack.append(int(k))
                n_comp += 1
            # components ordered by smallest member (valid is sorted, so the
            # first occurrence order IS smallest-member order)
            comp_ids = {}
            for i, u in enumerate(valid):
                c = comp[i]
                if c not in comp_ids:
                    comp_ids[c] = MoleculeId("S", self.counter.next_id())
                umi_to_id[u] = comp_ids[c]
        return _with_invalid_fallback(upper, lambda _i, u: umi_to_id.get(u), self.counter)


def _count_sorted_unique(upper, keys=None):
    """(unique_key, count) sorted by (-count, key). keys default to the UMIs."""
    from collections import Counter

    counts = Counter(keys if keys is not None else upper)
    return sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))


def _adjacency_bfs(unique, counts, graph: NeighborGraph):
    """UMI-tools directed BFS (assigner.rs:1480-1548).

    unique/counts sorted by (-count, string); graph.neighbors(i) = ascending
    candidate matches. Returns (roots, parent_of) where parent_of[i] is the
    component root index.
    """
    n = len(unique)
    counts_arr = np.asarray(counts)
    from ..native import batch as nb

    if n >= _NATIVE_BFS_THRESHOLD and nb.available():
        flat, starts = graph.flat()
        root_of = nb.adjacency_bfs(flat, starts,
                                   counts_arr.astype(np.int64))
        # roots in discovery order == ascending root index (each root is
        # its own first-assigned node), exactly the scalar loop's order
        return np.unique(root_of).tolist(), root_of
    assigned = np.zeros(n, dtype=bool)
    root_of = np.full(n, -1, dtype=np.int64)
    roots = []
    for root in range(n):
        if assigned[root]:
            continue
        roots.append(root)
        assigned[root] = True
        root_of[root] = root
        queue = deque([root])
        while queue:
            idx = queue.popleft()
            max_child = counts[idx] // 2 + 1
            nbrs = graph.neighbors(idx)
            cand = nbrs[~assigned[nbrs] & (counts_arr[nbrs] <= max_child)]
            for child in cand:
                child = int(child)
                assigned[child] = True
                root_of[child] = root_of[idx]
                queue.append(child)
    return roots, root_of


class AdjacencyUmiAssigner:
    """UMI-tools directed adjacency strategy."""

    # above this many input strings, per-read Python loops (upper, Counter,
    # fallback dict walk) dominate the whole group command; the vectorized
    # path does uppercase/unique/count/map-back as numpy C passes over the
    # full input and runs Python only per DISTINCT UMI. Byte-parity with the
    # scalar path is pinned by tests/test_umi_assigners.py.
    _VEC_THRESHOLD = 2048

    def __init__(self, max_mismatches: int = 1):
        self.max_mismatches = max_mismatches
        self.counter = _Counter()

    def split_by_orientation(self) -> bool:
        return True

    def _assign_uniques(self, unique, counts):
        """Molecule ids for (-count, string)-sorted valid unique UMIs.

        Returns a list of MoleculeIds aligned with `unique`; id minting
        order (roots in BFS-root order) is the shared contract of both the
        scalar and vectorized assign paths."""
        if len(unique) == 1:
            return [MoleculeId("S", self.counter.next_id())]
        mat = _umi_matrix(unique)
        graph = build_neighbor_graph(mat, self.max_mismatches)
        roots, root_of = _adjacency_bfs(unique, counts, graph)
        root_ids = {r: MoleculeId("S", self.counter.next_id()) for r in roots}
        return [root_ids[int(root_of[i])] for i in range(len(unique))]

    def assign(self, raw_umis):
        if not raw_umis:
            return []
        if len(raw_umis) >= self._VEC_THRESHOLD:
            return self._assign_vectorized(raw_umis)
        upper = [u.upper() for u in raw_umis]
        # count first, validate per DISTINCT string: distinct UMIs are a
        # small fraction of reads in large position groups, and the filtered
        # list keeps the (-count, umi) order _count_sorted_unique establishes
        counted = [(u, c) for u, c in _count_sorted_unique(upper)
                   if _is_encodable(u)]
        if not counted:
            return _with_invalid_fallback(upper, lambda *_: None, self.counter)
        _assert_uniform_length(len(u) for u, _ in counted)
        unique = [u for u, _ in counted]
        counts = [c for _, c in counted]
        umi_to_id = dict(zip(unique, self._assign_uniques(unique, counts)))
        return _with_invalid_fallback(upper, lambda _i, u: umi_to_id.get(u), self.counter)

    def _assign_vectorized(self, raw_umis):
        """Large-group assign: numpy passes over the input, Python per
        distinct UMI only. Semantics identical to the scalar path:

        - valid uniques sorted by (-count, string) — np.unique returns
          string-ascending uniques, so a stable sort by -count reproduces
          _count_sorted_unique's order (filter-then-sort == sort-then-filter);
        - valid molecule ids minted first (BFS-root order), then one id per
          distinct invalid string in first-occurrence input order, exactly
          as _with_invalid_fallback's forward walk mints them."""
        arr = np.char.upper(np.asarray(raw_umis, dtype=np.str_))
        uniq, first_idx, inverse, ucounts = np.unique(
            arr, return_index=True, return_inverse=True, return_counts=True)
        valid_mask = np.fromiter((_is_encodable(u) for u in uniq),
                                 bool, len(uniq))
        mids_u = np.empty(len(uniq), dtype=object)
        valid_idx = np.nonzero(valid_mask)[0]
        if len(valid_idx):
            order = np.argsort(-ucounts[valid_idx], kind="stable")
            sorted_idx = valid_idx[order]
            unique = [str(uniq[i]) for i in sorted_idx]
            _assert_uniform_length(len(u) for u in unique)
            counts = ucounts[sorted_idx].tolist()
            for i, mid in zip(sorted_idx,
                              self._assign_uniques(unique, counts)):
                mids_u[i] = mid
        invalid_idx = np.nonzero(~valid_mask)[0]
        if len(invalid_idx):
            for i in invalid_idx[np.argsort(first_idx[invalid_idx],
                                            kind="stable")]:
                mids_u[i] = MoleculeId("S", self.counter.next_id())
        return list(mids_u[inverse])


class PairedUmiAssigner:
    """Dual-UMI (duplex) strategy: A-B and B-A group together with /A-/B strand ids."""

    def __init__(self, max_mismatches: int = 1):
        self.max_mismatches = max_mismatches
        self.counter = _Counter()
        prefix_len = max_mismatches + 1
        self.lower_prefix = "a" * prefix_len
        self.higher_prefix = "b" * prefix_len

    def split_by_orientation(self) -> bool:
        return False

    @staticmethod
    def _split(umi: str):
        parts = umi.split("-")
        if len(parts) != 2:
            raise ValueError(f"UMI {umi!r} is not a valid paired UMI (expected 'A-B')")
        return parts[0], parts[1]

    @classmethod
    def _reverse(cls, umi: str) -> str:
        a, b = cls._split(umi)
        return f"{b}-{a}"

    @classmethod
    def _canonical(cls, umi: str) -> str:
        a, b = cls._split(umi)
        return umi if a <= b else f"{b}-{a}"

    def assign(self, raw_umis):
        if not raw_umis:
            return []
        upper = [u.upper() for u in raw_umis]
        # structure-validate, BitEnc-validate, and canonicalize per DISTINCT
        # string (the '-' split is case-invariant, so distinct uppers cover
        # every raw input); counts aggregate per canonical form exactly as
        # the per-read pass did
        counted_all = _count_sorted_unique(upper)
        for u, _ in counted_all:
            self._split(u)  # validates exactly one '-'
        dvalid = {u for u, _ in counted_all if _is_encodable(u)}
        canon_counts = {}
        for u, c in counted_all:
            if u in dvalid:
                k = self._canonical(u)
                canon_counts[k] = canon_counts.get(k, 0) + c
        counted = sorted(canon_counts.items(), key=lambda kv: (-kv[1], kv[0]))
        if not counted:
            return _with_invalid_fallback(upper, lambda *_: None, self.counter)

        def underlying_len(u):
            a, b = self._split(u)
            return len(a.rsplit(":", 1)[-1]) + len(b.rsplit(":", 1)[-1])

        _assert_uniform_length(underlying_len(u) for u, _ in counted)
        unique = [u for u, _ in counted]
        counts = [c for _, c in counted]

        umi_to_id = {}
        if len(unique) == 1:
            mid = self.counter.next_id()
            ab, ba = MoleculeId("A", mid), MoleculeId("B", mid)
            u = unique[0]
            umi_to_id[u] = ab
            umi_to_id[self._reverse(u)] = ba
        else:
            mat = _umi_matrix(unique)
            rev_mat = _umi_matrix([self._reverse(u) for u in unique])
            graph = build_neighbor_graph(mat, self.max_mismatches,
                                         rev_mat=rev_mat)
            roots, root_of = _adjacency_bfs(unique, counts, graph)
            root_mid = {r: self.counter.next_id() for r in roots}
            for i, u in enumerate(unique):
                root = int(root_of[i])
                mid = root_mid[root]
                ab, ba = MoleculeId("A", mid), MoleculeId("B", mid)
                if i == root:
                    umi_to_id[u] = ab
                    umi_to_id[self._reverse(u)] = ba
                else:
                    root_umi = unique[root]
                    d_fwd = sum(x != y for x, y in zip(root_umi, u))
                    d_rev = sum(x != y for x, y in zip(root_umi, self._reverse(u)))
                    if d_fwd < d_rev:
                        umi_to_id[u] = ab
                        umi_to_id[self._reverse(u)] = ba
                    else:
                        umi_to_id[u] = ba
                        umi_to_id[self._reverse(u)] = ab
        return _with_invalid_fallback(
            upper, lambda i, u: umi_to_id.get(u) if u in dvalid else None,
            self.counter)


def make_assigner(strategy: str, edits: int = 1):
    """Strategy factory (group.rs Strategy enum)."""
    if strategy == "identity":
        return IdentityUmiAssigner()
    if strategy == "edit":
        return SimpleErrorUmiAssigner(edits)
    if strategy == "adjacency":
        return AdjacencyUmiAssigner(edits)
    if strategy == "paired":
        return PairedUmiAssigner(edits)
    raise ValueError(f"unknown UMI strategy: {strategy}")
