"""fgumi-tpu command-line interface.

CLI layer analog of the reference's clap subcommands (/root/reference/src/main.rs:72-221),
argparse-based. One subcommand per tool; shared options grouped like commands/common.rs.
"""

import argparse
import logging
import os
import sys
import time

log = logging.getLogger("fgumi_tpu")


_DEFAULT_SCHEDULER = "balanced-chase-drain"
# the reference's 14 selectable strategies (scheduler/mod.rs:70-178): known
# names are accepted (logged as no-ops); anything else is a loud error so a
# typo cannot silently change nothing
_KNOWN_SCHEDULERS = frozenset({
    "fixed-priority", "chase-bottleneck", "thompson-sampling", "ucb",
    "epsilon-greedy", "thompson-with-priors", "hybrid-adaptive",
    "backpressure-proportional", "two-phase", "sticky-work-stealing",
    "learned-affinity", "optimized-chase", "balanced-chase",
    "balanced-chase-drain"})


def _add_pipeline_compat(p):
    """Reference pipeline-tuning flags, accepted for CLI compatibility.

    The batch engines replace the reference's adaptive worker scheduler
    (scheduler/mod.rs:70-178) and deadlock watchdog (deadlock.rs:1-60) with a
    fixed reader->process->writer stage pipeline over bounded queues, so most
    of these knobs have no behavior to tune here; they parse cleanly (a
    migrating user's scripts keep working) and `_apply_pipeline_compat` maps
    the ones that do have a counterpart (common.rs:625-646,954).
    """
    p.add_argument("--scheduler", default=_DEFAULT_SCHEDULER,
                   metavar="NAME",
                   help="accepted for compatibility; the batch engine uses a "
                        "fixed stage schedule")
    p.add_argument("--pipeline-stats", action="store_true",
                   help="alias for --stats on commands that report a "
                        "per-stage timing table")
    p.add_argument("--deadlock-timeout", type=float, default=60.0,
                   metavar="SECONDS",
                   help="stall-watchdog check interval for threaded runs")
    p.add_argument("--deadlock-recover", action="store_true",
                   help="double queue/byte limits when the watchdog detects "
                        "a stall (reference deadlock.rs:409)")
    p.add_argument("--async-reader", action="store_true",
                   help="accepted for compatibility (the reader thread is "
                        "already asynchronous when --threads >= 2)")
    p.add_argument("--memory-per-thread", default=None, metavar="SIZE",
                   help="per-thread working-set budget; multiplied by the "
                        "thread count into --max-memory when that knob exists")
    p.add_argument("--compression-level", type=int, default=None,
                   metavar="N",
                   help="BGZF level for BAM outputs, 0-12 (reference "
                        "CompressionOptions, default 1; 0 = stored blocks)")


def _apply_pipeline_compat(args):
    """Map accepted compat flags onto this engine's knobs (called once after
    parse_args; commands without the flags are untouched). Returns an exit
    code: 0, or 2 on an unparseable value."""
    from .io import bam as bam_io

    lvl = getattr(args, "compression_level", None)
    if lvl is not None and not 0 <= lvl <= 12:
        log.error("--compression-level %d: must be 0-12", lvl)
        return 2
    # set unconditionally: main() may be called many times in one process
    # (the `pipeline` command chains stages), so a prior stage's level must
    # not leak into the next (context-scoped, so concurrent daemon jobs
    # with different levels stay independent)
    bam_io.set_default_compression_level(lvl)
    if getattr(args, "memory_per_thread", None):
        from .utils.memory import parse_size

        try:
            per = parse_size(args.memory_per_thread)
        except ValueError as e:
            log.error("--memory-per-thread: %s", e)
            return 2
        # reference semantics are per-worker x worker-count (common.rs:954);
        # with no explicit --threads the reference defaults to the core
        # count, so mirror that rather than collapsing to x1
        threads = int(getattr(args, "threads", 0) or 0)
        n = threads if threads > 0 else (os.cpu_count() or 1)
        mm = getattr(args, "max_memory", None)
        if mm is not None and str(mm).strip().lower() != "auto":
            log.info("--memory-per-thread: --max-memory %s set explicitly "
                     "and takes precedence", args.max_memory)
        elif hasattr(args, "max_memory"):
            # explicit byte suffix: a bare number means MiB to parse_size
            args.max_memory = f"{per * n}B"
        else:
            log.info("--memory-per-thread: no memory knob on this command; "
                     "ignored")
    if getattr(args, "scheduler", _DEFAULT_SCHEDULER) != _DEFAULT_SCHEDULER:
        if args.scheduler not in _KNOWN_SCHEDULERS:
            log.error("--scheduler %s: unknown strategy (the reference "
                      "accepts: %s)", args.scheduler,
                      ", ".join(sorted(_KNOWN_SCHEDULERS)))
            return 2
        log.info("--scheduler %s: accepted for compatibility; the batch "
                 "engine uses a fixed reader->process->writer schedule",
                 args.scheduler)
    if getattr(args, "deadlock_recover", False):
        log.info("--deadlock-recover: stall watchdog will double queue/byte "
                 "limits on each stall (reference deadlock.rs:409)")
    if getattr(args, "max_memory", None) is not None:
        # validate once here so every command fails with rc=2 and a clean
        # message, not a traceback from deep inside _stage_kwargs
        from .utils.memory import resolve_budget

        try:
            resolve_budget(args.max_memory)
        except ValueError as e:
            log.error("--max-memory: %s", e)
            return 2
    if getattr(args, "pipeline_stats", False):
        if hasattr(args, "stats"):
            args.stats = True
        else:
            log.info("--pipeline-stats: this command reports no per-stage "
                     "timing table; ignored")
    if getattr(args, "async_reader", False) \
            and int(getattr(args, "threads", 0) or 0) < 2:
        if hasattr(args, "threads"):
            log.info("--async-reader: accepted for compatibility; add "
                     "--threads >= 2 for an asynchronous reader thread")
        else:
            log.info("--async-reader: accepted for compatibility (this "
                     "command reads inline)")
    return 0


def _stage_kwargs(args):
    """run_stages kwargs from the shared pipeline flags: byte-accurate input
    queue governance from --max-memory (reference QueueMemoryOptions,
    commands/common.rs:759-993) and watchdog interval/recovery (deadlock.rs).
    """
    wi = getattr(args, "deadlock_timeout", None)
    kw = {
        # 0 means "watchdog off" (run_stages contract), so no `or`-defaulting
        "watchdog_interval": 120.0 if wi is None else wi,
        "deadlock_recover": getattr(args, "deadlock_recover", False),
    }
    mm = getattr(args, "max_memory", None)
    if mm is not None:
        from .utils.memory import resolve_budget

        # half the budget governs queued input batches; the rest covers the
        # process stage's padded device arrays and pending output chunks
        kw["max_bytes"] = max(resolve_budget(mm) // 2, 1 << 20)
        # a queued batch's working set: decompressed buffer + decoded SoA
        # columns + padded device gathers ~= 3x the raw bytes
        kw["item_bytes"] = lambda b: 3 * b.buf.nbytes
    return kw


def _consensus_stage_kwargs(args):
    """_stage_kwargs + resolve-pool sizing for device-attached consensus
    runs: >=2 resolve workers so a worker blocked on a device fetch never
    starves a host-engine (hybrid) chunk queued behind it. Host-only runs
    keep the threads-3 default (no point oversubscribing pure CPU work).
    Only for commands that pass a real resolve_fn (simplex/duplex) — a
    pool applying the identity is pure queue overhead."""
    kw = _stage_kwargs(args)
    from .ops.kernel import use_host_engine

    if not use_host_engine():
        kw["resolve_workers"] = max(getattr(args, "threads", 0) - 3, 2)
    return kw


def _print_stats(stats, wall_s=None):
    """--stats output: per-stage busy/blocked table + queue occupancy,
    peak RSS, the device-boundary accounting (dispatches, fetch-wait,
    GFLOP/s, MFU estimate, device fraction of wall) and the per-dispatch
    device timeline when any kernel dispatched this run (the
    PipelineStats::format_summary analog, reference base.rs:3379-3947;
    VERDICT r4 item 9)."""
    print(stats.format_table())
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM"):
                    print(f"peak RSS   {line.split()[1]} kB")
                    break
    except OSError:
        pass
    from .ops.kernel import DEVICE_STATS

    if DEVICE_STATS.dispatches:
        print(DEVICE_STATS.format_summary(wall_s))
        tl = DEVICE_STATS.timeline_snapshot()
        done = [t for t in tl if "t_fetched" in t]
        if done:
            lats = sorted(t["t_fetched"] - t["t_dispatch"] for t in done)
            mid = lats[len(lats) // 2]
            print(f"device timeline: {len(done)} dispatches resolved, "
                  f"latency p50 {mid:.3f}s max {lats[-1]:.3f}s")
            for t in done[:12]:
                print(f"  t+{t['t_dispatch']:7.3f}s  up {t['up_bytes']:>9}B"
                      f"  -> fetched t+{t['t_fetched']:7.3f}s"
                      f"  down {t.get('down_bytes', 0):>8}B"
                      f"  wait {t.get('fetch_wait_s', 0.0):.3f}s")
            if len(done) > 12:
                print(f"  ... {len(done) - 12} more")


def _cmdline() -> str:
    """The command line recorded in output provenance (@PG CL, metric
    headers): the serve daemon overrides it per job with the *client's*
    argv (observe.scope.command_argv) so daemon-run outputs are
    byte-identical to the same command run standalone; outside a job it is
    plain ``sys.argv``."""
    from .observe.scope import current_argv

    return " ".join(current_argv())


def _unmapped_consensus_header(read_group_id: str):
    """Unmapped-consensus output header: no reference sequences, single RG,
    @PG capturing the command line (consensus_runner.rs:115+)."""
    from .io.bam import BamHeader

    return BamHeader(
        text="@HD\tVN:1.6\tSO:unsorted\tGO:query\n"
             f"@RG\tID:{read_group_id}\tSM:sample\n"
             "@PG\tID:fgumi-tpu\tPN:fgumi-tpu\tCL:" + _cmdline() + "\n",
        ref_names=[], ref_lengths=[])


def _build_dp_mesh(devices_arg, mesh_spec=None):
    """A (dp, sp) mesh over the requested device count, or None (<=1 device).

    Shape resolution, most specific wins (docs/multi-chip.md):

    1. ``--mesh`` / ``FGUMI_TPU_MESH``: ``dpNxspM`` forces an exact shape
       (validated against the live device count with a loud error),
       ``auto`` uses every visible device, ``off`` disables the mesh.
    2. Otherwise the legacy surface: ``--devices`` (count) +
       ``FGUMI_TPU_SP`` (read-axis split; dp = n // sp, default sp=1).

    Sharding is transparent — single-device output is byte-identical
    (tests/test_mesh.py, tools/mesh_smoke.py). Raises
    :class:`~fgumi_tpu.parallel.mesh.MeshConfigError` on an unsatisfiable
    shape; commands map it to exit 2.
    """
    from .parallel.mesh import parse_mesh_spec, publish_mesh, resolve_mesh

    spec = parse_mesh_spec(mesh_spec if mesh_spec is not None
                           else os.environ.get("FGUMI_TPU_MESH"))
    explicit_off = ((mesh_spec is not None
                     or os.environ.get("FGUMI_TPU_MESH") is not None)
                    and spec is None)
    if explicit_off:
        return None
    # CPU pinned without a forced virtual device count => exactly one device:
    # skip the jax import/backend init entirely (host-engine cold-start
    # path) — unless an explicit mesh shape demands validation
    if (spec is None
            and os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"
            and "host_platform_device_count"
            not in os.environ.get("XLA_FLAGS", "")
            and not os.environ.get("FGUMI_TPU_COORDINATOR")):
        return None
    # multi-host: join the process group BEFORE the first backend touch so
    # jax.devices() below is the global device list (parallel/distributed.py)
    from .parallel.distributed import initialize_from_env
    from .parallel.mesh import MeshConfigError

    dist = initialize_from_env()
    import jax

    devs = jax.devices()
    sp_env = os.environ.get("FGUMI_TPU_SP", "1")
    sp = max(int(sp_env), 1) if sp_env.isdigit() else 1
    if dist:
        # every process must participate with all of its local devices
        # (shard_map cannot run on a mesh missing the caller's devices),
        # and sp groups must stay on one host's ICI — make_global_mesh
        # enforces both; an explicit --devices count cannot apply here
        if devices_arg not in (None, "auto") and int(devices_arg) != len(devs):
            log.warning("--devices %s ignored in multi-host mode: the mesh "
                        "uses all %d global devices", devices_arg, len(devs))
        explicit_sp = False
        if isinstance(spec, tuple):
            dp_req, sp_req = spec
            if dp_req * sp_req != len(devs):
                raise MeshConfigError(
                    f"FGUMI_TPU_MESH=dp{dp_req}xsp{sp_req} does not cover "
                    f"the {len(devs)}-device process group; multi-host "
                    "meshes always use every global device")
            sp = sp_req
            explicit_sp = True
        local = len(jax.local_devices())
        if local % sp != 0:
            if explicit_sp:
                # the --mesh contract: a forced shape is honored exactly
                # or fails loudly — never silently rebuilt with sp=1
                raise MeshConfigError(
                    f"FGUMI_TPU_MESH sp={sp} does not divide the per-host "
                    f"device count {local}; sp groups must stay on one "
                    "host's ICI")
            log.warning("FGUMI_TPU_SP=%d does not divide the per-host "
                        "device count %d; using sp=1", sp, local)
            sp = 1
        from .parallel.distributed import make_global_mesh

        mesh = make_global_mesh(sp=sp)
        publish_mesh(mesh)
        return mesh
    if spec is not None:
        mesh = resolve_mesh(devs, spec, sp_default=sp)
        if mesh is not None:
            publish_mesh(mesh)
        return mesh
    n = len(devs) if devices_arg in (None, "auto") else int(devices_arg)
    n = max(1, min(n, len(devs)))
    if n <= 1:
        return None
    if n % sp != 0:
        log.warning("FGUMI_TPU_SP=%d does not divide device count %d; "
                    "using sp=1", sp, n)
        sp = 1
    from .parallel.mesh import make_mesh

    mesh = make_mesh(devs[:n], sp=sp)
    publish_mesh(mesh)
    return mesh


def _devices_arg(s: str):
    if s == "auto":
        return s
    try:
        return int(s)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected 'auto' or an integer device count, got {s!r}")


def _parse_bool(s: str) -> bool:
    """fgbio-style boolean flag values (commands/common.rs parse_bool)."""
    if s.lower() in ("true", "t", "yes", "y", "1"):
        return True
    if s.lower() in ("false", "f", "no", "n", "0"):
        return False
    raise argparse.ArgumentTypeError(f"expected true/false, got {s!r}")


def _add_device_filter_opts(p):
    """--device-filter option group shared by the consensus commands: fuse
    the consensus-read filter into the calling command (ISSUE 11). Same
    option grammar/defaults as the standalone ``filter`` command."""
    g = p.add_argument_group(
        "fused filtering",
        "fuse `filter` into this command: consensus columns stay "
        "device-resident, per-read verdicts come from a fused mask "
        "kernel, and only surviving records are fetched + serialized "
        "(byte-identical to piping through `fgumi-tpu filter`)")
    g.add_argument("--device-filter", action="store_true",
                   help="enable the fused consensus→filter stage "
                        "(FGUMI_TPU_DEVICE_FILTER=1 is equivalent)")
    g.add_argument("--filter-min-reads", default="3",
                   help="filter --min-reads (1-3 comma-separated values)")
    g.add_argument("--filter-max-read-error-rate", default="0.025",
                   help="filter --max-read-error-rate")
    g.add_argument("--filter-max-base-error-rate", default="0.1",
                   help="filter --max-base-error-rate")
    g.add_argument("--filter-min-base-quality", type=int, default=None,
                   help="filter --min-base-quality")
    g.add_argument("--filter-min-mean-base-quality", type=float,
                   default=None, help="filter --min-mean-base-quality")
    g.add_argument("--filter-max-no-call-fraction", type=float, default=0.2,
                   help="filter --max-no-call-fraction")
    g.add_argument("--filter-by-template", nargs="?", const=True,
                   default=True, type=_parse_bool,
                   help="drop the whole template when any primary fails")


def _log_filter_stats(stats, label: str):
    log.info("%s filter: %d records -> kept %d, rejected %d, masked %d "
             "bases", label, stats.total_records, stats.passed_records,
             stats.failed_records, stats.bases_masked)
    if stats.rejection_reasons:
        log.info("rejections (filter): %s",
                 dict(stats.rejection_reasons.most_common()))


def _add_shard_opts(p):
    """Scatter sub-job option group shared by the consensus commands (and
    forwarded by `pipeline` to its simplex stage): process only shard K of
    an N-way content-hash split of the grouped input (core/sharding.py;
    docs/serving.md "Scatter/gather")."""
    g = p.add_argument_group(
        "scatter sharding",
        "run as one shard of a scattered whale job (`balance --scatter` "
        "plans these): the grouped input streams through a deterministic "
        "content-hash family filter, and a sidecar manifest records the "
        "kept families' global ordinals for the byte-deterministic gather "
        "merge")
    g.add_argument("--shard", default=None, metavar="K/N",
                   help="keep only MI families hashing to slot K of an "
                        "N-way split (0-based; e.g. 1/4)")
    g.add_argument("--shard-by", choices=["umi", "coord"], default="umi",
                   help="shard axis: umi = numeric MI value hash, coord = "
                        "both-ends template position hash (default umi)")
    g.add_argument("--shard-manifest", default=None, metavar="PATH",
                   help="write the kept-family (ordinal, MI) manifest "
                        "sidecar here (required by the gather stage)")
    g.add_argument("--pg-argv", default=None, metavar="CMDLINE",
                   help="record THIS command line (shlex-quoted) in output "
                        "provenance (@PG CL) instead of the actual argv, so "
                        "shard outputs carry the whale job's provenance and "
                        "gather merges byte-identically")


def _shard_filter_from_args(args):
    """ShardFilter from the --shard option group, or None. Raises
    ValueError (caller logs + exits 2) on a malformed spec."""
    spec_arg = getattr(args, "shard", None)
    if not spec_arg:
        return None
    from .core.sharding import ShardFilter, parse_shard_arg

    spec = parse_shard_arg(spec_arg, getattr(args, "shard_by", "umi"))
    return ShardFilter(spec, getattr(args, "shard_manifest", None))


def _add_simplex(sub):
    p = sub.add_parser("simplex", help="Call simplex consensus reads over MI groups")
    p.add_argument("-i", "--input", required=True, help="grouped BAM (MI tags)")
    p.add_argument("-o", "--output", required=True, help="output consensus BAM")
    p.add_argument("--tag", default="MI")
    p.add_argument("--read-name-prefix", default="fgumi")
    p.add_argument("--read-group-id", default="A")
    p.add_argument("--error-rate-pre-umi", type=int, default=45)
    p.add_argument("--error-rate-post-umi", type=int, default=40)
    p.add_argument("--min-input-base-quality", type=int, default=10)
    p.add_argument("--min-reads", type=int, default=1)
    p.add_argument("--max-reads", type=int, default=None)
    p.add_argument("--min-consensus-base-quality", type=int, default=40)
    p.add_argument("--trim", action="store_true")
    p.add_argument("--no-per-base-tags", action="store_true")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--allow-unmapped", action="store_true")
    p.add_argument("--rejects", default=None,
                   help="optional BAM for raw reads that contribute to no "
                        "consensus (secondary output stream)")
    p.add_argument("--consensus-call-overlapping-bases", type=_parse_bool,
                   nargs="?", const=True, default=True, metavar="true|false",
                   help="pre-correct R1/R2 insert-overlap bases before UMI "
                        "consensus (default true)")
    p.add_argument("--em-seq", action="store_true",
                   help="EM-Seq methylation-aware calling (requires --ref); "
                        "emits MM/ML and cu/ct tags")
    p.add_argument("--taps", action="store_true",
                   help="TAPS methylation-aware calling (requires --ref)")
    p.add_argument("--methylation-mode", choices=["em-seq", "taps"],
                   default=None,
                   help="reference spelling of --em-seq/--taps")
    p.add_argument("--ref", default=None,
                   help="reference FASTA (required for --em-seq/--taps)")
    p.add_argument("--batch-groups", type=int, default=2000,
                   help="MI groups per device batch (classic engine)")
    p.add_argument("--batch-bytes", type=int, default=16 << 20,
                   help="decompressed bytes per record batch (fast engine)")
    p.add_argument("--threads", type=int, default=0,
                   help=">=2 adds reader/writer threads around the "
                        "processing thread (pipeline.run_stages); 0/1 runs "
                        "inline (single-threaded fast path)")
    p.add_argument("--stats", action="store_true",
                   help="print per-stage busy/blocked timing table")
    p.add_argument("--max-memory", default="auto",
                   help="pipeline working-set budget (MiB count, human size, "
                        "or auto): governs queue depths relative to "
                        "--batch-bytes")
    p.add_argument("--classic", action="store_true",
                   help="force the per-record Python engine (the semantic "
                        "reference for the vectorized fast engine)")
    p.add_argument("--devices", default="auto", type=_devices_arg,
                   help="device count for data-parallel consensus dispatch: "
                        "auto (all visible), or an explicit N; 1 disables "
                        "sharding (fast engine only)")
    _add_device_filter_opts(p)
    _add_shard_opts(p)
    _add_pipeline_compat(p)
    p.set_defaults(func=cmd_simplex)


def cmd_simplex(args, source=None, sink=None):
    from .consensus.vanilla import VanillaConsensusCaller, VanillaOptions
    from .core.grouper import consensus_pregroup_keep, iter_mi_group_batches
    from .io.bam import BamHeader, BamReader, BamWriter

    # mirrors the reference's argument validation (simplex.rs:521-526)
    if args.min_reads < 1:
        log.error("--min-reads must be >= 1 (a value of 0 admits empty groups)")
        return 2
    if args.max_reads is not None and args.max_reads < args.min_reads:
        log.error("--max-reads (%d) must be >= --min-reads (%d)",
                  args.max_reads, args.min_reads)
        return 2

    opts = VanillaOptions(
        tag=args.tag,
        error_rate_pre_umi=args.error_rate_pre_umi,
        error_rate_post_umi=args.error_rate_post_umi,
        min_input_base_quality=args.min_input_base_quality,
        min_reads=args.min_reads,
        max_reads=args.max_reads,
        produce_per_base_tags=not args.no_per_base_tags,
        seed=args.seed,
        trim=args.trim,
        min_consensus_base_quality=args.min_consensus_base_quality,
    )
    if args.methylation_mode == "em-seq":
        args.em_seq = True
    elif args.methylation_mode == "taps":
        args.taps = True
    if args.em_seq and args.taps:
        log.error("--em-seq and --taps are mutually exclusive")
        return 2
    reference = None
    if args.em_seq or args.taps:
        if args.ref is None:
            log.error("--ref is required with --em-seq/--taps")
            return 2
        from .core.reference import ReferenceReader

        opts.methylation_mode = "em-seq" if args.em_seq else "taps"
        try:
            reference = ReferenceReader(args.ref)
        except OSError as e:
            log.error("cannot read reference %s: %s", args.ref, e)
            return 2

    from .native import batch as nb

    use_fast = nb.available() and not args.classic
    if source is not None and not use_fast:
        log.error("simplex: fused chain requires the native batch engine")
        return 2
    filter_stage = None
    filter_tap = None
    from .consensus.device_filter import device_filter_requested

    if device_filter_requested(args):
        from .consensus.device_filter import (HostFilterTap,
                                              SimplexFilterStage,
                                              filter_config_from_args)

        try:
            fcfg = filter_config_from_args(args)
        except ValueError as e:
            log.error("%s", e)
            return 2
        if use_fast:
            filter_stage = SimplexFilterStage(fcfg, opts,
                                              args.filter_by_template)
        else:
            # classic engine: fused in-process filtering via the record tap
            filter_tap = HostFilterTap(fcfg, args.filter_by_template)
    oc_caller = None
    if args.consensus_call_overlapping_bases:
        from .consensus.overlapping import OverlappingBasesConsensusCaller

        oc_caller = OverlappingBasesConsensusCaller("consensus", "consensus")
    out_header = _unmapped_consensus_header(args.read_group_id)
    try:
        shard = _shard_filter_from_args(args)
    except ValueError as e:
        log.error("%s", e)
        return 2

    t0 = time.monotonic()
    if use_fast:
        from .consensus.fast import FastSimplexCaller, resolve_chunk
        from .io.batch_reader import BamBatchReader
        from .pipeline import StageTimes, run_stages

        from .utils.memory import resolve_budget

        try:
            budget = resolve_budget(args.max_memory)
        except ValueError as e:
            log.error("%s", e)
            return 2
        # each queued item holds ~3x batch-bytes (decompressed chunk + padded
        # device gathers); two queues bound the in-flight working set
        queue_items = int(max(1, min(8, budget // (6 * args.batch_bytes))))
        stats = StageTimes()
        mesh = _build_dp_mesh(getattr(args, "devices", "auto"),
                              getattr(args, "mesh", None))
        with (BamBatchReader(args.input, target_bytes=args.batch_bytes)
              if source is None else source) as reader:
            caller = VanillaConsensusCaller(
                args.read_name_prefix, args.read_group_id, opts,
                reference=reference, ref_names=reader.header.ref_names,
                track_rejects=args.rejects is not None)
            fast = FastSimplexCaller(caller, args.tag.encode(),
                                     overlap_caller=oc_caller, mesh=mesh,
                                     filter_stage=filter_stage)
            allow_unmapped = args.allow_unmapped
            from .utils.progress import ProgressTracker

            progress = ProgressTracker("simplex")
            from .consensus.rejects import RejectsSink

            with RejectsSink(args.rejects, reader.header) as rejects:

                def _process(batch):
                    progress.add(batch.n)
                    out = fast.process_batch(batch, allow_unmapped)
                    rejects.drain(caller)
                    return out

                src = iter(reader)
                if shard is not None:
                    src = shard.wrap_batches(src)
                with (BamWriter(args.output, out_header) if sink is None
                      else sink(out_header)) as writer:
                    # device fetch + thresholds + serialize run as the
                    # parallel resolve stage (threads >= 4: a worker pool
                    # with ordered output; 2-3: on the writer thread), so
                    # they overlap the next batch's host prep
                    run_stages(
                        src, _process, writer.write_serialized,
                        threads=args.threads, queue_items=queue_items,
                        stats=stats, resolve_fn=resolve_chunk,
                        **_consensus_stage_kwargs(args))
                    for blob in fast.flush():
                        writer.write_serialized(resolve_chunk(blob))
                    rejects.drain(caller)
            progress.finish()
        n_out = caller.stats.consensus_reads
        if args.stats:
            _print_stats(stats, time.monotonic() - t0)
    else:
        from .consensus.overlapping import apply_overlapping_consensus

        with BamReader(args.input) as reader:
            caller = VanillaConsensusCaller(
                args.read_name_prefix, args.read_group_id, opts,
                reference=reference, ref_names=reader.header.ref_names,
                track_rejects=args.rejects is not None)
            from .consensus.rejects import RejectsSink

            with RejectsSink(args.rejects, reader.header) as rejects, \
                    BamWriter(args.output, out_header) as writer:
                n_out = 0
                allow_unmapped = args.allow_unmapped
                pregroup = lambda r: consensus_pregroup_keep(r.flag,
                                                             allow_unmapped)
                if shard is not None:
                    # the shard gate runs FIRST: its run tracker must see
                    # every record in stream order, including records the
                    # pregroup would drop (ordinals count ALL families)
                    base_keep = pregroup
                    pregroup = lambda r: shard.record_keep(r) and base_keep(r)
                from .consensus.device_filter import wrap_filter_writer

                writer = wrap_filter_writer(writer, filter_tap)
                for batch in iter_mi_group_batches(
                        reader, args.batch_groups, tag=args.tag.encode(),
                        record_filter=pregroup):
                    if oc_caller is not None:
                        batch = [(umi, apply_overlapping_consensus(
                            recs, oc_caller)) for umi, recs in batch]
                    for rec_bytes in caller.call_groups(batch):
                        writer.write_record_bytes(rec_bytes)
                        n_out += 1
                    rejects.drain(caller)
                if filter_tap is not None:
                    writer.finish()
    if shard is not None:
        shard.write_manifest()
        log.info("simplex shard %s: %d/%d families kept (%d records)",
                 args.shard, len(shard.manifest()), shard.families_seen,
                 shard.records_kept)
    dt = time.monotonic() - t0
    s = caller.stats
    log.info("simplex[%s]: %d input reads -> %d consensus reads in %.2fs "
             "(%.0f reads/s)", "fast" if use_fast else "classic",
             s.input_reads, n_out, dt, s.input_reads / dt if dt else 0)
    if oc_caller is not None and oc_caller.stats.overlapping_bases:
        ocs = oc_caller.stats
        log.info("overlap correction: %d overlapping bases, %d agree, %d disagree, "
                 "%d corrected", ocs.overlapping_bases, ocs.bases_agreeing,
                 ocs.bases_disagreeing, ocs.bases_corrected)
    if s.rejected:
        log.info("rejections: %s", dict(sorted(s.rejected.items())))
    kf, kt = caller.kernel.fallback_positions, caller.kernel.total_positions
    if kt:
        log.info("kernel fallback rate: %.4f%% (%d/%d positions)",
                 100.0 * kf / kt, kf, kt)
    if filter_stage is not None:
        _log_filter_stats(filter_stage.stats, "simplex")
    elif filter_tap is not None:
        _log_filter_stats(filter_tap.stats, "simplex")
    return 0


def _add_duplex(sub):
    p = sub.add_parser("duplex", help="Call duplex consensus reads over /A+/B MI groups")
    p.add_argument("-i", "--input", required=True, help="grouped BAM (MI tags with /A,/B)")
    p.add_argument("-o", "--output", required=True, help="output consensus BAM")
    p.add_argument("--read-name-prefix", default="fgumi")
    p.add_argument("--read-group-id", default="A")
    p.add_argument("--error-rate-pre-umi", type=int, default=45)
    p.add_argument("--error-rate-post-umi", type=int, default=40)
    p.add_argument("--min-input-base-quality", type=int, default=10)
    p.add_argument("--min-reads", type=int, nargs="+", default=[1],
                   help="1-3 values: total [XY [YX]] (high to low)")
    p.add_argument("--max-reads-per-strand", type=int, default=None)
    p.add_argument("--trim", action="store_true")
    p.add_argument("--no-per-base-tags", action="store_true")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--allow-unmapped", action="store_true")
    p.add_argument("--consensus-call-overlapping-bases", type=_parse_bool,
                   nargs="?", const=True, default=True, metavar="true|false",
                   help="pre-correct R1/R2 insert-overlap bases before UMI "
                        "consensus (default true)")
    p.add_argument("--rejects", default=None,
                   help="optional BAM for raw reads that contribute to no "
                        "consensus (secondary output stream; uses the classic "
                        "engine)")
    p.add_argument("--batch-molecules", type=int, default=1000)
    p.add_argument("--threads", type=int, default=0,
                   help="reader/writer threads around the vectorized engine "
                        "(0/1 = inline)")
    p.add_argument("--batch-bytes", type=int, default=16 << 20,
                   help="decompressed bytes per record batch (fast engine)")
    p.add_argument("--stats", action="store_true",
                   help="print per-stage pipeline timing table")
    p.add_argument("--classic", action="store_true",
                   help="force the per-molecule engine (no batch vectorization)")
    p.add_argument("--devices", default="auto", type=_devices_arg,
                   help="device count for data-parallel SS dispatch: auto "
                        "(all visible) or an explicit N; 1 disables sharding "
                        "(fast engine only)")
    p.add_argument("--methylation-mode", choices=["em-seq", "taps"],
                   default=None,
                   help="EM-Seq/TAPS methylation-aware duplex calling "
                        "(requires --ref); emits per-strand am/au/at + "
                        "bm/bu/bt and combined MM/ML + cu/ct tags")
    p.add_argument("--ref", default=None,
                   help="reference FASTA (required with --methylation-mode)")
    _add_device_filter_opts(p)
    _add_shard_opts(p)
    _add_pipeline_compat(p)
    p.set_defaults(func=cmd_duplex)


def cmd_duplex(args):
    from .consensus.duplex import DuplexConsensusCaller, iter_duplex_groups
    from .core.grouper import consensus_pregroup_keep
    from .io.bam import BamHeader, BamReader, BamWriter

    reference = None
    ref_names = None
    if args.methylation_mode:
        if args.ref is None:
            log.error("--ref is required with --methylation-mode")
            return 2
        from .core.reference import ReferenceReader
        from .io.bam import BamReader as _BR

        try:
            reference = ReferenceReader(args.ref)
        except OSError as e:
            log.error("cannot read reference %s: %s", args.ref, e)
            return 2
        with _BR(args.input) as _r:
            ref_names = _r.header.ref_names
    elif args.ref is not None:
        log.error("--ref requires --methylation-mode to be set")
        return 2
    try:
        caller_kw = dict(
            min_reads=args.min_reads,
            min_input_base_quality=args.min_input_base_quality,
            produce_per_base_tags=not args.no_per_base_tags, trim=args.trim,
            max_reads_per_strand=args.max_reads_per_strand,
            error_rate_pre_umi=args.error_rate_pre_umi,
            error_rate_post_umi=args.error_rate_post_umi, seed=args.seed,
            track_rejects=args.rejects is not None,
            methylation_mode=args.methylation_mode,
            reference=reference, ref_names=ref_names)
        caller = DuplexConsensusCaller(args.read_name_prefix,
                                       args.read_group_id, **caller_kw)
    except ValueError as e:
        log.error("%s", e)
        return 2

    from .native import batch as nb

    # the vectorized engine cannot express quality trimming; rejects tracking
    # routes every molecule through the slow fallback, so use the classic
    # loop directly there
    use_fast = (nb.available() and not getattr(args, "classic", False)
                and not args.trim and args.rejects is None)
    from .consensus.device_filter import make_filter_tap, wrap_filter_writer

    try:
        filter_tap = make_filter_tap(args)
    except ValueError as e:
        log.error("%s", e)
        return 2
    try:
        shard = _shard_filter_from_args(args)
    except ValueError as e:
        log.error("%s", e)
        return 2
    t0 = time.monotonic()
    allow_unmapped = args.allow_unmapped
    oc_caller = None
    if args.consensus_call_overlapping_bases:
        from .consensus.overlapping import (OverlappingBasesConsensusCaller,
                                            apply_overlapping_consensus)
        oc_caller = OverlappingBasesConsensusCaller("consensus", "consensus")
    out_header = _unmapped_consensus_header(args.read_group_id)
    if use_fast:
        from .consensus.fast import resolve_chunk
        from .consensus.fast_duplex import FastDuplexCaller
        from .io.batch_reader import BamBatchReader
        from .pipeline import StageTimes, run_stages
        from .utils.progress import ProgressTracker

        stats_t = StageTimes()
        mesh = _build_dp_mesh(getattr(args, "devices", "auto"),
                              getattr(args, "mesh", None))
        fast = FastDuplexCaller(caller, b"MI", overlap_caller=oc_caller,
                                mesh=mesh)
        # inline mode: resolve_chunk runs on this same thread in FIFO order,
        # so the SS device round trip can defer into the double-buffer
        # window (threaded modes run resolve on another thread and stage-2
        # mutates shared stats/ordinals — keep those synchronous)
        fast.defer_device = args.threads <= 1
        progress = ProgressTracker("duplex")
        with BamBatchReader(args.input,
                            target_bytes=args.batch_bytes) as reader:

            def _process(batch):
                progress.add(batch.n)
                return fast.process_batch(batch, allow_unmapped)

            src = iter(reader)
            if shard is not None:
                src = shard.wrap_batches(src)
            with BamWriter(args.output, out_header) as writer:
                writer = wrap_filter_writer(writer, filter_tap)
                run_stages(
                    src, _process, writer.write_serialized,
                    threads=args.threads, stats=stats_t,
                    resolve_fn=resolve_chunk, **_consensus_stage_kwargs(args))
                for blob in fast.flush():
                    writer.write_serialized(resolve_chunk(blob))
                if filter_tap is not None:
                    writer.finish()
        progress.finish()
        n_out = caller.stats.consensus_reads
        if args.stats:
            _print_stats(stats_t, time.monotonic() - t0)
    else:
        with BamReader(args.input) as reader:
            from .consensus.rejects import RejectsSink

            with RejectsSink(args.rejects, reader.header) as rejects, \
                    BamWriter(args.output, out_header) as writer:
                writer = wrap_filter_writer(writer, filter_tap)
                n_out = 0
                pregroup = lambda r: consensus_pregroup_keep(r.flag,
                                                             allow_unmapped)
                if shard is not None:
                    # shard gate first: it must see every record in stream
                    # order (same contract as the simplex classic path)
                    base_keep = pregroup
                    pregroup = lambda r: shard.record_keep(r) and base_keep(r)
                batch = []
                for group in iter_duplex_groups(reader,
                                                record_filter=pregroup):
                    if oc_caller is not None:
                        base_mi, a_recs, b_recs = group
                        # skip single-strand groups: no duplex possible anyway
                        # (duplex.rs:496-499 has_both_strands_raw gate)
                        if a_recs and b_recs:
                            group = (base_mi,
                                     apply_overlapping_consensus(a_recs,
                                                                 oc_caller),
                                     apply_overlapping_consensus(b_recs,
                                                                 oc_caller))
                    batch.append(group)
                    if len(batch) >= args.batch_molecules:
                        for rec_bytes in caller.call_groups(batch):
                            writer.write_record_bytes(rec_bytes)
                            n_out += 1
                        rejects.drain(caller)
                        batch = []
                if batch:
                    for rec_bytes in caller.call_groups(batch):
                        writer.write_record_bytes(rec_bytes)
                        n_out += 1
                    rejects.drain(caller)
                if filter_tap is not None:
                    writer.finish()
    if shard is not None:
        shard.write_manifest()
        log.info("duplex shard %s: %d/%d families kept (%d records)",
                 args.shard, len(shard.manifest()), shard.families_seen,
                 shard.records_kept)
    dt = time.monotonic() - t0
    s = caller.merged_stats()
    log.info("duplex[%s]: %d input reads -> %d consensus reads in %.2fs "
             "(%.0f reads/s)", "fast" if use_fast else "classic",
             s.input_reads, n_out, dt, s.input_reads / dt if dt else 0)
    if oc_caller is not None and oc_caller.stats.overlapping_bases:
        ocs = oc_caller.stats
        log.info("overlap correction: %d overlapping bases, %d agree, %d disagree, "
                 "%d corrected", ocs.overlapping_bases, ocs.bases_agreeing,
                 ocs.bases_disagreeing, ocs.bases_corrected)
    if s.rejected:
        log.info("rejections: %s", dict(sorted(s.rejected.items())))
    if filter_tap is not None:
        _log_filter_stats(filter_tap.stats, "duplex")
    return 0


def _add_duplex_metrics(sub):
    p = sub.add_parser("duplex-metrics",
                       help="Collect QC metrics for duplex sequencing (grouped BAM)")
    p.add_argument("-i", "--input", required=True,
                   help="grouped BAM (MI tags with /A,/B, template-coordinate order)")
    p.add_argument("-o", "--output", required=True,
                   help="output path prefix for metric files")
    p.add_argument("--intervals", default=None,
                   help="BED or Picard interval list restricting analysis")
    p.add_argument("--min-ab-reads", type=int, default=1,
                   help="min AB-strand reads for a family to count as duplex")
    p.add_argument("--min-ba-reads", type=int, default=1,
                   help="min BA-strand reads for a family to count as duplex")
    p.add_argument("--duplex-umi-counts", action="store_true",
                   help="also write duplex UMI pair counts (memory intensive)")
    p.add_argument("--description", default=None,
                   help="accepted for compatibility: the reference uses this "
                        "only to title its optional R plot PDFs, which this "
                        "build does not generate (metrics TSVs carry no "
                        "title)")
    p.set_defaults(func=_cmd_duplex_metrics)


def _cmd_duplex_metrics(args):
    from .commands.duplex_metrics import run_duplex_metrics

    return run_duplex_metrics(args)


def _add_simplex_metrics(sub):
    p = sub.add_parser("simplex-metrics",
                       help="Collect QC metrics for simplex sequencing (grouped BAM)")
    p.add_argument("-i", "--input", required=True,
                   help="grouped BAM (MI tags, template-coordinate order)")
    p.add_argument("-o", "--output", required=True,
                   help="output path prefix for metric files")
    p.add_argument("--intervals", default=None,
                   help="BED or Picard interval list restricting analysis")
    p.add_argument("--min-reads", type=int, default=1,
                   help="min family size counted toward ss_consensus_families")
    p.add_argument("--description", default=None,
                   help="accepted for compatibility: the reference uses this "
                        "only to title its optional R plot PDFs, which this "
                        "build does not generate (metrics TSVs carry no "
                        "title)")
    p.set_defaults(func=_cmd_simplex_metrics)


def _cmd_simplex_metrics(args):
    from .commands.simplex_metrics import run_simplex_metrics

    return run_simplex_metrics(args)


def _add_review(sub):
    p = sub.add_parser("review",
                       help="Extract data to review variant calls from "
                            "consensus reads")
    p.add_argument("-i", "--input", required=True,
                   help="VCF or interval list of variant positions")
    p.add_argument("-c", "--consensus-bam", required=True,
                   help="coordinate-sorted consensus BAM")
    p.add_argument("-g", "--grouped-bam", required=True,
                   help="coordinate-sorted grouped raw-read BAM")
    p.add_argument("-r", "--ref", default=None,
                   help="reference FASTA (required for interval-list input)")
    p.add_argument("-o", "--output", required=True,
                   help="output prefix (.consensus.bam/.grouped.bam/.txt)")
    p.add_argument("-s", "--sample", default=None,
                   help="sample name for VCF genotype extraction")
    p.add_argument("-N", "--ignore-ns", type=_parse_bool, nargs="?",
                   const=True, default=False, metavar="true|false",
                   help="ignore N bases in consensus reads")
    p.add_argument("-m", "--maf", type=float, default=0.05,
                   help="only review variants at or below this MAF")
    p.set_defaults(func=_cmd_review)


def _cmd_review(args):
    from .commands.review import run_review

    return run_review(args)


def _add_compare(sub):
    p = sub.add_parser("compare", help="Compare files for testing and validation")
    ps = p.add_subparsers(dest="compare_mode", required=True)
    b = ps.add_parser("bams", help="Compare two BAMs (exit 1 on mismatch)")
    b.add_argument("-a", required=True, help="first BAM")
    b.add_argument("-b", required=True, help="second BAM")
    b.add_argument("--mode", choices=["content", "grouping"], default=None,
                   help="content: exact record compare; grouping: MI-invariant "
                        "molecule equivalence (default: content, or the "
                        "--command preset's mode)")
    b.add_argument("--command", default=None, dest="preset",
                   choices=["extract", "zipper", "sort", "correct", "dedup",
                            "clip", "filter", "group", "simplex", "duplex",
                            "codec"],
                   help="canonical mode/ignore-order defaults for comparing "
                        "the output of one pipeline stage (reference "
                        "compare/bams.rs CommandPreset): group -> grouping "
                        "mode; sort -> the sort-verify engine; everything "
                        "else -> exact content. Explicit --mode/"
                        "--ignore-order override the preset")
    b.add_argument("--ignore-order", type=_parse_bool, nargs="?",
                   const=True, default=None,
                   help="content mode: compare as multisets (true/false; "
                        "an explicit value overrides a --command preset in "
                        "either direction)")
    b.add_argument("--ignore-tags", nargs="*", default=[],
                   help="tags excluded from comparison")
    b.add_argument("--tag", default="MI", help="grouping tag (grouping mode)")
    b.add_argument("--verify-sort", action="store_true",
                   help="also verify each input satisfies its header's "
                        "declared sort order (sort_verify engine)")
    b.set_defaults(func=_cmd_compare_bams)
    m = ps.add_parser("metrics", help="Compare two metric TSVs (exit 1 on mismatch)")
    m.add_argument("-a", required=True)
    m.add_argument("-b", required=True)
    m.add_argument("--float-tolerance", type=float, default=1e-5)
    m.set_defaults(func=_cmd_compare_metrics)


def _cmd_compare_bams(args):
    from .commands.compare import run_compare_bams

    return run_compare_bams(args)


def _cmd_compare_metrics(args):
    from .commands.compare import run_compare_metrics

    return run_compare_metrics(args)


def _add_codec(sub):
    p = sub.add_parser(
        "codec",
        help="Call CODEC consensus (one read-pair covers both strands)")
    p.add_argument("-i", "--input", required=True,
                   help="grouped BAM (MI tags, no /A,/B suffixes)")
    p.add_argument("-o", "--output", required=True, help="output consensus BAM")
    p.add_argument("-r", "--rejects", default=None,
                   help="optional BAM for rejected records")
    p.add_argument("--tag", default="MI")
    p.add_argument("--read-name-prefix", default="fgumi")
    p.add_argument("--read-group-id", default="A")
    p.add_argument("--error-rate-pre-umi", type=int, default=45)
    p.add_argument("--error-rate-post-umi", type=int, default=40)
    p.add_argument("--min-input-base-quality", type=int, default=10)
    p.add_argument("-M", "--min-reads", type=int, default=1,
                   help="min read pairs per strand")
    p.add_argument("--max-reads", type=int, default=None,
                   help="max read pairs per strand (downsample)")
    p.add_argument("-d", "--min-duplex-length", type=int, default=1)
    p.add_argument("--single-strand-qual", type=int, default=None)
    p.add_argument("-Q", "--outer-bases-qual", type=int, default=None)
    p.add_argument("-O", "--outer-bases-length", type=int, default=5)
    p.add_argument("-x", "--max-duplex-disagreement-rate", type=float, default=1.0)
    p.add_argument("-X", "--max-duplex-disagreements", type=int, default=None)
    p.add_argument("--cell-tag", default=None, help="cell barcode tag (e.g. CB)")
    p.add_argument("--per-base-tags", action="store_true",
                   help="emit ad/bd/ae/be/ac/bc/aq/bq tags")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--batch-groups", type=int, default=1000)
    p.add_argument("--batch-bytes", type=int, default=16 << 20,
                   help="decompressed bytes per record batch (fast engine)")
    p.add_argument("--threads", type=int, default=0,
                   help="reader/writer threads around the batch engine "
                        "(0/1 = inline)")
    p.add_argument("--stats", action="store_true",
                   help="print per-stage pipeline timing table")
    p.add_argument("--classic", action="store_true",
                   help="force the per-molecule engine (no batch vectorization)")
    p.add_argument("--devices", default="auto", type=_devices_arg,
                   help="device count for data-parallel SS dispatch: auto "
                        "(all visible) or an explicit N; 1 disables sharding "
                        "(batch engine only)")
    _add_device_filter_opts(p)
    _add_pipeline_compat(p)
    p.set_defaults(func=cmd_codec)


def cmd_codec(args):
    from .consensus.codec import CodecConsensusCaller, CodecOptions
    from .core.grouper import iter_mi_group_batches
    from .io.bam import BamHeader, BamReader, BamWriter

    if args.min_reads < 1:
        log.error("--min-reads must be >= 1")
        return 2
    if args.max_reads is not None and args.max_reads < args.min_reads:
        log.error("--max-reads (%d) must be >= --min-reads (%d)",
                  args.max_reads, args.min_reads)
        return 2

    opts = CodecOptions(
        min_input_base_quality=args.min_input_base_quality,
        error_rate_pre_umi=args.error_rate_pre_umi,
        error_rate_post_umi=args.error_rate_post_umi,
        min_reads_per_strand=args.min_reads,
        max_reads_per_strand=args.max_reads,
        min_duplex_length=args.min_duplex_length,
        single_strand_qual=args.single_strand_qual,
        outer_bases_qual=args.outer_bases_qual,
        outer_bases_length=args.outer_bases_length,
        max_duplex_disagreements=args.max_duplex_disagreements,
        max_duplex_disagreement_rate=args.max_duplex_disagreement_rate,
        cell_tag=args.cell_tag,
        produce_per_base_tags=args.per_base_tags,
        seed=args.seed)
    caller = CodecConsensusCaller(args.read_name_prefix, args.read_group_id, opts,
                                  track_rejects=args.rejects is not None)

    from .native import batch as nbat

    # the batch engine shares the classic caller's stage 2 but cannot feed
    # the rejects stream (records stay array-resident); rejects -> classic
    use_fast = (nbat.available() and args.rejects is None
                and not getattr(args, "classic", False))
    from .consensus.device_filter import make_filter_tap, wrap_filter_writer

    try:
        filter_tap = make_filter_tap(args)
    except ValueError as e:
        log.error("%s", e)
        return 2
    if not use_fast and (args.threads or args.stats):
        log.info("--threads/--stats apply to the batch engine only; this "
                 "run uses the classic per-molecule engine (%s)",
                 "--rejects set" if args.rejects is not None
                 else "--classic" if getattr(args, "classic", False)
                 else "native runtime unavailable")
    t0 = time.monotonic()
    if use_fast:
        from .consensus.fast_codec import FastCodecCaller
        from .io.batch_reader import BamBatchReader
        from .pipeline import StageTimes, run_stages
        from .utils.progress import ProgressTracker

        stats_t = StageTimes()
        progress = ProgressTracker("codec")
        mesh = _build_dp_mesh(getattr(args, "devices", "auto"),
                              getattr(args, "mesh", None))
        with BamBatchReader(args.input,
                            target_bytes=args.batch_bytes) as reader:
            out_header = _unmapped_consensus_header(args.read_group_id)
            fast = FastCodecCaller(caller, args.tag.encode(), mesh=mesh)

            def _process(batch):
                progress.add(batch.n)
                return fast.process_batch(batch)

            with BamWriter(args.output, out_header) as writer:
                writer = wrap_filter_writer(writer, filter_tap)
                run_stages(iter(reader), _process, writer.write_serialized,
                           threads=args.threads, stats=stats_t,
                           **_stage_kwargs(args))
                for chunk in fast.flush():
                    writer.write_serialized(chunk)
                if filter_tap is not None:
                    writer.finish()
                n_out = caller.stats.consensus_reads_generated
        progress.finish()
        if args.stats:
            _print_stats(stats_t, time.monotonic() - t0)
    else:
        if nbat.available():
            from .io.batch_reader import BatchedRecordReader as _CodecReader
        else:
            _CodecReader = BamReader
        with _CodecReader(args.input) as reader:
            out_header = _unmapped_consensus_header(args.read_group_id)
            rejects_writer = None
            if args.rejects is not None:
                # rejects keep the input header (raw RG/PG/contig metadata
                # preserved)
                rejects_writer = BamWriter(args.rejects, reader.header)
            ok = False
            try:
                with BamWriter(args.output, out_header) as writer:
                    writer = wrap_filter_writer(writer, filter_tap)
                    n_out = 0
                    for batch in iter_mi_group_batches(
                            reader, args.batch_groups, tag=args.tag.encode()):
                        for rec_bytes in caller.call_groups(batch):
                            writer.write_record_bytes(rec_bytes)
                            n_out += 1
                        if rejects_writer is not None \
                                and caller.rejected_reads:
                            for rec in caller.rejected_reads:
                                rejects_writer.write_record(rec)
                            caller.rejected_reads.clear()
                    if filter_tap is not None:
                        writer.finish()
                ok = True
            finally:
                if rejects_writer is not None:
                    (rejects_writer.close if ok
                     else rejects_writer.discard)()
    dt = time.monotonic() - t0
    s = caller.stats
    log.info("codec: %d input reads -> %d consensus reads in %.2fs (%.0f reads/s)",
             s.total_input_reads, n_out, dt,
             s.total_input_reads / dt if dt else 0)
    if s.rejection_reasons:
        log.info("rejections: %s", dict(sorted(s.rejection_reasons.items())))
    if s.consensus_duplex_bases_emitted:
        log.info("duplex disagreement rate: %.6f (%d/%d)",
                 s.duplex_disagreement_rate(), s.duplex_disagreement_base_count,
                 s.consensus_duplex_bases_emitted)
    if filter_tap is not None:
        _log_filter_stats(filter_tap.stats, "codec")
    return 0


def _add_group(sub):
    p = sub.add_parser("group", help="Group reads by UMI (GroupReadsByUmi)")
    p.add_argument("-i", "--input", required=True,
                   help="template-coordinate sorted BAM with RX tags")
    p.add_argument("-o", "--output", required=True)
    p.add_argument("--max-memory", default="auto",
                   help="pipeline working-set budget (MiB count, human "
                        "size, or auto): bytes-in-flight bound on queued "
                        "batches in threaded runs")
    p.add_argument("-s", "--strategy", default="adjacency",
                   choices=["identity", "edit", "adjacency", "paired"])
    p.add_argument("-e", "--edits", type=int, default=1)
    p.add_argument("-t", "--raw-tag", default="RX")
    p.add_argument("-T", "--assign-tag", default="MI")
    p.add_argument("-m", "--min-map-q", type=int, default=1)
    p.add_argument("-n", "--include-non-pf-reads", action="store_true")
    p.add_argument("--min-umi-length", type=int, default=None)
    p.add_argument("--no-umi", action="store_true")
    p.add_argument("--allow-unmapped", action="store_true")
    p.add_argument("--index-threshold", type=int, default=None,
                   help="minimum distinct UMIs per group before the indexed "
                        "candidate search (pigeonhole/BK-tree) replaces the "
                        "dense pairwise scan; 0 = always dense. Default is "
                        "measured for the vectorized scan (8192)")
    p.add_argument("--parallel-group-min-templates", default=None,
                   metavar="N|auto",
                   help="accepted for compatibility: this engine "
                        "auto-selects its vectorized/device assigner by "
                        "group size, so the parallel-assigner cutover knob "
                        "has no separate schedule to tune")
    p.add_argument("-f", "--family-size-histogram", default=None,
                   help="optional TSV of the family size distribution "
                        "(fgbio format: count/fraction/cumulative)")
    p.add_argument("-g", "--grouping-metrics", default=None,
                   help="optional TSV of UMI grouping metrics (fgbio's "
                        "5-column UmiGroupingMetric)")
    p.add_argument("-M", "--metrics", default=None, metavar="PREFIX",
                   help="write PREFIX.family_sizes.txt, "
                        "PREFIX.grouping_metrics.txt and "
                        "PREFIX.position_group_sizes.txt")
    p.add_argument("--family-size-out", default=None,
                   help="deprecated: plain size/count TSV (use "
                        "--family-size-histogram)")
    p.add_argument("--threads", type=int, default=0,
                   help="reader/writer threads around the batch engine "
                        "(0/1 = inline)")
    p.add_argument("--stats", action="store_true",
                   help="print per-stage pipeline timing table")
    p.add_argument("--classic", action="store_true",
                   help="force the per-template engine (no batch vectorization)")
    _add_pipeline_compat(p)
    p.set_defaults(func=cmd_group)


def cmd_group(args, source=None, sink=None):
    from .commands.group import run_group
    from .io.bam import BamHeader, BamReader, BamWriter

    from .core.template import is_query_grouped, is_template_coordinate_sorted

    from .native import batch as nbat

    if getattr(args, "index_threshold", None) is not None:
        from .umi.assigners import set_index_threshold

        set_index_threshold(args.index_threshold)
    use_fast = nbat.available() and not getattr(args, "classic", False)
    if source is not None and not use_fast:
        log.error("group: fused chain requires the native batch engine")
        return 2
    t0 = time.monotonic()
    if source is not None:
        reader = source
    elif use_fast:
        from .io.batch_reader import BamBatchReader

        reader = BamBatchReader(args.input)
    else:
        reader = BamReader(args.input)
    with reader:
        hdr_text = reader.header.text
        # classify_input_ordering (group.rs:470-500): template-coordinate, or
        # query-grouped under --allow-unmapped; anything else is unusable.
        if not is_template_coordinate_sorted(hdr_text):
            if not (args.allow_unmapped and is_query_grouped(hdr_text)):
                log.error(
                    "group requires template-coordinate sorted input (header must "
                    "advertise SS:template-coordinate); sort with "
                    "`fgumi-tpu sort --order template-coordinate` first. "
                    "--allow-unmapped additionally accepts query-grouped input "
                    "(GO:query / SO:queryname).")
                return 2
        out_header = BamHeader(text=hdr_text, ref_names=reader.header.ref_names,
                               ref_lengths=reader.header.ref_lengths)
        # the ValueError catch wraps the writer context (not the other way
        # around) so a mid-run failure exits through writer.__exit__ with
        # the exception in hand: the output is discarded/aborted, never
        # committed — in the fused chain a clean close here would hand the
        # downstream stage a valid-looking EOF of a truncated stream
        try:
            with (BamWriter(args.output, out_header) if sink is None
                  else sink(out_header)) as writer:
                if use_fast:
                    from .commands.fast_group import FastGrouper
                    from .umi.assigners import make_assigner

                    if args.no_umi and args.strategy == "paired":
                        raise ValueError(
                            "--no-umi cannot be combined with the paired "
                            "strategy")
                    from .pipeline import StageTimes, run_stages
                    from .utils.progress import ProgressTracker

                    stats_t = StageTimes()
                    progress = ProgressTracker("group")
                    grouper = FastGrouper(
                        reader.header, make_assigner(args.strategy, args.edits),
                        umi_tag=args.raw_tag.encode(),
                        assigned_tag=args.assign_tag.encode(),
                        min_mapq=args.min_map_q,
                        include_non_pf=args.include_non_pf_reads,
                        min_umi_length=args.min_umi_length,
                        no_umi=args.no_umi,
                        allow_unmapped=args.allow_unmapped)

                    def _process(batch):
                        progress.add(batch.n)
                        return grouper.process_batch(batch)

                    try:
                        run_stages(iter(reader), _process,
                                   writer.write_serialized,
                                   threads=args.threads, stats=stats_t,
                                   **_stage_kwargs(args))
                        for chunk in grouper.flush():
                            writer.write_serialized(chunk)
                    finally:
                        # failure reports still carry records.group
                        progress.finish()
                    result = grouper.result()
                    if getattr(args, "stats", False):
                        _print_stats(stats_t)
                else:
                    result = run_group(
                        reader, writer, strategy=args.strategy,
                        edits=args.edits, umi_tag=args.raw_tag.encode(),
                        assigned_tag=args.assign_tag.encode(),
                        min_mapq=args.min_map_q,
                        include_non_pf=args.include_non_pf_reads,
                        min_umi_length=args.min_umi_length,
                        no_umi=args.no_umi,
                        allow_unmapped=args.allow_unmapped)
        except ValueError as e:
            log.error("%s", e)
            return 2
    dt = time.monotonic() - t0
    log.info("group: wrote %d records in %.2fs; filter=%s", result["records_out"],
             dt, result["filter"])
    if args.family_size_out:
        from .commands.dedup import write_family_size_histogram

        write_family_size_histogram(result["family_sizes"],
                                    args.family_size_out)
    if (args.family_size_histogram or args.grouping_metrics or args.metrics):
        from .metrics import (size_distribution_fields,
                              size_distribution_rows,
                              umi_grouping_metrics_row, write_metrics)

        dist_fields = size_distribution_fields
        fam_rows = size_distribution_rows(result["family_sizes"],
                                          "family_size")
        group_row = [umi_grouping_metrics_row(result["filter"])]
        if args.family_size_histogram:
            write_metrics(args.family_size_histogram, fam_rows,
                          fieldnames=dist_fields("family_size"))
        if args.grouping_metrics:
            write_metrics(args.grouping_metrics, group_row)
        if args.metrics:
            write_metrics(args.metrics + ".family_sizes.txt", fam_rows,
                          fieldnames=dist_fields("family_size"))
            write_metrics(args.metrics + ".grouping_metrics.txt", group_row)
            write_metrics(
                args.metrics + ".position_group_sizes.txt",
                size_distribution_rows(result["position_group_sizes"],
                                       "position_group_size"),
                fieldnames=dist_fields("position_group_size"))
    return 0


def _add_sort(sub):
    p = sub.add_parser("sort", help="Sort a BAM (coordinate/queryname/template-coordinate)")
    p.add_argument("-i", "--input", required=True)
    p.add_argument("-o", "--output", default=None,
                   help="output BAM (not needed with --verify)")
    p.add_argument("--verify", nargs="?", const=True, default=False,
                   type=_parse_bool,
                   help="verify the input satisfies --order (no output "
                        "written); exits non-zero on the first out-of-order "
                        "record")
    p.add_argument("--sort-threads", type=int, default=None,
                   help="threads for the sort/spill phase (defaults to "
                        "--threads; scheduling only, output byte-identical)")
    p.add_argument("--merge-threads", type=int, default=None,
                   help="threads for the merge/output phase (defaults to "
                        "--threads; scheduling only, output byte-identical)")
    p.add_argument("--max-temp-files", type=int, default=None,
                   help="advisory cap on spill runs (the k-way merge here "
                        "streams any run count; values < 2 are rejected)")
    p.add_argument("--temp-codec", default="deflate",
                   help="spill codec: deflate (libdeflate). zstd is not "
                        "available in this build and is rejected loudly")
    p.add_argument("--temp-compression", type=int, default=1,
                   help="accepted for compatibility (0-9 validated): spill "
                        "frames here always use deflate level 1, the "
                        "measured throughput/size sweet spot for "
                        "merge-once temporaries")
    p.add_argument("--key-types", default="full",
                   help="sort-key lanes: full (default; library+MI lanes, "
                        "the layout this engine always builds). Lane "
                        "subsetting is not supported here — any other value "
                        "is rejected loudly rather than silently changing "
                        "grouping semantics")
    p.add_argument("--threads", type=int, default=0,
                   help="N > 1 runs N-1 background spill workers: Phase-1 "
                        "sort/compress/write overlaps ingest "
                        "(worker_pool.rs analog; needs real cores to help)")
    p.add_argument("--order", default="template-coordinate",
                   choices=["coordinate", "queryname", "template-coordinate"])
    p.add_argument("--subsort", default="natural", choices=["natural", "lex"],
                   help="queryname comparator")
    p.add_argument("--max-memory", default="auto",
                   help="sort accumulation budget: MiB count, human size "
                        "(512M, 2G), or auto (cgroup-aware available minus "
                        "reserve)")
    p.add_argument("--memory-reserve", default="1G",
                   help="held back from auto-detected memory")
    p.add_argument("--max-records-in-ram", type=int, default=None,
                   help="optional additional record-count cap on the in-RAM "
                        "chunk (the primary budget is --max-memory bytes)")
    p.add_argument("--tmp-dir", default=None)
    p.add_argument("--write-index", type=_parse_bool, nargs="?", const=True,
                   default=True, metavar="true|false",
                   help="write an index alongside coordinate-sorted output")
    p.add_argument("--index-format", default="bai", choices=["bai", "csi"],
                   help="index flavor (csi handles references > 512 Mbp)")
    _add_pipeline_compat(p)
    p.set_defaults(func=cmd_sort)


def _rewrite_hd(text, so, go, ss):
    lines = text.splitlines()
    fields = {"VN": "1.6"}
    rest = []
    for line in lines:
        if line.startswith("@HD"):
            fields.update(f.split(":", 1) for f in line.split("\t")[1:] if ":" in f)
        else:
            rest.append(line)
    fields["SO"] = so
    fields.pop("GO", None)
    fields.pop("SS", None)
    if go:
        fields["GO"] = go
    if ss:
        fields["SS"] = ss
    hd = "@HD\t" + "\t".join(f"{k}:{v}" for k, v in fields.items())
    return "\n".join([hd] + rest) + "\n"


def cmd_sort(args, source=None, sink=None):
    from .io.bam import FLAG_UNMAPPED, BamHeader, BamReader, BamWriter, RawRecord
    from .sort.external import header_tags_for_order
    from .sort.keys import make_key_bytes_fn
    from .utils.memory import resolve_budget

    from .utils.memory import parse_size

    if args.key_types.strip().lower() not in ("full", "library,mi",
                                              "library mi", "mi,library"):
        log.error("--key-types %s: this engine always builds the full "
                  "library+MI key layout; lane subsetting would silently "
                  "change grouping semantics and is not supported",
                  args.key_types)
        return 2
    if args.temp_codec.strip().lower() not in ("deflate", "libdeflate"):
        log.error("--temp-codec %s: only deflate (libdeflate) is available "
                  "in this build (zstd is not in the image)", args.temp_codec)
        return 2
    if not 0 <= args.temp_compression <= 9:
        log.error("--temp-compression must be 0-9")
        return 2
    if args.max_temp_files is not None and args.max_temp_files < 2:
        log.error("--max-temp-files must be >= 2")
        return 2
    if args.verify:
        # verify-only mode (sort.rs:207-212): key monotonicity against the
        # REQUESTED --order over the packed byte keys, no output written
        with BamReader(args.input) as reader:
            key_fn = make_key_bytes_fn(args.order, reader.header,
                                       args.subsort)
            prev = b""
            for i, rec in enumerate(reader):
                k = key_fn(rec)
                if k < prev:
                    log.error("sort --verify: record %d out of %s order",
                              i, args.order)
                    return 1
                prev = k
        log.info("sort --verify: input satisfies %s order", args.order)
        return 0
    if args.output is None:
        log.error("-o/--output is required (unless --verify)")
        return 2
    if args.sort_threads is not None or args.merge_threads is not None:
        # scheduling-only knobs: this engine's worker pool serves both
        # phases, so the wider of the two sizes it
        args.threads = max(args.threads, args.sort_threads or 0,
                           args.merge_threads or 0)
    try:
        budget = resolve_budget(args.max_memory, parse_size(args.memory_reserve))
    except ValueError as e:
        log.error("%s", e)
        return 2
    if source is not None:
        return _cmd_sort_chain(args, source, sink, budget)
    t0 = time.monotonic()
    with BamReader(args.input) as reader:
        key_fn = make_key_bytes_fn(args.order, reader.header, args.subsort)
        so, go, ss = header_tags_for_order(args.order, args.subsort)
        out_header = BamHeader(
            text=_rewrite_hd(reader.header.text, so, go, ss),
            ref_names=reader.header.ref_names, ref_lengths=reader.header.ref_lengths)
        bai = None
        if args.order == "coordinate" and args.write_index:
            from .io.bai import BaiBuilder, CsiBuilder, depth_for_length

            if args.index_format == "csi":
                # depth sized to the longest reference (htslib rule) so
                # >512 Mbp chromosomes get valid bins
                bai = CsiBuilder(
                    len(reader.header.ref_names),
                    depth=depth_for_length(
                        max(reader.header.ref_lengths, default=0)))
            else:
                bai = BaiBuilder(len(reader.header.ref_names))
        from .utils.progress import ProgressTracker

        progress = ProgressTracker("sort")
        wprogress = ProgressTracker("sort-write")
        from .sort.keys import make_batch_keys_fn

        batch_keys_fn = make_batch_keys_fn(args.order, reader.header,
                                           args.subsort)
        from .sort.external import NativeExternalSorter, create_sorter

        # --threads N > 1: N-1 background spill workers overlap Phase-1
        # sort/compress/write with ingest (worker_pool.rs analog)
        spill_workers = max(getattr(args, "threads", 0) - 1, 0)
        with create_sorter(key_fn, max_bytes=budget, tmp_dir=args.tmp_dir,
                           max_records=args.max_records_in_ram,
                           spill_workers=spill_workers) as sorter:
            if isinstance(sorter, NativeExternalSorter) \
                    and batch_keys_fn is not None:
                # whole-batch path: native key extraction + two pool memcpys
                # per batch, native sort/spill/merge
                from .io.batch_reader import BamBatchReader

                with BamBatchReader(args.input) as br:
                    for b in br:
                        sorter.add_record_batch(b, batch_keys_fn)
                        progress.add(b.n)
            elif batch_keys_fn is not None:
                from .sort.keys import iter_keyed_records

                add_entry = sorter.add_entry
                for key, data in iter_keyed_records(args.input, batch_keys_fn,
                                                    progress.add):
                    add_entry(key, data)
            else:
                for rec in reader:
                    sorter.add(rec)
                    progress.add()
            progress.finish()
            with BamWriter(args.output, out_header) as writer:
                if bai is None and isinstance(sorter, NativeExternalSorter):
                    for blob, lens in sorter.sorted_chunks_with_lens():
                        writer.write_serialized(blob)
                        wprogress.add(len(lens))
                elif bai is None:
                    for data in sorter.sorted_records():
                        writer.write_record_bytes(data)
                        wprogress.add()
                elif isinstance(sorter, NativeExternalSorter):
                    # indexed blob path: one multi-block write per chunk,
                    # virtual offsets reconstructed from the block table,
                    # record geometry decoded natively
                    import numpy as np

                    from .native import batch as nbat

                    for blob, lens in sorter.sorted_chunks_with_lens():
                        starts = np.zeros(len(lens) + 1, dtype=np.int64)
                        np.cumsum(lens, out=starts[1:])
                        voffs = writer.write_indexed(blob, starts)
                        buf = np.frombuffer(blob, dtype=np.uint8)
                        f = nbat.decode_fields(buf, starts[:-1])
                        cigar_off = (f["data_off"] + 32
                                     + f["l_read_name"].astype(np.int64))
                        ends = nbat.ref_spans(buf, cigar_off, f["n_cigar"],
                                              f["pos"])
                        bai.add_many(
                            f["ref_id"], f["pos"], ends, voffs[:-1],
                            voffs[1:], (f["flag"] & FLAG_UNMAPPED) == 0)
                        wprogress.add(len(lens))
                else:
                    for data in sorter.sorted_records():
                        rec = RawRecord(data)
                        vo0 = writer.tell_virtual()
                        writer.write_record_bytes(data)
                        wprogress.add()
                        bai.add(rec.ref_id, rec.pos,
                                rec.pos + max(rec.reference_length(), 1),
                                vo0, writer.tell_virtual(),
                                not rec.flag & FLAG_UNMAPPED)
            wprogress.finish()
        if bai is not None:
            bai.write(args.output + "." + args.index_format)
    dt = time.monotonic() - t0
    log.info("sort: %d records (%s, budget %dMB) in %.2fs (%.0f rec/s)",
             sorter.n_records, args.order, budget >> 20, dt,
             sorter.n_records / dt if dt else 0)
    return 0


def _cmd_sort_chain(args, source, sink, budget):
    """Channel-fed sort stage for the fused pipeline: ingest RecordBatches
    from `source` as the upstream stage produces them (Phase-1 spill
    workers overlap the producer), k-way merge, stream sorted wire chunks
    into `sink`. Native engine only — the fused chain is gated on native
    availability, so the pure-Python fallback never lands here."""
    from .io.bam import BamHeader
    from .sort.external import (NativeExternalSorter, create_sorter,
                                header_tags_for_order)
    from .sort.keys import make_batch_keys_fn, make_key_bytes_fn
    from .utils.progress import ProgressTracker

    t0 = time.monotonic()
    with source:
        in_header = source.header
        batch_keys_fn = make_batch_keys_fn(args.order, in_header,
                                           args.subsort)
        key_fn = make_key_bytes_fn(args.order, in_header, args.subsort)
        if batch_keys_fn is None:
            log.error("sort: fused chain requires the native batch engine")
            return 2
        so, go, ss = header_tags_for_order(args.order, args.subsort)
        out_header = BamHeader(
            text=_rewrite_hd(in_header.text, so, go, ss),
            ref_names=in_header.ref_names,
            ref_lengths=in_header.ref_lengths)
        progress = ProgressTracker("sort")
        spill_workers = max(getattr(args, "threads", 0) - 1, 0)
        with create_sorter(key_fn, max_bytes=budget, tmp_dir=args.tmp_dir,
                           max_records=args.max_records_in_ram,
                           spill_workers=spill_workers) as sorter:
            if not isinstance(sorter, NativeExternalSorter):
                log.error("sort: fused chain requires the native sorter")
                return 2
            sorter.ingest_batches(iter(source), batch_keys_fn, progress.add)
            progress.finish()
            wprogress = ProgressTracker("sort-write")
            with sink(out_header) as writer:
                for arr in sorter.iter_sorted_wire():
                    writer.write_serialized(arr)
                    wprogress.add()
            wprogress.finish()
    dt = time.monotonic() - t0
    log.info("sort: %d records (%s, budget %dMB) in %.2fs (%.0f rec/s)",
             sorter.n_records, args.order, budget >> 20, dt,
             sorter.n_records / dt if dt else 0)
    return 0


def _add_merge(sub):
    p = sub.add_parser("merge", help="Merge same-order sorted BAMs")
    p.add_argument("-i", "--input", nargs="+", default=[])
    p.add_argument("--input-list", default=None,
                   help="file with one input BAM path per line (combined "
                        "with -i)")
    p.add_argument("-o", "--output", required=True)
    p.add_argument("--order", default="template-coordinate",
                   choices=["coordinate", "queryname", "template-coordinate"])
    p.add_argument("--subsort", default="natural", choices=["natural", "lex"])
    _add_pipeline_compat(p)
    p.set_defaults(func=cmd_merge)


def cmd_merge(args):
    from .io.bam import BamHeader, BamReader, BamWriter
    from .sort.external import header_tags_for_order, make_key_fn, merge_sorted

    from .core.template import _hd_fields

    if args.input_list:
        try:
            with open(args.input_list) as f:
                stripped = (line.strip() for line in f)
                args.input = list(args.input) + [
                    s for s in stripped if s and not s.startswith("#")]
        except OSError as e:
            log.error("cannot read --input-list %s: %s", args.input_list, e)
            return 2
        missing = [p for p in args.input if not os.path.exists(p)]
        if missing:
            log.error("--input-list names missing file(s): %s",
                      ", ".join(missing[:5]))
            return 2
    if not args.input:
        log.error("no inputs: pass -i and/or --input-list")
        return 2
    readers = [BamReader(path) for path in args.input]
    try:
        first = readers[0].header
        so, go, ss = header_tags_for_order(args.order, args.subsort)
        for path, r in zip(args.input, readers):
            if (r.header.ref_names != first.ref_names
                    or r.header.ref_lengths != first.ref_lengths):
                log.error("merge: inputs have differing reference sequences")
                return 2
            hd = _hd_fields(r.header.text)
            ok = (hd.get("SO") == so and (go is None or hd.get("GO") == go)
                  and (ss is None or hd.get("SS") == ss))
            if not ok:
                log.error("merge: %s is not sorted by the requested order "
                          "(--order %s needs SO:%s%s%s; header has %s)",
                          path, args.order, so,
                          f" GO:{go}" if go else "", f" SS:{ss}" if ss else "", hd)
                return 2
        # union the @RG/@PG/@CO lines across all inputs (first occurrence wins)
        seen_lines = []
        seen_set = set()
        for r in readers:
            for line in r.header.text.splitlines():
                if line.startswith(("@RG", "@PG", "@CO")) and line not in seen_set:
                    seen_set.add(line)
                    seen_lines.append(line)
        base_lines = [l for l in first.text.splitlines()
                      if not l.startswith(("@RG", "@PG", "@CO"))]
        merged_text = "\n".join(base_lines + seen_lines) + "\n"
        out_header = BamHeader(text=_rewrite_hd(merged_text, so, go, ss),
                               ref_names=first.ref_names, ref_lengths=first.ref_lengths)
        from .sort.keys import make_batch_keys_fn

        batch_keys_fn = make_batch_keys_fn(args.order, first, args.subsort)
        n = 0
        with BamWriter(args.output, out_header) as writer:
            if batch_keys_fn is not None:
                # native path: packed byte keys extracted per batch; memcmp
                # order == semantic order, so heapq merges the byte keys.
                # The header-validation readers close first (the batch
                # readers re-open each path).
                import heapq

                from .sort.keys import iter_keyed_records

                for r in readers:
                    r.close()
                streams = [
                    ((key, idx, data)
                     for key, data in iter_keyed_records(p, batch_keys_fn))
                    for idx, p in enumerate(args.input)]
                for _, _, data in heapq.merge(*streams):
                    writer.write_record_bytes(data)
                    n += 1
            else:
                key_fn = make_key_fn(args.order, first, args.subsort)
                for data in merge_sorted(readers, key_fn):
                    writer.write_record_bytes(data)
                    n += 1
    finally:
        for r in readers:
            r.close()
    log.info("merge: %d records from %d inputs", n, len(args.input))
    return 0


def _add_fastq(sub):
    def _flags(s):
        return int(s, 16) if s.lower().startswith("0x") else int(s)

    p = sub.add_parser("fastq", help="BAM -> mate-paired interleaved FASTQ")
    p.add_argument("-i", "--input", required=True)
    p.add_argument("-o", "--output", default="-", help="output FASTQ (- for stdout)")
    p.add_argument("-n", "--no-read-suffix", nargs="?", const=True,
                   default=False, type=_parse_bool,
                   help="don't append /1 and /2 to read names")
    p.add_argument("-F", "--exclude-flags", type=_flags, default=0x900,
                   help="exclude reads with ANY of these flags "
                        "(default 0x900 = secondary|supplementary)")
    p.add_argument("-f", "--require-flags", type=_flags, default=0,
                   help="only include reads with ALL of these flags")
    p.add_argument("-a", "-U", "--annotate-read-names", nargs="?", const=True,
                   default=False, type=_parse_bool,
                   help="append the UMI to the read name before any /1 "
                        "suffix (samtools fastq -U / DRAGEN layout)")
    p.add_argument("--umi-tag", default="RX,OX",
                   help="comma list of tags to read the UMI from, first "
                        "present wins")
    p.add_argument("--umi-name-delim", default=":",
                   help="delimiter between read name and UMI")
    p.add_argument("--umi-sep", default="+",
                   help="duplex-UMI half separator in the read name "
                        "(stored '-' is rewritten to this)")
    p.add_argument("-K", "--bwa-chunk-size", type=int, default=150000000,
                   help="accepted for compatibility (bwa -K output buffer "
                        "sizing hint)")
    _add_pipeline_compat(p)
    p.set_defaults(func=cmd_fastq)


def cmd_fastq(args):
    from .constants import reverse_complement_bytes
    from .io.bam import BamReader, FLAG_FIRST, FLAG_REVERSE

    from .io.bam import FLAG_LAST, FLAG_PAIRED

    from .utils.atomic import discard_output, open_output

    out = sys.stdout.buffer if args.output == "-" else open_output(args.output)
    n = 0
    umi_tags = [t.strip().encode() for t in args.umi_tag.split(",")
                if t.strip()]
    name_delim = args.umi_name_delim.encode()
    umi_sep = args.umi_sep.encode()
    exclude = args.exclude_flags
    require = args.require_flags

    def umi_of(rec):
        for tag in umi_tags:
            v = rec.get_str(tag)
            if v:
                # stored duplex UMIs use '-' between halves; aligner-facing
                # names use --umi-sep (DRAGEN/samtools '+')
                return v.replace("-", umi_sep.decode()).encode()
        return None

    def emit(rec):
        nonlocal n
        seq = rec.seq_bytes()
        quals = rec.quals()
        if rec.flag & FLAG_REVERSE:
            seq = reverse_complement_bytes(seq)
            quals = quals[::-1]
        name = rec.name
        if args.annotate_read_names:
            umi = umi_of(rec)
            if umi:
                name = name + name_delim + umi
        suffix = b""
        if not args.no_read_suffix:
            suffix = b"/1" if rec.flag & FLAG_FIRST else (
                b"/2" if rec.flag & FLAG_LAST else b"")
        out.write(b"@" + name + suffix + b"\n" + seq + b"\n+\n"
                  + (quals + 33).tobytes() + b"\n")
        n += 1

    # R1/R2 are interleaved adjacently by buffering each read until its mate
    # arrives (mates may be far apart in coordinate-sorted input)
    from .io.bam import FLAG_SECONDARY, FLAG_SUPPLEMENTARY

    pending = {}
    try:
        with BamReader(args.input) as reader:
            for rec in reader:
                if (rec.flag & exclude) or (rec.flag & require) != require:
                    continue
                if rec.flag & (FLAG_SECONDARY | FLAG_SUPPLEMENTARY):
                    # a non-default -F may admit secondary/supplementary
                    # records: they are emitted verbatim but NEVER enter the
                    # name-keyed mate pairing (a supplementary R1 would
                    # otherwise pair with its own primary and corrupt the
                    # interleaving)
                    emit(rec)
                    continue
                if not rec.flag & FLAG_PAIRED:
                    emit(rec)
                    continue
                mate = pending.pop(rec.name, None)
                if mate is None:
                    pending[rec.name] = rec
                else:
                    r1, r2 = (rec, mate) if rec.flag & FLAG_FIRST else (mate, rec)
                    emit(r1)
                    emit(r2)
        for rec in pending.values():  # orphaned mates, in input order
            emit(rec)
    except BaseException:
        if out is not sys.stdout.buffer:
            discard_output(out)
        raise
    else:
        out.flush()
        if out is not sys.stdout.buffer:
            out.close()
    log.info("fastq: wrote %d reads", n)
    return 0


def _add_extract(sub):
    p = sub.add_parser("extract", help="Extract UMIs from FASTQ into unmapped BAM")
    p.add_argument("-i", "--input", required=True, nargs="+",
                   help="FASTQ file per sequencing read (R1 [R2 I1 I2 ...])")
    p.add_argument("-o", "--output", required=True, help="output unmapped BAM")
    p.add_argument("-r", "--read-structures", nargs="*", default=[],
                   help="one per FASTQ, e.g. 8M12S+T (default +T for 1-2 inputs)")
    p.add_argument("-q", "--store-umi-quals", action="store_true")
    p.add_argument("-C", "--store-cell-quals", action="store_true")
    p.add_argument("-Q", "--store-sample-barcode-qualities", action="store_true")
    p.add_argument("-n", "--extract-umis-from-read-names", action="store_true")
    p.add_argument("-a", "--annotate-read-names", action="store_true")
    p.add_argument("-s", "--single-tag", default=None)
    p.add_argument("--read-group-id", default="A")
    p.add_argument("--sample", required=True)
    p.add_argument("--library", required=True)
    p.add_argument("-b", "--barcode", default=None)
    p.add_argument("--platform", default="illumina")
    p.add_argument("--platform-unit", default=None)
    p.add_argument("--platform-model", default=None)
    p.add_argument("--sequencing-center", default=None)
    p.add_argument("--predicted-insert-size", type=int, default=None)
    p.add_argument("--description", default=None)
    p.add_argument("--run-date", default=None)
    p.add_argument("--comment", nargs="*", default=[])
    _add_pipeline_compat(p)
    p.set_defaults(func=cmd_extract)


def cmd_extract(args, sink=None):
    from .commands.extract import ExtractError, ExtractOptions, run_extract

    opts = ExtractOptions(
        read_structures=args.read_structures, sample=args.sample,
        library=args.library, read_group_id=args.read_group_id,
        store_umi_quals=args.store_umi_quals,
        store_cell_quals=args.store_cell_quals,
        store_sample_barcode_quals=args.store_sample_barcode_qualities,
        extract_umis_from_read_names=args.extract_umis_from_read_names,
        annotate_read_names=args.annotate_read_names,
        single_tag=args.single_tag, barcode=args.barcode,
        platform=args.platform, platform_unit=args.platform_unit,
        platform_model=args.platform_model,
        sequencing_center=args.sequencing_center,
        predicted_insert_size=args.predicted_insert_size,
        description=args.description, run_date=args.run_date,
        comments=args.comment, command_line=_cmdline())
    t0 = time.monotonic()
    try:
        n_records, n_sets = run_extract(args.input, args.output, opts,
                                        sink=sink)
    except (ValueError, OSError) as e:  # ExtractError, ReadStructureError, bad I/O
        log.error("%s", e)
        return 2
    dt = time.monotonic() - t0
    log.info("extract: %d read sets -> %d records in %.2fs (%.0f reads/s)",
             n_sets, n_records, dt, n_records / dt if dt else 0)
    return 0


def _parse_bool(v):
    if isinstance(v, bool):
        return v
    if v.lower() in ("true", "t", "yes", "1"):
        return True
    if v.lower() in ("false", "f", "no", "0"):
        return False
    raise argparse.ArgumentTypeError(f"expected true/false, got {v!r}")


def _header_with_pg(header, command_line):
    """Copy a header, appending an @PG record chained to the last one."""
    from .io.bam import BamHeader

    lines = header.text.splitlines()
    pg_ids = set()
    last_pg = None
    for line in lines:
        if line.startswith("@PG"):
            fields = dict(f.split(":", 1) for f in line.split("\t")[1:] if ":" in f)
            if "ID" in fields:
                pg_ids.add(fields["ID"])
                last_pg = fields["ID"]
    pg_id = "fgumi-tpu"
    n = 1
    while pg_id in pg_ids:
        pg_id = f"fgumi-tpu.{n}"
        n += 1
    pg = f"@PG\tID:{pg_id}\tPN:fgumi-tpu"
    if last_pg is not None:
        pg += f"\tPP:{last_pg}"
    pg += f"\tCL:{command_line}"
    return BamHeader(text="\n".join(lines + [pg]) + "\n",
                     ref_names=header.ref_names, ref_lengths=header.ref_lengths)


def _merge_zipper_headers(mapped, unmapped):
    """Mapped header plus @RG/@PG/@CO lines only the unmapped header carries
    (build_output_header, zipper.rs:232-278): the aligner often drops the @RG
    written by extract, which downstream library lookups need."""
    from .io.bam import BamHeader

    def ids(lines, kind):
        out = set()
        for line in lines:
            if line.startswith(kind):
                fields = dict(f.split(":", 1) for f in line.split("\t")[1:] if ":" in f)
                if "ID" in fields:
                    out.add(fields["ID"])
        return out

    mapped_lines = mapped.text.splitlines()
    extra = []
    for kind in ("@RG", "@PG"):
        have = ids(mapped_lines, kind)
        for line in unmapped.text.splitlines():
            if line.startswith(kind):
                fields = dict(f.split(":", 1) for f in line.split("\t")[1:] if ":" in f)
                if fields.get("ID") not in have:
                    extra.append(line)
    mapped_co = {l for l in mapped_lines if l.startswith("@CO")}
    extra.extend(l for l in unmapped.text.splitlines()
                 if l.startswith("@CO") and l not in mapped_co)
    if not extra:
        return mapped
    return BamHeader(text="\n".join(mapped_lines + extra) + "\n",
                     ref_names=mapped.ref_names, ref_lengths=mapped.ref_lengths)


def _add_zipper(sub):
    p = sub.add_parser("zipper", help="Zip unmapped BAM with aligned BAM")
    p.add_argument("-i", "--input", required=True,
                   help="mapped BAM from the aligner (queryname ordered)")
    p.add_argument("-u", "--unmapped", required=True,
                   help="unmapped BAM with tags to restore (same ordering)")
    p.add_argument("-o", "--output", required=True)
    p.add_argument("--tags-to-remove", nargs="*", default=[])
    p.add_argument("--tags-to-reverse", nargs="*", default=[],
                   help="tags (or the 'Consensus' set) to reverse on negative strand")
    p.add_argument("--tags-to-revcomp", nargs="*", default=[],
                   help="tags (or the 'Consensus' set) to revcomp on negative strand")
    p.add_argument("--skip-tc-tags", nargs="?", const=True, default=False,
                   type=_parse_bool)
    p.add_argument("--exclude-missing-reads", nargs="?", const=True,
                   default=False, type=_parse_bool,
                   help="drop unmapped-BAM reads the aligner omitted")
    p.add_argument("--restore-unconverted-bases", nargs="?", const=True,
                   default=False, type=_parse_bool,
                   help="EM-Seq: rewrite converted bases back to the "
                        "unconverted reference form at aligned ref-C/ref-G "
                        "positions after bwameth re-alignment (uses the "
                        "bwameth YD strand tag; requires --ref)")
    p.add_argument("-r", "--ref", default=None,
                   help="reference FASTA (required with "
                        "--restore-unconverted-bases)")
    p.add_argument("-K", "--bwa-chunk-size", type=int, default=150000000,
                   help="accepted for compatibility (bwa -K stdin buffer "
                        "sizing hint; this reader sizes buffers adaptively)")
    p.add_argument("--classic", action="store_true",
                   help="force the per-template engine (no batch "
                        "vectorization)")
    _add_pipeline_compat(p)
    p.set_defaults(func=cmd_zipper)


def cmd_zipper(args):
    from .commands.zipper import TagInfo, run_zipper
    from .core.template import is_query_grouped
    from .io.bam import BamReader, BamWriter

    tag_info = TagInfo.from_options(
        remove=args.tags_to_remove, reverse=args.tags_to_reverse,
        revcomp=args.tags_to_revcomp)
    from .native import batch as nbat

    restore = None
    if args.restore_unconverted_bases:
        if args.ref is None:
            log.error("--restore-unconverted-bases requires --ref")
            return 2
        from .core.reference import ReferenceReader

        try:
            restore_ref = ReferenceReader(args.ref)
        except OSError as e:
            log.error("cannot read reference %s: %s", args.ref, e)
            return 2
        with BamReader(args.input) as _r:
            restore = (restore_ref, _r.header.ref_names)
    # the batch engine's staged-append model cannot express static removal
    # of the tags it itself appends (MQ/MC/ms/AS/XS) -> classic engine
    # there; the EM-Seq restore also runs per record in the classic engine
    use_fast = (nbat.available() and not getattr(args, "classic", False)
                and restore is None
                and not (tag_info.remove & {"MQ", "MC", "ms", "AS", "XS"}))
    if nbat.available():
        from .io.batch_reader import BatchedRecordReader as _Reader
    else:
        _Reader = BamReader
    t0 = time.monotonic()
    try:
        if use_fast:
            from .commands.fast_zipper import run_zipper_fast
            from .io.batch_reader import BamBatchReader

            with BamBatchReader(args.input) as mapped, \
                    BamBatchReader(args.unmapped) as unmapped:
                for name, r in (("mapped", mapped), ("unmapped", unmapped)):
                    if not is_query_grouped(r.header.text):
                        log.error(
                            "zipper requires queryname-sorted or "
                            "query-grouped %s input (@HD must advertise "
                            "SO:queryname or GO:query)", name)
                        return 2
                out_header = _header_with_pg(
                    _merge_zipper_headers(mapped.header, unmapped.header),
                    _cmdline())
                with BamWriter(args.output, out_header) as writer:
                    n_templates, n_records, n_missing = run_zipper_fast(
                        mapped, unmapped, writer, tag_info,
                        skip_tc_tags=args.skip_tc_tags,
                        exclude_missing_reads=args.exclude_missing_reads)
        else:
            with _Reader(args.input) as mapped, \
                    _Reader(args.unmapped) as unmapped:
                for name, r in (("mapped", mapped), ("unmapped", unmapped)):
                    if not is_query_grouped(r.header.text):
                        log.error(
                            "zipper requires queryname-sorted or "
                            "query-grouped %s input (@HD must advertise "
                            "SO:queryname or GO:query)", name)
                        return 2
                out_header = _header_with_pg(
                    _merge_zipper_headers(mapped.header, unmapped.header),
                    _cmdline())
                with BamWriter(args.output, out_header) as writer:
                    n_templates, n_records, n_missing = run_zipper(
                        mapped, unmapped, writer, tag_info,
                        skip_tc_tags=args.skip_tc_tags,
                        exclude_missing_reads=args.exclude_missing_reads,
                        restore_unconverted=restore)
    except (ValueError, OSError) as e:
        log.error("%s", e)
        return 2
    dt = time.monotonic() - t0
    log.info("zipper: %d templates (%d records) in %.2fs (%.0f rec/s)",
             n_templates, n_records, dt, n_records / dt if dt else 0)
    if n_missing:
        verb = "excluded" if args.exclude_missing_reads else "passed through"
        log.info("zipper: %d templates not present in the aligned BAM (%s)",
                 n_missing, verb)
    return 0


def _add_filter(sub):
    p = sub.add_parser("filter", help="Filter and mask consensus reads")
    p.add_argument("-i", "--input", required=True,
                   help="consensus BAM (queryname sorted or query grouped)")
    p.add_argument("-o", "--output", required=True)
    p.add_argument("-M", "--min-reads", required=True,
                   help="1-3 comma-separated values [duplex,AB,BA]")
    p.add_argument("-E", "--max-read-error-rate", default="0.025",
                   help="1-3 comma-separated values")
    p.add_argument("-e", "--max-base-error-rate", default="0.1",
                   help="1-3 comma-separated values")
    p.add_argument("-N", "--min-base-quality", type=int, default=None)
    p.add_argument("-q", "--min-mean-base-quality", type=float, default=None)
    p.add_argument("-n", "--max-no-call-fraction", type=float, default=0.2,
                   help="<1.0: fraction of read length; >=1.0: absolute count")
    p.add_argument("-R", "--reverse-per-base-tags", nargs="?", const=True,
                   default=False, type=_parse_bool)
    p.add_argument("--filter-by-template", nargs="?", const=True,
                   default=True, type=_parse_bool)
    p.add_argument("-s", "--require-single-strand-agreement", nargs="?",
                   const=True, default=False, type=_parse_bool)
    p.add_argument("--min-methylation-depth", default=None,
                   help="EM-Seq/TAPS: mask bases whose methylation evidence "
                        "(cu+ct) is below this; 1-3 comma values "
                        "[duplex,AB,BA] (duplex also checks au+at / bu+bt)")
    p.add_argument("--require-strand-methylation-agreement", nargs="?",
                   const=True, default=False, type=_parse_bool,
                   help="mask both positions of a CpG when top/bottom strand "
                        "methylation calls disagree (duplex; requires --ref)")
    p.add_argument("--min-conversion-fraction", type=float, default=None,
                   help="reject reads whose conversion fraction at non-CpG "
                        "ref-C positions is below this (requires --ref and "
                        "--methylation-mode)")
    p.add_argument("--methylation-mode", choices=["em-seq", "taps"],
                   default=None,
                   help="numerator convention for --min-conversion-fraction "
                        "(em-seq: converted, taps: unconverted)")
    p.add_argument("--rejects", default=None, help="BAM for rejected reads")
    p.add_argument("-r", "--ref", default=None,
                   help="reference FASTA: regenerate NM/UQ/MD after masking "
                        "(required for mapped input)")
    p.add_argument("--classic", action="store_true",
                   help="force the per-record engine (no batch vectorization)")
    _add_pipeline_compat(p)
    p.set_defaults(func=cmd_filter)


def cmd_filter(args, source=None):
    from .commands.filter import run_filter
    from .consensus.filter import FilterConfig
    from .io.bam import BamReader, BamWriter

    if args.min_conversion_fraction is not None and not args.methylation_mode:
        log.error("--min-conversion-fraction requires --methylation-mode")
        return 2
    if (args.require_strand_methylation_agreement
            or args.min_conversion_fraction is not None) and not args.ref:
        log.error("--require-strand-methylation-agreement and "
                  "--min-conversion-fraction require --ref")
        return 2
    try:
        config = FilterConfig.new(
            [int(v) for v in args.min_reads.split(",")],
            [float(v) for v in args.max_read_error_rate.split(",")],
            [float(v) for v in args.max_base_error_rate.split(",")],
            min_base_quality=args.min_base_quality,
            min_mean_base_quality=args.min_mean_base_quality,
            max_no_call_fraction=args.max_no_call_fraction,
            require_ss_agreement=args.require_single_strand_agreement,
            methylation_depth=(args.min_methylation_depth.split(",")
                               if args.min_methylation_depth else None),
            require_strand_methylation_agreement=(
                args.require_strand_methylation_agreement),
            min_conversion_fraction=args.min_conversion_fraction,
            methylation_mode=args.methylation_mode)
    except ValueError as e:
        log.error("%s", e)
        return 2
    from .native import batch as nbat

    use_fast = (nbat.available() and not args.ref
                and not args.reverse_per_base_tags
                and not args.require_single_strand_agreement
                and not getattr(args, "classic", False))
    if source is not None and not use_fast:
        log.error("filter: fused chain requires the native batch engine")
        return 2
    t0 = time.monotonic()
    try:
        reference = None
        if args.ref:
            from .core.reference import ReferenceReader
            reference = ReferenceReader(args.ref)

        _SORT_ERR = (
            "filter requires queryname-sorted or query-grouped input "
            "(@HD must advertise SO:queryname or GO:query); run "
            "`fgumi-tpu sort --order queryname` first")

        def classic_run():
            with BamReader(args.input) as reader:
                from .core.template import is_query_grouped
                if not is_query_grouped(reader.header.text):
                    return None
                out_header = _header_with_pg(reader.header,
                                             _cmdline())
                rejects = (BamWriter(args.rejects, out_header)
                           if args.rejects else None)
                ok = False
                try:
                    with BamWriter(args.output, out_header) as writer:
                        stats_ = run_filter(
                            reader, writer, config,
                            filter_by_template=args.filter_by_template,
                            reverse_per_base=args.reverse_per_base_tags,
                            rejects_writer=rejects, reference=reference)
                    ok = True
                    return stats_
                finally:
                    if rejects is not None:
                        (rejects.close if ok else rejects.discard)()

        stats = None
        if use_fast:
            from .commands.fast_filter import FastFilter, _OddSubtype
            from .io.batch_reader import BamBatchReader

            try:
                with (BamBatchReader(args.input) if source is None
                      else source) as reader:
                    from .core.template import is_query_grouped
                    # Template filtering needs mates adjacent
                    # (filter.rs:343-349 require_query_grouped).
                    if not is_query_grouped(reader.header.text):
                        log.error("%s", _SORT_ERR)
                        return 2
                    out_header = _header_with_pg(reader.header,
                                                 _cmdline())
                    rejects = (BamWriter(args.rejects, out_header)
                               if args.rejects else None)
                    ok = False
                    try:
                        with BamWriter(args.output, out_header) as writer:
                            ff = FastFilter(
                                config,
                                filter_by_template=args.filter_by_template)
                            emit_rej = (rejects.write_serialized
                                        if rejects else None)
                            for batch in reader:
                                ff.process_batch(
                                    batch, writer.write_serialized, emit_rej)
                            ff.flush(writer.write_serialized, emit_rej)
                            stats = ff.stats
                        ok = True
                    finally:
                        if rejects is not None:
                            (rejects.close if ok else rejects.discard)()
            except _OddSubtype:
                if source is not None:
                    # a channel cannot be re-read; the fused driver gates on
                    # the standard consensus tag surface so this is a bug,
                    # not a user-reachable state
                    log.error("filter: unexpected per-base tag subtype on a "
                              "fused stream (cannot re-run classic)")
                    return 2
                log.info("filter: unexpected per-base tag subtype; "
                         "re-running with the classic engine")
                stats = None
        if stats is None:
            stats = classic_run()
            if stats is None:
                log.error("%s", _SORT_ERR)
                return 2
    except (ValueError, OSError, KeyError) as e:
        log.error("%s", e)
        return 2
    dt = time.monotonic() - t0
    log.info("filter: %d records -> kept %d, rejected %d, masked %d bases "
             "in %.2fs", stats.total_records, stats.passed_records,
             stats.failed_records, stats.bases_masked, dt)
    if stats.rejection_reasons:
        log.info("rejections: %s", dict(stats.rejection_reasons.most_common()))
    return 0


def _add_downsample(sub):
    p = sub.add_parser("downsample", help="Downsample BAM by UMI family")
    p.add_argument("-i", "--input", required=True,
                   help="grouped BAM with MI tags (template-coordinate order)")
    p.add_argument("-o", "--output", required=True)
    p.add_argument("-f", "--fraction", type=float, required=True,
                   help="fraction of UMI families to keep, in (0.0, 1.0]")
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--rejects", default=None)
    p.add_argument("--validate-mi-order", nargs="?", const=True,
                   default=True, type=_parse_bool)
    p.add_argument("--histogram-kept", default=None)
    p.add_argument("--histogram-rejected", default=None)
    _add_pipeline_compat(p)
    p.set_defaults(func=cmd_downsample)


def cmd_downsample(args):
    from .commands.downsample import run_downsample, write_histogram
    from .io.bam import BamReader, BamWriter

    t0 = time.monotonic()
    try:
        with BamReader(args.input) as reader:
            out_header = _header_with_pg(reader.header, _cmdline())
            rejects = (BamWriter(args.rejects, out_header)
                       if args.rejects else None)
            ok = False
            try:
                with BamWriter(args.output, out_header) as writer:
                    stats = run_downsample(
                        reader, writer, args.fraction, seed=args.seed,
                        rejects_writer=rejects,
                        validate_mi_order=args.validate_mi_order)
                ok = True
            finally:
                if rejects is not None:
                    (rejects.close if ok else rejects.discard)()
    except (ValueError, OSError) as e:
        log.error("%s", e)
        return 2
    if args.histogram_kept:
        write_histogram(stats.kept_sizes, args.histogram_kept)
    if args.histogram_rejected:
        write_histogram(stats.rejected_sizes, args.histogram_rejected)
    dt = time.monotonic() - t0
    log.info("downsample: kept %d/%d families (%d/%d records) in %.2fs",
             stats.families_kept, stats.families_total, stats.records_kept,
             stats.records_total, dt)
    return 0


def _add_simulate(sub):
    p = sub.add_parser("simulate", help="Generate synthetic test data")
    ps = p.add_subparsers(dest="sim_mode", required=True)
    g = ps.add_parser("grouped-reads", help="MI-grouped BAM (simplex input)")
    g.add_argument("-o", "--output", required=True)
    g.add_argument("--num-families", type=int, default=100)
    g.add_argument("--family-size", type=int, default=5)
    g.add_argument("--family-size-distribution", default="fixed",
                   choices=["fixed", "lognormal", "longtail"],
                   help="longtail = Pareto-tailed 1-50 mixture (BASELINE "
                        "eval config 2 shape)")
    g.add_argument("--read-length", type=int, default=100)
    g.add_argument("--read-length-jitter", type=int, default=0,
                   help="per-read 3' truncation up to N bases (ragged "
                        "consensus-length stress)")
    g.add_argument("--qual-slope", type=float, default=0.0,
                   help="per-position Phred decay along the read")
    g.add_argument("--insert-size-mean", type=int, default=None,
                   help="normal insert-size model (default: uniform "
                        "1.5-3x read length)")
    g.add_argument("--insert-size-sd", type=int, default=0)
    g.add_argument("--error-rate", type=float, default=0.01)
    g.add_argument("--base-quality", type=int, default=35)
    g.add_argument("--single-end", action="store_true")
    g.add_argument("--seed", type=int, default=42)
    g.set_defaults(func=cmd_simulate_grouped)
    d = ps.add_parser("duplex-reads", help="duplex-grouped BAM (/A,/B MI tags)")
    d.add_argument("-o", "--output", required=True)
    d.add_argument("--num-molecules", type=int, default=100)
    d.add_argument("--reads-per-strand", type=int, default=3)
    d.add_argument("--read-length", type=int, default=100)
    d.add_argument("--error-rate", type=float, default=0.01)
    d.add_argument("--base-quality", type=int, default=35)
    d.add_argument("--ba-fraction", type=float, default=1.0)
    d.add_argument("--strand-bias-alpha", type=float, default=None,
                   help="Beta(alpha, beta) A/B strand read split (PCR "
                        "amplification bias model); default: symmetric "
                        "fixed split")
    d.add_argument("--strand-bias-beta", type=float, default=None)
    d.add_argument("--seed", type=int, default=42)
    d.set_defaults(func=cmd_simulate_duplex)
    c = ps.add_parser("codec-reads", help="CODEC-shaped BAM (overlapping FR pairs, MI tags)")
    c.add_argument("-o", "--output", required=True)
    c.add_argument("--num-molecules", type=int, default=100)
    c.add_argument("--pairs-per-molecule", type=int, default=1)
    c.add_argument("--read-length", type=int, default=100)
    c.add_argument("--error-rate", type=float, default=0.01)
    c.add_argument("--base-quality", type=int, default=35)
    c.add_argument("--overlap-fraction", type=float, default=0.5)
    c.add_argument("--seed", type=int, default=42)
    c.set_defaults(func=cmd_simulate_codec)
    m = ps.add_parser("mapped-reads", help="template-coordinate BAM with RX tags (group input)")
    m.add_argument("-o", "--output", required=True)
    m.add_argument("--num-families", type=int, default=100)
    m.add_argument("--family-size", type=int, default=5)
    m.add_argument("--read-length", type=int, default=100)
    m.add_argument("--umi-length", type=int, default=8)
    m.add_argument("--umi-error-rate", type=float, default=0.02)
    m.add_argument("--paired-umis", action="store_true")
    m.add_argument("--seed", type=int, default=42)
    m.set_defaults(func=cmd_simulate_mapped)
    f = ps.add_parser("fastq-reads",
                      help="paired gzip FASTQ with UMI prefixes (extract input)")
    f.add_argument("-1", "--r1", required=True, dest="r1")
    f.add_argument("-2", "--r2", required=True, dest="r2")
    f.add_argument("--truth", default=None, help="truth TSV output")
    f.add_argument("--num-families", type=int, default=100)
    f.add_argument("--family-size", type=int, default=5)
    f.add_argument("--family-size-distribution", default="fixed",
                   choices=["fixed", "lognormal", "longtail"])
    f.add_argument("--read-length", type=int, default=100)
    f.add_argument("--umi-length", type=int, default=8)
    f.add_argument("--error-rate", type=float, default=0.0)
    f.add_argument("--base-quality", type=int, default=35)
    f.add_argument("--duplex", action="store_true",
                   help="UMI prefix on both reads (duplex extraction)")
    f.add_argument("--includelist", default=None,
                   help="sample UMIs from this file (one per line)")
    f.add_argument("--seed", type=int, default=42)
    f.set_defaults(func=cmd_simulate_fastq)
    cr = ps.add_parser("consensus-reads",
                       help="mapped BAM shaped like consensus output (filter input)")
    cr.add_argument("-o", "--output", required=True)
    cr.add_argument("--truth", default=None)
    cr.add_argument("-n", "--num-reads", type=int, default=1000)
    cr.add_argument("-l", "--read-length", type=int, default=150)
    cr.add_argument("--min-depth", type=int, default=1)
    cr.add_argument("--max-depth", type=int, default=10)
    cr.add_argument("--depth-mean", type=float, default=5.0)
    cr.add_argument("--depth-stddev", type=float, default=2.0)
    cr.add_argument("--error-rate-mean", type=float, default=0.01)
    cr.add_argument("--no-per-base-tags", action="store_true")
    cr.add_argument("--seed", type=int, default=42)
    cr.set_defaults(func=cmd_simulate_consensus)
    co = ps.add_parser("correct-reads",
                       help="unmapped BAM + UMI includelist (correct input)")
    co.add_argument("-o", "--output", required=True)
    co.add_argument("-i", "--includelist", required=True,
                    help="includelist file to write")
    co.add_argument("--truth", default=None)
    co.add_argument("-n", "--num-reads", type=int, default=10000)
    co.add_argument("--num-umis", type=int, default=1000)
    co.add_argument("-u", "--umi-length", type=int, default=8)
    co.add_argument("-l", "--read-length", type=int, default=100)
    co.add_argument("--max-errors", type=int, default=2)
    co.add_argument("--seed", type=int, default=42)
    co.set_defaults(func=cmd_simulate_correct)


def cmd_simulate_fastq(args):
    from .simulate import simulate_fastq_reads

    n = simulate_fastq_reads(
        args.r1, args.r2, truth_path=args.truth,
        num_families=args.num_families, family_size=args.family_size,
        family_size_distribution=args.family_size_distribution,
        read_length=args.read_length, umi_length=args.umi_length,
        error_rate=args.error_rate, base_quality=args.base_quality,
        duplex=args.duplex, includelist=args.includelist, seed=args.seed)
    log.info("simulate: wrote %d read pairs to %s / %s", n, args.r1, args.r2)
    return 0


def cmd_simulate_consensus(args):
    from .simulate import simulate_consensus_bam

    n = simulate_consensus_bam(
        args.output, truth_path=args.truth, num_reads=args.num_reads,
        read_length=args.read_length, min_depth=args.min_depth,
        max_depth=args.max_depth, depth_mean=args.depth_mean,
        depth_stddev=args.depth_stddev, error_rate_mean=args.error_rate_mean,
        per_base_tags=not args.no_per_base_tags, seed=args.seed)
    log.info("simulate: wrote %d consensus records to %s", n, args.output)
    return 0


def cmd_simulate_correct(args):
    from .simulate import simulate_correct_reads

    n = simulate_correct_reads(
        args.output, args.includelist, truth_path=args.truth,
        num_reads=args.num_reads, num_umis=args.num_umis,
        umi_length=args.umi_length, read_length=args.read_length,
        max_errors=args.max_errors, seed=args.seed)
    log.info("simulate: wrote %d reads to %s (includelist %s)", n,
             args.output, args.includelist)
    return 0


def cmd_simulate_grouped(args):
    from .simulate import simulate_grouped_bam

    n = simulate_grouped_bam(
        args.output, num_families=args.num_families, family_size=args.family_size,
        family_size_distribution=args.family_size_distribution,
        read_length=args.read_length, error_rate=args.error_rate,
        base_quality=args.base_quality, paired=not args.single_end,
        read_length_jitter=args.read_length_jitter,
        qual_slope=args.qual_slope,
        insert_size_mean=args.insert_size_mean,
        insert_size_sd=args.insert_size_sd, seed=args.seed)
    log.info("simulate: wrote %d records to %s", n, args.output)
    return 0


def cmd_simulate_duplex(args):
    from .simulate import simulate_duplex_bam

    if args.strand_bias_beta is not None and args.strand_bias_alpha is None:
        log.error("--strand-bias-beta requires --strand-bias-alpha")
        return 2
    for name, v in (("--strand-bias-alpha", args.strand_bias_alpha),
                    ("--strand-bias-beta", args.strand_bias_beta)):
        if v is not None and v <= 0:
            log.error("%s must be > 0 (Beta distribution parameter)", name)
            return 2
    n = simulate_duplex_bam(
        args.output, num_molecules=args.num_molecules,
        reads_per_strand=args.reads_per_strand, read_length=args.read_length,
        error_rate=args.error_rate, base_quality=args.base_quality,
        ba_fraction=args.ba_fraction, seed=args.seed,
        strand_bias_alpha=args.strand_bias_alpha,
        strand_bias_beta=args.strand_bias_beta)
    log.info("simulate: wrote %d records to %s", n, args.output)
    return 0


def cmd_simulate_codec(args):
    from .simulate import simulate_codec_bam

    n = simulate_codec_bam(
        args.output, num_molecules=args.num_molecules,
        pairs_per_molecule=args.pairs_per_molecule, read_length=args.read_length,
        error_rate=args.error_rate, base_quality=args.base_quality,
        overlap_fraction=args.overlap_fraction, seed=args.seed)
    log.info("simulate: wrote %d records to %s", n, args.output)
    return 0


def cmd_simulate_mapped(args):
    from .simulate import simulate_mapped_bam

    n = simulate_mapped_bam(
        args.output, num_families=args.num_families, family_size=args.family_size,
        read_length=args.read_length, umi_length=args.umi_length,
        umi_error_rate=args.umi_error_rate, paired_umis=args.paired_umis,
        seed=args.seed)
    log.info("simulate: wrote %d records to %s", n, args.output)
    return 0


def _add_clip(sub):
    p = sub.add_parser("clip", help="Clip overlapping reads in BAM files")
    p.add_argument("-i", "--input", required=True,
                   help="queryname sorted/grouped BAM")
    p.add_argument("-o", "--output", required=True)
    p.add_argument("-r", "--reference", required=True,
                   help="reference FASTA (for NM/UQ/MD regeneration)")
    p.add_argument("-c", "--clipping-mode", default="hard",
                   choices=["soft", "soft-with-mask", "hard"])
    p.add_argument("--clip-overlapping-reads", action="store_true")
    p.add_argument("--clip-bases-past-mate", "--clip-extending-past-mate",
                   dest="clip_extending_past_mate", action="store_true")
    p.add_argument("--read-one-five-prime", type=int, default=0)
    p.add_argument("--read-one-three-prime", type=int, default=0)
    p.add_argument("--read-two-five-prime", type=int, default=0)
    p.add_argument("--read-two-three-prime", type=int, default=0)
    p.add_argument("-H", "--upgrade-clipping", action="store_true",
                   help="upgrade existing clipping to the configured mode")
    p.add_argument("-a", "--auto-clip-attributes", action="store_true",
                   help="hard-clip per-base tags matching read length")
    p.add_argument("-m", "--metrics", default=None)
    _add_pipeline_compat(p)
    p.set_defaults(func=cmd_clip)


def cmd_clip(args):
    from .commands.clip import ClipParams, run_clip, write_clip_metrics
    from .core.reference import ReferenceReader
    from .core.template import is_query_grouped
    from .io.bam import BamReader, BamWriter

    params = ClipParams(
        clipping_mode=args.clipping_mode,
        clip_overlapping_reads=args.clip_overlapping_reads,
        clip_extending_past_mate=args.clip_extending_past_mate,
        read_one_five_prime=args.read_one_five_prime,
        read_one_three_prime=args.read_one_three_prime,
        read_two_five_prime=args.read_two_five_prime,
        read_two_three_prime=args.read_two_three_prime,
        upgrade_clipping=args.upgrade_clipping,
        auto_clip_attributes=args.auto_clip_attributes)
    if not params.any_clipping():
        log.error("At least one clipping option is required")
        return 2
    t0 = time.monotonic()
    try:
        reference = ReferenceReader(args.reference)
        with BamReader(args.input) as reader:
            if not is_query_grouped(reader.header.text):
                log.error("clip requires queryname sorted or query grouped "
                          "input (@HD must advertise SO:queryname or GO:query); "
                          "sort with `fgumi-tpu sort --order queryname` first")
                return 2
            out_header = _header_with_pg(reader.header, _cmdline())
            with BamWriter(args.output, out_header) as writer:
                metrics = run_clip(reader, writer, reference, params)
    except (ValueError, OSError, KeyError) as e:
        log.error("%s", e)
        return 2
    dt = time.monotonic() - t0
    log.info("clip: %d templates (%d overlap-clipped, %d extend-clipped) "
             "in %.2fs", metrics.templates, metrics.overlap_clipped,
             metrics.extend_clipped, dt)
    if args.metrics:
        write_clip_metrics(metrics, args.metrics)
    return 0


def _add_correct(sub):
    p = sub.add_parser("correct", help="Correct UMIs to a fixed whitelist")
    p.add_argument("-i", "--input", required=True)
    p.add_argument("-o", "--output", required=True)
    p.add_argument("-u", "--umis", nargs="*", default=[],
                   help="whitelist UMI sequences")
    p.add_argument("-U", "--umi-files", nargs="*", default=[],
                   help="files with one whitelist UMI per line")
    p.add_argument("-m", "--metrics", default=None, help="per-UMI metrics TSV")
    p.add_argument("-r", "--rejects", default=None,
                   help="BAM for records whose UMI could not be corrected")
    p.add_argument("--target", choices=["umi", "barcode"], default="umi",
                   help="umi: RX (original in OX); barcode: BC (original in ob)")
    p.add_argument("--max-mismatches", type=int, default=2)
    p.add_argument("--min-distance", type=int, default=2, dest="min_distance_diff")
    p.add_argument("--dont-store-original", action="store_true")
    p.add_argument("--cache-size", type=int, default=100_000)
    p.add_argument("--min-corrected", type=float, default=None,
                   help="fail if kept/total falls below this fraction")
    p.add_argument("--revcomp", action="store_true",
                   help="reverse-complement observed UMIs before matching")
    p.add_argument("--classic", action="store_true",
                   help="force the per-template engine (no batch "
                        "vectorization)")
    _add_pipeline_compat(p)
    p.set_defaults(func=cmd_correct)


def cmd_correct(args):
    from .commands.correct import (UmiMatcher, find_umi_pairs_within_distance,
                                   load_umi_sequences, run_correct,
                                   write_correction_metrics)
    from .io.bam import BamReader, BamWriter

    if args.min_corrected is not None and not 0.0 <= args.min_corrected <= 1.0:
        log.error("--min-corrected must be between 0 and 1")
        return 2
    try:
        umis, umi_length = load_umi_sequences(args.umis, args.umi_files)
    except (ValueError, OSError) as e:
        log.error("%s", e)
        return 2
    log.info("correct: loaded %d whitelist UMIs of length %d", len(umis), umi_length)
    # ambiguity warning (fgbio uses min_distance_diff - 1; 0 reports nothing)
    if args.min_distance_diff > 0:
        pairs = find_umi_pairs_within_distance(umis, args.min_distance_diff - 1)
        for u1, u2, d in pairs:
            log.warning("whitelist UMIs within min-distance-diff: %s <-> %s "
                        "(distance %d) — may be ambiguous and fail to match",
                        u1, u2, d)
    matcher = UmiMatcher(umis, args.max_mismatches, args.min_distance_diff,
                         args.cache_size)
    from .native import batch as nbat

    use_fast = nbat.available() and not getattr(args, "classic", False)
    t0 = time.monotonic()
    try:
        if use_fast:
            from .commands.fast_correct import run_correct_fast
            from .io.batch_reader import BamBatchReader

            _Reader, _run = BamBatchReader, run_correct_fast
        else:
            _Reader, _run = BamReader, run_correct
        with _Reader(args.input) as reader:
            out_header = _header_with_pg(reader.header, _cmdline())
            import contextlib
            with contextlib.ExitStack() as stack:
                writer = stack.enter_context(BamWriter(args.output, out_header))
                rejects_writer = None
                if args.rejects:
                    rejects_writer = stack.enter_context(
                        BamWriter(args.rejects, out_header))
                stats = _run(
                    reader, writer, matcher, umi_length, target=args.target,
                    revcomp=args.revcomp,
                    store_original=not args.dont_store_original,
                    rejects_writer=rejects_writer)
    except (ValueError, OSError) as e:
        log.error("%s", e)
        return 2
    dt = time.monotonic() - t0
    rejected = stats.missing_umis + stats.wrong_length + stats.mismatched
    total = stats.records_written + rejected
    log.info("correct: read %d records; kept %d, rejected %d "
             "(%d missing, %d wrong length, %d mismatched) in %.2fs",
             total, stats.records_written, rejected, stats.missing_umis,
             stats.wrong_length, stats.mismatched, dt)
    if stats.missing_umis or stats.wrong_length:
        log.error("%d records missing UMI attributes; %d had UMIs of "
                  "unexpected length", stats.missing_umis, stats.wrong_length)
    if args.metrics:
        write_correction_metrics(stats, umi_length, args.metrics)
    if args.min_corrected is not None and total:
        ratio = stats.records_written / total
        if ratio < args.min_corrected:
            log.error("Final ratio of reads kept / total was %.2f (minimum "
                      "%.2f); this could indicate a mismatch between library "
                      "preparation and the provided UMI whitelist",
                      ratio, args.min_corrected)
            return 1
    return 0


def _add_dedup(sub):
    p = sub.add_parser("dedup", help="Mark or remove PCR duplicates using UMIs")
    p.add_argument("-i", "--input", required=True,
                   help="template-coordinate sorted BAM (zipper + sort)")
    p.add_argument("-o", "--output", required=True)
    p.add_argument("--max-memory", default="auto",
                   help="pipeline working-set budget (MiB count, human "
                        "size, or auto): bytes-in-flight bound on queued "
                        "batches in threaded runs")
    p.add_argument("-m", "--metrics", default=None, help="dedup metrics TSV")
    p.add_argument("-H", "--family-size-histogram", default=None)
    p.add_argument("-r", "--remove-duplicates", action="store_true",
                   help="drop duplicates instead of setting the 0x400 flag")
    p.add_argument("-q", "--min-map-q", type=int, default=0)
    p.add_argument("-n", "--include-non-pf-reads", action="store_true")
    p.add_argument("--include-unmapped", action="store_true",
                   help="emit no-mapped-read templates untouched instead of dropping")
    p.add_argument("-s", "--strategy", default="adjacency",
                   choices=["identity", "edit", "adjacency", "paired"])
    p.add_argument("-e", "--edits", type=int, default=1)
    p.add_argument("-l", "--min-umi-length", type=int, default=None)
    p.add_argument("--no-umi", action="store_true",
                   help="dedup by position only, orientation-agnostic (Picard-like)")
    p.add_argument("--index-threshold", type=int, default=None,
                   help="minimum distinct UMIs per group before the indexed "
                        "candidate search replaces the dense pairwise scan; "
                        "0 = always dense")
    p.add_argument("--threads", type=int, default=0,
                   help="reader/writer threads around the batch engine "
                        "(0/1 = inline)")
    p.add_argument("--stats", action="store_true",
                   help="print per-stage pipeline timing table")
    p.add_argument("--classic", action="store_true",
                   help="force the per-template engine (no batch vectorization)")
    _add_pipeline_compat(p)
    p.set_defaults(func=cmd_dedup)


def cmd_dedup(args):
    from .commands.dedup import (run_dedup, write_family_size_histogram,
                                 write_metrics)
    from .core.template import is_template_coordinate_sorted
    from .io.bam import BamReader, BamWriter

    if getattr(args, "index_threshold", None) is not None:
        from .umi.assigners import set_index_threshold

        set_index_threshold(args.index_threshold)
    # argument-combination validation before the output file is touched
    if args.strategy == "paired" and args.no_umi:
        log.error("--no-umi cannot be used with --strategy paired")
        return 2
    if args.strategy == "paired" and args.min_umi_length is not None:
        log.error("Paired strategy cannot be used with --min-umi-length")
        return 2

    from .native import batch as nbat

    use_fast = nbat.available() and not getattr(args, "classic", False)
    t0 = time.monotonic()
    try:
        if use_fast:
            from .io.batch_reader import BamBatchReader

            reader = BamBatchReader(args.input)
        else:
            reader = BamReader(args.input)
        with reader:
            hdr_text = reader.header.text
            if not is_template_coordinate_sorted(hdr_text):
                log.error(
                    "dedup requires template-coordinate sorted input (header must "
                    "advertise SS:template-coordinate). Prepare with:\n"
                    "  fgumi-tpu zipper ... | fgumi-tpu sort --order template-coordinate")
                return 2
            out_header = _header_with_pg(reader.header, _cmdline())
            with BamWriter(args.output, out_header) as writer:
                if use_fast:
                    from .commands.fast_group import FastDedup
                    from .umi.assigners import make_assigner

                    strategy, edits = args.strategy, args.edits
                    if args.no_umi:
                        strategy, edits = "identity", 0
                    from .pipeline import StageTimes, run_stages
                    from .utils.progress import ProgressTracker

                    stats_t = StageTimes()
                    progress = ProgressTracker("dedup")
                    dd = FastDedup(
                        reader.header, make_assigner(strategy, edits),
                        min_mapq=args.min_map_q,
                        include_non_pf=args.include_non_pf_reads,
                        min_umi_length=args.min_umi_length,
                        no_umi=args.no_umi,
                        include_unmapped=args.include_unmapped,
                        remove_duplicates=args.remove_duplicates)

                    def _process(batch):
                        progress.add(batch.n)
                        return dd.process_batch(batch)

                    try:
                        run_stages(iter(reader), _process,
                                   writer.write_serialized,
                                   threads=args.threads, stats=stats_t,
                                   **_stage_kwargs(args))
                        for chunk in dd.flush():
                            writer.write_serialized(chunk)
                    finally:
                        # failure reports still carry records.dedup
                        progress.finish()
                    metrics, family_sizes = dd.result()
                    if getattr(args, "stats", False):
                        _print_stats(stats_t)
                else:
                    metrics, family_sizes = run_dedup(
                        reader, writer, strategy=args.strategy,
                        edits=args.edits, min_mapq=args.min_map_q,
                        include_non_pf=args.include_non_pf_reads,
                        min_umi_length=args.min_umi_length,
                        no_umi=args.no_umi,
                        include_unmapped=args.include_unmapped,
                        remove_duplicates=args.remove_duplicates)
    except (ValueError, OSError) as e:
        log.error("%s", e)
        return 2
    dt = time.monotonic() - t0
    log.info("dedup: %d templates (%d unique, %d duplicate, rate %.4f), "
             "%d reads in %.2fs",
             metrics.total_templates, metrics.unique_templates,
             metrics.duplicate_templates, metrics.duplicate_rate(),
             metrics.total_reads, dt)
    dropped = metrics.filter.as_dict()
    dropped.pop("total_templates", None)
    dropped.pop("accepted", None)
    if dropped:
        log.info("dedup: templates dropped by filtering: %s", dropped)
    if metrics.missing_tc_tag:
        log.warning("%d secondary/supplementary reads missing the tc tag "
                    "(run zipper before sort)", metrics.missing_tc_tag)
    if args.metrics:
        write_metrics(metrics, args.metrics)
    if args.family_size_histogram:
        write_family_size_histogram(family_sizes, args.family_size_histogram)
    return 0


def _add_pipeline(sub):
    p = sub.add_parser(
        "pipeline",
        help="FASTQ -> filtered consensus BAM: extract, sort, group, "
             "simplex, filter chained in one process")
    p.add_argument("-i", "--input", required=True, nargs="+",
                   help="FASTQ file per sequencing read (R1 [R2 ...])")
    p.add_argument("-r", "--read-structures", nargs="*", default=[],
                   help="one per FASTQ, e.g. 8M12S+T (default +T)")
    p.add_argument("-o", "--output", required=True,
                   help="filtered consensus BAM")
    p.add_argument("--sample", required=True)
    p.add_argument("--library", required=True)
    p.add_argument("-s", "--strategy", default="adjacency",
                   help="UMI assignment strategy (group -s)")
    p.add_argument("--consensus-min-reads", type=int, default=1,
                   help="simplex --min-reads")
    p.add_argument("--filter-min-reads", type=int, default=3,
                   help="filter --min-reads")
    p.add_argument("--threads", type=int, default=0,
                   help="stage threads, forwarded to every stage that "
                        "accepts them (sort spill workers, group, simplex)")
    p.add_argument("--keep-intermediates", default=None, metavar="DIR",
                   help="write stage outputs here and keep them (forces the "
                        "classic staged path; default without it: fused "
                        "in-memory chain, no intermediate files)")
    p.add_argument("--no-fuse", action="store_true",
                   help="run the classic staged path (intermediate BAMs in "
                        "a temp dir) instead of the fused in-memory chain; "
                        "output is byte-identical either way")
    p.add_argument("--device-filter", action="store_true",
                   help="fuse the filter stage INTO simplex (ISSUE 11): "
                        "consensus columns stay device-resident, verdicts "
                        "come from the fused mask kernel, and only "
                        "surviving records are fetched + serialized — "
                        "byte-identical records to the chained filter "
                        "stage")
    _add_shard_opts(p)
    _add_pipeline_compat(p)
    p.set_defaults(func=cmd_pipeline)


def _pipeline_stage_argvs(args, j):
    """The five stage argv lists of the FastqToConsensus chain, shared by
    the staged and fused drivers (identical argv in both modes, so flag
    handling and any argv-derived behavior cannot drift between them).
    ``j(name)`` maps an intermediate file name to its path — a real temp
    path in staged mode, an unused placeholder in fused mode."""
    thr = ["--threads", str(args.threads)] if args.threads else []
    lvl0 = ["--compression-level", "0"]
    # user-facing compat flags forward to every stage; the user's
    # --compression-level applies to the FINAL output only (intermediates
    # stay level 0 by design — they are deleted as soon as they are read)
    fwd = []
    if args.memory_per_thread:
        fwd += ["--memory-per-thread", args.memory_per_thread]
    out_lvl = ([] if args.compression_level is None
               else ["--compression-level", str(args.compression_level)])
    rs = (["-r"] + args.read_structures) if args.read_structures else []
    # scatter sub-job: the front stages (extract/sort/group) replicate the
    # full deterministic stream on every shard — identical MI assignment
    # and family ordinals everywhere — and the shard filter cuts the
    # stream down at the simplex stage, where families become independent
    shard_fwd = []
    if getattr(args, "shard", None):
        shard_fwd = ["--shard", args.shard, "--shard-by", args.shard_by]
        if args.shard_manifest:
            shard_fwd += ["--shard-manifest", args.shard_manifest]
    # --threads reaches every stage with threaded internals: sort's Phase-1
    # spill workers and group's reader/writer stages are deterministic
    # (byte-identical output), not just simplex
    stages = [
        ("extract", ["extract", "-i"] + args.input + rs +
         ["-o", j("unmapped.bam"), "--sample", args.sample,
          "--library", args.library] + lvl0 + fwd),
        ("sort", ["sort", "-i", j("unmapped.bam"), "-o", j("sorted.bam"),
                  "--order", "template-coordinate"] + lvl0 + thr + fwd),
        ("group", ["group", "-i", j("sorted.bam"), "-o", j("grouped.bam"),
                   "-s", args.strategy, "--allow-unmapped"] + lvl0 + thr
         + fwd),
    ]
    if getattr(args, "device_filter", False):
        # fused consensus→filter (ISSUE 11): the filter stage disappears —
        # simplex carries the filter thresholds, judges every read from
        # the device-resident columns, and writes the FINAL output
        stages.append(
            ("simplex", ["simplex", "-i", j("grouped.bam"),
                         "-o", args.output,
                         "--min-reads", str(args.consensus_min_reads),
                         "--allow-unmapped", "--device-filter",
                         "--filter-min-reads", str(args.filter_min_reads)]
             + shard_fwd + out_lvl + thr + fwd))
        return stages
    stages += [
        ("simplex", ["simplex", "-i", j("grouped.bam"), "-o", j("cons.bam"),
                     "--min-reads", str(args.consensus_min_reads),
                     "--allow-unmapped"] + shard_fwd + lvl0 + thr + fwd),
        ("filter", ["filter", "-i", j("cons.bam"), "-o", args.output,
                    "--min-reads", str(args.filter_min_reads)] + out_lvl
         + fwd),
    ]
    return stages


def cmd_pipeline(args):
    """FastqToConsensus best-practice chain in one process.

    The reference ships this as a Snakemake workflow over separate fgumi
    invocations (/root/reference/docs/FastqToConsensus-RnD.smk:1-40). Two
    in-process drivers, byte-identical outputs:

    - **fused** (default when the native engine is available): adjacent
      stages hand decoded record batches through bounded in-memory channels
      (``pipeline_chain``) — no intermediate files, no BGZF encode/decode
      between stages, and the stages genuinely overlap (extract feeds
      sort's Phase-1 spill ingest as it produces; the sort merge is the
      natural barrier; group ⇒ simplex ⇒ filter stream as one segment).
    - **staged** (``--no-fuse``, ``--keep-intermediates``, or no native
      runtime): each stage re-enters main() and writes a stored (level-0)
      intermediate BAM, deleted as soon as the next stage has consumed it.
    """
    from .native import batch as nbat

    fuse = (not args.no_fuse and args.keep_intermediates is None
            and nbat.available())
    if fuse:
        return _pipeline_fused(args)
    if not args.no_fuse and args.keep_intermediates is None:
        log.info("pipeline: native batch engine unavailable; running the "
                 "staged chain")
    return _pipeline_staged(args)


def _pipeline_fused(args):
    """The fused in-memory chain driver: one thread per stage, adjacent
    stages joined by byte-budgeted channels. Failure in any stage aborts
    the chain (channels cascade ``ChainAborted`` both ways); the first
    stage in chain order with a real error decides the exit code, exactly
    like the staged driver's first-failing-stage contract."""
    import threading as _threading

    from .observe import heartbeat as _hb
    from .observe.metrics import METRICS
    from .observe.scope import spawn_thread
    from .pipeline_chain import (ChainAborted, ChainChannel,
                                 ChannelBamWriter, ChannelBatchReader)

    stages = _pipeline_stage_argvs(args, lambda name: f"<fused:{name}>")
    # nested-stage flag travel, exactly like the staged driver's `pre`
    pre = ["--no-atomic-output"] if args.no_atomic_output else []
    if args.audit_output:
        pre.append("--audit-output")
    parser = build_parser()
    ns = {name: parser.parse_args(pre + argv) for name, argv in stages}

    dfilt = getattr(args, "device_filter", False)
    c1 = ChainChannel("extract.sort")
    c2 = ChainChannel("sort.group")
    c3 = ChainChannel("group.simplex")
    c4 = None if dfilt else ChainChannel("simplex.filter")
    chans = [c1, c2, c3] + ([] if dfilt else [c4])

    def _sink(chan):
        return lambda header: ChannelBamWriter(chan, header)

    # writable=False only where the consumer provably never writes its
    # batches (sort ingest memcpys into pools, group builds fresh records).
    # simplex (overlap correction) and filter (native in-place N/Q2
    # masking via apply_masks, which writes through the raw pointer and
    # would bypass numpy's read-only guard entirely) need writable input
    calls = {
        "extract": lambda a: cmd_extract(a, sink=_sink(c1)),
        "sort": lambda a: cmd_sort(
            a, source=ChannelBatchReader(c1, writable=False),
            sink=_sink(c2)),
        "group": lambda a: cmd_group(
            a, source=ChannelBatchReader(c2, writable=False),
            sink=_sink(c3)),
        # --device-filter: simplex fuses the filter and writes the final
        # output itself (sink=None -> the ordinary BamWriter)
        "simplex": lambda a: cmd_simplex(
            a, source=ChannelBatchReader(
                c3, target_bytes=ns["simplex"].batch_bytes),
            sink=None if dfilt else _sink(c4)),
    }
    ins = {"extract": [], "sort": [c1], "group": [c2], "simplex": [c3]}
    outs = {"extract": [c1], "sort": [c2], "group": [c3],
            "simplex": [] if dfilt else [c4]}
    if not dfilt:
        calls["filter"] = lambda a: cmd_filter(a,
                                               source=ChannelBatchReader(c4))
        ins["filter"] = [c4]
        outs["filter"] = []

    lock = _threading.Lock()
    results = {}
    active = {}

    def runner(name):
        sargs = ns[name]
        t0 = time.monotonic()
        rc = None
        err = None
        aborted = False
        with lock:
            active[name] = True
        try:
            # per-stage compat mapping (BGZF level contextvar etc.) runs in
            # this thread's context copy, so stages stay isolated exactly
            # like the staged driver's per-main() invocations
            rc = _apply_pipeline_compat(sargs)
            if rc == 0:
                sargs.func = calls[name]
                rc = _run_command(sargs)
        except ChainAborted:
            aborted = True  # cascade victim; the root cause is elsewhere
        except BaseException as e:  # noqa: BLE001 - relayed to the driver
            err = e
        finally:
            wall = time.monotonic() - t0
            with lock:
                active.pop(name, None)
                results[name] = {"rc": rc, "error": err, "aborted": aborted}
            METRICS.inc(f"pipeline.stage.{name}.wall_s", round(wall, 6))
            ok = rc == 0 and err is None and not aborted
            if ok:
                # the stage's writer already closed its channel; this close
                # is an idempotent backstop
                for c in outs[name]:
                    c.close()
                log.info("pipeline: %s done in %.2fs", name, wall)
            else:
                for c in outs[name]:
                    c.abort(f"pipeline stage {name} failed")
                for c in ins[name]:
                    c.cancel()

    METRICS.set("pipeline.chain.fused", 1)

    def _running_stages():
        # a started stage parked in its input-header wait (group/simplex/
        # filter until the sort merge opens the segment) is not "running"
        # yet — the heartbeat should show the stages actually doing work,
        # e.g. stage=extract+sort during the ingest-overlap phase
        with lock:
            started = [n for n, _ in stages if n in active]
        return {"stage": "+".join(
            n for n in started
            if all(c.has_header for c in ins[n])) or "-"}

    gauge_token = _hb.register_gauge(_running_stages)
    t00 = time.monotonic()
    threads = []
    try:
        for name, _ in stages:
            t = spawn_thread(runner, args=(name,),
                             name=f"fgumi-chain-{name}")
            threads.append(t)
            t.start()
        try:
            for t in threads:
                while t.is_alive():
                    t.join(timeout=0.2)
        except BaseException:
            # KeyboardInterrupt (or anything else) on the driver thread:
            # tear the chain down so every stage unwinds, then re-raise for
            # the top-level exit-code mapping
            for c in chans:
                c.abort("pipeline interrupted")
                c.cancel()
            for t in threads:
                t.join(timeout=10)
            raise
    finally:
        _hb.unregister_gauge(gauge_token)
        for c in chans:
            c.fold_metrics()
    for name, _ in stages:
        r = results.get(name)
        if r is None:
            continue
        if r["error"] is not None:
            raise r["error"]
        if r["rc"] not in (0, None):
            log.error("pipeline: stage %s failed (rc=%d)", name, r["rc"])
            return r["rc"]
    aborted = [n for n, _ in stages if results.get(n, {}).get("aborted")]
    if aborted:
        log.error("pipeline: stage(s) %s aborted with no root cause "
                  "recorded", ",".join(aborted))
        return 1
    log.info("pipeline: total %.2fs (fused) -> %s", time.monotonic() - t00,
             args.output)
    return 0


def _pipeline_staged(args):
    """The classic staged driver: each stage re-enters main() and writes a
    level-0 intermediate BAM (tmpfs-backed when the host has headroom),
    deleted as soon as the next stage has consumed it."""
    import shutil
    import tempfile

    out_dir = os.path.dirname(os.path.abspath(args.output)) or "."
    keep = args.keep_intermediates
    # intermediates are transient by design — put them on tmpfs when the
    # host has one (file writes become memory copies; ~0.7s of the chain
    # on the bench workload was BufferedWriter.write to disk-backed tmp),
    # falling back next to the output. --keep-intermediates keeps the
    # user-visible directory on the output filesystem as before.
    if keep:
        tmp = keep
        os.makedirs(tmp, exist_ok=True)
    else:
        tmp_parent = out_dir
        shm = "/dev/shm"
        if os.path.isdir(shm) and os.access(shm, os.W_OK):
            try:
                # stored (level-0) intermediates expand gzip inputs ~4x and
                # up to two are alive at once; only use tmpfs when it has
                # clear headroom, else intermediates stay disk-backed
                from .utils.memory import _mem_available

                need = 8 * sum(os.path.getsize(p) for p in args.input)
                st = os.statvfs(shm)
                headroom = st.f_bavail * st.f_frsize
                # tmpfs "free" is the mount quota, not free RAM: tmpfs
                # pages consume physical memory, so also require real
                # MemAvailable headroom or risk inviting the OOM killer
                avail = _mem_available()
                if avail is not None:
                    headroom = min(headroom, avail)
                if headroom > 2 * need:
                    tmp_parent = shm
            except OSError:
                pass
        tmp = tempfile.mkdtemp(prefix="fgumi_pipeline_", dir=tmp_parent)

    def j(name):
        return os.path.join(tmp, name)

    # each stage re-enters main(), which resets the atomic-commit global
    # from its own flags — so an outer --no-atomic-output must travel
    pre = ["--no-atomic-output"] if args.no_atomic_output else []
    if args.audit_output:
        pre.append("--audit-output")
    stages = _pipeline_stage_argvs(args, j)
    consumed = {"sort": "unmapped.bam", "group": "sorted.bam",
                "simplex": "grouped.bam", "filter": "cons.bam"}
    from .observe import heartbeat as _hb
    from .observe.metrics import METRICS

    current = {"stage": "-"}
    gauge_token = _hb.register_gauge(lambda: dict(current))
    try:
        t00 = time.monotonic()
        for name, argv in stages:
            current["stage"] = name
            t0 = time.monotonic()
            rc = main(pre + argv)
            dt = time.monotonic() - t0
            METRICS.inc(f"pipeline.stage.{name}.wall_s", round(dt, 6))
            if rc:
                log.error("pipeline: stage %s failed (rc=%d)", name, rc)
                return rc
            log.info("pipeline: %s done in %.2fs", name, dt)
            prev = consumed.get(name)
            if prev and not keep:
                try:
                    os.unlink(j(prev))
                except OSError:
                    pass
        log.info("pipeline: total %.2fs -> %s", time.monotonic() - t00,
                 args.output)
    finally:
        _hb.unregister_gauge(gauge_token)
        if not keep:
            shutil.rmtree(tmp, ignore_errors=True)
    return 0


def _add_serve(sub):
    p = sub.add_parser(
        "serve",
        help="Run the persistent job-service daemon (warm-kernel serving)")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="Unix-domain socket path to listen on (docs/"
                        "serving.md; relative job paths resolve against "
                        "the daemon's working directory). At least one of "
                        "--socket/--tcp is required")
    p.add_argument("--tcp", default=None, metavar="HOST:PORT",
                   help="additionally listen on TCP (fleet operation): "
                        "per-connection read/write deadlines "
                        "(--io-timeout), a connection cap (--conn-cap), "
                        "and — for any non-loopback HOST — a REQUIRED "
                        "shared-secret handshake (--token-file or "
                        "FGUMI_TPU_SERVE_TOKEN; the wire protocol "
                        "executes submitted commands). Port 0 binds an "
                        "ephemeral port. A busy port exits 2 before the "
                        "device warm-up")
    p.add_argument("--token-file", default=None, metavar="PATH",
                   help="file holding the shared-secret handshake token "
                        "for TCP connections (surrounding whitespace "
                        "stripped; default: FGUMI_TPU_SERVE_TOKEN)")
    p.add_argument("--conn-cap", type=int, default=None, metavar="N",
                   help="max concurrent TCP connections; over-cap "
                        "connects are answered with one explicit error "
                        "frame and closed (default 64; 0 = unlimited)")
    p.add_argument("--io-timeout", type=float, default=None, metavar="S",
                   help="per-connection read/write deadline on TCP "
                        "connections (default 30; 0 = none)")
    p.add_argument("--workers", type=int, default=2,
                   help="concurrent job slots (bounded worker pool)")
    p.add_argument("--queue-limit", type=int, default=8,
                   help="queued jobs admitted beyond the running ones; "
                        "submissions past workers+queue-limit are rejected "
                        "with an explicit reason")
    p.add_argument("--max-per-client", type=int, default=0,
                   help="per-submitter admission quota: a `submit "
                        "--client ID` may hold at most this many active "
                        "(queued+running) jobs; over-quota submits are "
                        "rejected with an explicit reason (0 = unlimited; "
                        "anonymous submits are never limited)")
    p.add_argument("--report-dir", default=None, metavar="DIR",
                   help="write per-job run reports (<job>.report.json) and "
                        "on-request traces here (created if missing)")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent XLA compile-cache directory for warm "
                        "serving (default: the standard cache under "
                        "~/.cache/fgumi_tpu)")
    p.add_argument("--max-frame-bytes", type=int, default=None,
                   help="protocol frame size cap (default 1 MiB); larger "
                        "frames are rejected and the connection closed")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip the startup jax import/device touch (first "
                        "job pays cold start instead)")
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="append-only job journal (JSONL WAL): submits and "
                        "state transitions are fsync'd here, and on "
                        "restart incomplete jobs are requeued in order "
                        "(docs/serving.md crash recovery). Unset = "
                        "in-memory only, the pre-journal behavior")
    p.add_argument("--journal-dir", default=None, metavar="DIR",
                   help="FLEET journaling: journal at DIR/<fleet-id>."
                        "journal with an fcntl lease held for the "
                        "daemon's lifetime. Daemons sharing DIR (one real "
                        "filesystem) take over a dead peer's journal "
                        "exactly once and requeue its incomplete jobs "
                        "under their original ids (docs/serving.md "
                        "\"Fleet operation\"). Exclusive with --journal")
    p.add_argument("--fleet-id", default=None, metavar="NAME",
                   help="this daemon's identity in --journal-dir "
                        "([A-Za-z0-9._-], <=64 chars; job ids become "
                        "<fleet-id>-j-<n> so they are fleet-unique). "
                        "Default: derived from the socket basename or "
                        "the TCP port")
    p.add_argument("--lease-scan-period", type=float, default=2.0,
                   metavar="S",
                   help="how often the fleet lease scanner probes peer "
                        "journals for takeover (0 = never scan; the "
                        "daemon still recovers its own journal)")
    p.add_argument("--health-period", type=float, default=None,
                   metavar="S",
                   help="run a tiny device canary every S seconds feeding "
                        "the wedge circuit breaker (default: "
                        "FGUMI_TPU_HEALTH_PERIOD_S, else off)")
    p.add_argument("--metrics-port", type=int, default=None, metavar="PORT",
                   help="serve Prometheus text-format /metrics and a "
                        "/healthz liveness endpoint on this loopback HTTP "
                        "port (0 = an ephemeral port, logged at startup; "
                        "unset = no listener). The scrape and the `stats` "
                        "protocol op read the same live snapshot "
                        "(docs/serving.md)")
    p.add_argument("--coalesce-window-ms", type=float, default=None,
                   metavar="MS",
                   help="cross-job dispatch coalescing window: while >= 2 "
                        "jobs are running, compatible device batches from "
                        "different jobs are held up to this long and "
                        "merged into one launch, split back per job at "
                        "resolve (byte-identical per job; docs/serving.md "
                        "\"Cross-job batching\"). 0 disables; default: "
                        "FGUMI_TPU_COALESCE_WINDOW_MS, else 2")
    p.set_defaults(func=cmd_serve)


def _default_fleet_id(args):
    """A stable default identity in --journal-dir: the socket basename
    (without extension) or the TCP port. Good enough for one-host fleets;
    multi-host fleets should pass --fleet-id explicitly. Returns None
    when no stable default exists (ephemeral --tcp port 0: every such
    daemon would collide on the same lease)."""
    import re as _re

    if args.socket:
        base = os.path.basename(args.socket)
        base = base[:-5] if base.endswith(".sock") else base
        base = _re.sub(r"[^A-Za-z0-9._-]", "-", base).strip("-.")
        if base:
            return base[:64]
    if args.tcp:
        port = args.tcp.rsplit(":", 1)[-1]
        if port != "0":
            return "tcp-" + port
    return None


def cmd_serve(args):
    import signal

    from .serve import transport as transport_mod
    from .serve.daemon import JobService, SocketBusy
    from .serve.journal import LeaseHeld

    if not args.socket and not args.tcp:
        log.error("serve needs --socket and/or --tcp")
        return 2
    if args.workers < 1:
        log.error("--workers must be >= 1")
        return 2
    if args.queue_limit < 0:
        log.error("--queue-limit must be >= 0")
        return 2
    if args.max_per_client < 0:
        log.error("--max-per-client must be >= 0")
        return 2
    if args.max_frame_bytes is not None and args.max_frame_bytes < 1024:
        # a sub-1KiB cap cannot carry a realistic submit frame, and 0 or a
        # negative value would defeat the size limit entirely
        log.error("--max-frame-bytes must be >= 1024")
        return 2
    if args.metrics_port is not None \
            and not 0 <= args.metrics_port <= 65535:
        log.error("--metrics-port must be in 0..65535")
        return 2
    if args.journal and args.journal_dir:
        log.error("--journal and --journal-dir are exclusive")
        return 2
    if args.conn_cap is not None and args.conn_cap < 0:
        log.error("--conn-cap must be >= 0 (0 = unlimited)")
        return 2
    if args.coalesce_window_ms is not None:
        if args.coalesce_window_ms < 0:
            log.error("--coalesce-window-ms must be >= 0 (0 = off)")
            return 2
        # the coalescer reads the env per dispatch, so the flag is just
        # the daemon-scoped spelling of FGUMI_TPU_COALESCE_WINDOW_MS
        os.environ["FGUMI_TPU_COALESCE_WINDOW_MS"] = \
            str(args.coalesce_window_ms)
    if args.report_dir:
        try:
            os.makedirs(args.report_dir, exist_ok=True)
        except OSError as e:
            log.error("cannot create --report-dir %s: %s", args.report_dir, e)
            return 2
    tcp = None
    if args.tcp:
        try:
            kind, tcp = transport_mod.parse_address("tcp:" + args.tcp)
        except ValueError as e:
            log.error("--tcp: %s", e)
            return 2
    try:
        token = transport_mod.load_token(args.token_file)
    except (OSError, ValueError) as e:
        log.error("--token-file: %s", e)
        return 2
    from .ops.breaker import monitor_period_s
    from .serve import protocol as _proto

    health = args.health_period if args.health_period is not None \
        else monitor_period_s()
    if health < 0:
        log.error("--health-period must be >= 0")
        return 2
    fleet_id = None
    if args.journal_dir:
        fleet_id = args.fleet_id or _default_fleet_id(args)
        if fleet_id is None:
            log.error("--journal-dir with an ephemeral --tcp port has no "
                      "stable default identity; pass --fleet-id")
            return 2
    try:
        service = JobService(
            args.socket, workers=args.workers, queue_limit=args.queue_limit,
            report_dir=args.report_dir,
            max_frame_bytes=args.max_frame_bytes or _proto.MAX_FRAME_BYTES,
            journal_path=args.journal, health_period_s=health,
            max_per_client=args.max_per_client,
            metrics_port=args.metrics_port, tcp=tcp, auth_token=token,
            conn_cap=(args.conn_cap if args.conn_cap is not None
                      else transport_mod.DEFAULT_CONN_CAP),
            io_timeout_s=(args.io_timeout if args.io_timeout is not None
                          else transport_mod.DEFAULT_IO_TIMEOUT_S),
            journal_dir=args.journal_dir, fleet_id=fleet_id,
            lease_scan_period_s=args.lease_scan_period)
    except ValueError as e:
        log.error("%s", e)
        return 2
    # claim the listeners BEFORE the device warm-up: an accidental
    # duplicate start must fail fast without touching the single-tenant
    # chip — a busy TCP port or fleet lease is the same exit-2 contract
    try:
        service.bind()
        service.acquire_lease()
    except (SocketBusy, LeaseHeld) as e:
        log.error("%s", e)
        service.close()
        return 2
    except ValueError as e:
        # a refused listener configuration (non-loopback TCP without a
        # handshake token)
        log.error("%s", e)
        service.close()
        return 2
    except OSError as e:
        if service._unix is not None and service._unix.sock is None:
            log.error("cannot bind %s: %s", args.socket, e)
        elif args.tcp and (service._tcp_listener is None
                           or service._tcp_listener.sock is None):
            log.error("cannot bind tcp %s: %s", args.tcp, e)
        else:
            log.error("cannot bind metrics port %s: %s",
                      args.metrics_port, e)
        service.close()
        return 2
    service.warm_up(compile_cache_dir=args.compile_cache,
                    touch_device=not args.no_warmup)
    service.start()

    def _on_signal(signum, frame):
        # SIGTERM drain contract: stop admitting, finish queued + running.
        # Event-set only — no locks or logging in signal context; the main
        # loop below performs (and logs) the actual drain
        service.request_shutdown()

    old = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            old[sig] = signal.signal(sig, _on_signal)
        except (ValueError, OSError):
            pass  # not the main thread (in-process test harness)
    try:
        service.wait_until_shutdown()
    finally:
        for sig, handler in old.items():
            signal.signal(sig, handler)
        service.close()
    return 0


def _add_submit(sub):
    p = sub.add_parser(
        "submit",
        help="Submit a command to a running serve daemon (warm execution)")
    p.add_argument("--socket", required=True, metavar="ADDR",
                   help="daemon address: a Unix socket path (serve "
                        "--socket), unix:PATH, or tcp:HOST:PORT (serve "
                        "--tcp / a balance front end)")
    p.add_argument("--token-file", default=None, metavar="PATH",
                   help="shared-secret handshake token for TCP daemons "
                        "(default: FGUMI_TPU_SERVE_TOKEN)")
    p.add_argument("--priority", default="normal",
                   choices=["high", "normal", "low"],
                   help="scheduling class (FIFO within a class)")
    p.add_argument("--tag", default=None,
                   help="free-form label kept on the job record")
    p.add_argument("--job-trace", action="store_true",
                   help="ask the daemon for a per-job Perfetto trace next "
                        "to the job's run report (needs serve --report-dir)")
    p.add_argument("--dedupe", default=None, metavar="KEY",
                   help="idempotency key: resubmitting the same key "
                        "returns the original job (even across a daemon "
                        "restart with serve --journal) instead of running "
                        "it twice")
    p.add_argument("--client", default=None, metavar="ID",
                   help="submitter identity for the daemon's per-client "
                        "admission quota (serve --max-per-client); "
                        "omitted = anonymous, never quota-limited")
    p.add_argument("--no-wait", action="store_true",
                   help="return immediately after admission (poll later "
                        "with `fgumi-tpu jobs`)")
    p.add_argument("--timeout", type=float, default=None,
                   help="max seconds to wait for completion (with waiting)")
    p.add_argument("job_argv", nargs=argparse.REMAINDER, metavar="COMMAND",
                   help="the fgumi-tpu command to run, e.g. "
                        "`submit --socket S simplex -i in.bam -o out.bam` "
                        "(everything after the submit options, verbatim)")
    p.set_defaults(func=cmd_submit)


def _submit_with_shed_retry(client, submit_kwargs: dict, wait: bool,
                            timeout: float = None, sleep=time.sleep):
    """Submit, honoring the governor's resource-pressure hint.

    A shed (``resource_pressure`` with ``retry_after_s``) is not a
    failure when the caller intends to wait: sleep EXACTLY the daemon's
    hint and resubmit instead of hot-looping or giving up, bounded by
    the overall ``timeout``. Raises the final ShedError when not waiting
    or out of time. ``sleep`` is injectable for tests."""
    from .serve.client import ShedError

    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        try:
            return client.submit(**submit_kwargs)
        except ShedError as e:
            if not wait:
                raise
            hint = max(float(e.retry_after_s), 0.05)
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise
                hint = min(hint, remaining)
            log.info("submit: daemon shedding under resource pressure; "
                     "retrying in %.1fs (%s)", hint, e)
            sleep(hint)


def _serve_client(args, label: str):
    """(client, rc) for the serve-client verbs: resolves the handshake
    token and the address; a config problem logs one line and returns
    (None, 2)."""
    from .serve import transport as transport_mod
    from .serve.client import ServeClient

    try:
        token = transport_mod.load_token(args.token_file)
        return ServeClient(args.socket, token=token), 0
    except (OSError, ValueError) as e:
        log.error("%s: %s", label, e)
        return None, 2


def cmd_submit(args):
    from .serve.client import ServeError

    job_argv = list(args.job_argv)
    if job_argv and job_argv[0] == "--":
        job_argv = job_argv[1:]
    if not job_argv:
        log.error("submit: no command given (usage: fgumi-tpu submit "
                  "--socket S <command> [args...])")
        return 2
    client, rc = _serve_client(args, "submit")
    if client is None:
        return rc
    # ONE wall-clock budget for the whole command: shed-retry sleeps and
    # the completion wait share it, so --timeout 60 means 60, not 120
    deadline = None if args.timeout is None \
        else time.monotonic() + args.timeout
    try:
        job = _submit_with_shed_retry(
            client,
            dict(argv=job_argv, priority=args.priority, tag=args.tag,
                 trace=args.job_trace, dedupe=args.dedupe,
                 client=args.client),
            wait=not args.no_wait, timeout=args.timeout)
    except ServeError as e:
        log.error("submit: %s", e)
        return 2
    log.info("submitted %s (%s): %s", job["id"], job["state"],
             " ".join(job["argv"]))
    if args.no_wait:
        print(job["id"])
        return 0
    try:
        job = client.wait(
            job["id"],
            timeout=None if deadline is None
            else max(deadline - time.monotonic(), 0.0))
    except ServeError as e:
        log.error("submit: %s", e)
        return 2
    rc = job["exit_status"]
    if job["state"] == "done":
        log.info("job %s done in %.2fs", job["id"],
                 job["finished_unix"] - job["started_unix"])
        return 0
    if job["state"] == "cancelled":
        log.error("job %s was cancelled", job["id"])
        return 130
    log.error("job %s failed: %s", job["id"], job["error"])
    return rc if isinstance(rc, int) and rc else 1


def _add_balance(sub):
    p = sub.add_parser(
        "balance",
        help="Run the fleet balancer: a health-routed front end over N "
             "serve daemons speaking the same wire protocol "
             "(docs/serving.md \"Fleet operation\")")
    p.add_argument("--listen", required=True, metavar="ADDR",
                   help="front-end address: unix:PATH or tcp:HOST:PORT "
                        "(non-loopback TCP requires the handshake token, "
                        "like serve --tcp; port 0 = ephemeral)")
    p.add_argument("--backend", action="append", required=True,
                   metavar="ADDR", dest="backends",
                   help="one serve daemon address (repeat per backend): "
                        "unix:PATH or tcp:HOST:PORT")
    p.add_argument("--token-file", default=None, metavar="PATH",
                   help="shared-secret handshake token used BOTH for the "
                        "front listener and toward TCP backends — a fleet "
                        "shares one secret (default: "
                        "FGUMI_TPU_SERVE_TOKEN)")
    p.add_argument("--poll-period", type=float, default=1.0, metavar="S",
                   help="health/depth poll period: each backend's `stats` "
                        "op feeds queue-depth routing and the ejection "
                        "breaker")
    p.add_argument("--eject-failures", type=int, default=2, metavar="N",
                   help="consecutive probe/request failures that eject a "
                        "backend (closed -> open)")
    p.add_argument("--cooldown", type=float, default=5.0, metavar="S",
                   help="ejection cooldown before the half-open re-probe "
                        "(doubles per consecutive re-trip, capped 8x)")
    p.add_argument("--probes", type=int, default=2, metavar="N",
                   help="consecutive half-open probe successes that "
                        "re-admit a backend")
    p.add_argument("--conn-cap", type=int, default=None, metavar="N",
                   help="max concurrent front-end TCP connections "
                        "(default 64)")
    p.add_argument("--io-timeout", type=float, default=None, metavar="S",
                   help="per-connection read/write deadline on front-end "
                        "TCP connections (default 30; 0 = none)")
    p.add_argument("--backend-timeout", type=float, default=30.0,
                   metavar="S",
                   help="per-request timeout toward a backend")
    p.add_argument("--max-frame-bytes", type=int, default=None,
                   help="protocol frame size cap (default 1 MiB)")
    p.add_argument("--metrics-port", type=int, default=None, metavar="PORT",
                   help="serve the fleet Prometheus /metrics endpoint (+ a "
                        "/healthz that goes 503 when no backend is "
                        "routable) on this loopback HTTP port: fleet "
                        "rollups plus every backend's cached series "
                        "re-exported with a backend=\"ADDR\" label, from "
                        "the same health-poll snapshot the `stats` op "
                        "reports (0 = ephemeral; unset = no listener; "
                        "docs/serving.md \"Fleet metrics\")")
    g = p.add_argument_group("whale scatter/gather")
    g.add_argument("--scatter", type=int, default=0, metavar="N",
                   help="split every submitted pipeline/simplex/duplex "
                        "job into N dedupe-keyed shard sub-jobs fanned "
                        "out across the backends, then k-way merge the "
                        "shard outputs into ONE BAM byte-identical to a "
                        "single-backend run (N >= 2; 0 = off; requires a "
                        "filesystem shared with the backends; "
                        "docs/serving.md \"Scatter/gather\")")
    g.add_argument("--scatter-axis", default="umi",
                   choices=("umi", "coord"),
                   help="content-hash axis for the family split: the "
                        "UMI's MI value, or the template coordinate "
                        "(default umi; both are explicit hashes — "
                        "deterministic across hosts and Python hash "
                        "seeds)")
    g.add_argument("--scatter-wal", default=None, metavar="PATH",
                   help="fsync'd JSONL write-ahead log of whale/shard "
                        "state: a restarted balancer resumes in-flight "
                        "whales from it, resubmitting shards under their "
                        "idempotent dedupe keys (unset = whales do not "
                        "survive a balancer restart)")
    g.add_argument("--scatter-grace", type=float, default=20.0,
                   metavar="S",
                   help="how long a shard job may stay unknown "
                        "fleet-wide before the coordinator requeues it "
                        "under an attempt-suffixed dedupe key — keep "
                        "this LONGER than the daemons' lease-scan "
                        "period so a journal takeover wins the race "
                        "(default 20)")
    p.set_defaults(func=cmd_balance)


def cmd_balance(args):
    import signal

    from .serve import protocol as _proto
    from .serve import transport as transport_mod
    from .serve.balancer import Balancer
    from .serve.daemon import SocketBusy

    if args.poll_period <= 0:
        log.error("--poll-period must be > 0")
        return 2
    if args.eject_failures < 1 or args.probes < 1:
        log.error("--eject-failures and --probes must be >= 1")
        return 2
    if args.max_frame_bytes is not None and args.max_frame_bytes < 1024:
        log.error("--max-frame-bytes must be >= 1024")
        return 2
    if args.metrics_port is not None \
            and not 0 <= args.metrics_port <= 65535:
        log.error("--metrics-port must be in 0..65535")
        return 2
    if args.scatter and args.scatter < 2:
        log.error("--scatter needs at least 2 shards (0 disables it)")
        return 2
    if args.scatter_grace <= 0:
        log.error("--scatter-grace must be > 0")
        return 2
    try:
        token = transport_mod.load_token(args.token_file)
        for addr in [args.listen] + args.backends:
            transport_mod.parse_address(addr)
        balancer = Balancer(
            args.listen, args.backends, token=token, backend_token=token,
            max_frame_bytes=args.max_frame_bytes or _proto.MAX_FRAME_BYTES,
            poll_period_s=args.poll_period,
            eject_failures=args.eject_failures, cooldown_s=args.cooldown,
            probe_successes=args.probes,
            conn_cap=(args.conn_cap if args.conn_cap is not None
                      else transport_mod.DEFAULT_CONN_CAP),
            io_timeout_s=(args.io_timeout if args.io_timeout is not None
                          else transport_mod.DEFAULT_IO_TIMEOUT_S),
            backend_timeout_s=args.backend_timeout,
            metrics_port=args.metrics_port,
            scatter_shards=args.scatter, scatter_axis=args.scatter_axis,
            scatter_wal=args.scatter_wal,
            scatter_grace_s=args.scatter_grace)
    except (OSError, ValueError) as e:
        log.error("balance: %s", e)
        return 2
    try:
        balancer.bind()
    except SocketBusy as e:
        log.error("%s", e)
        return 2
    except OSError as e:
        log.error("cannot bind %s: %s", args.listen, e)
        return 2
    balancer.start()

    def _on_signal(signum, frame):
        # SIGTERM drain contract: event-set only; the main loop below
        # does the drain (and its logging) outside signal context
        balancer.request_shutdown()

    old = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            old[sig] = signal.signal(sig, _on_signal)
        except (ValueError, OSError):
            pass  # not the main thread (in-process test harness)
    try:
        balancer.wait_until_shutdown()
    finally:
        for sig, handler in old.items():
            signal.signal(sig, handler)
        balancer.close()
    return 0


def _add_stats(sub):
    p = sub.add_parser(
        "stats",
        help="Print a running serve daemon's live introspection snapshot "
             "(scheduler/quota/journal/breaker/governor/device/fleet "
             "state + latency histogram summaries) as JSON")
    p.add_argument("--socket", required=True, metavar="ADDR",
                   help="daemon address: a Unix socket path, unix:PATH, "
                        "or tcp:HOST:PORT (a balance front end answers "
                        "with per-backend health)")
    p.add_argument("--token-file", default=None, metavar="PATH",
                   help="shared-secret handshake token for TCP daemons "
                        "(default: FGUMI_TPU_SERVE_TOKEN)")
    p.add_argument("--section", default=None, metavar="KEY",
                   help="print only one top-level section of the snapshot "
                        "(e.g. latency, scheduler, breaker)")
    p.set_defaults(func=cmd_stats)


def cmd_stats(args):
    import json as _json

    from .serve.client import ServeError

    client, rc = _serve_client(args, "stats")
    if client is None:
        return rc
    try:
        stats = client.stats()
    except ServeError as e:
        # includes the old-daemon rejection ("unknown op 'stats' ...")
        # verbatim — the version-negotiation contract
        log.error("stats: %s", e)
        return 2
    if args.section is not None:
        if args.section not in stats:
            log.error("stats: no section %r (have: %s)", args.section,
                      ", ".join(sorted(stats)))
            return 2
        stats = {args.section: stats[args.section]}
    print(_json.dumps(stats, indent=1, sort_keys=True))
    return 0


def _add_jobs(sub):
    p = sub.add_parser(
        "jobs", help="Inspect or manage a serve daemon's job queue")
    p.add_argument("--socket", required=True, metavar="ADDR",
                   help="daemon address: a Unix socket path, unix:PATH, "
                        "or tcp:HOST:PORT")
    p.add_argument("--token-file", default=None, metavar="PATH",
                   help="shared-secret handshake token for TCP daemons "
                        "(default: FGUMI_TPU_SERVE_TOKEN)")
    g = p.add_mutually_exclusive_group()
    g.add_argument("--id", default=None, help="show one job as JSON")
    g.add_argument("--cancel", default=None, metavar="ID",
                   help="cancel a queued job")
    g.add_argument("--drain", action="store_true",
                   help="close admission (running/queued jobs finish; the "
                        "daemon keeps answering status)")
    g.add_argument("--shutdown", action="store_true",
                   help="drain, finish queued+running jobs, then exit")
    g.add_argument("--ping", action="store_true",
                   help="print daemon liveness/config as JSON")
    g.add_argument("--scatter", nargs="?", const="", default=None,
                   metavar="WHALE_ID",
                   help="print a `balance --scatter` front end's whale "
                        "scatter section as JSON (with WHALE_ID: that "
                        "whale's per-shard states); daemons and "
                        "non-scatter balancers answer their documented "
                        "refusal (docs/serving.md \"Whale "
                        "scatter/gather\")")
    p.set_defaults(func=cmd_jobs)


def cmd_jobs(args):
    import json as _json

    from .serve.client import ServeError

    client, rc = _serve_client(args, "jobs")
    if client is None:
        return rc
    try:
        if args.ping:
            print(_json.dumps(client.ping(), indent=1, sort_keys=True))
            return 0
        if args.scatter is not None:
            sc = client.scatter(args.scatter or None)
            print(_json.dumps(sc, indent=1, sort_keys=True))
            return 0
        if args.id:
            print(_json.dumps(client.job(args.id), indent=1, sort_keys=True))
            return 0
        if args.cancel:
            job = client.cancel(args.cancel)
            log.info("job %s cancelled", job["id"])
            return 0
        if args.drain:
            depth = client.drain()
            if "running" in depth:
                log.info("draining: %d running, %d queued",
                         depth["running"], depth["queued"])
            else:  # a balance front answers with its own (depthless) ack
                log.info("draining: balancer admission closed")
            return 0
        if args.shutdown:
            depth = client.shutdown()
            if "running" in depth:
                log.info("shutdown requested: %d running, %d queued to "
                         "finish", depth["running"], depth["queued"])
            else:
                log.info("shutdown requested: balancer draining and "
                         "exiting")
            return 0
        status = client.status()
        jobs = status["jobs"]
        if not jobs:
            print("no jobs")
            return 0
        print(f"{'id':<8} {'state':<10} {'prio':<7} {'rc':<4} command")
        for j in jobs:
            rc = "" if j["exit_status"] is None else str(j["exit_status"])
            print(f"{j['id']:<8} {j['state']:<10} {j['priority']:<7} "
                  f"{rc:<4} {' '.join(j['argv'])}")
        return 0
    except ServeError as e:
        log.error("jobs: %s", e)
        return 2


def _add_trace_merge(sub):
    p = sub.add_parser(
        "trace-merge",
        help="Stitch per-process --trace files from one fleet-routed job "
             "(client, balancer, backend) into a single Perfetto "
             "timeline, clock-aligned on each file's wall-clock anchor "
             "(docs/observability.md \"Fleet tracing\")")
    p.add_argument("traces", nargs="+", metavar="TRACE.json",
                   help="Chrome trace-event files to merge (each process's "
                        "--trace output)")
    p.add_argument("-o", "--output", required=True, metavar="PATH",
                   help="merged trace file to write")
    p.add_argument("--trace-id", default=None, metavar="HEX32",
                   help="keep only inputs stamped with this fleet trace "
                        "id; others are skipped (recorded under "
                        "otherData.skipped)")
    p.add_argument("--shift", action="append", default=None,
                   metavar="FILE=SECONDS", dest="shifts",
                   help="add SECONDS to FILE's timeline on top of the "
                        "automatic anchor/handshake-offset alignment "
                        "(FILE matches the path or its basename; repeat "
                        "per file)")
    p.add_argument("--force", action="store_true",
                   help="merge even when the inputs carry different trace "
                        "ids (default: that is an error)")
    p.set_defaults(func=cmd_trace_merge)


def cmd_trace_merge(args):
    from .observe.trace_merge import (MergeError, merge_traces,
                                      parse_shift_specs, write_merged)

    try:
        shifts = parse_shift_specs(args.shifts)
        merged = merge_traces(args.traces, trace_id=args.trace_id,
                              shifts=shifts, force=args.force)
        write_merged(merged, args.output)
    except MergeError as e:
        log.error("trace-merge: %s", e)
        return 2
    except OSError as e:
        log.error("trace-merge: cannot write %s: %s", args.output, e)
        return 2
    skipped = (merged.get("otherData") or {}).get("skipped") or []
    for s in skipped:
        log.info("trace-merge: skipped %s (trace id %s)", s["path"],
                 s.get("trace_id"))
    merged_from = merged["otherData"]["merged_from"]
    log.info("trace-merge: merged %d file(s), %d event(s) -> %s",
             len(merged_from), len(merged["traceEvents"]), args.output)
    return 0


def _add_tune(sub):
    p = sub.add_parser(
        "tune",
        help="Measure this host's device/host crossovers on a simulated "
             "workload matrix and write a deployment profile (tuned "
             "knobs + measured router/chooser priors, loaded via "
             "--profile/FGUMI_TPU_PROFILE) plus a crossover atlas "
             "(docs/performance-tuning.md \"Deployment profiles\")")
    p.add_argument("-o", "--output", default="deploy_profile.json",
                   metavar="PATH",
                   help="deployment profile to write")
    p.add_argument("--atlas", default="TUNE_ATLAS.json", metavar="PATH",
                   help="crossover atlas to write ('' disables)")
    p.add_argument("--quick", action="store_true",
                   help="CI-sized sweep: the three fixed-depth crossover "
                        "cells only, small pileups (seconds, not minutes)")
    p.add_argument("--replay", action="append", default=None,
                   metavar="JSON", dest="replay",
                   help="derive the profile from recorded evidence "
                        "instead of sweeping: run-report JSONs "
                        "(device.routing EWMAs) and/or microbench JSONs "
                        "(tune_cells from the --backend matrix); repeat "
                        "per file")
    p.set_defaults(func=cmd_tune)


def cmd_tune(args):
    from .tune.autotune import run_autotune
    from .tune.profile import ProfileError

    try:
        return run_autotune(args.output, args.atlas or None,
                            quick=args.quick, replay_paths=args.replay)
    except ProfileError as e:
        log.error("%s", e)
        return 2


def build_parser():
    parser = argparse.ArgumentParser(
        prog="fgumi-tpu",
        description="TPU-native toolkit for UMI-tagged sequencing data",
    )
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="alias for --log-level debug (superseded by an "
                             "explicit --log-level)")
    parser.add_argument(
        "--log-level", choices=["debug", "info", "warning", "error"],
        default=None,
        help="log verbosity (also FGUMI_TPU_LOG); every line carries "
             "elapsed time and the emitting thread's name")
    parser.add_argument(
        "--no-atomic-output", action="store_true",
        help="write outputs directly to their final names instead of the "
             "crash-safe temp-file + atomic-rename commit (escape hatch "
             "for FIFO outputs; also FGUMI_TPU_NO_ATOMIC=1)")
    parser.add_argument(
        "--audit-output", action="store_true",
        help="verify every written BAM end to end (per-member BGZF "
             "CRC32/ISIZE, BAM structure, record count and sort-key-order "
             "digest against the writer's own tallies) BEFORE the atomic "
             "rename publishes it; a mismatch aborts the commit with exit "
             "5 so host-side corruption cannot ship a bad file "
             "(also FGUMI_TPU_AUDIT_OUTPUT=1; docs/resilience.md)")
    parser.add_argument(
        "--trace", default=None, metavar="PATH",
        help="record pipeline/IO/device spans and write a Chrome "
             "trace-event JSON loadable in Perfetto (also FGUMI_TPU_TRACE)")
    parser.add_argument(
        "--xla-profile", default=None, metavar="DIR",
        help="capture a one-shot jax.profiler device trace of the first "
             "device dispatch into DIR (TensorBoard/xprof format; "
             "FGUMI_TPU_XLA_PROFILE_NTH=N profiles the Nth dispatch "
             "instead — N=2 skips the XLA compile); the run report "
             "records the directory (also FGUMI_TPU_XLA_PROFILE)")
    parser.add_argument(
        "--run-report", default=None, metavar="PATH",
        help="write a schema-versioned JSON run report (wall time, "
             "per-stage busy/blocked, queue occupancy, device + I/O "
             "counters, exit status) at command end "
             "(also FGUMI_TPU_RUN_REPORT)")
    parser.add_argument(
        "--heartbeat", type=float, default=None, metavar="SECONDS",
        help="log a one-line progress heartbeat (stage counters, queue "
             "depths, device activity, p99 dispatch wall, records/s + ETA, "
             "RSS) every N seconds "
             "(also FGUMI_TPU_HEARTBEAT_S; 0 = off, the default)")
    parser.add_argument(
        "--flight-dump-dir", default=None, metavar="DIR",
        help="write flight-recorder black boxes (ring of recent events + "
             "all-thread stacks + metrics/device/breaker/governor "
             "snapshots) here on unhandled exceptions, resource "
             "exhaustion, dispatch-deadline overruns, breaker trips, and "
             "SIGTERM (also FGUMI_TPU_FLIGHT; unset = record the ring but "
             "never write a file)")
    parser.add_argument(
        "--profile", default=None, metavar="PATH",
        help="load a deployment profile (fgumi-tpu tune output): tuned "
             "knob values fill any FGUMI_TPU_* vars not explicitly set "
             "(explicit env/flags always win) and measured router/chooser "
             "priors seed the adaptive offload machinery so the first "
             "batch routes on the measured side of each crossover "
             "(also FGUMI_TPU_PROFILE; docs/performance-tuning.md)")
    parser.add_argument(
        "--shape-buckets", type=_shape_buckets_arg, default=None,
        metavar="GROWTH[:CAP]",
        help="device padded-shape bucket ladder: geometric growth factor "
             "in [1.01, 2.0] between adjacent buckets (default 1.0625) and "
             "optional ladder cap (default 2^24); bounds the XLA "
             "executable vocabulary and the padding waste "
             "(also FGUMI_TPU_SHAPE_BUCKETS; docs/device-datapath.md)")
    parser.add_argument(
        "--mesh", type=_mesh_arg, default=None, metavar="dpNxspM",
        help="device mesh for sharded consensus dispatch: dpNxspM forces "
             "an exact (data-parallel x sequence-parallel) shape validated "
             "against the visible device count, 'auto' uses every device, "
             "'off' disables sharding; overrides --devices/FGUMI_TPU_SP "
             "(also FGUMI_TPU_MESH; docs/multi-chip.md)")
    sub = parser.add_subparsers(dest="command", required=True)
    _add_extract(sub)
    _add_correct(sub)
    _add_zipper(sub)
    _add_simplex(sub)
    _add_duplex(sub)
    _add_codec(sub)
    _add_duplex_metrics(sub)
    _add_simplex_metrics(sub)
    _add_review(sub)
    _add_compare(sub)
    _add_filter(sub)
    _add_clip(sub)
    _add_group(sub)
    _add_dedup(sub)
    _add_sort(sub)
    _add_merge(sub)
    _add_fastq(sub)
    _add_downsample(sub)
    _add_simulate(sub)
    _add_pipeline(sub)
    _add_serve(sub)
    _add_submit(sub)
    _add_jobs(sub)
    _add_stats(sub)
    _add_balance(sub)
    _add_trace_merge(sub)
    _add_tune(sub)
    return parser


# nesting depth of in-process main() calls: the `pipeline` command re-enters
# main() per stage, and the telemetry lifecycle (trace export, run report,
# per-command scope) belongs to the OUTERMOST invocation only. A contextvar,
# not a module global: the serve daemon runs several top-level commands
# concurrently on worker threads, and each must see its own depth
import contextvars

_main_depth = contextvars.ContextVar("fgumi_tpu_main_depth", default=0)


def _run_command(args):
    """Dispatch to the subcommand with the top-level exception contract."""
    import errno as _errno

    from .io.errors import InputFormatError, OutputIntegrityError
    from .parallel import MeshConfigError
    from .utils.faults import InjectedFault
    from .utils.governor import GOVERNOR, ResourceExhausted

    try:
        pg = getattr(args, "pg_argv", None)
        if pg:
            # scatter sub-job provenance: @PG CL (and every other argv-
            # derived header field) records the WHALE job's command line,
            # so shard outputs are byte-compatible with the unsharded run.
            # Innermost wins over the daemon's per-job command_argv wrap.
            import shlex as _shlex

            from .observe.scope import command_argv

            with command_argv(_shlex.split(pg)):
                return args.func(args)
        return args.func(args)
    except MeshConfigError as e:
        # an unsatisfiable --mesh/FGUMI_TPU_MESH shape: one loud line, not
        # a traceback — a silently smaller mesh would misreport itself
        log.error("%s", e)
        return 2
    except (InputFormatError, EOFError) as e:
        # a diagnosed input problem (truncated/corrupt stream, torn record):
        # one line with path + offset, nonzero exit — not a traceback
        log.error("%s", e)
        return 2
    except InjectedFault as e:
        # chaos testing: an injected fault that propagated to the top is a
        # *clean* failure (distinct rc so the harness can tell it apart)
        log.error("%s", e)
        return 3
    except OutputIntegrityError as e:
        # the --audit-output pre-commit pass refuted the written file: the
        # atomic rename was aborted (no partial/corrupt file published)
        # and the black box carries the evidence — a distinct exit code so
        # harnesses can tell "the output would have been wrong" from every
        # other failure class (docs/resilience.md)
        from .observe.flight import FLIGHT

        FLIGHT.dump("output-integrity", exc=e)
        log.error("%s", e)
        return 5
    except ResourceExhausted as e:
        # resource hard limit (disk full, RSS hard watermark): atomic temps
        # were swept by the ordinary error unwinding; the run report gets a
        # `resource` section from the governor's event log, and the flight
        # recorder freezes a black box (ring + thread stacks + governor
        # snapshot) naming what was starved
        from .observe.flight import FLIGHT

        FLIGHT.dump("resource-exhausted", exc=e)
        log.error("%s", e)
        return 4
    except BrokenPipeError:
        # before the OSError backstop: BrokenPipeError IS an OSError, and a
        # bare raise there would skip this clause entirely. Detach stdout so
        # the interpreter's exit-time flush of the still-buffered stream
        # doesn't print "Exception ignored" noise
        try:
            os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        except OSError:
            pass
        return 1
    except OSError as e:
        if e.errno == _errno.ENOSPC:
            # backstop for any disk write not explicitly hardened: same
            # exit-code contract as the converted paths
            GOVERNOR.record_event("enospc", where="unhandled")
            log.error("disk full: %s", e)
            return 4
        raise
    except KeyboardInterrupt:
        log.error("interrupted")
        return 130


def _shape_buckets_arg(value: str) -> str:
    """argparse validator for --shape-buckets: loud parse errors at the
    command line instead of at first device dispatch."""
    import argparse as _ap

    from .ops.datapath import parse_shape_buckets

    try:
        parse_shape_buckets(value)
    except ValueError as e:
        raise _ap.ArgumentTypeError(str(e)) from None
    return value


def _mesh_arg(value: str) -> str:
    """argparse validator for --mesh: loud format errors at the command
    line (the shape-vs-device-count check runs at mesh build, where the
    live device list exists). Pure-regex parse — no jax import here."""
    import argparse as _ap
    import re as _re

    v = value.strip().lower()
    if v in ("", "off", "none", "0", "1", "auto") \
            or _re.match(r"^dp\d+(xsp\d+)?$", v):
        return value
    raise _ap.ArgumentTypeError(
        f"--mesh {value!r}: expected 'auto', 'off', or 'dpNxspM' "
        f"(e.g. dp4xsp2)")


def _apply_shape_buckets(args):
    """Reconfigure the process-global shape-bucket ladder for this
    invocation; returns a zero-arg restore callable (or None).

    The environment is deliberately left untouched and the ladder reverts
    at command exit: in the serve daemon one job's flag must not leak into
    every later job (the ladder is still a process-wide property while
    jobs overlap — daemon operators set FGUMI_TPU_SHAPE_BUCKETS on the
    daemon itself instead). Nested ``pipeline`` stages run in-process at
    depth > 0 and inherit the configured registry."""
    spec = getattr(args, "shape_buckets", None)
    if not spec:
        return None
    from .ops.datapath import SHAPE_REGISTRY

    gen = SHAPE_REGISTRY.reconfigure(spec)

    def restore():
        # back to env/defaults — unless a concurrent invocation (daemon
        # job) reconfigured since, in which case its ladder wins
        SHAPE_REGISTRY.reconfigure(only_if_gen=gen)

    return restore


def _telemetry_config(args):
    """(trace_path, report_path, heartbeat_s) from flags + environment."""
    trace_path = args.trace or os.environ.get("FGUMI_TPU_TRACE") or None
    report_path = (args.run_report
                   or os.environ.get("FGUMI_TPU_RUN_REPORT") or None)
    hb_s = args.heartbeat
    if hb_s is None:
        try:
            hb_s = float(os.environ.get("FGUMI_TPU_HEARTBEAT_S", "0") or 0)
        except ValueError:
            log.warning("FGUMI_TPU_HEARTBEAT_S=%s: not a number; heartbeat "
                        "off", os.environ["FGUMI_TPU_HEARTBEAT_S"])
            hb_s = 0.0
    return trace_path, report_path, hb_s


def main(argv=None):
    parser = build_parser()
    args = parser.parse_args(argv)
    from .observe.logs import setup_logging

    # nested stages of a chained command (depth > 0) inherit the outer
    # invocation's level unless they carry an explicit flag: re-running
    # setup at the default would reset an operator's --log-level debug
    # back to info after the first `pipeline` stage
    depth = _main_depth.get()
    if depth == 0 or args.log_level or args.verbose:
        setup_logging(args.log_level, args.verbose)
    from .io.bam import set_audit_output
    from .utils.atomic import set_atomic_enabled

    set_atomic_enabled(not args.no_atomic_output)
    # set BOTH ways: the contextvar must not leak a previous in-process
    # invocation's flag into this one (nested pipeline stages re-enter
    # main() with the flag forwarded explicitly, like --no-atomic-output)
    set_audit_output(bool(args.audit_output))
    rc = _apply_pipeline_compat(args)
    if rc:
        return rc
    if depth > 0:
        # nested stage of a chained command: the outer invocation owns the
        # telemetry lifecycle; this stage just accumulates into it
        return _run_command(args)

    # per-command isolation: every top-level invocation gets its own
    # telemetry scope (metrics + DeviceStats + tracer), so back-to-back or
    # *concurrent* in-process commands — tests, the chained `pipeline`
    # driver, serve-daemon jobs on worker threads — never cross-contaminate
    # counters. Nested stages (depth > 0 above) inherit this scope through
    # the contextvar and accumulate into it, exactly like the old global
    # registries did under the outermost reset.
    from .observe.scope import (adopt_job_context, publish_to_global,
                                scoped_telemetry)

    # deployment profile (--profile / FGUMI_TPU_PROFILE): applied BEFORE
    # the telemetry scope so the env knobs it fills are in place for every
    # downstream env read, and process-once (a daemon job re-entering
    # main() in a fresh context must not re-apply or re-warn). A bad
    # profile is the same exit-2 contract as every other knob parse error.
    from .tune import profile as _profile

    try:
        _profile.maybe_apply_from_env(getattr(args, "profile", None))
    except _profile.ProfileError as e:
        log.error("%s", e)
        return 2

    restore_buckets = None
    try:
        restore_buckets = _apply_shape_buckets(args)
        with scoped_telemetry(args.command) as scope:
            # a serve-daemon job re-enters main() under a job_context: its
            # job id, propagated trace ids, and upstream hop timestamps
            # land on this scope (standalone runs: a no-op)
            adopt_job_context(scope)
            try:
                return _main_scoped(args, argv)
            finally:
                # legacy surface: leave the finished command's counters
                # visible on the process-global METRICS/DEVICE_STATS,
                # exactly like the old reset-at-entry globals did (bench/
                # probe harnesses read them right after cli_main returns)
                publish_to_global(scope)
    finally:
        # outside scoped_telemetry: the per-invocation ladder must revert
        # even when entering the scope itself raises, or a daemon job's
        # --shape-buckets would leak into every later job
        if restore_buckets is not None:
            restore_buckets()


def _main_scoped(args, argv):
    """The depth-0 command body: telemetry lifecycle around the dispatch
    (runs inside this invocation's telemetry scope)."""
    trace_path, report_path, hb_s = _telemetry_config(args)
    # arm the process-wide resource governor (dynamic budget rebalancing +
    # memory/disk pressure sentinels; FGUMI_TPU_GOVERNOR=0 keeps every
    # budget static). Idempotent — the thread is shared across commands.
    from .utils.governor import GOVERNOR

    GOVERNOR.maybe_start()
    # re-stamp the process's profile-application outcome into THIS
    # invocation's scoped registry (application itself is process-once)
    from .tune import profile as _profile

    _profile.stamp_metrics()
    # flight recorder destination: the ring always records; a configured
    # dump dir additionally turns failures into black-box files. The flag
    # sets the process-wide destination (like the env var it mirrors) —
    # daemon operators set it on the daemon, not per job.
    from .observe.flight import FLIGHT, install_signal_dump

    if getattr(args, "flight_dump_dir", None):
        FLIGHT.configure(args.flight_dump_dir)
    FLIGHT.note("command.start", command=args.command)
    install_signal_dump()
    # one-shot XLA device profile (--xla-profile): armed here, triggered
    # by the kernel's Nth dispatch, recorded in the run report
    xla_dir = (getattr(args, "xla_profile", None)
               or os.environ.get("FGUMI_TPU_XLA_PROFILE") or None)
    if xla_dir:
        from .observe import xprof

        try:
            nth = int(os.environ.get("FGUMI_TPU_XLA_PROFILE_NTH", "1") or 1)
        except ValueError:
            log.warning("FGUMI_TPU_XLA_PROFILE_NTH=%s: not a number; "
                        "profiling the first dispatch",
                        os.environ["FGUMI_TPU_XLA_PROFILE_NTH"])
            nth = 1
        xprof.configure(xla_dir, nth)
    tracer = hb = None
    if trace_path:
        from .observe.scope import current_scope
        from .observe.trace import start_trace

        tracer = start_trace()
        scope = current_scope()
        if scope is not None and (scope.trace_id or scope.job_id):
            # fleet-routed job: the per-job trace carries the propagated
            # context + a track-group label, so trace-merge can stitch it
            # under the client's trace-id next to the other processes
            tracer.set_context(
                trace_id=scope.trace_id,
                parent_span_id=scope.parent_span_id,
                process_label=(f"backend {scope.job_id}" if scope.job_id
                               else None))
    if hb_s > 0:
        from .observe.heartbeat import Heartbeat

        hb = Heartbeat(hb_s)
    t0 = time.monotonic()
    t0_unix = time.time()
    rc = 1  # report value when the command dies on an unmapped exception
    token = _main_depth.set(_main_depth.get() + 1)
    try:
        rc = _run_command(args)
        return rc
    except Exception as e:
        # anything _run_command's exit-code contract did not map is an
        # unhandled crash: freeze a black box before unwinding (the run
        # report below still records exit_status 1 + the dump path)
        FLIGHT.dump("unhandled-exception", exc=e)
        raise
    finally:
        _main_depth.reset(token)
        if hb is not None:
            hb.stop()
        if tracer is not None:
            from .observe.trace import stop_trace, write_trace

            stop_trace()
            try:
                write_trace(trace_path, tracer)
                log.info("trace: %d spans -> %s (open in "
                         "https://ui.perfetto.dev)",
                         len(tracer.snapshot()), trace_path)
            except OSError as e:
                log.error("failed to write trace %s: %s", trace_path, e)
        # let in-flight shadow audits (ops/sentinel.py) reach their
        # verdicts before the command exits: a divergence found by a
        # background audit must still trip the breaker, write its black
        # box, and land in this run's report. Cheap when nothing is
        # pending; lazy so audit-free commands never import the module.
        _sentinel = sys.modules.get("fgumi_tpu.ops.sentinel")
        if _sentinel is not None and not _sentinel.SENTINEL.drain():
            log.warning("audit sentinel: background audits still pending "
                        "at command exit; report may undercount")
        if report_path:
            from .observe.report import emit, fold_device_stats

            fold_device_stats()
            GOVERNOR.fold_metrics()
            report = emit(report_path, args.command,
                          list(argv) if argv is not None else sys.argv[1:],
                          t0_unix, time.monotonic() - t0, rc, trace_path)
            if report is not None:
                log.info("run report -> %s", report_path)


if __name__ == "__main__":
    sys.exit(main())
