"""Async file read-ahead + sequential-access OS hints.

Analog of the reference's PrefetchReader
(/root/reference/crates/fgumi-bam-io/src/prefetch_reader.rs:93) and
POSIX_FADV_SEQUENTIAL hints (src/os_hints.rs): a daemon thread reads
fixed-size chunks ahead of the consumer into a bounded queue, so disk
latency overlaps decompress/decode work even in single-threaded command
mode (where there is no separate reader stage to hide it).

Disable with FGUMI_TPU_NO_PREFETCH=1.
"""

import logging
import os
import queue
import threading

log = logging.getLogger("fgumi_tpu")

_EOF = object()


def advise_sequential(fileobj):
    """Best-effort POSIX_FADV_SEQUENTIAL on a real file (os_hints.rs)."""
    try:
        os.posix_fadvise(fileobj.fileno(), 0, 0, os.POSIX_FADV_SEQUENTIAL)
    except (AttributeError, OSError, ValueError):
        pass  # not a real file / platform without fadvise


def prefetch_enabled() -> bool:
    return os.environ.get("FGUMI_TPU_NO_PREFETCH", "").lower() \
        not in ("1", "true", "yes")


class PrefetchFile:
    """Read-only file wrapper with a background read-ahead thread.

    Serves `read(n)` from an internal queue of `chunk`-sized blocks fetched
    ahead by a daemon thread (at most `depth` blocks in flight, so memory
    stays bounded at depth * chunk). A read error in the thread is re-raised
    on the consumer's next read() — errors are never swallowed.
    """

    def __init__(self, fileobj, chunk: int = 1 << 20, depth: int = 4,
                 owns_fileobj: bool = True):
        self._f = fileobj
        self._owns = owns_fileobj
        self.name = getattr(fileobj, "name", None)  # diagnostics passthrough
        self._q = queue.Queue(maxsize=depth)
        self._buf = memoryview(b"")
        self._eof = False
        self._exc = None
        self._stop = threading.Event()
        advise_sequential(fileobj)
        # context-carrying spawn: prefetch spans/metrics attribute to the
        # owning command's telemetry scope (observe.scope)
        from ..observe.scope import spawn_thread

        self._t = spawn_thread(self._loop, args=(chunk,),
                               name="fgumi-prefetch")
        self._t.start()

    def _loop(self, chunk):
        from ..observe import trace as _trace

        trace_on = _trace.tracing_enabled()
        try:
            while not self._stop.is_set():
                with _trace.span("io.prefetch.read") \
                        if trace_on else _trace.NULL_SPAN:
                    data = self._f.read(chunk)
                while not self._stop.is_set():
                    try:
                        self._q.put(data if data else _EOF, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if not data:
                    return
        except BaseException as e:  # noqa: BLE001 - re-raised on read()
            self._exc = e
            while not self._stop.is_set():
                try:
                    self._q.put(_EOF, timeout=0.1)
                    return
                except queue.Full:
                    continue

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            parts = []
            while True:
                got = self.read(1 << 20)
                if not got:
                    return b"".join(parts)
                parts.append(got)
        out = bytearray()
        while len(out) < n:
            if self._buf:
                take = min(n - len(out), len(self._buf))
                out += self._buf[:take]
                self._buf = self._buf[take:]
                continue
            if self._eof:
                break
            got = self._q.get()
            if got is _EOF:
                self._eof = True
                if self._exc is not None:
                    exc, self._exc = self._exc, None
                    raise exc
                break
            if not out and len(got) <= n:
                # common steady state (consumer chunk == producer chunk):
                # hand the queued bytes over without copying
                return got
            self._buf = memoryview(got)
        return bytes(out)

    def fileno(self):
        return self._f.fileno()

    def close(self):
        self._stop.set()
        # drain so the thread can't be wedged on a full queue
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._t.join(timeout=5)
        if self._exc is not None:
            # a producer error the consumer never read() far enough to hit:
            # surface it instead of dropping it silently (the data already
            # delivered may be short)
            exc, self._exc = self._exc, None
            log.warning("prefetch: pending read error discarded on close "
                        "of %s: %r", getattr(self._f, "name", "<file>"), exc)
        if self._owns:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
