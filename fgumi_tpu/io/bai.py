"""BAI index: writer (coordinate sort / standalone index) and reader.

Implements the BAM index format from the SAM spec (binning index with 16 KiB
linear windows), the analog of the reference's BAI write on coordinate sort
(/root/reference/src/lib/commands/sort.rs BAI output) and its indexed reader
(/root/reference/crates/fgumi-raw-bam/src/indexed_reader.rs).

Virtual offsets are (compressed_block_offset << 16) | within_block_offset,
provided by BgzfWriter.tell_virtual().
"""

import struct

_BAI_MAGIC = b"BAI\x01"
_LINEAR_SHIFT = 14  # 16 KiB windows
_PSEUDO_BIN = 37450


def reg2bin(beg: int, end: int) -> int:
    """SAM spec bin for a [beg, end) zero-based interval."""
    end -= 1
    if beg >> 14 == end >> 14:
        return ((1 << 15) - 1) // 7 + (beg >> 14)
    if beg >> 17 == end >> 17:
        return ((1 << 12) - 1) // 7 + (beg >> 17)
    if beg >> 20 == end >> 20:
        return ((1 << 9) - 1) // 7 + (beg >> 20)
    if beg >> 23 == end >> 23:
        return ((1 << 6) - 1) // 7 + (beg >> 23)
    if beg >> 26 == end >> 26:
        return ((1 << 3) - 1) // 7 + (beg >> 26)
    return 0


def reg2bins(beg: int, end: int):
    """All bins overlapping [beg, end) (spec loop, for the reader)."""
    end -= 1
    bins = [0]
    for shift, offset in ((26, 1), (23, 9), (20, 73), (17, 585), (14, 4681)):
        bins.extend(range(offset + (beg >> shift), offset + (end >> shift) + 1))
    return bins


class BaiBuilder:
    """Accumulates (tid, beg, end, vo_start, vo_end) of coordinate-ordered
    records and writes the .bai file."""

    def __init__(self, n_refs: int):
        self.n_refs = n_refs
        self._bins = [dict() for _ in range(n_refs)]  # bin -> [chunks]
        self._linear = [dict() for _ in range(n_refs)]  # window -> min voffset
        self._stats = [[None, None, 0, 0] for _ in range(n_refs)]
        self.n_no_coor = 0

    def add(self, tid: int, beg: int, end: int, vo_start: int, vo_end: int,
            mapped: bool):
        """Record one placed record; call with tid < 0 for unplaced ones."""
        if tid < 0:
            self.n_no_coor += 1
            return
        end = max(end, beg + 1)
        b = reg2bin(beg, end)
        chunks = self._bins[tid].setdefault(b, [])
        if chunks and chunks[-1][1] == vo_start:
            chunks[-1][1] = vo_end  # coalesce adjacent chunks
        else:
            chunks.append([vo_start, vo_end])
        linear = self._linear[tid]
        for win in range(beg >> _LINEAR_SHIFT, ((end - 1) >> _LINEAR_SHIFT) + 1):
            if win not in linear or vo_start < linear[win]:
                linear[win] = vo_start
        st = self._stats[tid]
        st[0] = vo_start if st[0] is None else min(st[0], vo_start)
        st[1] = vo_end if st[1] is None else max(st[1], vo_end)
        st[2 if mapped else 3] += 1

    def write(self, path: str):
        with open(path, "wb") as f:
            f.write(_BAI_MAGIC)
            f.write(struct.pack("<i", self.n_refs))
            for tid in range(self.n_refs):
                bins = self._bins[tid]
                st = self._stats[tid]
                n_bin = len(bins) + (1 if st[0] is not None else 0)
                f.write(struct.pack("<i", n_bin))
                for b in sorted(bins):
                    chunks = bins[b]
                    f.write(struct.pack("<Ii", b, len(chunks)))
                    for beg, end in chunks:
                        f.write(struct.pack("<QQ", beg, end))
                if st[0] is not None:  # samtools-style pseudo-bin metadata
                    f.write(struct.pack("<Ii", _PSEUDO_BIN, 2))
                    f.write(struct.pack("<QQ", st[0], st[1]))
                    f.write(struct.pack("<QQ", st[2], st[3]))
                linear = self._linear[tid]
                n_intv = max(linear) + 1 if linear else 0
                f.write(struct.pack("<i", n_intv))
                filled = 0
                for win in range(n_intv):
                    filled = linear.get(win, filled)
                    f.write(struct.pack("<Q", filled))
            f.write(struct.pack("<Q", self.n_no_coor))


class BaiIndex:
    """Parsed .bai: per-ref bins/chunks + linear index, for region queries."""

    def __init__(self, path: str):
        with open(path, "rb") as f:
            data = f.read()
        if data[:4] != _BAI_MAGIC:
            raise ValueError(f"not a BAI file: {path}")
        off = 4
        (n_ref,) = struct.unpack_from("<i", data, off)
        off += 4
        self.bins = []
        self.linear = []
        self.stats = []
        for _ in range(n_ref):
            (n_bin,) = struct.unpack_from("<i", data, off)
            off += 4
            bins = {}
            stats = None
            for _ in range(n_bin):
                b, n_chunk = struct.unpack_from("<Ii", data, off)
                off += 8
                chunks = []
                for _ in range(n_chunk):
                    beg, end = struct.unpack_from("<QQ", data, off)
                    off += 16
                    chunks.append((beg, end))
                if b == _PSEUDO_BIN:
                    stats = chunks
                else:
                    bins[b] = chunks
            (n_intv,) = struct.unpack_from("<i", data, off)
            off += 4
            intv = list(struct.unpack_from(f"<{n_intv}Q", data, off))
            off += 8 * n_intv
            self.bins.append(bins)
            self.linear.append(intv)
            self.stats.append(stats)
        self.n_no_coor = struct.unpack_from("<Q", data, off)[0] \
            if off + 8 <= len(data) else 0

    def query_chunks(self, tid: int, beg: int, end: int):
        """Merged, linear-index-filtered chunk list overlapping [beg, end)."""
        if tid < 0 or tid >= len(self.bins):
            return []
        bins = self.bins[tid]
        linear = self.linear[tid]
        win = beg >> _LINEAR_SHIFT
        min_vo = linear[win] if win < len(linear) else (
            linear[-1] if linear else 0)
        chunks = []
        for b in reg2bins(beg, end):
            for c_beg, c_end in bins.get(b, ()):
                if c_end > min_vo:
                    chunks.append((max(c_beg, min_vo), c_end))
        chunks.sort()
        merged = []
        for c in chunks:
            if merged and c[0] <= merged[-1][1]:
                merged[-1] = (merged[-1][0], max(merged[-1][1], c[1]))
            else:
                merged.append(c)
        return merged
