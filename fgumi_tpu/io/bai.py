"""BAI index: writer (coordinate sort / standalone index) and reader.

Implements the BAM index format from the SAM spec (binning index with 16 KiB
linear windows), the analog of the reference's BAI write on coordinate sort
(/root/reference/src/lib/commands/sort.rs BAI output) and its indexed reader
(/root/reference/crates/fgumi-raw-bam/src/indexed_reader.rs).

Virtual offsets are (compressed_block_offset << 16) | within_block_offset,
provided by BgzfWriter.tell_virtual().
"""

import struct

_BAI_MAGIC = b"BAI\x01"
_LINEAR_SHIFT = 14  # 16 KiB windows
_PSEUDO_BIN = 37450


def reg2bin(beg: int, end: int) -> int:
    """SAM spec bin for a [beg, end) zero-based interval."""
    end -= 1
    if beg >> 14 == end >> 14:
        return ((1 << 15) - 1) // 7 + (beg >> 14)
    if beg >> 17 == end >> 17:
        return ((1 << 12) - 1) // 7 + (beg >> 17)
    if beg >> 20 == end >> 20:
        return ((1 << 9) - 1) // 7 + (beg >> 20)
    if beg >> 23 == end >> 23:
        return ((1 << 6) - 1) // 7 + (beg >> 23)
    if beg >> 26 == end >> 26:
        return ((1 << 3) - 1) // 7 + (beg >> 26)
    return 0


def reg2bins(beg: int, end: int):
    """All bins overlapping [beg, end) (spec loop, for the reader)."""
    end -= 1
    bins = [0]
    for shift, offset in ((26, 1), (23, 9), (20, 73), (17, 585), (14, 4681)):
        bins.extend(range(offset + (beg >> shift), offset + (end >> shift) + 1))
    return bins


def _reg2bin_vec(beg, end):
    """Vectorized reg2bin over int64 arrays (identical to the scalar spec
    loop: smallest containing bin wins)."""
    import numpy as np

    e = end - 1
    b = np.zeros(len(beg), dtype=np.int64)
    unset = np.ones(len(beg), dtype=bool)
    for shift, off in ((14, 4681), (17, 585), (20, 73), (23, 9), (26, 1)):
        hit = unset & ((beg >> shift) == (e >> shift))
        b[hit] = off + (beg[hit] >> shift)
        unset &= ~hit
    return b


class _ChunkMerger:
    """Shared vectorized core of BaiBuilder.add_many / CsiBuilder.add_many:
    groups records by bin, builds coalesced [vo_start, vo_end] chunk lists,
    and merges them into the per-tid bin dicts (continuing coalescing across
    calls). Records must arrive in file order (the builders' add contract)."""

    @staticmethod
    def merge(bins_dict, bins, vo_starts, vo_ends):
        import numpy as np

        n = len(bins)
        order = np.lexsort((np.arange(n), bins))  # stable by bin, file order
        bs = bins[order]
        vs = vo_starts[order]
        ve = vo_ends[order]
        new_chunk = np.ones(n, dtype=bool)
        new_chunk[1:] = (bs[1:] != bs[:-1]) | (vs[1:] != ve[:-1])
        starts_idx = np.nonzero(new_chunk)[0]
        ends_idx = np.append(starts_idx[1:], n) - 1
        c_bin = bs[starts_idx]
        c_vs = vs[starts_idx]
        c_ve = ve[ends_idx]
        # bin boundaries among the chunk list
        bin_start = np.ones(len(c_bin), dtype=bool)
        bin_start[1:] = c_bin[1:] != c_bin[:-1]
        bin_pos = np.nonzero(bin_start)[0]
        bin_end = np.append(bin_pos[1:], len(c_bin))
        for p, q in zip(bin_pos, bin_end):
            b = int(c_bin[p])
            chunks = bins_dict.setdefault(b, [])
            i = int(p)
            if chunks and chunks[-1][1] == c_vs[i]:
                chunks[-1][1] = int(c_ve[i])
                i += 1
            chunks.extend([int(c_vs[k]), int(c_ve[k])]
                          for k in range(i, int(q)))


class BaiBuilder:
    """Accumulates (tid, beg, end, vo_start, vo_end) of coordinate-ordered
    records and writes the .bai file."""

    def __init__(self, n_refs: int):
        self.n_refs = n_refs
        self._bins = [dict() for _ in range(n_refs)]  # bin -> [chunks]
        self._linear = [dict() for _ in range(n_refs)]  # window -> min voffset
        self._stats = [[None, None, 0, 0] for _ in range(n_refs)]
        self.n_no_coor = 0

    def add(self, tid: int, beg: int, end: int, vo_start: int, vo_end: int,
            mapped: bool):
        """Record one placed record; call with tid < 0 for unplaced ones."""
        if tid < 0:
            self.n_no_coor += 1
            return
        end = max(end, beg + 1)
        b = reg2bin(beg, end)
        chunks = self._bins[tid].setdefault(b, [])
        if chunks and chunks[-1][1] == vo_start:
            chunks[-1][1] = vo_end  # coalesce adjacent chunks
        else:
            chunks.append([vo_start, vo_end])
        linear = self._linear[tid]
        for win in range(beg >> _LINEAR_SHIFT, ((end - 1) >> _LINEAR_SHIFT) + 1):
            if win not in linear or vo_start < linear[win]:
                linear[win] = vo_start
        st = self._stats[tid]
        st[0] = vo_start if st[0] is None else min(st[0], vo_start)
        st[1] = vo_end if st[1] is None else max(st[1], vo_end)
        st[2 if mapped else 3] += 1

    def add_many(self, tids, begs, ends, vo_starts, vo_ends, mapped):
        """Vectorized add() over coordinate-ordered arrays (identical index
        output to the per-record loop; the fast BAI path of cmd_sort)."""
        import numpy as np

        tids = np.asarray(tids, dtype=np.int64)
        placed = tids >= 0
        self.n_no_coor += int((~placed).sum())
        if not placed.any():
            return
        t = tids[placed]
        beg = np.asarray(begs, dtype=np.int64)[placed]
        end = np.maximum(np.asarray(ends, dtype=np.int64)[placed], beg + 1)
        vs = np.asarray(vo_starts, dtype=np.int64)[placed]
        ve = np.asarray(vo_ends, dtype=np.int64)[placed]
        mp = np.asarray(mapped, dtype=bool)[placed]
        bins = _reg2bin_vec(beg, end)
        uniq, first = np.unique(t, return_index=True)
        bounds = np.append(first, len(t))  # t ascending (coordinate order)
        for u, lo, hi in zip(uniq, bounds[:-1], bounds[1:]):
            tid = int(u)
            sl = slice(int(lo), int(hi))
            _ChunkMerger.merge(self._bins[tid], bins[sl], vs[sl], ve[sl])
            # linear index: min vo_start per 16 KiB window
            win_lo = beg[sl] >> _LINEAR_SHIFT
            win_hi = (end[sl] - 1) >> _LINEAR_SHIFT
            dense = np.full(int(win_hi.max()) + 1, np.iinfo(np.int64).max,
                            dtype=np.int64)
            np.minimum.at(dense, win_lo, vs[sl])
            multi = np.nonzero(win_hi > win_lo)[0]
            for i in multi:  # rare: records spanning >1 window
                dense[win_lo[i] + 1:win_hi[i] + 1] = np.minimum(
                    dense[win_lo[i] + 1:win_hi[i] + 1], vs[sl][i])
            linear = self._linear[tid]
            for w in np.nonzero(dense != np.iinfo(np.int64).max)[0]:
                v = int(dense[w])
                w = int(w)
                if w not in linear or v < linear[w]:
                    linear[w] = v
            st = self._stats[tid]
            v0, v1 = int(vs[sl].min()), int(ve[sl].max())
            st[0] = v0 if st[0] is None else min(st[0], v0)
            st[1] = v1 if st[1] is None else max(st[1], v1)
            n_mapped = int(mp[sl].sum())
            st[2] += n_mapped
            st[3] += int(hi - lo) - n_mapped

    def write(self, path: str):
        from ..utils.atomic import open_output

        with open_output(path) as f:
            f.write(_BAI_MAGIC)
            f.write(struct.pack("<i", self.n_refs))
            for tid in range(self.n_refs):
                bins = self._bins[tid]
                st = self._stats[tid]
                n_bin = len(bins) + (1 if st[0] is not None else 0)
                f.write(struct.pack("<i", n_bin))
                for b in sorted(bins):
                    chunks = bins[b]
                    f.write(struct.pack("<Ii", b, len(chunks)))
                    for beg, end in chunks:
                        f.write(struct.pack("<QQ", beg, end))
                if st[0] is not None:  # samtools-style pseudo-bin metadata
                    f.write(struct.pack("<Ii", _PSEUDO_BIN, 2))
                    f.write(struct.pack("<QQ", st[0], st[1]))
                    f.write(struct.pack("<QQ", st[2], st[3]))
                linear = self._linear[tid]
                n_intv = max(linear) + 1 if linear else 0
                f.write(struct.pack("<i", n_intv))
                filled = 0
                for win in range(n_intv):
                    filled = linear.get(win, filled)
                    f.write(struct.pack("<Q", filled))
            f.write(struct.pack("<Q", self.n_no_coor))


class BaiIndex:
    """Parsed .bai: per-ref bins/chunks + linear index, for region queries."""

    def __init__(self, path: str):
        with open(path, "rb") as f:
            data = f.read()
        if data[:4] != _BAI_MAGIC:
            raise ValueError(f"not a BAI file: {path}")
        off = 4
        (n_ref,) = struct.unpack_from("<i", data, off)
        off += 4
        self.bins = []
        self.linear = []
        self.stats = []
        for _ in range(n_ref):
            (n_bin,) = struct.unpack_from("<i", data, off)
            off += 4
            bins = {}
            stats = None
            for _ in range(n_bin):
                b, n_chunk = struct.unpack_from("<Ii", data, off)
                off += 8
                chunks = []
                for _ in range(n_chunk):
                    beg, end = struct.unpack_from("<QQ", data, off)
                    off += 16
                    chunks.append((beg, end))
                if b == _PSEUDO_BIN:
                    stats = chunks
                else:
                    bins[b] = chunks
            (n_intv,) = struct.unpack_from("<i", data, off)
            off += 4
            intv = list(struct.unpack_from(f"<{n_intv}Q", data, off))
            off += 8 * n_intv
            self.bins.append(bins)
            self.linear.append(intv)
            self.stats.append(stats)
        self.n_no_coor = struct.unpack_from("<Q", data, off)[0] \
            if off + 8 <= len(data) else 0

    def query_chunks(self, tid: int, beg: int, end: int):
        """Merged, linear-index-filtered chunk list overlapping [beg, end)."""
        if tid < 0 or tid >= len(self.bins):
            return []
        linear = self.linear[tid]
        win = beg >> _LINEAR_SHIFT
        min_vo = linear[win] if win < len(linear) else (
            linear[-1] if linear else 0)
        return _filter_merge_chunks(self.bins[tid], reg2bins(beg, end), min_vo)


def _filter_merge_chunks(bins: dict, bin_ids, min_vo: int):
    """Chunk overlap filter + clamp + sort + adjacent merge (shared by the
    BAI and CSI readers)."""
    chunks = []
    for b in bin_ids:
        for c_beg, c_end in bins.get(b, ()):
            if c_end > min_vo:
                chunks.append((max(c_beg, min_vo), c_end))
    chunks.sort()
    merged = []
    for c in chunks:
        if merged and c[0] <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], c[1]))
        else:
            merged.append(c)
    return merged


def depth_for_length(max_ref_length: int, min_shift: int = 14) -> int:
    """Smallest CSI depth whose bin tree covers max_ref_length (htslib rule)."""
    depth = 5
    while max_ref_length > 1 << (min_shift + 3 * depth):
        depth += 1
    return depth


# ---------------------------------------------------------------------------
# CSI (.csi): the generalized binning index (BGZF-compressed, configurable
# min_shift/depth, so references longer than 2^29 index correctly). Same
# bin/chunk structures as BAI with loffset per bin replacing the linear
# index. Reference analog: indexed_reader.rs CSI support.

_CSI_MAGIC = b"CSI\x01"


def reg2bin_ext(beg: int, end: int, min_shift: int = 14, depth: int = 5) -> int:
    """Generalized reg2bin (CSI spec) over [beg, end)."""
    end -= 1
    level = depth
    s = min_shift
    t = ((1 << depth * 3) - 1) // 7
    while level > 0:
        if beg >> s == end >> s:
            return t + (beg >> s)
        level -= 1
        s += 3
        t -= 1 << level * 3
    return 0


def reg2bins_ext(beg: int, end: int, min_shift: int = 14, depth: int = 5):
    """All bins overlapping [beg, end) for arbitrary min_shift/depth
    (CSI spec loop: level 0 is the root bin at shift min_shift + depth*3)."""
    end -= 1
    bins = []
    s = min_shift + depth * 3
    t = 0
    for level in range(depth + 1):
        bins.extend(range(t + (beg >> s), t + (end >> s) + 1))
        t += 1 << (level * 3)
        s -= 3
    return bins


class CsiIndex:
    """Parsed .csi: bins/chunks + per-bin loffset, for region queries."""

    def __init__(self, path: str):
        import gzip

        with gzip.open(path, "rb") as f:
            data = f.read()
        if data[:4] != _CSI_MAGIC:
            raise ValueError(f"not a CSI file: {path}")
        self.min_shift, self.depth, l_aux = struct.unpack_from("<iii", data, 4)
        off = 16 + l_aux
        (n_ref,) = struct.unpack_from("<i", data, off)
        off += 4
        self.bins = []
        self.loffsets = []
        for _ in range(n_ref):
            (n_bin,) = struct.unpack_from("<i", data, off)
            off += 4
            bins = {}
            loff = {}
            for _ in range(n_bin):
                b, l_off, n_chunk = struct.unpack_from("<IQi", data, off)
                off += 16
                chunks = []
                for _ in range(n_chunk):
                    cb, ce = struct.unpack_from("<QQ", data, off)
                    off += 16
                    chunks.append((cb, ce))
                bins[b] = chunks
                loff[b] = l_off
            self.bins.append(bins)
            self.loffsets.append(loff)

    def query_chunks(self, tid: int, beg: int, end: int):
        """Merged chunk list overlapping [beg, end).

        min_vo is deliberately 0: a bin's loffset only reflects records
        *assigned* to it (not every record overlapping its interval), so
        using it to prune can drop boundary-spanning records stored in
        ancestor bins; correctness over the micro-optimization.
        """
        if tid < 0 or tid >= len(self.bins):
            return []
        return _filter_merge_chunks(
            self.bins[tid],
            reg2bins_ext(beg, end, self.min_shift, self.depth), 0)


class CsiBuilder:
    """Accumulates placed records and writes a .csi index."""

    def __init__(self, n_refs: int, min_shift: int = 14, depth: int = 5):
        self.n_refs = n_refs
        self.min_shift = min_shift
        self.depth = depth
        self._bins = [dict() for _ in range(n_refs)]
        self._loff = [dict() for _ in range(n_refs)]
        self.n_no_coor = 0

    def add(self, tid: int, beg: int, end: int, vo_start: int, vo_end: int,
            mapped: bool = True):
        if tid < 0:
            self.n_no_coor += 1
            return
        end = max(end, beg + 1)
        b = reg2bin_ext(beg, end, self.min_shift, self.depth)
        chunks = self._bins[tid].setdefault(b, [])
        if chunks and chunks[-1][1] == vo_start:
            chunks[-1][1] = vo_end
        else:
            chunks.append([vo_start, vo_end])
        # loffset propagates to ancestors too: a record overlapping bin b
        # overlaps every ancestor's interval (external readers prune on it)
        loff = self._loff[tid]
        bb = b
        while True:
            if bb not in loff or vo_start < loff[bb]:
                loff[bb] = vo_start
            if bb == 0:
                break
            bb = (bb - 1) >> 3

    def add_many(self, tids, begs, ends, vo_starts, vo_ends, mapped=None):
        """Vectorized add() over coordinate-ordered arrays (same output)."""
        import numpy as np

        tids = np.asarray(tids, dtype=np.int64)
        placed = tids >= 0
        self.n_no_coor += int((~placed).sum())
        if not placed.any():
            return
        t = tids[placed]
        beg = np.asarray(begs, dtype=np.int64)[placed]
        end = np.maximum(np.asarray(ends, dtype=np.int64)[placed], beg + 1)
        vs = np.asarray(vo_starts, dtype=np.int64)[placed]
        ve = np.asarray(vo_ends, dtype=np.int64)[placed]
        # generalized reg2bin, vectorized: deepest level whose window
        # contains [beg, end) wins (reg2bin_ext loop)
        e = end - 1
        bins = np.zeros(len(beg), dtype=np.int64)
        unset = np.ones(len(beg), dtype=bool)
        s = self.min_shift
        t_off = ((1 << self.depth * 3) - 1) // 7
        level = self.depth
        while level > 0:
            hit = unset & ((beg >> s) == (e >> s))
            bins[hit] = t_off + (beg[hit] >> s)
            unset &= ~hit
            level -= 1
            s += 3
            t_off -= 1 << level * 3
        uniq, first = np.unique(t, return_index=True)
        bounds = np.append(first, len(t))
        for u, lo, hi in zip(uniq, bounds[:-1], bounds[1:]):
            tid = int(u)
            sl = slice(int(lo), int(hi))
            _ChunkMerger.merge(self._bins[tid], bins[sl], vs[sl], ve[sl])
            loff = self._loff[tid]
            # per unique bin: groupwise min vo_start, propagated to ancestors
            order = np.argsort(bins[sl], kind="stable")
            bsrt = bins[sl][order]
            vsrt = vs[sl][order]
            grp = np.ones(len(bsrt), dtype=bool)
            grp[1:] = bsrt[1:] != bsrt[:-1]
            mins = np.minimum.reduceat(vsrt, np.nonzero(grp)[0]) \
                if len(bsrt) else vsrt
            for b, v in zip(bsrt[grp], mins):
                bb = int(b)
                v = int(v)
                while True:
                    if bb not in loff or v < loff[bb]:
                        loff[bb] = v
                    if bb == 0:
                        break
                    bb = (bb - 1) >> 3

    def write(self, path: str):
        import gzip

        out = bytearray(_CSI_MAGIC)
        out += struct.pack("<iii", self.min_shift, self.depth, 0)
        out += struct.pack("<i", self.n_refs)
        for tid in range(self.n_refs):
            bins = self._bins[tid]
            out += struct.pack("<i", len(bins))
            for b in sorted(bins):
                chunks = bins[b]
                out += struct.pack("<IQi", b, self._loff[tid][b], len(chunks))
                for beg, end in chunks:
                    out += struct.pack("<QQ", beg, end)
        out += struct.pack("<Q", self.n_no_coor)
        from ..utils.atomic import open_output

        with open_output(path) as raw:
            with gzip.GzipFile(fileobj=raw, mode="wb",
                               compresslevel=1, mtime=0) as f:
                f.write(bytes(out))
