"""FASTQ input: plain, gzip, or BGZF, auto-detected by magic bytes.

Mirrors the reference's FASTQ front-end behavior (detect_compression_format,
/root/reference/src/lib/commands/extract.rs:96-150; record shape
/root/reference/src/lib/fastq_parse.rs). The reference lexes newline boundaries
with SIMD bitmasks (crates/fgumi-simd-fastq); here boundary finding is delegated
to C-speed bulk ``bytes.split`` over large decompressed chunks, which serves the
same purpose: never scan bytes one at a time in the interpreter.
"""

from dataclasses import dataclass

from .bgzf import BgzfReader

GZIP_MAGIC = b"\x1f\x8b"


@dataclass
class FastqRead:
    """One FASTQ record. `name` is the header line without the leading '@'."""
    name: bytes
    seq: bytes
    quals: bytes  # ASCII quality bytes as stored in the file (offset NOT removed)


def _open_stream(path: str):
    """Return a read(n)->bytes object for plain/gzip/bgzf FASTQ."""
    f = open(path, "rb")
    magic = f.read(2)
    f.seek(0)
    if magic == GZIP_MAGIC:
        return BgzfReader(f, owns_fileobj=True)
    return f


class FastqReader:
    """Iterates FastqRead over a (possibly compressed) FASTQ file.

    Reads large chunks and splits on newlines in bulk; carries a partial last
    line between chunks. Handles both \\n and \\r\\n line endings.
    """

    def __init__(self, path: str, chunk_size: int = 1 << 20):
        self._path = path
        self._stream = _open_stream(path)
        self._chunk = chunk_size
        self._lines = iter(())
        self._tail = b""
        self._done = False

    def _next_line(self):
        while True:
            line = next(self._lines, None)
            if line is not None:
                return line
            if self._done:
                if self._tail:
                    out, self._tail = self._tail, b""
                    return out
                return None
            raw = self._stream.read(self._chunk)
            if not raw:
                self._done = True
                continue
            data = self._tail + raw
            parts = data.split(b"\n")
            self._tail = parts.pop()
            self._lines = iter(parts)

    def __iter__(self):
        return self

    def __next__(self) -> FastqRead:
        header = self._next_line()
        # skip blank trailing lines
        while header is not None and not header.strip():
            header = self._next_line()
        if header is None:
            raise StopIteration
        seq = self._next_line()
        plus = self._next_line()
        quals = self._next_line()
        if quals is None:
            raise ValueError(f"{self._path}: truncated FASTQ record at {header!r}")
        header = header.rstrip(b"\r")
        seq = seq.rstrip(b"\r")
        quals = quals.rstrip(b"\r")
        if not header.startswith(b"@"):
            raise ValueError(f"{self._path}: FASTQ header must start with '@': {header!r}")
        if not plus.rstrip(b"\r").startswith(b"+"):
            raise ValueError(f"{self._path}: FASTQ separator must start with '+': {plus!r}")
        if len(seq) != len(quals):
            raise ValueError(
                f"{self._path}: sequence/quality length mismatch for {header!r} "
                f"({len(seq)} vs {len(quals)})")
        return FastqRead(header[1:], seq, quals)

    def close(self):
        close = getattr(self._stream, "close", None)
        if close:
            close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def strip_read_suffix(name: bytes) -> bytes:
    """Strip a trailing space comment and an old-style ``/1``/``/2`` suffix.

    Matches the reference's strip_read_suffix (src/lib/fastq_parse.rs usage at
    extract.rs:787-790): only ``/`` followed by a single digit is removed, after
    first truncating at the first space/tab.
    """
    for i, b in enumerate(name):
        if b in (0x20, 0x09):
            name = name[:i]
            break
    if len(name) >= 2 and name[-2] == ord("/") and name[-1] in b"0123456789":
        name = name[:-2]
    return name
