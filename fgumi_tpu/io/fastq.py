"""FASTQ input: plain, gzip, or BGZF, auto-detected by magic bytes.

Mirrors the reference's FASTQ front-end behavior (detect_compression_format,
/root/reference/src/lib/commands/extract.rs:96-150; record shape
/root/reference/src/lib/fastq_parse.rs). The reference lexes newline boundaries
with SIMD bitmasks (crates/fgumi-simd-fastq); here boundary finding is delegated
to C-speed bulk ``bytes.split`` over large decompressed chunks, which serves the
same purpose: never scan bytes one at a time in the interpreter.
"""

import os
from dataclasses import dataclass

from .bgzf import BgzfReader

GZIP_MAGIC = b"\x1f\x8b"


@dataclass
class FastqRead:
    """One FASTQ record. `name` is the header line without the leading '@'."""
    name: bytes
    seq: bytes
    quals: bytes  # ASCII quality bytes as stored in the file (offset NOT removed)


class _BufferStream:
    """read(n) over an in-memory buffer (memoryview slices, no copies)."""

    def __init__(self, buf):
        self._mv = memoryview(buf)
        self._pos = 0

    def read(self, n: int = -1):
        if n is None or n < 0:
            n = len(self._mv) - self._pos
        out = self._mv[self._pos:self._pos + n]
        self._pos += len(out)
        # bytes, not a view: consumers concatenate with carried tails
        return bytes(out)

    def close(self):
        self._mv = memoryview(b"")
        self._pos = 0


# plain-gzip inputs up to this compressed size decompress whole-buffer via
# libdeflate (~2-3x streaming zlib); larger files stream to bound memory.
# Peak transient footprint on this path is compressed + decompressed
# simultaneously, i.e. up to ~9x this limit (ADVICE r4) — the 128 MB
# default keeps that ~1.2 GB worst-case; tune with FGUMI_TPU_GZIP_WHOLE_LIMIT
# (documented in docs/performance-tuning.md).
_GZIP_WHOLE_LIMIT = int(os.environ.get("FGUMI_TPU_GZIP_WHOLE_LIMIT",
                                       str(128 << 20)))


def _open_stream(path: str):
    """Return a read(n)->bytes object for plain/gzip/bgzf FASTQ."""
    f = open(path, "rb")
    head = f.read(18)
    f.seek(0)
    if head[:2] == GZIP_MAGIC:
        from .. import native

        is_bgzf = len(head) >= 18 and head[:4] == b"\x1f\x8b\x08\x04" \
            and BgzfReader._is_bgzf_member(head)
        if (not is_bgzf and native.get_lib() is not None
                and os.fstat(f.fileno()).st_size <= _GZIP_WHOLE_LIMIT):
            raw = f.read()
            f.close()
            decoded = None
            try:
                # 8x the FILE size bounds the DECOMPRESSED side (FASTQ gzip
                # compresses ~3-4x): past that, stream with bounded memory
                # (gzip_decompress_all -> None)
                decoded = native.gzip_decompress_all(
                    raw, max_out=8 * max(len(raw), 1 << 20))
            except (ValueError, MemoryError):
                decoded = None  # let the streaming path report the error
            raw = None
            if decoded is not None:
                return _BufferStream(decoded)
            f = open(path, "rb")
        return BgzfReader(f, owns_fileobj=True, name=path)
    return f


class FastqReader:
    """Iterates FastqRead over a (possibly compressed) FASTQ file.

    Reads large chunks and splits on newlines in bulk; carries a partial last
    line between chunks. Handles both \\n and \\r\\n line endings.
    """

    def __init__(self, path: str, chunk_size: int = 1 << 20):
        self._path = path
        self._stream = _open_stream(path)
        self._chunk = chunk_size
        self._lines = iter(())
        self._tail = b""
        self._done = False

    def _next_line(self):
        while True:
            line = next(self._lines, None)
            if line is not None:
                return line
            if self._done:
                if self._tail:
                    out, self._tail = self._tail, b""
                    return out
                return None
            raw = self._stream.read(self._chunk)
            if not raw:
                self._done = True
                continue
            data = self._tail + raw
            parts = data.split(b"\n")
            self._tail = parts.pop()
            self._lines = iter(parts)

    def __iter__(self):
        return self

    def __next__(self) -> FastqRead:
        header = self._next_line()
        # skip blank trailing lines
        while header is not None and not header.strip():
            header = self._next_line()
        if header is None:
            raise StopIteration
        seq = self._next_line()
        plus = self._next_line()
        quals = self._next_line()
        if quals is None:
            raise ValueError(f"{self._path}: truncated FASTQ record at {header!r}")
        header = header.rstrip(b"\r")
        seq = seq.rstrip(b"\r")
        quals = quals.rstrip(b"\r")
        if not header.startswith(b"@"):
            raise ValueError(f"{self._path}: FASTQ header must start with '@': {header!r}")
        if not plus.rstrip(b"\r").startswith(b"+"):
            raise ValueError(f"{self._path}: FASTQ separator must start with '+': {plus!r}")
        if len(seq) != len(quals):
            raise ValueError(
                f"{self._path}: sequence/quality length mismatch for {header!r} "
                f"({len(seq)} vs {len(quals)})")
        return FastqRead(header[1:], seq, quals)

    def close(self):
        close = getattr(self._stream, "close", None)
        if close:
            close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class FastqBatchReader:
    """Batched FASTQ reading: numpy newline scan -> per-record offset arrays.

    The fast-lexer analog of the reference's SIMD FASTQ front-end
    (crates/fgumi-simd-fastq/src/lib.rs:1-13): decompressed chunks are scanned
    for line boundaries in one vectorized pass, and each batch exposes
    (buf, name_off, name_len, seq_off, seq_len, qual_off) arrays that the
    native record assembler consumes without per-record Python.

    Yields one batch per decompressed chunk; a trailing partial record
    carries into the next chunk. Blank lines at record boundaries are
    skipped, matching FastqReader's header-position blank handling.
    """

    def __init__(self, path: str, chunk_size: int = 8 << 20):
        import numpy as np

        self._np = np
        self._stream = _open_stream(path)
        self._path = path
        self._chunk = chunk_size
        self._tail = b""
        self._done = False

    def __iter__(self):
        np = self._np
        while True:
            raw = self._stream.read(self._chunk) if not self._done else b""
            if not raw:
                self._done = True
                if not self._tail:
                    return
                data = self._tail
                if not data.endswith(b"\n"):
                    data += b"\n"  # final unterminated line
                self._tail = b""
            else:
                data = self._tail + raw
            buf = np.frombuffer(data, dtype=np.uint8)
            nl = np.flatnonzero(buf == 10)
            all_start = np.empty(len(nl), dtype=np.int64)
            if len(nl):
                all_start[0] = 0
                all_start[1:] = nl[:-1] + 1
            all_end = nl.astype(np.int64)
            all_end = all_end - (buf[np.maximum(all_end - 1, 0)] == 13)
            empty = all_end <= all_start
            if empty.any():
                # rare path: skip blank lines occurring at record boundaries
                # (FastqReader skips blanks at the header position)
                keep = []
                for i in range(len(nl)):
                    if empty[i] and len(keep) % 4 == 0:
                        continue
                    keep.append(i)
                keep = np.asarray(keep, dtype=np.int64)
            else:
                keep = None
            n_lines = len(nl) if keep is None else len(keep)
            n_rec = n_lines // 4
            if n_rec == 0:
                if self._done and data.strip():
                    raise ValueError(
                        f"{self._path}: truncated FASTQ record at EOF")
                self._tail = data
                if self._done:
                    return
                continue
            if keep is None:
                used = int(nl[4 * n_rec - 1]) + 1
                line_start = all_start[:4 * n_rec]
                line_end = all_end[:4 * n_rec]
            else:
                last = int(keep[4 * n_rec - 1])
                used = int(nl[last]) + 1
                line_start = all_start[keep[:4 * n_rec]]
                line_end = all_end[keep[:4 * n_rec]]
            self._tail = data[used:]
            name_off = line_start[0::4] + 1  # past '@'
            name_len = (line_end[0::4] - name_off).astype(np.int32)
            seq_off = line_start[1::4]
            seq_len = (line_end[1::4] - seq_off).astype(np.int32)
            qual_off = line_start[3::4]
            qual_len = (line_end[3::4] - qual_off).astype(np.int32)
            # structural validation (cheap, vectorized)
            if not (buf[line_start[0::4]] == ord("@")).all():
                raise ValueError(f"{self._path}: FASTQ header must start "
                                 "with '@'")
            if not (buf[line_start[2::4]] == ord("+")).all():
                raise ValueError(f"{self._path}: FASTQ separator must start "
                                 "with '+'")
            if not (seq_len == qual_len).all():
                bad = int(np.nonzero(seq_len != qual_len)[0][0])
                raise ValueError(f"{self._path}: sequence/quality length "
                                 f"mismatch at batch record {bad}")
            yield buf, name_off, name_len, seq_off, seq_len, qual_off
            if self._done and not self._tail:
                return

    def close(self):
        close = getattr(self._stream, "close", None)
        if close:
            close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def strip_read_suffix(name: bytes) -> bytes:
    """Strip a trailing space comment and an old-style ``/1``/``/2`` suffix.

    Matches the reference's strip_read_suffix (src/lib/fastq_parse.rs usage at
    extract.rs:787-790): only ``/`` followed by a single digit is removed, after
    first truncating at the first space/tab.
    """
    for i, b in enumerate(name):
        if b in (0x20, 0x09):
            name = name[:i]
            break
    if len(name) >= 2 and name[-2] == ord("/") and name[-1] in b"0123456789":
        name = name[:-2]
    return name
