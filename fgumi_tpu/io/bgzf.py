"""BGZF block compression I/O.

BGZF is gzip with fixed-size members carrying a BSIZE extra field, enabling random
access and parallel compression (reference: /root/reference/crates/fgumi-bgzf/src/lib.rs).

Reading: sequential BGZF is a valid multi-member gzip stream, so decompression is
delegated to zlib's C streaming decompressor (block boundaries are only needed for
random access / BAI, handled separately). Writing produces spec-conformant BGZF
blocks (BC extra subfield + EOF sentinel) so htslib/samtools can read the output.
"""

import io
import struct
import time
import zlib

from ..observe import trace as _trace
from ..observe.metrics import METRICS
from ..utils import faults
from .errors import InputFormatError, OutputIntegrityError

# Maximum uncompressed payload per BGZF block.
MAX_BLOCK_DATA = 0xFF00

# The fixed 28-byte BGZF EOF sentinel block (SAM spec §4.1.2).
BGZF_EOF = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000"
)

_HEADER = struct.Struct("<4BI2BH2BHH")  # gzip header + XLEN + BC subfield + BSIZE


def _reraise_disk_full(exc: BaseException, fileobj):
    """A full output disk becomes the resource clean-failure contract
    (ResourceExhausted -> exit 4, resource section in the run report)
    instead of an anonymous mid-write OSError traceback; every other
    exception returns so the caller re-raises the original."""
    from ..utils.governor import reraise_enospc

    reraise_enospc(exc, "bgzf.write", path=getattr(fileobj, "name", None))


def _block_header(bsize_minus1: int) -> bytes:
    return _HEADER.pack(
        0x1F, 0x8B, 0x08, 0x04,  # magic, deflate, FEXTRA
        0,  # mtime
        0, 0xFF,  # XFL, OS=unknown
        6,  # XLEN
        0x42, 0x43,  # 'B','C'
        2,  # SLEN
        bsize_minus1,
    )


def compress_block(data: bytes, level: int = 1) -> bytes:
    """Compress one <=64KiB chunk into a standalone BGZF block.

    Uses the C++/libdeflate codec when available (fgumi_tpu.native, the
    InlineBgzfCompressor analog); zlib otherwise.
    """
    assert len(data) <= 0x10000
    from .. import native

    blk = native.bgzf_compress_block(data, level)
    if blk is not None:
        return blk
    co = zlib.compressobj(level, zlib.DEFLATED, -15)
    payload = co.compress(data) + co.flush()
    bsize = len(payload) + _HEADER.size + 8
    assert bsize <= 0x10000, "BGZF block overflow (incompressible data)"
    return (
        _block_header(bsize - 1)
        + payload
        + struct.pack("<II", zlib.crc32(data), len(data) & 0xFFFFFFFF)
    )


class BgzfWriter(io.RawIOBase):
    """Streaming BGZF writer: buffers to MAX_BLOCK_DATA and emits blocks."""

    def __init__(self, fileobj, level: int = 1, owns_fileobj: bool = False):
        self._f = fileobj
        self._level = level
        self._buf = bytearray()
        self._owns = owns_fileobj
        self._coffset = 0  # compressed bytes emitted so far
        # a failed write/flush poisons the stream: close() then discards
        # instead of committing — otherwise GC-driven IOBase.__del__ would
        # atomically rename a half-written file under the final name
        self._broken = False
        # fire() costs a lock + env read; write() runs once per BAM record,
        # so the armed check is hoisted to construction time (chaos tests
        # arm FGUMI_TPU_FAULT before the writer exists) — the tracing
        # check is hoisted for the same reason
        self._fault_armed = faults.armed("writer.compress")
        self._trace_on = _trace.tracing_enabled()
        self._counted = False

    def write(self, data) -> int:
        try:
            return self._write(data)
        except BaseException as e:
            self._broken = True
            _reraise_disk_full(e, self._f)
            raise

    def _write(self, data) -> int:
        if self._fault_armed:
            data = faults.fire("writer.compress", data)
        self._buf += data
        n_full = len(self._buf) // MAX_BLOCK_DATA
        if n_full == 0:
            return len(data)
        if n_full > 1:
            # multi-block: one native call compresses all complete blocks
            # (parallel across blocks — the reference's parallel Compress
            # step, base.rs:1123-1150); identical output bytes to the
            # block-at-a-time loop below
            from .. import native

            chunk_len = n_full * MAX_BLOCK_DATA
            t0 = time.monotonic()
            with _trace.span("bgzf.compress", blocks=n_full) \
                    if self._trace_on else _trace.NULL_SPAN:
                got = native.bgzf_compress_many(
                    memoryview(self._buf)[:chunk_len], self._level)
            if got is not None:
                METRICS.observe("io.bgzf.compress_s", time.monotonic() - t0)
                blob, _ = got
                del self._buf[:chunk_len]
                self._coffset += len(blob)
                self._f.write(blob)
                return len(data)
        t0 = time.monotonic()
        with _trace.span("bgzf.compress", blocks=n_full) \
                if self._trace_on else _trace.NULL_SPAN:
            while len(self._buf) >= MAX_BLOCK_DATA:
                chunk = bytes(self._buf[:MAX_BLOCK_DATA])
                del self._buf[:MAX_BLOCK_DATA]
                block = compress_block(chunk, self._level)
                self._coffset += len(block)
                self._f.write(block)
        METRICS.observe("io.bgzf.compress_s", time.monotonic() - t0)
        return len(data)

    def tell_virtual(self) -> int:
        """BGZF virtual offset of the next byte to be written:
        (compressed offset of the current block) << 16 | in-block offset."""
        return (self._coffset << 16) | len(self._buf)

    def write_indexed(self, blob, starts):
        """Write `blob` and return the BGZF virtual offset of each position
        in `starts` (uncompressed offsets relative to blob, ascending; pass
        len(blob) as the final entry to get the end offset).

        Equivalent to interleaving tell_virtual() with per-record write()
        calls, but with one multi-block compression per blob — the offsets
        are reconstructed from the block-offset table (a record at
        uncompressed offset u lands in block u // MAX_BLOCK_DATA of this
        flush, at in-block offset u % MAX_BLOCK_DATA).
        """
        try:
            return self._write_indexed(blob, starts)
        except BaseException as e:
            self._broken = True
            _reraise_disk_full(e, self._f)
            raise

    def _write_indexed(self, blob, starts):
        import numpy as np

        from .. import native

        base = len(self._buf)
        self._buf += blob
        u = np.asarray(starts, dtype=np.int64) + base
        total = len(self._buf)
        n_full = total // MAX_BLOCK_DATA
        chunk_len = n_full * MAX_BLOCK_DATA
        coff0 = self._coffset
        if n_full == 0:
            return (coff0 << 16) | u
        got = native.bgzf_compress_many(
            memoryview(self._buf)[:chunk_len], self._level) \
            if native.get_lib() is not None else None
        if got is not None:
            cblob, block_off = got
            self._f.write(cblob)
            self._coffset += len(cblob)
            del self._buf[:chunk_len]
        else:  # pure-python fallback: per block, recording offsets
            block_off = np.zeros(n_full + 1, dtype=np.int64)
            for i in range(n_full):
                block = compress_block(
                    bytes(self._buf[i * MAX_BLOCK_DATA:(i + 1)
                                    * MAX_BLOCK_DATA]), self._level)
                self._f.write(block)
                self._coffset += len(block)
                block_off[i + 1] = block_off[i] + len(block)
            del self._buf[:chunk_len]
        in_full = u < chunk_len
        blk = np.minimum(u // MAX_BLOCK_DATA, n_full - 1)
        vo_full = ((coff0 + block_off[blk]) << 16) | (u % MAX_BLOCK_DATA)
        vo_tail = (self._coffset << 16) | np.maximum(u - chunk_len, 0)
        return np.where(in_full, vo_full, vo_tail)

    def flush(self):
        try:
            # fire only when there is buffered data to flush: IOBase.close
            # re-invokes flush() (from both close() and discard()), and an
            # unconditional fire there would consume count-limited fault
            # budgets — or raise out of the error-path cleanup itself
            if self._fault_armed and self._buf:
                faults.fire("writer.compress")
            if self._buf:
                with _trace.span("bgzf.compress", blocks=1) \
                        if self._trace_on else _trace.NULL_SPAN:
                    block = compress_block(bytes(self._buf), self._level)
                    self._coffset += len(block)
                    self._f.write(block)
                self._buf.clear()
        except BaseException as e:
            self._broken = True
            _reraise_disk_full(e, self._f)
            raise

    def close(self):
        if self.closed:
            return
        if self._broken:
            self.discard()
            return
        self.flush()
        try:
            self._f.write(BGZF_EOF)
            self._f.flush()
        except BaseException as e:
            self._broken = True
            _reraise_disk_full(e, self._f)
            raise
        self._coffset += len(BGZF_EOF)
        if not self._counted:
            self._counted = True
            METRICS.inc("io.bytes_written", self._coffset)
        if self._owns:
            self._f.close()
        super().close()

    def discard(self):
        """Abandon the stream: drop buffered data and discard (atomic
        outputs) or close the underlying file without writing the EOF
        sentinel — the error-path counterpart of close()."""
        if self.closed:
            return
        self._buf.clear()
        if self._owns:
            from ..utils.atomic import discard_output

            discard_output(self._f)
        super().close()


def _parse_member_bsize(extra: bytes) -> int:
    """BSIZE (total member length - 1) from a member's FEXTRA subfields,
    or -1 when no BC subfield is present (not a BGZF member)."""
    off = 0
    while off + 4 <= len(extra):
        slen = int.from_bytes(extra[off + 2: off + 4], "little")
        if extra[off: off + 2] == b"BC" and slen == 2:
            return int.from_bytes(extra[off + 4: off + 6], "little")
        off += 4 + slen
    return -1


def verify_members(path: str, sink=None) -> dict:
    """Re-walk a written BGZF file member by member, verifying each one
    end to end (the ``--audit-output`` compressed-layer pass).

    For every gzip member: parse the fixed header + FEXTRA BC subfield,
    inflate the raw deflate payload with a fresh decompressor, and check
    the member's CRC32 and ISIZE trailer against the *freshly decoded*
    bytes — so a bit flipped anywhere between the writer's buffers and
    the page cache (payload, trailer, or header) fails loudly instead of
    being published. ``sink(decoded_bytes)``, when given, receives each
    member's decompressed payload in order (the BAM record walk rides
    this). Returns ``{"members", "data_bytes", "eof_sentinel"}``; raises
    :class:`~fgumi_tpu.io.errors.OutputIntegrityError` naming the member
    offset on the first inconsistency."""
    members = 0
    data_bytes = 0
    last_empty = False
    with open(path, "rb") as f:
        offset = 0
        while True:
            head = f.read(12)
            if not head:
                break
            if len(head) < 12 or head[:4] != b"\x1f\x8b\x08\x04":
                raise OutputIntegrityError(
                    "not a BGZF member header", path=path, offset=offset)
            xlen = int.from_bytes(head[10:12], "little")
            extra = f.read(xlen)
            if len(extra) < xlen:
                raise OutputIntegrityError(
                    "truncated member header", path=path, offset=offset)
            bsize = _parse_member_bsize(extra)
            if bsize < 0:
                raise OutputIntegrityError(
                    "member has no BC subfield", path=path, offset=offset)
            payload_len = bsize + 1 - 12 - xlen - 8
            if payload_len < 0:
                raise OutputIntegrityError(
                    f"member BSIZE {bsize + 1} smaller than its own "
                    "header", path=path, offset=offset)
            payload = f.read(payload_len)
            trailer = f.read(8)
            if len(payload) < payload_len or len(trailer) < 8:
                raise OutputIntegrityError(
                    "truncated member (file ends mid-block)", path=path,
                    offset=offset)
            z = zlib.decompressobj(wbits=-15)
            try:
                decoded = z.decompress(payload) + z.flush()
            except zlib.error as e:
                raise OutputIntegrityError(
                    f"member payload does not inflate: {e}", path=path,
                    offset=offset) from e
            if z.unconsumed_tail or not z.eof:
                raise OutputIntegrityError(
                    "member deflate stream did not terminate cleanly",
                    path=path, offset=offset)
            crc = int.from_bytes(trailer[:4], "little")
            isize = int.from_bytes(trailer[4:8], "little")
            if zlib.crc32(decoded) != crc:
                raise OutputIntegrityError(
                    f"member CRC32 mismatch (stored {crc:#010x}, "
                    f"computed {zlib.crc32(decoded):#010x})", path=path,
                    offset=offset)
            if (len(decoded) & 0xFFFFFFFF) != isize:
                raise OutputIntegrityError(
                    f"member ISIZE mismatch (stored {isize}, computed "
                    f"{len(decoded)})", path=path, offset=offset)
            members += 1
            data_bytes += len(decoded)
            last_empty = len(decoded) == 0
            if sink is not None and decoded:
                sink(decoded)
            offset += bsize + 1
    return {"members": members, "data_bytes": data_bytes,
            "eof_sentinel": last_empty}


class BgzfReader:
    """Streaming multi-member gzip/BGZF reader over a file object.

    read(n) returns exactly n bytes unless EOF. Uses zlib's C decompressor; also
    accepts plain gzip input (the reference similarly auto-detects, bam-io reader).
    """

    def __init__(self, fileobj, chunk_size: int = 1 << 20,
                 owns_fileobj: bool = False, name: str = None):
        self._f = fileobj
        self._owns = owns_fileobj
        self._chunk = chunk_size
        self._z = zlib.decompressobj(wbits=31)
        self._buf = bytearray()
        self._eof = False
        # native batch path state: None = undecided, False = zlib fallback
        self._native = None
        self._raw = bytearray()
        # diagnostics: source path (when known) + compressed bytes consumed,
        # so a corrupt/truncated stream reports *where*, not just *that*
        self.name = name if name is not None \
            else getattr(fileobj, "name", None)
        self._in_off = 0
        self._z_started = False  # current zlib member got any input
        self._trace_on = _trace.tracing_enabled()
        self._counted = False

    def _read_raw(self, n: int) -> bytes:
        """One raw chunk off the underlying file, offset-tracked and
        routed through the reader.decompress fault point."""
        raw = self._f.read(n)
        if raw:
            self._in_off += len(raw)
            raw = faults.fire("reader.decompress", raw)
        return raw

    def _input_error(self, message: str) -> InputFormatError:
        # the undecoded residue starts at in_off - len(_raw)
        return InputFormatError(message, path=self.name,
                                offset=self._in_off - len(self._raw))

    def _zdecomp(self, data) -> bytes:
        self._z_started = True
        try:
            return self._z.decompress(data)
        except zlib.error as e:
            raise self._input_error(f"corrupt gzip/BGZF data: {e}") from e

    def _decide_native(self, first_chunk: bytes):
        """Engage the C++ batch decompressor only for genuine BGZF input
        (BGZF magic + FEXTRA); plain gzip keeps the zlib streaming path."""
        from .. import native

        if len(first_chunk) >= 18 and first_chunk[:4] == b"\x1f\x8b\x08\x04" \
                and native.get_lib() is not None:
            self._native = True
        else:
            self._native = False

    def _demote_to_zlib(self):
        """Switch to the zlib streaming path mid-stream (e.g. a plain-gzip
        member concatenated after BGZF blocks): replay the undecoded raw
        bytes through a fresh decompressor."""
        self._native = False
        self._z = zlib.decompressobj(wbits=31)
        if self._raw:
            self._buf += self._zdecomp(bytes(self._raw))
            self._raw.clear()

    @staticmethod
    def _is_bgzf_member(buf) -> bool:
        """True iff buf starts with a BGZF member header (gzip + BC subfield).
        Call with >=18 bytes."""
        if buf[:4] != b"\x1f\x8b\x08\x04":
            return False
        xlen = int.from_bytes(buf[10:12], "little")
        extra = buf[12 : 12 + xlen]
        off = 0
        while off + 4 <= len(extra):
            slen = int.from_bytes(extra[off + 2 : off + 4], "little")
            if extra[off : off + 2] == b"BC" and slen == 2:
                return True
            off += 4 + slen
        return False

    def _fill_native(self, need: int):
        from .. import native

        while len(self._buf) < need and not (self._eof and not self._raw):
            if not self._eof:
                raw = self._read_raw(self._chunk)
                if raw:
                    self._raw += raw
                else:
                    self._eof = True
            if not self._raw:
                continue
            try:
                t0 = time.monotonic()
                with _trace.span("bgzf.decompress") \
                        if self._trace_on else _trace.NULL_SPAN:
                    decoded, consumed = native.bgzf_decompress(self._raw)
                METRICS.observe("io.bgzf.decompress_s",
                                time.monotonic() - t0)
            except ValueError:
                # garbage where a member should start: let zlib report it
                self._demote_to_zlib()
                self._fill(need)
                return
            # memoryview: bytearray += ndarray would dispatch to numpy's
            # broadcasting __radd__ instead of a buffer append
            self._buf += memoryview(decoded)
            del self._raw[:consumed]
            if consumed == 0 and self._raw:
                if len(self._raw) >= 18 and not self._is_bgzf_member(self._raw):
                    # a non-BGZF gzip member concatenated mid-stream: the
                    # zlib streaming path handles it (docstring contract)
                    self._demote_to_zlib()
                    self._fill(need)
                    return
                if self._eof:
                    raise self._input_error(
                        "truncated BGZF stream (partial block at EOF)")

    def _fill(self, need: int):
        if self._native is None:
            first = self._read_raw(self._chunk)
            if not first:
                self._eof = True
                return
            self._decide_native(first)
            if self._native:
                self._raw += first
            else:
                self._buf += self._zdecomp(first)
        if self._native:
            self._fill_native(need)
            return
        while len(self._buf) < need:
            if self._z.eof:
                # recycle pending concatenated members even after file EOF
                rest = self._z.unused_data
                self._z = zlib.decompressobj(wbits=31)
                self._z_started = False
                if rest:
                    self._buf += self._zdecomp(rest)
                    continue
            if self._eof:
                # a member that consumed input but never reached its gzip
                # trailer is a torn download / chopped file: report it
                # instead of silently handing back a short stream
                if self._z_started and not self._z.eof:
                    raise self._input_error(
                        "truncated gzip stream (unexpected EOF mid-member)")
                break
            raw = self._read_raw(self._chunk)
            if not raw:
                self._eof = True
                continue
            self._buf += self._zdecomp(raw)

    def read(self, n: int) -> bytes:
        self._fill(n)
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    def read_into_available(self) -> bytes:
        """Return whatever is currently buffered plus one more raw chunk's worth."""
        self._fill(len(self._buf) + 1)
        out = bytes(self._buf)
        self._buf.clear()
        return out

    def read_decoded(self):
        """One decoded chunk as a uint8 numpy array (empty at EOF).

        The zero-copy variant of read_into_available for the native BGZF
        path: the decompressor's output buffer is handed over directly
        instead of round-tripping through the bytearray (whose append +
        bytes() drain cost two full copies per decompressed byte). Buffered
        bytes (header residue) and the zlib fallback go through the classic
        path.
        """
        import numpy as np

        if self._native is not True or self._buf:
            data = self.read_into_available()
            return np.frombuffer(bytearray(data), dtype=np.uint8)
        from .. import native

        while True:
            if not self._raw:
                if self._eof:
                    return np.empty(0, dtype=np.uint8)
                raw = self._read_raw(self._chunk)
                if raw:
                    self._raw += raw
                else:
                    self._eof = True
                continue
            try:
                t0 = time.monotonic()
                with _trace.span("bgzf.decompress") \
                        if self._trace_on else _trace.NULL_SPAN:
                    decoded, consumed = native.bgzf_decompress(self._raw)
                METRICS.observe("io.bgzf.decompress_s",
                                time.monotonic() - t0)
            except ValueError:
                self._demote_to_zlib()
                data = self.read_into_available()
                return np.frombuffer(bytearray(data), dtype=np.uint8)
            del self._raw[:consumed]
            if consumed == 0:
                # _raw holds a partial block (the steady state between
                # reads): pull more input and retry the native decode —
                # delegating to the copying fill here would make every
                # steady-state call take the slow path
                if len(self._raw) >= 18 and not self._is_bgzf_member(
                        self._raw):
                    # concatenated plain-gzip member mid-stream: the
                    # general fill demotes to zlib
                    self._fill(len(self._buf) + 1)
                    data = bytes(self._buf)
                    self._buf.clear()
                    return np.frombuffer(bytearray(data), dtype=np.uint8)
                if self._eof:
                    raise self._input_error(
                        "truncated BGZF stream (partial block at EOF)")
                raw = self._read_raw(self._chunk)
                if raw:
                    self._raw += raw
                else:
                    self._eof = True
                continue
            if len(decoded):
                return decoded

    def close(self):
        if not self._counted:
            self._counted = True
            if self._in_off:
                METRICS.inc("io.bytes_read", self._in_off)
        if self._owns:
            self._f.close()
