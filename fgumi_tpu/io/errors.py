"""Typed input-format errors carrying file path + byte offset context.

Subclasses ValueError so existing ``except ValueError`` callers (and
tests) keep working, while the CLI's top-level handler can recognize a
*diagnosed input problem* — truncated BGZF stream, corrupt block,
malformed record — and exit with a one-line message instead of a Python
traceback.
"""


class OutputIntegrityError(RuntimeError):
    """A written output failed its pre-commit integrity audit.

    Raised by the ``--audit-output`` pass (io/bam.py + io/bgzf.py) when
    the re-walked temp file disagrees with what the writer believes it
    wrote — a corrupt BGZF member (CRC32/ISIZE mismatch), a truncated
    member, a record-count mismatch, or a sort-key-order mismatch against
    the writer's own tallies. The atomic commit is aborted, so the bad
    file is never published under its final name; the CLI maps this to
    exit code 5 (docs/resilience.md)."""

    def __init__(self, message: str, path: str = None, offset: int = None):
        self.path = path
        self.offset = offset
        loc = f"{path}: " if path is not None else ""
        suffix = f" (near byte offset {offset})" if offset is not None \
            else ""
        super().__init__(
            f"{loc}output integrity audit failed: {message}{suffix}")


class InputFormatError(ValueError):
    """Corrupt, truncated, or malformed input.

    `path` and `offset` (compressed-stream byte offset, when known) are
    kept as attributes and folded into the message so a single str() is
    the full diagnostic.
    """

    def __init__(self, message: str, path: str = None, offset: int = None):
        self.path = path
        self.offset = offset
        loc = ""
        if path is not None:
            loc = f"{path}: "
        suffix = ""
        if offset is not None:
            suffix = f" (near byte offset {offset})"
        super().__init__(f"{loc}{message}{suffix}")
