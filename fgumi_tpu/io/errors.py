"""Typed input-format errors carrying file path + byte offset context.

Subclasses ValueError so existing ``except ValueError`` callers (and
tests) keep working, while the CLI's top-level handler can recognize a
*diagnosed input problem* — truncated BGZF stream, corrupt block,
malformed record — and exit with a one-line message instead of a Python
traceback.
"""


class InputFormatError(ValueError):
    """Corrupt, truncated, or malformed input.

    `path` and `offset` (compressed-stream byte offset, when known) are
    kept as attributes and folded into the message so a single str() is
    the full diagnostic.
    """

    def __init__(self, message: str, path: str = None, offset: int = None):
        self.path = path
        self.offset = offset
        loc = ""
        if path is not None:
            loc = f"{path}: "
        suffix = ""
        if offset is not None:
            suffix = f" (near byte offset {offset})"
        super().__init__(f"{loc}{message}{suffix}")
