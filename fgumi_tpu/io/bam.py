"""Raw-byte BAM record layer.

Record accessors work directly on BAM wire bytes at fixed offsets, mirroring the
reference's raw-record design (/root/reference/crates/fgumi-raw-bam/src/fields.rs:7-24:
refID/pos/l_read_name/mapq/bin/n_cigar_op/flag/l_seq/next_refID/next_pos/tlen then
name, cigar, packed seq, qual, aux TLV) — decoding only what each consumer touches,
which is what keeps host-side feeding cheap (raw_bam_record.rs:6-13 rationale).
"""

import contextvars
import struct
import zlib
from dataclasses import dataclass, field

import numpy as np

from .bgzf import BgzfReader, BgzfWriter

BAM_MAGIC = b"BAM\x01"
# SAM spec reg2bin(-1, 0) — the unmapped record bin (builder.rs:1-3).
UNMAPPED_BIN = 4680

# BAM flags (SAM spec).
FLAG_PAIRED = 0x1
FLAG_PROPER_PAIR = 0x2
FLAG_UNMAPPED = 0x4
FLAG_MATE_UNMAPPED = 0x8
FLAG_REVERSE = 0x10
FLAG_MATE_REVERSE = 0x20
FLAG_FIRST = 0x40
FLAG_LAST = 0x80
FLAG_SECONDARY = 0x100
FLAG_QC_FAIL = 0x200
FLAG_DUPLICATE = 0x400
FLAG_SUPPLEMENTARY = 0x800

# 4-bit seq nibble -> ASCII (=ACMGRSVTWYHKDBN).
NIBBLE_TO_BASE = np.frombuffer(b"=ACMGRSVTWYHKDBN", dtype=np.uint8)
BASE_TO_NIBBLE = np.full(256, 15, dtype=np.uint8)  # default N
for _i, _b in enumerate(b"=ACMGRSVTWYHKDBN"):
    BASE_TO_NIBBLE[_b] = _i
for _i, _b in enumerate(b"=acmgrsvtwyhkdbn"):
    BASE_TO_NIBBLE[_b] = _i

CIGAR_OPS = "MIDNSHP=X"
_CONSUMES_QUERY = frozenset("MIS=X")
_CONSUMES_REF = frozenset("MDN=X")


# canonical SAM-spec binning lives in io/bai.py (index writer/reader)
from .bai import reg2bin as _reg2bin  # noqa: E402


@dataclass
class BamHeader:
    text: str
    ref_names: list
    ref_lengths: list
    _name_to_id: dict = field(default_factory=dict, repr=False)

    def __post_init__(self):
        if not self._name_to_id:
            self._name_to_id = {n: i for i, n in enumerate(self.ref_names)}

    def ref_id(self, name: str) -> int:
        return self._name_to_id.get(name, -1)

    def encode(self) -> bytes:
        text_b = self.text.encode()
        out = bytearray(BAM_MAGIC)
        out += struct.pack("<i", len(text_b))
        out += text_b
        out += struct.pack("<i", len(self.ref_names))
        for name, length in zip(self.ref_names, self.ref_lengths):
            nb = name.encode() + b"\x00"
            out += struct.pack("<i", len(nb)) + nb + struct.pack("<i", length)
        return bytes(out)

    @classmethod
    def decode_from(cls, read):
        """Parse from a `read(n)` callable positioned at the stream start."""
        magic = read(4)
        if magic != BAM_MAGIC:
            raise ValueError(f"not a BAM stream (magic {magic!r})")
        (l_text,) = struct.unpack("<i", read(4))
        text = read(l_text).decode(errors="replace").rstrip("\x00")
        (n_ref,) = struct.unpack("<i", read(4))
        names, lengths = [], []
        for _ in range(n_ref):
            (l_name,) = struct.unpack("<i", read(4))
            names.append(read(l_name)[:-1].decode())
            (l_ref,) = struct.unpack("<i", read(4))
            lengths.append(l_ref)
        return cls(text=text, ref_names=names, ref_lengths=lengths)


def header_roundtrip(header: BamHeader) -> BamHeader:
    """The header exactly as a file round trip would deliver it.

    The fused pipeline chain (``pipeline_chain``) hands headers between
    stages in memory; downstream stages derive provenance from the header
    *text* (@HD rewrites in sort, @PG chaining in filter), so the handoff
    must replicate what ``encode()`` → ``decode_from()`` produces — byte
    for byte — or the fused run's headers could drift from the staged
    run's (e.g. trailing-NUL stripping)."""
    import io as _io

    return BamHeader.decode_from(_io.BytesIO(header.encode()).read)


class RawRecord:
    """A single BAM record's wire bytes (without the leading block_size)."""

    __slots__ = ("data", "_tag_idx", "_aux", "_cigar")

    def __init__(self, data: bytes):
        self.data = data
        self._tag_idx = None  # lazy {tag: (typ, value_off)} built on first lookup
        self._aux = None      # lazy cached aux-region offset
        self._cigar = None    # lazy cached decoded CIGAR

    # --- fixed-offset fields (fields.rs:7-24) ---
    @property
    def ref_id(self) -> int:
        return int.from_bytes(self.data[0:4], "little", signed=True)

    @property
    def pos(self) -> int:
        return int.from_bytes(self.data[4:8], "little", signed=True)

    @property
    def l_read_name(self) -> int:
        return self.data[8]

    @property
    def mapq(self) -> int:
        return self.data[9]

    @property
    def n_cigar_op(self) -> int:
        return int.from_bytes(self.data[12:14], "little")

    @property
    def flag(self) -> int:
        return int.from_bytes(self.data[14:16], "little")

    @property
    def l_seq(self) -> int:
        return int.from_bytes(self.data[16:20], "little")

    @property
    def next_ref_id(self) -> int:
        return int.from_bytes(self.data[20:24], "little", signed=True)

    @property
    def next_pos(self) -> int:
        return int.from_bytes(self.data[24:28], "little", signed=True)

    @property
    def tlen(self) -> int:
        return int.from_bytes(self.data[28:32], "little", signed=True)

    @property
    def name(self) -> bytes:
        return self.data[32 : 32 + self.l_read_name - 1]

    # --- variable sections ---
    def _cigar_off(self) -> int:
        return 32 + self.l_read_name

    def _seq_off(self) -> int:
        return self._cigar_off() + 4 * self.n_cigar_op

    def _qual_off(self) -> int:
        return self._seq_off() + (self.l_seq + 1) // 2

    def _aux_off(self) -> int:
        # cached: tag scans and record edits probe this repeatedly, and the
        # record's bytes are immutable
        aux = self._aux
        if aux is None:
            aux = self._aux = self._qual_off() + self.l_seq
        return aux

    def cigar(self):
        """[(op_char, length)] decoded CIGAR (cached; the record's bytes are
        immutable and consumers probe the CIGAR several times per record)."""
        out = self._cigar
        if out is None:
            off = self._cigar_off()
            data = self.data
            out = []
            for i in range(self.n_cigar_op):
                v = int.from_bytes(data[off + 4 * i: off + 4 * i + 4],
                                   "little")
                out.append((CIGAR_OPS[v & 0xF], v >> 4))
            self._cigar = out
        return out

    def seq_bytes(self) -> bytes:
        """ASCII sequence (unpacked from 4-bit codes)."""
        n = self.l_seq
        packed = np.frombuffer(self.data, dtype=np.uint8, count=(n + 1) // 2,
                               offset=self._seq_off())
        nibbles = np.empty(2 * len(packed), dtype=np.uint8)
        nibbles[0::2] = packed >> 4
        nibbles[1::2] = packed & 0xF
        return NIBBLE_TO_BASE[nibbles[:n]].tobytes()

    def quals(self) -> np.ndarray:
        return np.frombuffer(self.data, dtype=np.uint8, count=self.l_seq,
                             offset=self._qual_off()).copy()

    # --- aux tag TLV scan (tags.rs:8-40) ---
    def _iter_tags(self):
        data = self.data
        off = self._aux_off()
        end = len(data)
        while off + 3 <= end:
            tag = data[off : off + 2]
            typ = data[off + 2]
            off += 3
            yield tag, typ, off
            off = _skip_tag_value(data, typ, off)

    def find_tag(self, tag: bytes):
        """Return (type_char, python value) or None.

        The TLV scan runs once per record and caches {tag: (typ, off)} —
        commands typically probe several tags per record (filter reads 5+),
        and rescanning the aux region per probe dominated their profiles.
        """
        idx = self._tag_idx
        if idx is None:
            idx = {}
            for t, typ, off in self._iter_tags():
                if t not in idx:  # first occurrence wins, like the linear scan
                    idx[t] = (typ, off)
            self._tag_idx = idx
        got = idx.get(tag)
        if got is None:
            return None
        typ, off = got
        return chr(typ), _read_tag_value(self.data, typ, off)

    def get_str(self, tag: bytes):
        got = self.find_tag(tag)
        if got is None:
            return None
        typ, val = got
        return val if typ in ("Z", "H") else None

    def get_int(self, tag: bytes):
        got = self.find_tag(tag)
        if got is None:
            return None
        typ, val = got
        return int(val) if typ in "cCsSiI" else None

    def aux_bytes(self) -> bytes:
        return self.data[self._aux_off():]

    def data_without_tag(self, tag: bytes) -> bytes:
        """Record bytes with every occurrence of `tag` removed (aux TLV edit)."""
        spans = []
        for t, typ, off in self._iter_tags():
            if t == tag:
                spans.append((off - 3, _skip_tag_value(self.data, typ, off)))
        if not spans:
            return self.data
        out = bytearray()
        prev = 0
        for start, end in spans:
            out += self.data[prev:start]
            prev = end
        out += self.data[prev:]
        return bytes(out)

    def read_length_from_cigar(self) -> int:
        return sum(n for op, n in self.cigar() if op in _CONSUMES_QUERY)

    def reference_length(self) -> int:
        return sum(n for op, n in self.cigar() if op in _CONSUMES_REF)

    def unclipped_start(self) -> int:
        """0-based alignment start minus leading clips."""
        pos = self.pos
        for op, n in self.cigar():
            if op in "SH":
                pos -= n
            else:
                break
        return pos

    def unclipped_end(self) -> int:
        """0-based inclusive alignment end plus trailing clips."""
        end = self.pos + self.reference_length() - 1
        for op, n in reversed(self.cigar()):
            if op in "SH":
                end += n
            else:
                break
        return end


_TAG_SIZES = {ord("c"): 1, ord("C"): 1, ord("s"): 2, ord("S"): 2, ord("i"): 4,
              ord("I"): 4, ord("f"): 4, ord("A"): 1}
_ARRAY_DTYPES = {ord("c"): np.int8, ord("C"): np.uint8, ord("s"): np.int16,
                 ord("S"): np.uint16, ord("i"): np.int32, ord("I"): np.uint32,
                 ord("f"): np.float32}


def _skip_tag_value(data: bytes, typ: int, off: int) -> int:
    size = _TAG_SIZES.get(typ)
    if size is not None:
        return off + size
    if typ in (ord("Z"), ord("H")):
        return data.index(b"\x00", off) + 1
    if typ == ord("B"):
        sub = data[off]
        (count,) = struct.unpack_from("<I", data, off + 1)
        return off + 5 + count * _TAG_SIZES[sub]
    raise ValueError(f"unknown aux tag type {typ!r}")


def _read_tag_value(data: bytes, typ: int, off: int):
    c = chr(typ)
    if c == "A":
        return chr(data[off])
    if c in "cCsSiI":
        fmt = {"c": "<b", "C": "<B", "s": "<h", "S": "<H", "i": "<i", "I": "<I"}[c]
        return struct.unpack_from(fmt, data, off)[0]
    if c == "f":
        return struct.unpack_from("<f", data, off)[0]
    if c in "ZH":
        end = data.index(b"\x00", off)
        return data[off:end].decode(errors="replace")
    if c == "B":
        sub = data[off]
        (count,) = struct.unpack_from("<I", data, off + 1)
        dt = _ARRAY_DTYPES[sub]
        return np.frombuffer(data, dtype=dt, count=count, offset=off + 5).copy()
    raise ValueError(f"unknown aux tag type {c!r}")


def pack_seq(seq) -> bytes:
    """ASCII sequence (bytes or uint8 array) -> BAM 4-bit packed bytes."""
    codes = BASE_TO_NIBBLE[np.frombuffer(seq, dtype=np.uint8)
                           if isinstance(seq, (bytes, bytearray))
                           else np.asarray(seq, dtype=np.uint8)]
    if len(codes) % 2:
        codes = np.append(codes, 0)
    return ((codes[0::2] << 4) | codes[1::2]).astype(np.uint8).tobytes()


class RecordBuilder:
    """Builds raw BAM record bytes (mirrors UnmappedSamBuilder, builder.rs:69-200)."""

    def __init__(self):
        self._buf = bytearray()

    def start_unmapped(self, name: bytes, flag: int, seq: bytes, quals) -> "RecordBuilder":
        """Begin an unmapped record: ref_id=-1, pos=-1, mapq=0, bin=4680, no CIGAR."""
        buf = self._buf
        buf.clear()
        l_name = len(name) + 1
        if l_name > 255:
            raise ValueError(f"read name too long ({len(name)} bytes): {name[:40]!r}...")
        n = len(seq)
        buf += struct.pack("<iiBBHHHiiii", -1, -1, l_name, 0, UNMAPPED_BIN, 0,
                           flag, n, -1, -1, 0)
        buf += name
        buf += b"\x00"
        buf += pack_seq(seq)
        buf += np.asarray(quals, dtype=np.uint8).tobytes()
        return self

    def start_mapped(self, name: bytes, flag: int, ref_id: int, pos: int,
                     mapq: int, cigar, seq: bytes, quals,
                     next_ref_id: int = -1, next_pos: int = -1,
                     tlen: int = 0) -> "RecordBuilder":
        """Begin a mapped record. `cigar` is [(op_char, length)] (builder.rs:356)."""
        buf = self._buf
        buf.clear()
        l_name = len(name) + 1
        if l_name > 255:
            raise ValueError(f"read name too long ({len(name)} bytes)")
        n = len(seq)
        ref_len = sum(ln for op, ln in cigar if op in _CONSUMES_REF) or 1
        bin_ = _reg2bin(pos, pos + ref_len) if pos >= 0 else UNMAPPED_BIN
        buf += struct.pack("<iiBBHHHiiii", ref_id, pos, l_name, mapq, bin_,
                           len(cigar), flag, n, next_ref_id, next_pos, tlen)
        buf += name
        buf += b"\x00"
        for op, length in cigar:
            buf += struct.pack("<I", (length << 4) | CIGAR_OPS.index(op))
        buf += pack_seq(seq)
        buf += np.asarray(quals, dtype=np.uint8).tobytes()
        return self

    def tag_str(self, tag: bytes, value: bytes) -> "RecordBuilder":
        self._buf += tag + b"Z" + value + b"\x00"
        return self

    def tag_int(self, tag: bytes, value: int) -> "RecordBuilder":
        self._buf += tag + b"i" + struct.pack("<i", value)
        return self

    def tag_float(self, tag: bytes, value: float) -> "RecordBuilder":
        self._buf += tag + b"f" + struct.pack("<f", value)
        return self

    def tag_array_i16(self, tag: bytes, values) -> "RecordBuilder":
        arr = np.asarray(values, dtype=np.int16)
        self._buf += tag + b"Bs" + struct.pack("<I", arr.size) + arr.tobytes()
        return self

    def tag_array_u8(self, tag: bytes, values) -> "RecordBuilder":
        arr = np.asarray(values, dtype=np.uint8)
        self._buf += tag + b"BC" + struct.pack("<I", arr.size) + arr.tobytes()
        return self

    def finish(self) -> bytes:
        return bytes(self._buf)


class BamReader:
    """Sequential BAM reader yielding RawRecord over a BGZF/gzip stream."""

    def __init__(self, path_or_obj):
        owns = isinstance(path_or_obj, str)
        fileobj = open(path_or_obj, "rb") if owns else path_or_obj
        self._path = path_or_obj if owns else getattr(fileobj, "name", None)
        self._r = BgzfReader(fileobj, owns_fileobj=owns, name=self._path)
        self.header = BamHeader.decode_from(self._r.read)

    def __iter__(self):
        read = self._r.read
        while True:
            sz = read(4)
            if len(sz) < 4:
                return
            (block_size,) = struct.unpack("<I", sz)
            data = read(block_size)
            if len(data) < block_size:
                where = f" in {self._path}" if self._path else ""
                raise EOFError(
                    f"truncated BAM record{where} (expected {block_size} "
                    f"bytes, got {len(data)} before EOF)")
            yield RawRecord(data)

    def close(self):
        self._r.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _read_bgzf_block_at(f):
    """One BGZF block at the current file position -> (payload, csize),
    or None at EOF. Parses BSIZE from the BC extra subfield (BGZF spec)."""
    header = f.read(12)
    if len(header) < 12:
        return None
    if header[:4] != b"\x1f\x8b\x08\x04":
        raise ValueError("not a BGZF block (missing BC extra flag)")
    (xlen,) = struct.unpack_from("<H", header, 10)
    extra = f.read(xlen)
    bsize = None
    off = 0
    while off + 4 <= len(extra):
        si1, si2, slen = extra[off], extra[off + 1], \
            struct.unpack_from("<H", extra, off + 2)[0]
        if si1 == 66 and si2 == 67 and slen == 2:
            bsize = struct.unpack_from("<H", extra, off + 4)[0] + 1
        off += 4 + slen
    if bsize is None:
        raise ValueError("BGZF block lacks BSIZE")
    cdata_len = bsize - 12 - xlen - 8
    cdata = f.read(cdata_len)
    footer = f.read(8)
    if len(cdata) < cdata_len or len(footer) < 8:
        raise EOFError("truncated BGZF block")
    payload = zlib.decompress(cdata, wbits=-15)
    (isize,) = struct.unpack_from("<I", footer, 4)
    if len(payload) != isize:
        raise ValueError("BGZF ISIZE mismatch")
    return payload, bsize


class BamIndexedReader:
    """Random-access BAM reader over a coordinate-sorted BAM + .bai index.

    Analog of the reference's indexed reader
    (/root/reference/crates/fgumi-raw-bam/src/indexed_reader.rs): BAI bins +
    linear index select candidate chunks, BGZF blocks are decompressed from
    each chunk's virtual offset, and records are filtered by actual overlap.
    """

    def __init__(self, path: str, index_path: str = None):
        """`index_path`: explicit .bai/.csi path; by default .bai is tried
        first, then .csi (both expose the same query_chunks interface)."""
        import os

        with BamReader(path) as r:
            self.header = r.header
        from .bai import BaiIndex, CsiIndex

        if index_path is None:
            index_path = path + ".bai" if os.path.exists(path + ".bai") \
                else path + ".csi"
        self.index = CsiIndex(index_path) if index_path.endswith(".csi") \
            else BaiIndex(index_path)
        self._f = open(path, "rb")

    def query(self, tid: int, beg: int, end: int):
        """Yield RawRecords overlapping [beg, end) on reference `tid`."""
        for vo_beg, vo_end in self.index.query_chunks(tid, beg, end):
            yield from self._scan_chunk(vo_beg, vo_end, tid, beg, end)

    def _scan_chunk(self, vo_beg, vo_end, tid, beg, end):
        f = self._f
        coffset = vo_beg >> 16
        f.seek(coffset)
        got = _read_bgzf_block_at(f)
        if got is None:
            return
        payload, csize = got
        buf = bytearray(payload[vo_beg & 0xFFFF:])
        # markers: (buf_pos, block_file_offset, offset_of_buf_pos_in_block)
        markers = [(0, coffset, vo_beg & 0xFFFF)]
        next_coffset = coffset + csize
        pos = 0
        while True:
            if pos > (1 << 20):
                # stream with bounded memory: drop the consumed prefix and
                # rebase the block markers (whole-chromosome queries would
                # otherwise hold the full decompressed chunk)
                keep = max(i for i, m in enumerate(markers) if m[0] <= pos)
                rebased = []
                for bpos, blk_off, in_blk in markers[keep:]:
                    if bpos < pos:  # the block containing `pos`
                        rebased.append((0, blk_off, in_blk + pos - bpos))
                    else:
                        rebased.append((bpos - pos, blk_off, in_blk))
                markers = rebased
                del buf[:pos]
                pos = 0
            while len(buf) < pos + 4:
                got = _read_bgzf_block_at(f)
                if got is None:
                    return
                markers.append((len(buf), next_coffset, 0))
                buf += got[0]
                next_coffset += got[1]
            # virtual offset of this record's first byte
            m = next(m for m in reversed(markers) if m[0] <= pos)
            rec_vo = (m[1] << 16) | (m[2] + pos - m[0])
            if rec_vo >= vo_end:
                return
            (block_size,) = struct.unpack_from("<I", buf, pos)
            while len(buf) < pos + 4 + block_size:
                got = _read_bgzf_block_at(f)
                if got is None:
                    raise EOFError("truncated BAM record in indexed read")
                markers.append((len(buf), next_coffset, 0))
                buf += got[0]
                next_coffset += got[1]
            rec = RawRecord(bytes(buf[pos + 4:pos + 4 + block_size]))
            pos += 4 + block_size
            if rec.ref_id != tid or rec.pos >= end:
                if rec.ref_id > tid or (rec.ref_id == tid and rec.pos >= end):
                    return  # coordinate order: nothing later can overlap
                continue
            rec_end = rec.pos + max(rec.reference_length(), 1)
            if rec_end > beg:
                yield rec

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# default BGZF level for BamWriter (reference CompressionOptions default 1,
# commands/common.rs); the CLI's --compression-level sets it per invocation.
# Level 0 = stored blocks — used by the `pipeline` command for intermediates
# that are read back immediately. Context-scoped (not a bare module global)
# so two serve-daemon jobs with different levels in one process cannot
# clobber each other; the module constant is the fallback.
DEFAULT_COMPRESSION_LEVEL = 1

_level_var = contextvars.ContextVar("fgumi_tpu_bgzf_level", default=None)


def set_default_compression_level(level):
    """Set the context's default BGZF level (None = module default)."""
    _level_var.set(level)


def default_compression_level() -> int:
    lvl = _level_var.get()
    return DEFAULT_COMPRESSION_LEVEL if lvl is None else lvl


_audit_output_var = contextvars.ContextVar("fgumi_tpu_audit_output",
                                           default=False)


def set_audit_output(enabled: bool):
    """Arm (per invocation context) the ``--audit-output`` pre-commit
    integrity pass for BAM outputs (cli.py global flag)."""
    _audit_output_var.set(bool(enabled))


def audit_output_enabled() -> bool:
    import os

    return _audit_output_var.get() or \
        os.environ.get("FGUMI_TPU_AUDIT_OUTPUT", "").strip().lower() \
        in ("1", "true", "on", "all")


class _OutputTally:
    """The writer's own record accounting for the ``--audit-output``
    re-walk to check against: record count plus a streaming CRC32 over
    the exact record-stream bytes (block_size prefixes + payloads, in
    write order) as they were handed to the writer — so the audit proves
    not just "N records survived" but "the bytes on disk are, in order,
    the bytes the pipeline wrote": any loss, duplication, reordering, or
    single-bit corruption between the writer's buffer and the page cache
    flips the digest. The order-sensitivity of the chained CRC is the
    sort-invariant check — the on-disk key sequence cannot differ from
    the written one without flipping it."""

    __slots__ = ("records", "content_crc", "header_crc")

    def __init__(self):
        self.records = 0
        self.content_crc = 0
        self.header_crc = 0  # CRC32 of the encoded BAM header block

    def add_record(self, framed):
        """One record WITH its 4-byte block_size prefix."""
        self.records += 1
        self.content_crc = zlib.crc32(framed, self.content_crc)

    def add_serialized(self, blob):
        """A block_size-prefixed record blob (the native batch
        serializer's output)."""
        view = memoryview(blob)
        off = 0
        n = len(view)
        while off + 4 <= n:
            size = int.from_bytes(view[off:off + 4], "little")
            self.records += 1
            off += 4 + size
        if off != n:
            # the writer itself was handed a torn blob: fail now, not at
            # the re-walk (this is a caller bug, not disk corruption)
            from .errors import OutputIntegrityError

            raise OutputIntegrityError(
                "serialized record blob is torn (partial block_size "
                "prefix)")
        self.content_crc = zlib.crc32(view, self.content_crc)

    def add_indexed(self, blob, starts):
        """A prefix-framed blob whose record boundaries the caller
        already delimited (``starts``: cumulative offsets, one past the
        record count) — no per-record Python walk needed; the pre-commit
        re-walk still catches any disagreement between ``starts`` and
        the actual framing."""
        self.records += len(starts) - 1
        self.content_crc = zlib.crc32(memoryview(blob), self.content_crc)


class _BamStreamAudit:
    """Incremental BAM structure walker over decompressed member payloads
    (the ``--audit-output`` record-layer pass): parses magic/header/refs,
    then counts records and CRCs their (refID, pos) keys exactly like
    :class:`_OutputTally`; optionally checks coordinate order."""

    def __init__(self, path: str, expect_coordinate: bool = False):
        self._path = path
        self._buf = bytearray()
        self._state = "magic"
        self._text_len = 0
        self._refs_left = None
        self.records = 0
        self.content_crc = 0
        self.header_crc = 0
        self._expect_coord = expect_coordinate
        self._last_key = None

    def _fail(self, message):
        from .errors import OutputIntegrityError

        raise OutputIntegrityError(message, path=self._path)

    def _eat_header(self, n: int):
        """Consume n header-section bytes, folding them into header_crc
        (the pre-record BAM structure is digest-checked too — a flipped
        bit in @HD/@SQ/@PG provenance is as published as one in a read)."""
        self.header_crc = zlib.crc32(memoryview(self._buf)[:n],
                                     self.header_crc)
        del self._buf[:n]

    def feed(self, data):
        self._buf += data
        buf = self._buf
        while True:
            if self._state == "magic":
                if len(buf) < 8:
                    return
                if bytes(buf[:4]) != BAM_MAGIC:
                    self._fail("decompressed stream does not start with "
                               "the BAM magic")
                self._text_len = int.from_bytes(buf[4:8], "little")
                self._eat_header(8)
                self._state = "text"
            elif self._state == "text":
                if len(buf) < self._text_len + 4:
                    return
                self._refs_left = int.from_bytes(
                    buf[self._text_len:self._text_len + 4], "little")
                self._eat_header(self._text_len + 4)
                self._state = "refs"
            elif self._state == "refs":
                if self._refs_left == 0:
                    self._state = "records"
                    continue
                if len(buf) < 4:
                    return
                l_name = int.from_bytes(buf[:4], "little")
                if len(buf) < 8 + l_name:
                    return
                self._eat_header(8 + l_name)
                self._refs_left -= 1
            else:  # records
                if len(buf) < 4:
                    return
                size = int.from_bytes(buf[:4], "little")
                if len(buf) < 4 + size:
                    return
                if size < 32:
                    self._fail(f"record #{self.records} shorter than the "
                               "fixed BAM record header")
                key = bytes(buf[4:12])
                self.records += 1
                self.content_crc = zlib.crc32(memoryview(buf)[:4 + size],
                                              self.content_crc)
                if self._expect_coord:
                    # the sorter's own key semantics (sort/keys.py):
                    # refID unsigned (-1 = 0xFFFFFFFF, unmapped tail
                    # last) but pos+1 — a mapped record with pos=-1
                    # (RNAME set, POS 0) legally sorts FIRST within its
                    # reference, so the raw unsigned pos would falsely
                    # reject the sorter's correct output
                    k = (int.from_bytes(key[:4], "little"),
                         int.from_bytes(key[4:8], "little",
                                        signed=True) + 1)
                    if self._last_key is not None and k < self._last_key:
                        self._fail(
                            f"record #{self.records} out of coordinate "
                            "order in an SO:coordinate file")
                    self._last_key = k
                del buf[:4 + size]

    def finish(self):
        if self._state != "records" or self._buf:
            self._fail("decompressed stream ends mid-structure "
                       f"(state={self._state}, {len(self._buf)} residual "
                       "bytes)")


class BamWriter:
    """Sequential BAM writer over BGZF.

    With ``--audit-output`` armed (and the atomic commit enabled), the
    writer tallies every record it is handed and, at close, re-walks the
    finished temp file — per-member BGZF CRC32/ISIZE, BAM structure,
    record count, and sort-key-order digest against its own tallies —
    BEFORE the atomic rename publishes it. A host-side DMA or page-cache
    corruption therefore fails the run (exit 5) instead of shipping a bad
    file (docs/resilience.md "Silent-corruption sentinel")."""

    def __init__(self, path_or_obj, header: BamHeader, level: int = None):
        if level is None:
            level = default_compression_level()
        owns = isinstance(path_or_obj, str)
        self._audit = None
        self._audit_coord = False
        self._audit_path = path_or_obj if owns else None
        if owns:
            # crash-safe commit: write .<name>.tmp.<pid>, atomic-rename on
            # close so an interrupted run never leaves a torn BAM under the
            # final name (utils/atomic.py; --no-atomic-output disables)
            from ..utils.atomic import open_output

            fileobj = open_output(path_or_obj)
            if audit_output_enabled():
                if hasattr(fileobj, "pre_commit_check"):
                    self._audit = _OutputTally()
                    self._audit_coord = "SO:coordinate" in header.text
                    fileobj.pre_commit_check = self._run_output_audit
                else:
                    import logging

                    logging.getLogger("fgumi_tpu").debug(
                        "--audit-output: atomic commit disabled for %s; "
                        "no pre-rename window to audit in — skipping",
                        path_or_obj)
        else:
            fileobj = path_or_obj
        self._w = BgzfWriter(fileobj, level=level, owns_fileobj=owns)
        try:
            enc = header.encode()
            if self._audit is not None:
                self._audit.header_crc = zlib.crc32(enc)
            self._w.write(enc)
        except BaseException:
            # construction failed: drop the temp eagerly rather than at GC
            self._w.discard()
            raise

    def write_record_bytes(self, data: bytes):
        framed = struct.pack("<I", len(data)) + data
        if self._audit is not None:
            self._audit.add_record(framed)
        self._w.write(framed)

    def write_record(self, rec: RawRecord):
        self.write_record_bytes(rec.data)

    def write_serialized(self, blob: bytes):
        """Append records already carrying their block_size prefixes
        (the native batch serializer's output)."""
        if self._audit is not None:
            self._audit.add_serialized(blob)
        self._w.write(blob)

    def write_indexed(self, blob, starts):
        """Append a prefix-framed record blob and return the BGZF virtual
        offset of each ``starts`` position (the BAI/CSI builders' bulk
        path — see :meth:`BgzfWriter.write_indexed`). Tallied like
        write_serialized so ``--audit-output`` covers indexed sorts."""
        if self._audit is not None:
            self._audit.add_indexed(blob, starts)
        return self._w.write_indexed(blob, starts)

    def _run_output_audit(self, tmp_path: str):
        """The pre-commit hook (utils/atomic.py): verify the finished
        temp end to end; raise OutputIntegrityError to abort the rename."""
        import logging
        import time as _time

        from ..observe.metrics import METRICS
        from .bgzf import verify_members
        from .errors import OutputIntegrityError

        t0 = _time.monotonic()
        walker = _BamStreamAudit(tmp_path,
                                 expect_coordinate=self._audit_coord)
        stats = {"members": 0, "data_bytes": 0, "eof_sentinel": False}
        try:
            stats = verify_members(tmp_path, sink=walker.feed)
            walker.finish()
            if not stats["eof_sentinel"]:
                raise OutputIntegrityError("missing BGZF EOF sentinel",
                                           path=tmp_path)
            if walker.header_crc != self._audit.header_crc:
                raise OutputIntegrityError(
                    "BAM header digest mismatch: the header block on disk "
                    "is not the header the writer encoded", path=tmp_path)
            if walker.records != self._audit.records:
                raise OutputIntegrityError(
                    f"record count mismatch: file holds {walker.records}, "
                    f"writer tallied {self._audit.records}", path=tmp_path)
            if walker.content_crc != self._audit.content_crc:
                raise OutputIntegrityError(
                    "record-stream digest mismatch: the record bytes on "
                    "disk are not (in order) the bytes the writer was "
                    "handed", path=tmp_path)
        except OutputIntegrityError as e:
            self._note_audit(self._audit_path, False,
                             stats_members=stats["members"],
                             records=walker.records, error=str(e))
            raise
        dt = _time.monotonic() - t0
        METRICS.observe("io.output_audit_s", dt)
        self._note_audit(self._audit_path, True,
                         stats_members=stats["members"],
                         records=walker.records)
        logging.getLogger("fgumi_tpu").info(
            "output audit: %d BGZF members / %d records verified clean "
            "in %.2fs", stats["members"], walker.records, dt)

    @staticmethod
    def _note_audit(path, ok, stats_members, records, error=None):
        # record the verdict on the sentinel (run report / stats `audit`
        # section). ops.sentinel is numpy-light — importing it here does
        # not drag in jax for IO-only commands.
        from ..ops.sentinel import SENTINEL

        SENTINEL.note_output_audit(path or "", ok, members=stats_members,
                                   records=records, error=error)

    def tell_virtual(self) -> int:
        """BGZF virtual offset of the next record (for BAI building)."""
        return self._w.tell_virtual()

    def close(self):
        self._w.close()

    def discard(self):
        """Abandon the output (error path): no EOF sentinel is written and
        an atomic temp file is removed instead of renamed."""
        self._w.discard()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.close()
        else:
            self.discard()
