"""Batched BAM reading: SoA record batches over contiguous chunk buffers.

The per-batch analog of the reference's Decode step
(/root/reference/src/lib/unified_pipeline/bam.rs:180,329: FindBoundaries +
parallel Decode into cached GroupKeys): decompressed bytes are scanned for
record boundaries and field-decoded natively (fgumi_tpu.native.batch), so the
Python layer holds numpy arrays per batch instead of objects per record.
"""

import numpy as np

from ..native import batch as nb
from .bam import BamHeader, RawRecord
from .bgzf import BgzfReader

# Smallest possible BAM record on the wire: 4-byte block_size + 32 fixed +
# 1-byte name (NUL only); guards the boundary-array allocation.
_MIN_RECORD_WIRE = 37


class RecordBatch:
    """A contiguous run of BAM records decoded struct-of-arrays.

    `buf` is a writable uint8 view of the chunk (overlap correction mutates
    seq/qual bytes in place, consensus/overlapping.py semantics). All offset
    arrays index into `buf`.
    """

    __slots__ = ("buf", "rec_off", "n", "ref_id", "pos", "mapq", "flag",
                 "l_seq", "n_cigar", "l_read_name", "next_ref_id", "next_pos",
                 "tlen", "data_off", "data_end", "cigar_off", "seq_off",
                 "qual_off", "aux_off", "_tag_locs")

    def __init__(self, chunk: bytearray, rec_off: np.ndarray):
        self.buf = np.frombuffer(chunk, dtype=np.uint8)
        self.rec_off = rec_off
        self.n = len(rec_off)
        f = nb.decode_fields(self.buf, rec_off)
        for k, v in f.items():
            setattr(self, k, v)
        self.cigar_off = self.data_off + 32 + self.l_read_name
        self.seq_off = self.cigar_off + 4 * self.n_cigar.astype(np.int64)
        self.qual_off = self.seq_off + (self.l_seq + 1) // 2
        self.aux_off = self.qual_off + self.l_seq
        self._tag_locs = {}

    def prefetch_tags(self, tags):
        """Seed the per-batch tag cache with ONE native aux scan for every
        not-yet-cached tag (the C scan takes k tags per pass; commands that
        read many tags were paying one full-batch scan per tag)."""
        need = [t for t in tags if t not in self._tag_locs]
        if not need:
            return
        # the fused scan packs tags at 2-byte stride; a stray non-2-byte
        # tag would silently misalign every LATER tag's column
        bad = [t for t in need if len(t) != 2]
        if bad:
            raise ValueError(f"SAM tags must be exactly 2 bytes: {bad!r}")
        vo, vl, vt = nb.scan_tags(self.buf, self.aux_off, self.data_end,
                                  need)
        for j, t in enumerate(need):
            self._tag_locs[t] = (np.ascontiguousarray(vo[:, j]),
                                 np.ascontiguousarray(vl[:, j]),
                                 np.ascontiguousarray(vt[:, j]))

    def tag_locs(self, tag: bytes):
        """(val_off int64[n], val_len int32[n], val_type uint8[n]) for one tag;
        val_off -1 where absent. Cached per batch."""
        got = self._tag_locs.get(tag)
        if got is None:
            self.prefetch_tags([tag])
            got = self._tag_locs[tag]
        return got

    def tag_locs_str(self, tag: bytes):
        """tag_locs with non-string-typed (not Z/H) tags masked to absent,
        matching RawRecord.get_str's type gate. Cached per batch."""
        got = self._tag_locs.get((tag, "str"))
        if got is None:
            vo, vl, vt = self.tag_locs(tag)
            ok = (vt == ord("Z")) | (vt == ord("H"))
            got = (np.where(ok, vo, -1), vl, vt)
            self._tag_locs[(tag, "str")] = got
        return got

    def tag_bytes(self, tag: bytes, i: int):
        """One record's tag value bytes (Z/H string, no NUL), or None."""
        vo, vl, _ = self.tag_locs(tag)
        if vo[i] < 0:
            return None
        return self.buf[vo[i]: vo[i] + vl[i]].tobytes()

    def name(self, i: int) -> bytes:
        off = self.data_off[i] + 32
        return self.buf[off: off + self.l_read_name[i] - 1].tobytes()

    def raw_record(self, i: int) -> RawRecord:
        """Materialize one record as a RawRecord (slow-path interop)."""
        return RawRecord(self.buf[self.data_off[i]: self.data_end[i]].tobytes())

    def raw_records(self, indices) -> list:
        return [self.raw_record(int(i)) for i in indices]


class BatchedRecordReader:
    """BamReader-compatible record iterator backed by BamBatchReader.

    Yields RawRecords, but the decompress/boundary-scan path runs natively
    per batch instead of per record — a drop-in accelerator for streaming
    commands that still consume records one at a time (zipper, merge, ...).
    """

    def __init__(self, path_or_obj, target_bytes: int = 8 << 20):
        self._r = BamBatchReader(path_or_obj, target_bytes=target_bytes)
        self.header = self._r.header

    def __iter__(self):
        for batch in self._r:
            for i in range(batch.n):
                yield batch.raw_record(i)

    def close(self):
        self._r.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _BatchAssembler:
    """Accumulate → boundary-scan → tail-carry loop shared by every batch
    source: ``read_chunk()`` returns the next decoded uint8 array (empty at
    end of stream) — the BGZF reader for file-backed batches, a fused-chain
    channel for in-memory handoff (``pipeline_chain.ChannelBatchReader``) —
    and iteration yields :class:`RecordBatch` objects of ~``target_bytes``
    payload. Factoring it here keeps the re-chunking behavior (single-part
    no-copy wrap, concatenate-once, partial-record tail carry, oversized-
    record target growth) identical across sources."""

    def __init__(self, read_chunk, target_bytes: int):
        self._read_chunk = read_chunk
        # a non-positive target would make _fill yield nothing and the
        # command silently write an empty output; clamp to "one chunk"
        self._target = max(int(target_bytes), 1)
        # decoded chunks accumulate as arrays and concatenate ONCE per
        # batch: appending into a bytearray and re-wrapping cost several
        # full copies of every decompressed byte (chain profiles)
        self._parts = []
        self._parts_len = 0
        self._eof = False

    def _fill(self):
        while self._parts_len < self._target and not self._eof:
            arr = self._read_chunk()
            if not len(arr):
                self._eof = True
                break
            self._parts.append(arr)
            self._parts_len += len(arr)

    def __iter__(self):
        while True:
            self._fill()
            if not self._parts_len:
                return
            buf = (self._parts[0] if len(self._parts) == 1
                   else np.concatenate(self._parts))
            max_records = len(buf) // _MIN_RECORD_WIRE + 1
            offsets, scanned = nb.find_boundaries(buf, max_records)
            if len(offsets) == 0:
                if self._eof:
                    raise EOFError("truncated BAM record at end of stream")
                # a single record larger than the accumulated bytes: grow
                self._target *= 2
                self._parts = [buf]
                self._parts_len = len(buf)
                continue
            # tail: copy the (at most one partial record) remainder so the
            # next batch doesn't pin this batch's full buffer
            tail = buf[scanned:].copy()
            self._parts = [tail] if len(tail) else []
            self._parts_len = len(tail)
            # a trailing partial record at EOF surfaces as an empty scan on the
            # next iteration and raises there, after this chunk is consumed
            yield RecordBatch(buf[:scanned], offsets.copy())


class BamBatchReader:
    """Yields RecordBatch objects of ~target_bytes decompressed payload."""

    def __init__(self, path_or_obj, target_bytes: int = 16 << 20):
        owns = isinstance(path_or_obj, str)
        fileobj = open(path_or_obj, "rb") if owns else path_or_obj
        if owns:
            from .prefetch import PrefetchFile, prefetch_enabled

            if prefetch_enabled():
                # async read-ahead + POSIX_FADV_SEQUENTIAL (reference
                # PrefetchReader, prefetch_reader.rs:93 + os_hints.rs):
                # overlaps disk latency with decompress/decode even when
                # the command runs without a reader stage thread
                fileobj = PrefetchFile(fileobj)
        self._r = BgzfReader(fileobj, owns_fileobj=owns,
                             name=path_or_obj if owns else None)
        try:
            self.header = BamHeader.decode_from(self._r.read)
        except BaseException:
            # stop the prefetch thread + close the fd even when the header
            # is corrupt — an unreferenced running thread never gets GC'd
            self._r.close()
            raise
        self._asm = _BatchAssembler(self._r.read_decoded, target_bytes)

    def __iter__(self):
        return iter(self._asm)

    def close(self):
        self._r.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
