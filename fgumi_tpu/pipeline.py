"""Fixed-role threaded host pipeline.

The simplified unified-pipeline analog SURVEY §7 step 9 calls for (reference:
/root/reference/src/lib/unified_pipeline/base.rs:1123-1150 9-step pool;
worker loop base.rs:4439-4600): fixed-role stages — reader (BGZF decompress +
boundary scan, native), processor (decode/group/pack/device, main thread),
writer (BGZF compress, native) — joined by bounded queues for backpressure.
The native calls release the GIL, so stages genuinely overlap; the
14-scheduler zoo is deliberately skipped (fixed roles saturate a device-fed
pipeline).

`threads <= 1` runs everything inline on the caller thread — the
single-threaded fast path every command keeps as its semantic reference
(reference bam.rs:3301, performance-tuning.md:28-40).
"""

import logging
import queue
import threading
import time

log = logging.getLogger("fgumi_tpu")


class StageTimes:
    """Per-stage busy/blocked wall time (PipelineStats-lite, base.rs:2853)."""

    def __init__(self):
        self.busy = {}
        self.blocked = {}

    def add_busy(self, stage: str, dt: float):
        self.busy[stage] = self.busy.get(stage, 0.0) + dt

    def add_blocked(self, stage: str, dt: float):
        self.blocked[stage] = self.blocked.get(stage, 0.0) + dt

    def format_table(self) -> str:
        stages = sorted(set(self.busy) | set(self.blocked))
        lines = ["stage        busy_s   blocked_s"]
        for s in stages:
            lines.append(f"{s:<12} {self.busy.get(s, 0.0):7.3f}   "
                         f"{self.blocked.get(s, 0.0):7.3f}")
        return "\n".join(lines)


class _Err:
    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


_DONE = object()


class _Watchdog:
    """Stall detector for the threaded pipeline (deadlock-watchdog-lite,
    reference deadlock.rs:1-60): a daemon timer samples the stage counters
    every `interval` seconds; when no stage made progress between samples
    while work remains, it logs a queue/stage snapshot so a wedged run is
    diagnosable from the log instead of silent."""

    def __init__(self, counters, q_in, q_out, interval: float):
        self._counters = counters
        self._q_in = q_in
        self._q_out = q_out
        self._interval = interval
        # (0,0,0) start: a pipeline wedged on its very first item reports at
        # t=interval, not 2x
        self._last = (0, 0, 0)
        self._stop = threading.Event()
        self._t = None
        if interval > 0:  # <= 0 disables the watchdog entirely
            self._t = threading.Thread(target=self._loop,
                                       name="fgumi-watchdog", daemon=True)
            self._t.start()

    def _loop(self):
        while not self._stop.wait(self._interval):
            snap = tuple(self._counters)
            if snap == self._last:
                log.warning(
                    "pipeline stalled for %.0fs: read=%d processed=%d "
                    "written=%d q_in=%d/%d q_out=%d/%d — no stage progressed "
                    "(device hang or downstream block?)",
                    self._interval, snap[0], snap[1], snap[2],
                    self._q_in.qsize(), self._q_in.maxsize,
                    self._q_out.qsize(), self._q_out.maxsize)
            self._last = snap

    def stop(self):
        self._stop.set()


def run_stages(source_iter, process_fn, sink_fn, threads: int = 0,
               queue_items: int = 4, stats: StageTimes = None,
               watchdog_interval: float = 120.0):
    """source -> process -> sink, optionally with reader/writer threads.

    - source_iter: yields work items (e.g. RecordBatch)
    - process_fn(item) -> iterable of outputs
    - sink_fn(output)

    threads <= 1: fully inline. threads >= 2: reader thread + writer thread
    around the processing caller thread, plus a stall watchdog. Exceptions
    from any stage propagate to the caller; the first exception wins and the
    pipeline drains.
    """
    if stats is None:
        stats = StageTimes()
    if threads <= 1:
        t_last = time.monotonic()
        for item in source_iter:
            now = time.monotonic()
            stats.add_busy("read", now - t_last)
            for out in process_fn(item):
                sink_fn(out)
            t_last = time.monotonic()
            stats.add_busy("process+write", t_last - now)
        return stats

    q_in = queue.Queue(maxsize=queue_items)
    # the sink queue may carry deferred work holding whole padded batches
    # (consensus _PendingChunk), so its depth bounds in-flight memory too
    q_out = queue.Queue(maxsize=queue_items * 2)
    writer_exc = []
    counters = [0, 0, 0]  # read, processed, written
    stop = threading.Event()  # error path: tell the reader to die promptly

    def put_in(item) -> bool:
        while not stop.is_set():
            try:
                q_in.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def reader():
        try:
            t_last = time.monotonic()
            for item in source_iter:
                now = time.monotonic()
                stats.add_busy("read", now - t_last)
                if not put_in(item):
                    return
                counters[0] += 1
                t_last = time.monotonic()
                stats.add_blocked("read", t_last - now)
            put_in(_DONE)
        except BaseException as e:  # noqa: BLE001 - relayed to caller
            put_in(_Err(e))

    def writer():
        try:
            while True:
                t0 = time.monotonic()
                out = q_out.get()
                now = time.monotonic()
                stats.add_blocked("write", now - t0)
                if out is _DONE:
                    return
                sink_fn(out)
                counters[2] += 1
                stats.add_busy("write", time.monotonic() - now)
        except BaseException as e:  # noqa: BLE001 - relayed to caller
            writer_exc.append(e)
            # drain so the processor never blocks on a dead writer
            while q_out.get() is not _DONE:
                pass

    rt = threading.Thread(target=reader, name="fgumi-reader", daemon=True)
    wt = threading.Thread(target=writer, name="fgumi-writer", daemon=True)
    watchdog = _Watchdog(counters, q_in, q_out, watchdog_interval)
    rt.start()
    wt.start()
    try:
        while True:
            t0 = time.monotonic()
            item = q_in.get()
            now = time.monotonic()
            stats.add_blocked("process", now - t0)
            if item is _DONE:
                break
            if isinstance(item, _Err):
                raise item.exc
            for out in process_fn(item):
                q_out.put(out)
            counters[1] += 1
            stats.add_busy("process", time.monotonic() - now)
            if writer_exc:
                raise writer_exc[0]
    finally:
        q_out.put(_DONE)
        wt.join()  # watchdog stays armed while the writer drains
        watchdog.stop()
        # stop + drain until the reader exits: it re-checks the stop event on
        # every bounded put, so it cannot re-block and leak (with its open
        # source) past this join
        stop.set()
        while rt.is_alive():
            try:
                while True:
                    q_in.get_nowait()
            except queue.Empty:
                pass
            rt.join(timeout=0.2)
    if writer_exc:
        raise writer_exc[0]
    return stats
