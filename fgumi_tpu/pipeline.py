"""Fixed-role threaded host pipeline.

The simplified unified-pipeline analog SURVEY §7 step 9 calls for (reference:
/root/reference/src/lib/unified_pipeline/base.rs:1123-1150 9-step pool;
worker loop base.rs:4439-4600): fixed-role stages — reader (BGZF decompress +
boundary scan, native), processor (decode/group/pack/device, main thread),
writer (BGZF compress, native) — joined by bounded queues for backpressure.
The native calls release the GIL, so stages genuinely overlap; the
14-scheduler zoo is deliberately skipped (fixed roles saturate a device-fed
pipeline).

`threads <= 1` runs everything inline on the caller thread. Commands
without a resolve stage get the strictly serial fast path (the semantic
reference, reference bam.rs:3301, performance-tuning.md:28-40); with a
resolve stage the default holds one output in flight so a device dispatch
overlaps the next item's host work (FGUMI_TPU_INLINE_FLIGHT=1 restores
strict serial order for bisection).
"""

import logging
import queue
import threading
import time

log = logging.getLogger("fgumi_tpu")


class StageTimes:
    """Per-stage busy/blocked wall time + queue-occupancy samples
    (PipelineStats-lite, reference base.rs:2853-3379: per-step timers and
    QueueSample history; VERDICT r4 item 9)."""

    def __init__(self):
        self.busy = {}
        self.blocked = {}
        self.q_samples = 0
        self.q_in_sum = 0
        self.q_in_max = 0
        self.q_out_sum = 0
        self.q_out_max = 0

    def add_busy(self, stage: str, dt: float):
        self.busy[stage] = self.busy.get(stage, 0.0) + dt

    def add_blocked(self, stage: str, dt: float):
        self.blocked[stage] = self.blocked.get(stage, 0.0) + dt

    def sample_queues(self, q_in_depth: int, q_out_depth: int):
        """One occupancy sample per processed item (the analog of the
        reference's QueueSample monitor history, bam.rs:3640-3690)."""
        self.q_samples += 1
        self.q_in_sum += q_in_depth
        self.q_in_max = max(self.q_in_max, q_in_depth)
        self.q_out_sum += q_out_depth
        self.q_out_max = max(self.q_out_max, q_out_depth)

    def format_table(self) -> str:
        stages = sorted(set(self.busy) | set(self.blocked))
        lines = ["stage        busy_s   blocked_s"]
        for s in stages:
            lines.append(f"{s:<12} {self.busy.get(s, 0.0):7.3f}   "
                         f"{self.blocked.get(s, 0.0):7.3f}")
        if self.q_samples:
            lines.append(
                f"queues       in avg {self.q_in_sum / self.q_samples:.1f} "
                f"max {self.q_in_max}; out avg "
                f"{self.q_out_sum / self.q_samples:.1f} max {self.q_out_max} "
                f"({self.q_samples} samples)")
        return "\n".join(lines)


class _Err:
    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


_DONE = object()


# The input queue's bytes-in-flight governor (the byte-accurate analog of
# the reference's MemoryTracker hysteresis, base.rs:466-625, now the shared
# dynamic-budget primitive): producers block while admitting another item
# would exceed the limit, except that one item is always admitted (an
# oversized batch degrades to serial flow instead of deadlocking);
# limit <= 0 disables accounting. run_stages registers it with the
# process-wide ResourceGovernor so a demand-starved input queue can borrow
# budget from idle ones (utils/governor.py).
from .utils.governor import DynamicBudget as _ByteBudget  # noqa: E402


class _Watchdog:
    """Stall detector for the threaded pipeline (deadlock-watchdog-lite,
    reference deadlock.rs:1-60): a daemon timer samples the stage counters
    every `interval` seconds; when no stage made progress between samples
    while work remains, it logs a queue/stage snapshot so a wedged run is
    diagnosable from the log instead of silent. With recover=True it also
    doubles the queue and byte limits on each stall (the reference's
    --deadlock-recover adaptive widening, deadlock.rs:409)."""

    def __init__(self, counters, q_in, q_out, interval: float,
                 recover: bool = False, budget: "_ByteBudget" = None):
        self._counters = counters
        self._q_in = q_in
        self._q_out = q_out
        self._interval = interval
        self._recover = recover
        self._budget = budget
        self._widenings_left = 4  # a deadlock-breaking nudge, not unbounded
        # (0,0,0) start: a pipeline wedged on its very first item reports at
        # t=interval, not 2x
        self._last = (0, 0, 0)
        self._stop = threading.Event()
        self._t = None
        if interval > 0:  # <= 0 disables the watchdog entirely
            from .observe.scope import spawn_thread

            self._t = spawn_thread(self._loop, name="fgumi-watchdog")
            self._t.start()

    def _loop(self):
        while not self._stop.wait(self._interval):
            snap = tuple(self._counters)
            if snap == self._last:
                log.warning(
                    "pipeline stalled for %.0fs: read=%d processed=%d "
                    "written=%d q_in=%d/%d q_out=%d/%d — no stage progressed "
                    "(device hang or downstream block?)",
                    self._interval, snap[0], snap[1], snap[2],
                    self._q_in.qsize(), self._q_in.maxsize,
                    self._q_out.qsize(), self._q_out.maxsize)
                if self._recover and self._widenings_left > 0 \
                        and self._capacity_bound():
                    self._widenings_left -= 1
                    self._widen()
            self._last = snap

    def _capacity_bound(self):
        """Only widen when a limit is actually saturated — a stall with idle
        queues (device hang, slow stage) is not a capacity deadlock, and
        widening there just unbounds memory."""
        full_in = 0 < self._q_in.maxsize <= self._q_in.qsize()
        full_out = 0 < self._q_out.maxsize <= self._q_out.qsize()
        b = self._budget
        saturated = b is not None and b.limit > 0 and b.used >= b.limit
        return full_in or full_out or saturated

    def _widen(self):
        for q in (self._q_in, self._q_out):
            with q.mutex:
                if q.maxsize > 0:
                    q.maxsize *= 2
                q.not_full.notify_all()
        if self._budget is not None:
            self._budget.widen()
        log.warning("deadlock-recover: queue limits doubled to "
                    "q_in=%d q_out=%d bytes=%s", self._q_in.maxsize,
                    self._q_out.maxsize,
                    self._budget.limit if self._budget else "n/a")

    def stop(self):
        """Stop AND join the timer thread: a failed command must not leave
        a daemon watchdog sampling dead queues behind it (the error path
        out of run_stages calls this in its finally)."""
        self._stop.set()
        if self._t is not None:
            self._t.join(timeout=5)


def _traced_source(source_iter):
    """Wrap a source iterator so each pull is a pipeline.read span (runs on
    whichever thread drives the iterator — the reader thread when threaded,
    the caller inline — so thread attribution is automatic)."""
    from .observe.trace import span

    it = iter(source_iter)
    while True:
        with span("pipeline.read"):
            try:
                item = next(it)
            except StopIteration:
                return
        yield item


def _traced_stage(name, fn, materialize=False):
    """Wrap a stage callable in a named span. ``materialize`` forces lazy
    process outputs into a list so the span covers the actual work, not
    just generator construction (tracing is opt-in diagnostics; the small
    buffering change is acceptable there)."""
    from .observe.trace import span

    if materialize:
        def wrapped(item):
            with span(name):
                return list(fn(item))
    else:
        def wrapped(item):
            with span(name):
                return fn(item)
    return wrapped


def run_stages(source_iter, process_fn, sink_fn, threads: int = 0,
               queue_items: int = 4, stats: StageTimes = None,
               watchdog_interval: float = 120.0, resolve_fn=None,
               max_bytes: int = 0, item_bytes=None,
               deadlock_recover: bool = False, resolve_workers: int = None):
    """source -> process [-> resolve workers] -> sink, with optional threads.

    - source_iter: yields work items (e.g. RecordBatch)
    - process_fn(item) -> iterable of outputs (serial stage: carry/group
      state lives here, like the reference's exclusive Group step,
      base.rs:1123-1150)
    - resolve_fn(output) -> resolved output (optional PARALLEL stage: must be
      thread-safe and pure per item — e.g. consensus _PendingChunk.resolve,
      whose shared counters are lock-guarded). With threads >= 4 a pool of
      (threads - 3) workers applies it concurrently; outputs are re-ordered
      by serial number before the sink (the reference's Q7 write-reorder,
      base.rs:1724-1920).
    - sink_fn(resolved output) (serial, input order)
    - max_bytes + item_bytes(item): byte-accurate input-queue governance —
      the reader blocks while admitting another item would exceed max_bytes
      (one item always admits, so an oversized batch serializes instead of
      deadlocking). Items vary widely in bytes, so this is what makes
      --max-memory actually bound a streaming command's working set
      (reference MemoryTracker, base.rs:466-625).
    - deadlock_recover: the stall watchdog doubles queue/byte limits on each
      stall instead of only logging (reference deadlock.rs:409).

    threads <= 1: fully inline; with a resolve_fn the default keeps one
    output in flight (FGUMI_TPU_INLINE_FLIGHT outputs, default 2, =1 for
    strict serial order) so device dispatches overlap the next item's host
    prep. threads 2..3: reader + writer threads around the processing
    caller thread (resolve_fn runs on the writer). threads >= 4 with
    resolve_fn: reader + workers + writer. Exceptions from any stage
    propagate to the caller; the first exception wins and the pipeline
    drains. A stall watchdog logs a queue snapshot if no stage progresses.
    """
    if stats is None:
        stats = StageTimes()
    from .observe import trace as _trace

    if _trace.tracing_enabled():
        # wrap only when tracing is on: with flags off the hot path runs
        # the caller's bare callables (zero telemetry overhead, no new
        # per-item allocations — the acceptance contract of observe/)
        source_iter = _traced_source(source_iter)
        process_fn = _traced_stage("pipeline.process", process_fn,
                                   materialize=True)
        if resolve_fn is not None:
            resolve_fn = _traced_stage("pipeline.resolve", resolve_fn)
        sink_fn = _traced_stage("pipeline.sink", sink_fn)
    try:
        return _run_stages_impl(
            source_iter, process_fn, sink_fn, threads, queue_items, stats,
            watchdog_interval, resolve_fn, max_bytes, item_bytes,
            deadlock_recover, resolve_workers)
    finally:
        # fold per-stage timings into the metrics registry on every exit
        # path (success AND failure) so the run report can always answer
        # "where did the time go"
        from .observe.metrics import record_stage_times

        record_stage_times(stats)


def _run_stages_impl(source_iter, process_fn, sink_fn, threads, queue_items,
                     stats, watchdog_interval, resolve_fn, max_bytes,
                     item_bytes, deadlock_recover, resolve_workers):
    from .utils import faults

    if faults.armed("pipeline.process"):
        inner_process = process_fn

        def process_fn(item):
            faults.fire("pipeline.process")
            return inner_process(item)
    has_resolve = resolve_fn is not None
    if resolve_fn is None:
        resolve_fn = lambda out: out  # noqa: E731
    if threads <= 1:
        # Double buffering (only when a real resolve stage exists): hold one
        # output back so a device dispatch made inside process_fn overlaps
        # the NEXT item's read + host prep instead of being awaited
        # immediately. Semantically identical to the threaded resolve pool
        # at depth 1 (outputs stay FIFO); measured on the TPU tunnel it
        # removes ~70 ms of fetch wait per dispatch from the critical path.
        from collections import deque

        max_pend = 1
        if has_resolve:
            import os

            try:
                max_pend = max(int(os.environ.get(
                    "FGUMI_TPU_INLINE_FLIGHT", "2")), 1)
            except ValueError:
                max_pend = 2
        if max_pend == 1:
            t_last = time.monotonic()
            for item in source_iter:
                now = time.monotonic()
                stats.add_busy("read", now - t_last)
                for out in process_fn(item):
                    sink_fn(resolve_fn(out))
                t_last = time.monotonic()
                stats.add_busy("process+write", t_last - now)
            return stats
        pend = deque()
        in_resolve = False
        try:
            t_last = time.monotonic()
            for item in source_iter:
                now = time.monotonic()
                stats.add_busy("read", now - t_last)
                for out in process_fn(item):
                    pend.append(out)
                    while len(pend) >= max_pend:
                        in_resolve = True
                        sink_fn(resolve_fn(pend.popleft()))
                        in_resolve = False
                t_last = time.monotonic()
                stats.add_busy("process+write", t_last - now)
            now = time.monotonic()
            while pend:
                in_resolve = True
                sink_fn(resolve_fn(pend.popleft()))
                in_resolve = False
            stats.add_busy("process+write", time.monotonic() - now)
        except BaseException:
            # a source/process failure still writes the outputs it had in
            # flight — the serial path wrote output N before touching item
            # N+1, and a deferred resolve must not lose it. When the resolve
            # or sink ITSELF raised, draining would write outputs past the
            # failed one (a holed file the serial path can't produce), so
            # in-flight outputs are dropped exactly like the threaded error
            # path does. The original error wins either way.
            if not in_resolve:
                try:
                    while pend:
                        sink_fn(resolve_fn(pend.popleft()))
                except BaseException:
                    pass
            raise
        return stats

    # resolve_workers overrides the threads-3 pool size (device-attached
    # runs want >=2 so a worker blocked on a device fetch never starves a
    # host-engine chunk queued behind it; fetch waits hold no GIL, so
    # oversubscribing a 1-core host is free)
    if resolve_workers is not None and threads >= 2:
        n_workers = max(int(resolve_workers), 0)
    else:
        n_workers = max(threads - 3, 0)
    q_in = queue.Queue(maxsize=queue_items)
    # the sink queue may carry deferred work holding whole padded batches
    # (consensus _PendingChunk), so its depth bounds in-flight memory too
    q_out = queue.Queue(maxsize=queue_items * 2)
    writer_exc = []
    counters = [0, 0, 0]  # read, processed, written
    # a StopSignal, not a bare Event: budget.acquire subscribes its
    # condition so cancellation wakes a blocked reader immediately instead
    # of at the next 100 ms poll tick
    from .utils.governor import GOVERNOR, StopSignal

    stop = StopSignal()  # error path: tell the reader to die promptly
    budget = _ByteBudget("pipeline.input",
                         max_bytes if item_bytes is not None else 0)
    # under governance the input budget competes for the process cap with
    # the fused-chain channels and the device feeder; its demand signal is
    # the reader's own acquire wait (producer starved) vs the process
    # stage's empty-queue wait (consumer starved)
    gov_token = None
    if budget.limit > 0:
        gov_token = GOVERNOR.register_budget(
            budget,
            demand_fn=lambda: {
                "put_wait_s": budget.wait_s,
                "get_wait_s": stats.blocked.get("process", 0.0)})

    def put_in(item) -> bool:
        while not stop.is_set():
            try:
                q_in.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def reader():
        # real items travel as (charged_bytes, item) pairs so the charge is
        # released exactly once per admission (keying a side table by
        # id(item) would double-charge duplicate/interned objects)
        try:
            t_last = time.monotonic()
            for item in source_iter:
                now = time.monotonic()
                stats.add_busy("read", now - t_last)
                nb = 0
                if budget.limit > 0:
                    nb = int(item_bytes(item))
                    if not budget.acquire(nb, stop):
                        return
                if not put_in((nb, item)):
                    return
                counters[0] += 1
                t_last = time.monotonic()
                stats.add_blocked("read", t_last - now)
            put_in(_DONE)
        except BaseException as e:  # noqa: BLE001 - relayed to caller
            put_in(_Err(e))

    # ---- resolve worker pool (threads >= 4): q_out carries (serial, item);
    # workers push (serial, resolved | _Err) to q_done; the writer restores
    # serial order with a holdback map (bounded by in-flight = q_out depth +
    # n_workers, so memory stays bounded by queue_items)
    q_done = queue.Queue() if n_workers else None

    def worker(widx):
        while True:
            got = q_out.get()
            if got is _DONE:
                q_done.put(_DONE)
                return
            serial, item = got
            t0 = time.monotonic()
            try:
                q_done.put((serial, resolve_fn(item)))
            except BaseException as e:  # noqa: BLE001 - relayed via writer
                q_done.put((serial, _Err(e)))
            stats.add_busy(f"resolve[{widx}]", time.monotonic() - t0)

    def writer_pooled():
        next_serial = 0
        holdback = {}
        done_workers = 0
        try:
            while done_workers < n_workers:
                t0 = time.monotonic()
                got = q_done.get()
                now = time.monotonic()
                stats.add_blocked("write", now - t0)
                if got is _DONE:
                    done_workers += 1
                    continue
                serial, resolved = got
                holdback[serial] = resolved
                while next_serial in holdback:
                    out = holdback.pop(next_serial)
                    next_serial += 1
                    if isinstance(out, _Err):
                        raise out.exc
                    sink_fn(out)
                    counters[2] += 1
                stats.add_busy("write", time.monotonic() - now)
            # workers exited; flush any stragglers in serial order
            while next_serial in holdback:
                out = holdback.pop(next_serial)
                next_serial += 1
                if isinstance(out, _Err):
                    raise out.exc
                sink_fn(out)
                counters[2] += 1
        except BaseException as e:  # noqa: BLE001 - relayed to caller
            writer_exc.append(e)
            while done_workers < n_workers:
                if q_done.get() is _DONE:
                    done_workers += 1

    def writer_direct():
        try:
            while True:
                t0 = time.monotonic()
                out = q_out.get()
                now = time.monotonic()
                stats.add_blocked("write", now - t0)
                if out is _DONE:
                    return
                sink_fn(resolve_fn(out))
                counters[2] += 1
                stats.add_busy("write", time.monotonic() - now)
        except BaseException as e:  # noqa: BLE001 - relayed to caller
            writer_exc.append(e)
            # drain so the processor never blocks on a dead writer
            while q_out.get() is not _DONE:
                pass

    # stage threads run in a copy of the caller's context so a scoped
    # command's telemetry (metrics/trace/device stats — one scope per serve
    # daemon job) follows its whole thread tree (observe.scope)
    from .observe.scope import spawn_thread

    rt = spawn_thread(reader, name="fgumi-reader")
    wt = spawn_thread(writer_pooled if n_workers else writer_direct,
                      name="fgumi-writer")
    wts = [spawn_thread(worker, args=(i,), name=f"fgumi-worker-{i}")
           for i in range(n_workers)]
    watchdog = _Watchdog(counters, q_in, q_out, watchdog_interval,
                         recover=deadlock_recover, budget=budget)
    # publish the watchdog's view (stage counters + queue depths) to the
    # periodic heartbeat for the lifetime of this pipeline
    from .observe import heartbeat as _hb

    hb_token = _hb.register_gauge(lambda: {
        "read": counters[0], "processed": counters[1],
        "written": counters[2],
        "q_in": f"{q_in.qsize()}/{q_in.maxsize}",
        "q_out": f"{q_out.qsize()}/{q_out.maxsize}"})
    rt.start()
    wt.start()
    for t in wts:
        t.start()
    serial = 0
    try:
        while True:
            t0 = time.monotonic()
            item = q_in.get()
            now = time.monotonic()
            stats.add_blocked("process", now - t0)
            if item is _DONE:
                break
            if isinstance(item, _Err):
                raise item.exc
            nb, item = item
            try:
                for out in process_fn(item):
                    if n_workers:
                        q_out.put((serial, out))
                        serial += 1
                    else:
                        q_out.put(out)
            finally:
                if nb:
                    budget.release(nb)
            counters[1] += 1
            stats.add_busy("process", time.monotonic() - now)
            stats.sample_queues(q_in.qsize(), q_out.qsize())
            if writer_exc:
                raise writer_exc[0]
    finally:
        for _ in range(max(n_workers, 1)):
            q_out.put(_DONE)
        for t in wts:
            t.join()
        wt.join()  # watchdog stays armed while the writer drains
        watchdog.stop()
        # stop + drain until the reader exits: it re-checks the stop event on
        # every bounded put, so it cannot re-block and leak (with its open
        # source) past this join
        stop.set()
        while rt.is_alive():
            try:
                while True:
                    q_in.get_nowait()
            except queue.Empty:
                pass
            rt.join(timeout=0.2)
        _hb.unregister_gauge(hb_token)
        GOVERNOR.unregister_budget(gov_token)
    if writer_exc:
        raise writer_exc[0]
    if budget.limit > 0:
        stats.peak_in_flight_bytes = budget.peak
        # used/peak/limit land in METRICS as governor.budget.* gauges so
        # the run report can answer "was the input queue budget-bound"
        from .observe.metrics import METRICS

        p = f"governor.budget.{budget.name}"
        METRICS.set(f"{p}.limit", budget.limit)
        METRICS.max(f"{p}.peak", budget.peak)
        METRICS.inc(f"{p}.wait_s", round(budget.wait_s, 6))
    return stats
